"""Fixed-cell layout rule family (PXL11x).

PR 15 rewrote the five lane-major hot-path kernels (paxos, sdpaxos,
wpaxos, wankeeper, bpaxos) from the sliding-window ring layout onto
the fixed-cell mapping (``sim/cell.py``: absolute slot ``a`` at cell
``a % S`` forever), eliminating the per-step ``ring.shift_window``
alignment gathers that dominated XLA:CPU step cost.  The layout is a
*contract*: one re-introduced shift import quietly reinstates the
gather tax (the compiled-HLO gather count is the runtime witness —
``python -m paxi_tpu profile --gathers``), and a kernel mixing the
two layouts corrupts its ring silently (a shift moves cells whose
absolute slots the fixed mapping expects to stay put).

This family pins the contract statically over the rewritten kernel
files (the frozen ``sim_sw.py`` references and the still-sliding
kernels — epaxos, kpaxos, switchpaxos — are deliberately NOT targets):

- **PXL111** a fixed-cell kernel imports a sliding-window shift
  primitive (``shift_window`` / ``shift_row`` / ``shift_deps`` from
  ``sim/ring.py``), by name or as a module-attribute reference.
- **PXL112** a fixed-cell kernel imports the sliding-window consensus
  core (``sim/ballot_ring.py``) instead of its fixed-cell twin
  (``sim/cell_ring.py``; the twin re-exporting ballot_ring's
  layout-free helpers is fine — the rule fires on the kernel's own
  import).

Purely syntactic (imports + attribute references), so it runs in
milliseconds and never needs jax.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.model import Violation

RULE = "fixed-cell-layout"

# the rewritten kernels — the files the default run pins.  Fixture
# tests drive the rule over seeded modules by passing files= directly.
TARGETS = (
    "paxi_tpu/protocols/paxos/sim.py",
    "paxi_tpu/protocols/sdpaxos/sim.py",
    "paxi_tpu/protocols/wpaxos/sim.py",
    "paxi_tpu/protocols/wankeeper/sim.py",
    "paxi_tpu/protocols/bpaxos/sim.py",
)

SHIFT_NAMES = frozenset({"shift_window", "shift_row", "shift_deps"})
RING_MODULE = "paxi_tpu.sim.ring"
SW_CORE = "paxi_tpu.sim.ballot_ring"


def _check_file(path: Path, root: Path) -> List[Violation]:
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return []
    rel = astutil.rel(path, root)
    out: List[Violation] = []
    ring_aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == RING_MODULE or mod.endswith(".ring"):
                for a in node.names:
                    if a.name in SHIFT_NAMES:
                        out.append(Violation(
                            rule=RULE, code="PXL111", path=rel,
                            line=node.lineno, col=node.col_offset,
                            message=f"fixed-cell kernel imports "
                                    f"sliding-window shift primitive "
                                    f"{a.name!r} from sim/ring.py — "
                                    f"use sim/cell.py masks instead"))
            if mod == SW_CORE or mod.endswith(".ballot_ring"):
                out.append(Violation(
                    rule=RULE, code="PXL112", path=rel,
                    line=node.lineno, col=node.col_offset,
                    message="fixed-cell kernel imports the "
                            "sliding-window core sim/ballot_ring.py — "
                            "use sim/cell_ring.py"))
            if mod == "paxi_tpu.sim" or mod.endswith(".sim") \
                    or mod == "sim":
                for a in node.names:
                    if a.name == "ring":
                        ring_aliases.add(a.asname or a.name)
                    if a.name == "ballot_ring":
                        out.append(Violation(
                            rule=RULE, code="PXL112", path=rel,
                            line=node.lineno, col=node.col_offset,
                            message="fixed-cell kernel imports the "
                                    "sliding-window core "
                                    "sim/ballot_ring.py — use "
                                    "sim/cell_ring.py"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == RING_MODULE and a.asname:
                    # bare ``import paxi_tpu.sim.ring`` needs no alias:
                    # its references spell the full dotted path, which
                    # the attribute walk below matches directly
                    ring_aliases.add(a.asname)
                if a.name == SW_CORE:
                    out.append(Violation(
                        rule=RULE, code="PXL112", path=rel,
                        line=node.lineno, col=node.col_offset,
                        message="fixed-cell kernel imports the "
                                "sliding-window core "
                                "sim/ballot_ring.py — use "
                                "sim/cell_ring.py"))
    # module-attribute spellings: ``ring.shift_window(...)`` and the
    # fully dotted ``paxi_tpu.sim.ring.shift_window(...)``
    def _dotted(node) -> str:
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return ""

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in SHIFT_NAMES:
            base_path = _dotted(node.value)
            if base_path and (base_path in ring_aliases
                              or base_path == RING_MODULE
                              or base_path.endswith(".ring")):
                out.append(Violation(
                    rule=RULE, code="PXL111", path=rel,
                    line=node.lineno, col=node.col_offset,
                    message=f"fixed-cell kernel references "
                            f"sliding-window shift primitive "
                            f"{base_path}.{node.attr} — use "
                            f"sim/cell.py masks instead"))
    return out


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (files if files is not None
                 else astutil.iter_py(root, TARGETS)):
        out.extend(_check_file(Path(path), root))
    return sorted(out, key=lambda v: (v.path, v.line, v.code))
