"""paxi-lint: protocol-aware static analysis for the two runtimes.

Stage 1 — per-function AST rule families, each exploiting an invariant
the architecture already promises (see each module's docstring):

- ``kernel-purity``        (purity.py,      PXK1xx)
- ``handler-completeness`` (handlers.py,    PXH2xx)
- ``trace-map``            (tracemap.py,    PXT3xx)
- ``host-concurrency``     (concurrency.py, PXC4xx + PXC45x)

Stage 2 — protocol-*semantics* dataflow families on the shared
interprocedural engine (flow.py: module-local call graph, symbolic
int-expression evaluator, guard domination):

- ``quorum-safety``        (quorum.py,      PXQ5xx)
- ``ballot-guard``         (ballots.py,     PXB6xx)
- ``sim-host-parity``      (parity.py,      PXS7xx)

Stage 3 — whole-program families on the ProjectIndex (project.py:
import resolution, cross-module call graph with guard inheritance
across file boundaries; ``lint --graph`` dumps it as DOT):

- ``cross-module-flow``    (crossflow.py,   PXF8xx)
- ``async-atomicity``      (asyncflow.py,   PXA9xx)

Observability isolation (taint walk over the sim kernels' step
functions; guards the PR-11 on-device measurement layer):

- ``measurement-isolation`` (measure.py,    PXM10x)

Layout contracts (import/reference pins over the fixed-cell hot-path
kernels; guards the PR-15 shift-gather elimination):

- ``fixed-cell-layout``    (layout.py,      PXL11x)

Workload purity (counter-based draw contract over the workload
engine; guards the PR-16 cross-runtime pinned replay):

- ``workload-purity``      (workload.py,    PXW12x)

Span isolation (taint walk over the protocol host modules; guards the
obs/ tracing layer's write-only contract):

- ``span-isolation``       (spanrule.py,    PXO13x)

Stage 4 — replay-soundness proofs over the serving stack (the
determinism the whole replay/span/hunt story depends on):

- ``replay-determinism``   (determinism.py, PXD14x) — interprocedural
  clock/order/ambient taint over host/shard/switchnet/obs, sanctioned
  only by the documented fabric-resolution guards
- ``epoch-fence``          (epochfence.py,  PXE15x) — ShardMap fence
  proof: every map read fenced, every swap monotone (the migration
  precondition)

Stage 5 — read-tier and wire-schema preconditions (landed before the
read scale-out tier for the same reason PXE15x landed before
resharding):

- ``lease-flow``           (leaseflow.py,   PXR16x) — lease/read-
  staleness proof: local-state read serving dominated by
  ``_lease_ok``, monotone quorum-round lease renewals, fenced
  elections and 2PC recovery, resolved clocks only
- ``wire-record``          (wirerecord.py,  PXV17x) — wire-record
  schema proof over the derived ``*_MAGIC`` universe: prefix
  disjointness, pack/unpack field round-trip, guarded interpreter
  chain, reserved-prefix ingress rejection

Entry points: ``python -m paxi_tpu lint [--rule ...] [--json]`` (cli.py;
``--rule`` takes family names or code prefixes like ``PXQ,PXB``) and
:func:`run_lint` for tests/tooling.  Intentional exceptions live in
``analysis/baseline.toml``; one-line escapes use an inline
``# paxi-lint: disable=CODE`` comment.  Purely static — no module under
analysis is ever imported, so the linter needs no jax and is safe on
broken code.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

import time

from paxi_tpu.analysis import astutil, asyncflow, ballots, concurrency, \
    crossflow, determinism, epochfence, handlers, layout, leaseflow, \
    measure, parity, purity, quorum, spanrule, tracemap, wirerecord, \
    workload
from paxi_tpu.analysis.model import (LintReport, Suppression, Violation,
                                     apply_suppressions, inline_disables,
                                     load_baseline)

__all__ = ["RULES", "CODE_PREFIXES", "DEFAULT_BASELINE", "LintReport",
           "Suppression", "Violation", "repo_root", "resolve_rules",
           "run_lint"]

# rule family name -> module exposing check(root, files=None)
RULES = {
    purity.RULE: purity,
    handlers.RULE: handlers,
    tracemap.RULE: tracemap,
    concurrency.RULE: concurrency,
    quorum.RULE: quorum,
    ballots.RULE: ballots,
    parity.RULE: parity,
    crossflow.RULE: crossflow,
    asyncflow.RULE: asyncflow,
    measure.RULE: measure,
    layout.RULE: layout,
    workload.RULE: workload,
    spanrule.RULE: spanrule,
    determinism.RULE: determinism,
    epochfence.RULE: epochfence,
    leaseflow.RULE: leaseflow,
    wirerecord.RULE: wirerecord,
}

# violation-code prefix -> rule family, the CLI's short spelling
# (`--rule PXQ,PXB`); PXC covers both the stage-1 checks and the
# PXC45x deepening (one module)
CODE_PREFIXES = {
    "PXK": purity.RULE,
    "PXH": handlers.RULE,
    "PXT": tracemap.RULE,
    "PXC": concurrency.RULE,
    "PXQ": quorum.RULE,
    "PXB": ballots.RULE,
    "PXS": parity.RULE,
    "PXF": crossflow.RULE,
    "PXA": asyncflow.RULE,
    "PXM": measure.RULE,
    "PXL": layout.RULE,
    "PXW": workload.RULE,
    "PXO": spanrule.RULE,
    "PXD": determinism.RULE,
    "PXE": epochfence.RULE,
    "PXR": leaseflow.RULE,
    "PXV": wirerecord.RULE,
}

# pair-driven rules (registry-derived sim/host pairs instead of globs)
_PAIR_RULES = {tracemap.RULE: tracemap, parity.RULE: parity}


def resolve_rules(specs: Sequence[str]) -> List[str]:
    """Family names, ``PXQ``-style code prefixes, and comma-separated
    combinations thereof -> unique family names (raises KeyError on
    anything unknown)."""
    out: List[str] = []
    for spec in specs:
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            name = (token if token in RULES
                    else CODE_PREFIXES.get(token.upper()))
            if name is None:
                raise KeyError(
                    f"unknown rule {token!r}; have {sorted(RULES)} "
                    f"or prefixes {sorted(CODE_PREFIXES)}")
            if name not in out:
                out.append(name)
    return out

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.toml"


def repo_root() -> Path:
    """The directory holding the ``paxi_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def _target_files(root: Path, rule_mod,
                  paths: Sequence[Path],
                  strict: bool = False) -> List[Path]:
    """A rule's default file set restricted to ``paths`` (files or
    directories), plus any explicitly named file outside the rule's
    globs — that is how fixture tests drive a rule over seeded
    modules.  ``strict=True`` drops that out-of-glob escape so a
    scoped run (``lint --changed``) reports exactly what a full run
    would for the same files."""
    dirs = [p.resolve() for p in paths if p.is_dir()]
    files = {p.resolve() for p in paths if p.is_file()}
    defaults = list(astutil.iter_py(root, getattr(rule_mod, "TARGETS", ())))
    wanted = [p for p in defaults
              if p.resolve() in files
              or any(str(p.resolve()).startswith(str(d) + "/")
                     for d in dirs)]
    if not strict:
        default_set = {p.resolve() for p in defaults}
        wanted += [Path(f) for f in sorted(files - default_set)]
    return sorted(set(wanted))


def run_lint(root: Optional[Path] = None,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[Path] = DEFAULT_BASELINE,
             paths: Optional[Sequence[Path]] = None,
             strict_targets: bool = False) -> LintReport:
    """Run the selected rule families and apply both suppression
    layers.  ``baseline_path=None`` disables the baseline (the
    "show me everything" mode).  ``strict_targets=True`` keeps every
    rule on its own globs even for explicitly named files — the
    ``lint --changed`` contract that scoped and full runs agree."""
    root = (root or repo_root()).resolve()
    selected = resolve_rules(rules) if rules else list(RULES)
    if paths is not None:
        missing = [str(p) for p in paths if not Path(p).exists()]
        if missing:
            raise ValueError(f"no such path(s): {', '.join(missing)}")

    raw: List[Violation] = []
    checked: set = set()
    timings: Dict[str, float] = {}
    for name in selected:
        mod = RULES[name]
        t0 = time.perf_counter()
        if name in _PAIR_RULES:
            # pair-based, registry-driven: restriction matches the sim
            # or host module, directories match their subtrees
            for protocol, sp, hp in mod.analyzed_pairs(root, paths):
                raw.extend(mod.check_pair(protocol, sp, hp, root))
                checked.update((sp, hp))
            timings[name] = time.perf_counter() - t0
            continue
        files = (None if paths is None
                 else _target_files(root, mod, paths,
                                    strict=strict_targets))
        raw.extend(mod.check(root, files=files))
        checked.update(files if files is not None
                       else astutil.iter_py(root, mod.TARGETS))
        timings[name] = time.perf_counter() - t0

    baseline = (load_baseline(baseline_path)
                if baseline_path is not None else [])
    inline: Dict[str, Dict[int, set]] = {}
    for path in {v.path for v in raw}:
        try:
            inline[path] = inline_disables((root / path).read_text())
        except OSError:
            inline[path] = {}
    kept, dropped = apply_suppressions(raw, baseline, inline)
    # stale-baseline warnings only make sense when every rule ran over
    # the whole tree — a restricted run never exercises most entries
    complete = paths is None and set(selected) == set(RULES)
    unused = [s for s in baseline if not s.used] if complete else []
    return LintReport(violations=kept, suppressed=dropped,
                      unused_baseline=unused, checked_files=len(checked),
                      timings=timings)
