"""Cross-module flow rule family (PXF8xx) — stage 3 of paxi-verify.

PR 5's ballot-guard (PXB) and quorum (PXQ) families stopped at the
module boundary, which left the repo's most shared consensus code — the
``sim/ballot_ring.py`` helpers five kernels run on — analyzed without
their call-site guards (the explicit ROADMAP carry-forward).  This
family re-runs both obligations *through* the boundary on the
whole-program :class:`~paxi_tpu.analysis.project.ProjectIndex`:

- **epoch-write domination** (PXF801): every write to an epoch-state
  plane (``ballot``/``abal``/``vbal``/``log_bal``/``active``) in a sim
  kernel or a shared helper must be one of

  - *guarded*: the ``jnp.where`` mask (or the or-ed growth term for
    boolean planes) passes through a comparison that mentions a ballot
    register — directly, through local dataflow (tallies accumulated
    under ``m["bal"] == st["ballot"]`` count, because the threshold
    compare on such a tally IS the ballot guard), or through a
    **function parameter chased to every call site**, across file
    boundaries (``depose(st, mask, ...)`` is proven once per caller;
    ``merge_acker_logs``'s ``p1_win`` is proven per *kernel*, through
    the tuple returned by ``tally_p1b``);
  - *monotone by construction*: the new value is a ``max``/``maximum``
    over the current plane (the election ``(max(ballot)//stride+1)*
    stride + id`` idiom included);
  - *state-derived*: the new value's value-positions carry only
    current epoch state or constants (window shifts, snapshot
    adoption by reference, NOOP/zero resets, owning a slot under my
    already-promised ballot) — no foreign ballot enters;
  - *shrinking* (boolean planes): ``active & ~x`` only demotes.

- **shared-plane interference** (PXF802): a kernel writing a plane the
  imported helper module owns (its ``KEYS`` tuple) is flagged unless
  the kernel write's guard is *disjoint* from every helper write's
  guard for that plane (a complementary atom — ``x`` vs ``~x`` — after
  substituting helper parameters with the kernel's call-site
  arguments).  Two modules masking one carry field with overlapping
  guards is the lane-major analog of an unsynchronized shared write.

- **cross-module quorum flow** (PXF803/804): a threshold parameter
  compared against a tally inside a helper (``popcount(acks) >=
  majority``) is derived at each kernel call site (SymEval through the
  kernel's aliases and SimConfig's own property bodies) and every
  phase-1 x phase-2 pair a kernel feeds the helper must intersect for
  all n — the PXQ proof, re-run through the boundary.  Unresolvable
  sites are PXF804, never silence.

Like every paxi-lint family this is purely static; see
``coverage(root)`` for the per-kernel proof summary the tier-1 test
pins (all five ballot-ring consumers covered).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from fractions import Fraction
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from paxi_tpu.analysis import astutil, flow
from paxi_tpu.analysis.model import Violation
from paxi_tpu.analysis.project import CallSite, ModInfo, ProjectIndex, \
    shared_index
from paxi_tpu.analysis.quorum import Resolver

RULE = "cross-module-flow"

TARGETS = (
    "paxi_tpu/protocols/*/sim.py",
    "paxi_tpu/protocols/*/sim_pg.py",
    "paxi_tpu/sim/ballot_ring.py",
    # the fixed-cell twin of the ballot-ring core (PR 15): same
    # epoch-plane writes, same guard-domination obligation, proven
    # through ITS consumers' call sites (paxos/sdpaxos/wankeeper)
    "paxi_tpu/sim/cell_ring.py",
)

SIM_TYPES = "paxi_tpu/sim/types.py"

# planes whose writes owe domination (W) and ballot registers whose
# mention makes a comparison a ballot guard (C)
EPOCH_PLANES = frozenset({"ballot", "abal", "vbal", "log_bal", "active"})
BALLOT_REGS = frozenset({"ballot", "abal", "vbal", "log_bal", "rec_bal"})

# receivers treated as the state-plane dict in sim code
STATE_DICTS = frozenset({"st", "state", "new", "old"})

# functions that never run in the transition path
SKIP_FUNCS = frozenset({"init_state", "mailbox_spec"})

# quorum-ish parameter names for the PXF803 threshold derivation
QUORUM_PARAM_HINTS = ("major", "quorum", "fast_")

MAX_DEPTH = 5       # cross-function proof hops
MAX_N = 48          # cluster sizes the intersection proof enumerates

_MODULE_ROOTS = frozenset({"jnp", "jax", "np", "lax", "jr", "functools"})

# ``plane.at[idx].set(v)``-style updates: the args are VALUES written
# into the plane, not selectors
_AT_UPDATES = frozenset({"set", "add", "multiply", "divide", "power",
                         "apply"})


# ---------------------------------------------------------------------------
# per-function dataflow context
# ---------------------------------------------------------------------------


@dataclass
class CallElem:
    """RHS of a tuple-unpacking assignment from a call:
    ``st, p1_win, amask = br.tally_p1b(...)`` binds ``p1_win`` to
    element 1 of the callee's returned tuple."""

    call: ast.Call
    index: int


@dataclass
class Ctx:
    rel: str
    info: ModInfo
    fn: ast.AST


class Engine:
    """Shared machinery: assignment maps, ballot-derivation fixpoints,
    guard proofs with cross-module call-site chasing."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._assigns: Dict[Tuple[str, int], Dict[str, list]] = {}
        self._derived: Dict[Tuple[str, int, FrozenSet[str]],
                            Set[str]] = {}
        self._local_callers: Dict[str, Dict[str, List[CallSite]]] = {}

    # -- scaffolding ------------------------------------------------------
    def ctx(self, rel: str, fn: ast.AST) -> Optional[Ctx]:
        info = self.index.module(rel)
        return Ctx(rel, info, fn) if info is not None else None

    def _params(self, fn: ast.AST) -> List[str]:
        a = fn.args
        return [p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]]

    def assignments(self, ctx: Ctx) -> Dict[str, list]:
        """name -> [expr | CallElem] over the function body and its
        enclosing functions (inner shadows are unioned — the chase
        over-approximates, which errs toward accepting real guards)."""
        key = (ctx.rel, id(ctx.fn))
        hit = self._assigns.get(key)
        if hit is not None:
            return hit
        out: Dict[str, list] = {}
        chain = [*ctx.info.enclosing.get(id(ctx.fn), []), ctx.fn]
        for fn in chain:
            self._collect_assigns(fn, out)
        self._assigns[key] = out
        return out

    def _collect_assigns(self, fn: ast.AST, out: Dict[str, list]) -> None:
        skip: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, astutil.FuncNode) and node is not fn:
                for sub in ast.walk(node):
                    skip.add(id(sub))
        for node in ast.walk(fn):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._bind_target(t, node.value, out)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                out.setdefault(node.target.id, []).append(
                    ast.BinOp(left=ast.Name(id=node.target.id,
                                            ctx=ast.Load()),
                              op=node.op, right=node.value))
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name):
                out.setdefault(node.target.id, []).append(node.iter)

    def _bind_target(self, target: ast.expr, value: ast.expr,
                     out: Dict[str, list]) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and \
                    len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind_target(t, v, out)
            elif isinstance(value, ast.Call):
                for i, t in enumerate(target.elts):
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, []).append(
                            CallElem(value, i))

    # -- ballot derivation fixpoints -------------------------------------
    def _plane_sub(self, node: ast.AST,
                   keys: FrozenSet[str]) -> Optional[str]:
        """``st["ballot"]`` -> "ballot" when the key is in ``keys``."""
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in STATE_DICTS:
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value in keys:
                return sl.value
        return None

    def _derived_locals(self, ctx: Ctx,
                        keys: FrozenSet[str]) -> Set[str]:
        """Names transitively derived from any plane in ``keys`` — a
        fixpoint over the function's assignments."""
        cache_key = (ctx.rel, id(ctx.fn), keys)
        hit = self._derived.get(cache_key)
        if hit is not None:
            return hit
        assigns = self.assignments(ctx)
        derived: Set[str] = set()

        def mentions(expr) -> bool:
            if isinstance(expr, CallElem):
                return False          # cross-module: guard chase's job
            for n in ast.walk(expr):
                if self._plane_sub(n, keys) is not None:
                    return True
                if isinstance(n, ast.Name) and n.id in derived:
                    return True
            return False

        changed = True
        while changed:
            changed = False
            for name, exprs in assigns.items():
                if name in derived:
                    continue
                if any(mentions(e) for e in exprs):
                    derived.add(name)
                    changed = True
        self._derived[cache_key] = derived
        return derived

    def cplane_locals(self, ctx: Ctx) -> Set[str]:
        """Names transitively derived from a ballot register — the
        mention set the guard search matches comparisons against."""
        return self._derived_locals(ctx, BALLOT_REGS)

    def key_locals(self, ctx: Ctx, plane: str) -> Set[str]:
        """Names transitively derived from one specific plane."""
        return self._derived_locals(ctx, frozenset({plane}))

    def mentions_ballot(self, expr: ast.AST, ctx: Ctx) -> bool:
        derived = self.cplane_locals(ctx)
        for n in ast.walk(expr):
            if self._plane_sub(n, BALLOT_REGS) is not None:
                return True
            if isinstance(n, ast.Name) and n.id in derived:
                return True
        return False

    def mentions_key(self, expr: ast.AST, ctx: Ctx, plane: str) -> bool:
        derived = self.key_locals(ctx, plane)
        for n in ast.walk(expr):
            if self._plane_sub(n, frozenset({plane})) is not None:
                return True
            if isinstance(n, ast.Name) and n.id in derived:
                return True
        return False

    # -- callers ----------------------------------------------------------
    def local_callers(self, rel: str, name: str) -> List[CallSite]:
        mod_map = self._local_callers.get(rel)
        if mod_map is None:
            mod_map = {}
            info = self.index.module(rel)
            if info is not None:
                from paxi_tpu.analysis.project import _iter_defs
                for qual, fn in _iter_defs(info):
                    for node in ast.walk(fn):
                        if isinstance(node, ast.Call) and \
                                isinstance(node.func, ast.Name):
                            mod_map.setdefault(node.func.id, []).append(
                                CallSite(rel, fn, qual, node, rel,
                                         node.func.id))
            self._local_callers[rel] = mod_map
        return mod_map.get(name, [])

    def callers(self, rel: str, name: str) -> List[CallSite]:
        out = list(self.index.callers_of(rel, name))
        out.extend(c for c in self.local_callers(rel, name)
                   if c.caller_fn is not self.index.function_def(rel,
                                                                 name))
        return out

    # -- guard proof ------------------------------------------------------
    def find_ballot_cmp(self, expr: ast.AST, ctx: Ctx, depth: int,
                        visited: Set[Tuple[str, int]],
                        chain: List[str]) -> Tuple[bool, Set[str]]:
        """(found, params-of-ctx.fn touched).  Walks the expression's
        dataflow closure looking for a comparison mentioning a ballot
        register; expands local assignments, returned-tuple elements
        and resolvable callees across module boundaries."""
        if depth > MAX_DEPTH:
            self._exhausted = True
            return False, set()
        params: Set[str] = set()
        fn_params = set(self._params(ctx.fn))
        for enc in ctx.info.enclosing.get(id(ctx.fn), []):
            fn_params |= set(self._params(enc))
        assigns = self.assignments(ctx)
        names: List[str] = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Compare):
                for side in [n.left, *n.comparators]:
                    if self.mentions_ballot(side, ctx):
                        return True, params
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                names.append(n.id)
            elif isinstance(n, ast.Call):
                tgt = self._resolve(ctx, n)
                if tgt is not None:
                    ok = self._prove_callee_returns(n, tgt, depth,
                                                   visited, chain, ctx)
                    if ok:
                        return True, params
        for name in names:
            key = (f"{ctx.rel}:{id(ctx.fn)}:{name}", 0)
            if key in visited:
                continue
            visited.add(key)
            if name in assigns:
                for rhs in assigns[name]:
                    if isinstance(rhs, CallElem):
                        if self._prove_call_elem(rhs, ctx, depth,
                                                 visited, chain):
                            return True, params
                    else:
                        ok, _ = self.find_ballot_cmp(rhs, ctx, depth,
                                                     visited, chain)
                        if ok:
                            return True, params
            elif name in fn_params:
                params.add(name)
        return False, params

    def _resolve(self, ctx: Ctx,
                 call: ast.Call) -> Optional[Tuple[str, str]]:
        tgt = self.index.resolve_call(ctx.rel, call)
        if tgt is not None:
            return tgt
        if isinstance(call.func, ast.Name) and \
                call.func.id in ctx.info.functions:
            return ctx.rel, call.func.id
        return None

    def _callee_ctx(self, tgt: Tuple[str, str]) -> Optional[Ctx]:
        fn = self.index.function_def(*tgt)
        if fn is None:
            return None
        return self.ctx(tgt[0], fn)

    def _returns_of(self, fn: ast.AST) -> List[ast.expr]:
        out = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Return) and n.value is not None:
                out.append(n.value)
        return out

    def _map_params_back(self, call: ast.Call, callee: Ctx,
                         touched: Set[str], caller: Ctx, depth: int,
                         visited: Set, chain: List[str]) -> bool:
        """A callee proof stalled on its own parameters: substitute the
        call's arguments and continue in the caller's context."""
        params = self._params(callee.fn)
        argmap: Dict[str, ast.expr] = {}
        for p, a in zip(params, call.args):
            argmap[p] = a
        for kw in call.keywords:
            if kw.arg:
                argmap[kw.arg] = kw.value
        for p in touched:
            a = argmap.get(p)
            if a is None:
                continue
            ok, _ = self.find_ballot_cmp(a, caller, depth + 1, visited,
                                         chain)
            if ok:
                return True
        return False

    def _prove_callee_returns(self, call: ast.Call, tgt: Tuple[str, str],
                              depth: int, visited: Set,
                              chain: List[str], caller: Ctx) -> bool:
        key = (f"ret:{tgt[0]}:{tgt[1]}", id(call))
        if key in visited:
            return False
        visited.add(key)
        callee = self._callee_ctx(tgt)
        if callee is None:
            return False
        for ret in self._returns_of(callee.fn):
            ok, touched = self.find_ballot_cmp(ret, callee, depth + 1,
                                               visited, chain)
            if ok:
                chain.append(f"{tgt[0]}:{tgt[1]}")
                return True
            if touched and self._map_params_back(call, callee, touched,
                                                 caller, depth, visited,
                                                 chain):
                chain.append(f"{tgt[0]}:{tgt[1]}(args)")
                return True
        return False

    def _prove_call_elem(self, elem: CallElem, ctx: Ctx, depth: int,
                         visited: Set, chain: List[str]) -> bool:
        """``st, p1_win, _ = br.tally_p1b(...)``: prove through element
        ``elem.index`` of the callee's returned tuple."""
        tgt = self._resolve(ctx, elem.call)
        if tgt is None:
            return False
        callee = self._callee_ctx(tgt)
        if callee is None:
            return False
        for ret in self._returns_of(callee.fn):
            if not isinstance(ret, (ast.Tuple, ast.List)) or \
                    elem.index >= len(ret.elts):
                continue
            el = ret.elts[elem.index]
            ok, touched = self.find_ballot_cmp(el, callee, depth + 1,
                                               visited, chain)
            if ok:
                chain.append(f"{tgt[0]}:{tgt[1]}[{elem.index}]")
                return True
            if touched and self._map_params_back(elem.call, callee,
                                                 touched, ctx, depth,
                                                 visited, chain):
                chain.append(f"{tgt[0]}:{tgt[1]}[{elem.index}](args)")
                return True
        return False

    def prove_guard(self, expr: ast.AST, ctx: Ctx,
                    depth: int = 0) -> Tuple[str, str]:
        """("guarded"|"call-site"|"unresolved"|"unproven", detail)."""
        if depth == 0:
            self._exhausted = False
        chain: List[str] = []
        found, params = self.find_ballot_cmp(expr, ctx, depth, set(),
                                             chain)
        if found:
            via = " via " + " -> ".join(chain) if chain else ""
            return "guarded", f"ballot comparison{via}"
        if not params:
            # a proof that hit the depth cap mid-chain was cut off,
            # not refuted: PXF804 ("resolve or baseline"), never a
            # definite PXF801
            if self._exhausted:
                return "unresolved", (
                    f"proof depth exceeded ({MAX_DEPTH} hops)")
            return "unproven", "no ballot comparison in the guard's " \
                               "dataflow closure"
        fname = getattr(ctx.fn, "name", "<fn>")
        sites = self.callers(ctx.rel, fname)
        if not sites:
            return "unresolved", (
                f"guard depends on parameter(s) "
                f"{', '.join(sorted(params))} of `{fname}` and no call "
                "site is in the index")
        plist = self._params(ctx.fn)
        proven_at: List[str] = []
        for site in sites:
            argmap: Dict[str, ast.expr] = dict(zip(plist,
                                                   site.call.args))
            for kw in site.call.keywords:
                if kw.arg:
                    argmap[kw.arg] = kw.value
            cctx = self.ctx(site.caller_rel, site.caller_fn)
            ok = False
            for p in sorted(params):
                a = argmap.get(p)
                if a is None or cctx is None:
                    continue
                verdict, _ = self.prove_guard(a, cctx, depth + 1)
                if verdict in ("guarded", "call-site"):
                    ok = True
                    break
            if not ok:
                return "unproven", (
                    f"call site {site.caller_rel}:"
                    f"{site.call.lineno} ({site.caller_qual}) passes "
                    f"no ballot-guarded argument for "
                    f"{', '.join(sorted(params))}")
            proven_at.append(f"{site.caller_rel}:{site.call.lineno}")
        return "call-site", "proven at " + ", ".join(proven_at)

    # -- value shape checks ----------------------------------------------
    def state_pure(self, expr: ast.AST, ctx: Ctx,
                   visited: Optional[Set[str]] = None) -> bool:
        """True when every *value position* of ``expr`` carries only
        current epoch state or constants (selector/mask/shift-amount
        positions are ignored: they pick WHICH entries move, not what
        ballot value lands)."""
        if visited is None:
            visited = set()
        if isinstance(expr, ast.Constant):
            return True
        if self._plane_sub(expr, frozenset(
                EPOCH_PLANES | BALLOT_REGS)) is not None:
            return True
        if isinstance(expr, ast.Subscript):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id in STATE_DICTS:
                return False          # a non-plane state key: unknown
            return self.state_pure(expr.value, ctx, visited)
        if isinstance(expr, ast.Name):
            if expr.id in EPOCH_PLANES or expr.id in BALLOT_REGS:
                # a plane-named local IS current epoch state: every
                # assignment to it is its own verified write site, so
                # downstream value uses need no further chase
                return True
            key = f"{ctx.rel}:{id(ctx.fn)}:{expr.id}"
            if key in visited:
                return True           # cycle: judged by the other uses
            visited.add(key)
            assigns = self.assignments(ctx)
            if expr.id in assigns:
                return all(not isinstance(r, CallElem)
                           and self.state_pure(r, ctx, visited)
                           for r in assigns[expr.id])
            entry = ctx.info.imports.get(expr.id)
            if entry is not None and entry.kind == "symbol":
                const = self._module_const(entry.relpath, entry.symbol)
                return const is not None
            const = self._module_const(ctx.rel, expr.id)
            return const is not None
        if isinstance(expr, ast.Attribute):
            return self.state_pure(expr.value, ctx, visited)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self.state_pure(e, ctx, visited)
                       for e in expr.elts)
        if isinstance(expr, ast.UnaryOp):
            return self.state_pure(expr.operand, ctx, visited)
        if isinstance(expr, (ast.BinOp, ast.BoolOp, ast.Compare)):
            kids = ([expr.left, expr.right]
                    if isinstance(expr, ast.BinOp)
                    else expr.values if isinstance(expr, ast.BoolOp)
                    else [expr.left, *expr.comparators])
            return all(self.state_pure(k, ctx, visited) for k in kids)
        if isinstance(expr, ast.IfExp):
            return self.state_pure(expr.body, ctx, visited) and \
                self.state_pure(expr.orelse, ctx, visited)
        if isinstance(expr, ast.Call):
            tail = (astutil.dotted_name(expr.func) or "").split(".")[-1]
            # receiver of a method chain (x.astype(...),
            # plane.at[i].set(v)); a module attr (jnp.where) has none
            recv = None
            if isinstance(expr.func, ast.Attribute) and not (
                    isinstance(expr.func.value, ast.Name)
                    and (expr.func.value.id in _MODULE_ROOTS
                         or expr.func.value.id in ctx.info.imports)):
                recv = expr.func.value
            if tail in ("where", "select") and len(expr.args) >= 3:
                return self.state_pure(expr.args[1], ctx, visited) and \
                    self.state_pure(expr.args[2], ctx, visited)
            if tail in ("maximum", "minimum", "max", "min"):
                return (recv is None
                        or self.state_pure(recv, ctx, visited)) and \
                    all(self.state_pure(a, ctx, visited)
                        for a in expr.args)
            if tail in ("full", "full_like"):
                # fill family: the VALUE is args[1] (args[0] is the
                # shape/template) — the one call shape where the
                # first-arg heuristic below would launder a foreign
                # ballot into the plane
                return len(expr.args) >= 2 and \
                    self.state_pure(expr.args[1], ctx, visited)
            if recv is not None and tail in _AT_UPDATES:
                # plane.at[idx].set(v): idx selects, v is a value
                return self.state_pure(recv, ctx, visited) and \
                    all(self.state_pure(a, ctx, visited)
                        for a in expr.args)
            if recv is not None:
                # other method chains carry their receiver's value
                return self.state_pure(recv, ctx, visited)
            # helper calls (shift/take/pick/one-hot contractions): the
            # first argument is the value plane, the rest are selectors
            if expr.args:
                return self.state_pure(expr.args[0], ctx, visited)
            return True               # zeros()/arange(): constant-ish
        return False

    def _module_const(self, rel: str, name: str) -> Optional[ast.expr]:
        info = self.index.module(rel)
        if info is None:
            return None
        for node in info.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name and \
                            isinstance(node.value, (ast.Constant,
                                                    ast.UnaryOp)):
                        return node.value
        return None

    def monotone(self, expr: ast.AST, ctx: Ctx, plane: str,
                 _depth: int = 0) -> bool:
        """``max``/``maximum`` over the current plane somewhere in the
        value's dataflow closure — the new value cannot go backwards."""
        if _depth > 3:
            return False
        names: List[str] = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                tail = (astutil.dotted_name(n.func) or "").split(".")[-1]
                if tail in ("max", "maximum") and any(
                        self.mentions_key(a, ctx, plane)
                        for a in n.args):
                    return True
            elif isinstance(n, ast.Name):
                names.append(n.id)
        assigns = self.assignments(ctx)
        for name in names:
            for rhs in assigns.get(name, []):
                if not isinstance(rhs, CallElem) and \
                        self.monotone(rhs, ctx, plane, _depth + 1):
                    return True
        return False


# ---------------------------------------------------------------------------
# write-site enumeration and classification
# ---------------------------------------------------------------------------


@dataclass
class WriteSite:
    rel: str
    fn: ast.AST
    plane: str
    node: ast.AST                 # the value expression written
    line: int
    col: int
    verdict: str = ""             # guarded/call-site/monotone/...
    detail: str = ""


def _is_identity(value: ast.expr, plane: str) -> bool:
    if isinstance(value, ast.Name) and value.id == plane:
        return True
    if isinstance(value, ast.Subscript) and \
            isinstance(value.value, ast.Name) and \
            value.value.id in STATE_DICTS and \
            isinstance(value.slice, ast.Constant) and \
            value.slice.value == plane:
        return True
    return False


def _is_state_dict_literal(node: ast.Dict) -> bool:
    """``{**st, ...}``-shaped (spreads a state dict) or a state
    assembly with >= 2 identity plane pairs (``ballot=ballot`` style
    spelled as a literal)."""
    for k, v in zip(node.keys, node.values):
        if k is None and isinstance(v, ast.Name) and \
                v.id in STATE_DICTS:
            return True
    ident = sum(1 for k, v in zip(node.keys, node.values)
                if isinstance(k, ast.Constant) and v is not None
                and _is_identity(v, k.value))
    return ident >= 2


def _is_state_dict_call(node: ast.Call) -> bool:
    """``dict(st, ...)`` or a keyword assembly with >= 2 identity
    plane pairs (``dict(ballot=ballot, active=active, ...)``)."""
    if node.args and isinstance(node.args[0], ast.Name) and \
            node.args[0].id in STATE_DICTS:
        return True
    ident = sum(1 for kw in node.keywords
                if kw.arg is not None and _is_identity(kw.value, kw.arg))
    return ident >= 2


def _where_parts(value: ast.expr) -> Optional[Tuple[ast.expr, ast.expr,
                                                    ast.expr]]:
    if isinstance(value, ast.Call) and len(value.args) >= 3:
        tail = (astutil.dotted_name(value.func) or "").split(".")[-1]
        if tail in ("where", "select"):
            return value.args[0], value.args[1], value.args[2]
    return None


def write_sites(eng: Engine, rel: str,
                planes: FrozenSet[str]) -> List[WriteSite]:
    """Every write to a plane in ``planes`` in the module: dict-literal
    values (``{**st, "ballot": X}``), ``dict(st, ballot=X)`` keywords,
    and assignments to plane-named locals (the lane-major kernels'
    idiom), identity pass-throughs and init reads excluded."""
    info = eng.index.module(rel)
    if info is None:
        return []
    out: List[WriteSite] = []
    from paxi_tpu.analysis.project import _iter_defs
    for qual, fn in _iter_defs(info):
        if fn.name in SKIP_FUNCS:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                if not _is_state_dict_literal(node):
                    continue          # outbox/message dicts reuse the
                    # plane names as FIELD names; only state dicts
                    # (a ``**st`` spread or identity plane pairs) are
                    # write surfaces
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and \
                            k.value in planes and v is not None and \
                            not _is_identity(v, k.value):
                        out.append(WriteSite(rel, fn, k.value, v,
                                             v.lineno, v.col_offset))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "dict":
                if not _is_state_dict_call(node):
                    continue
                for kw in node.keywords:
                    if kw.arg in planes and \
                            not _is_identity(kw.value, kw.arg):
                        out.append(WriteSite(rel, fn, kw.arg, kw.value,
                                             kw.value.lineno,
                                             kw.value.col_offset))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in planes and \
                            not _is_identity(node.value, t.id):
                        out.append(WriteSite(rel, fn, t.id, node.value,
                                             node.lineno,
                                             node.col_offset))
    return out


def classify(eng: Engine, site: WriteSite) -> WriteSite:
    """Attach the domination verdict to one write site."""
    ctx = eng.ctx(site.rel, site.fn)
    v = site.node
    plane = site.plane

    parts = _where_parts(v)
    if parts is not None and eng.mentions_key(parts[2], ctx, plane):
        cond, newv, _old = parts
        if eng.state_pure(newv, ctx):
            site.verdict, site.detail = "state-derived", \
                "new value carries only current epoch state/constants"
            return site
        if eng.monotone(newv, ctx, plane):
            site.verdict, site.detail = "monotone", \
                "new value is a max over the current plane"
            return site
        verdict, detail = eng.prove_guard(cond, ctx)
        site.verdict, site.detail = verdict, detail
        return site

    if isinstance(v, ast.BinOp) and isinstance(v.op, ast.BitAnd):
        # boolean shrink: ``active & ~x`` only demotes
        if eng.mentions_key(v.left, ctx, plane) or \
                eng.mentions_key(v.right, ctx, plane):
            site.verdict, site.detail = "shrinking", \
                "conjunction with the current plane only clears bits"
            return site
    if isinstance(v, ast.BinOp) and isinstance(v.op, ast.BitOr):
        own = eng.mentions_key(v.left, ctx, plane)
        grow = v.right if own else v.left
        keep = v.left if own else v.right
        if eng.mentions_key(keep, ctx, plane):
            verdict, detail = eng.prove_guard(grow, ctx)
            site.verdict, site.detail = verdict, detail
            return site

    if eng.state_pure(v, ctx):
        site.verdict, site.detail = "state-derived", \
            "value carries only current epoch state/constants"
        return site
    if eng.monotone(v, ctx, plane):
        site.verdict, site.detail = "monotone", \
            "value is a max over the current plane"
        return site
    verdict, detail = eng.prove_guard(v, ctx)
    if verdict in ("guarded", "call-site"):
        # the whole value's dataflow passes a ballot comparison
        site.verdict, site.detail = verdict, detail
        return site
    site.verdict, site.detail = "unproven", detail
    return site


# ---------------------------------------------------------------------------
# PXF802: shared-plane interference
# ---------------------------------------------------------------------------


def _owned_planes(eng: Engine, rel: str) -> FrozenSet[str]:
    """The planes a helper module declares ownership of via a
    module-level ``KEYS = (...)`` tuple."""
    info = eng.index.module(rel)
    if info is None:
        return frozenset()
    keys = None
    for node in info.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KEYS" and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    keys = node.value
    if keys is None:
        return frozenset()
    return frozenset(e.value for e in keys.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))


def _guard_atoms(expr: ast.expr) -> Set[Tuple[str, bool]]:
    """Decompose a mask expression into (atom text, polarity) over
    ``&`` conjunction and ``~`` negation — the disjointness currency.
    The atom set represents a CONJUNCTION of literals, so ``~`` may
    only distribute over a single atom: ``~(a & b)`` is the
    disjunction ``~a | ~b``, and distributing would claim the strictly
    stronger ``~a & ~b`` — a complementary atom would then "prove"
    disjointness for masks that genuinely overlap.  Compound
    negations stay opaque (sound: fewer disjointness proofs)."""
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Invert):
        sub = _guard_atoms(expr.operand)
        if len(sub) == 1:
            ((t, p),) = sub
            return {(t, not p)}
        return {(ast.unparse(expr), True)}
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitAnd):
        return _guard_atoms(expr.left) | _guard_atoms(expr.right)
    return {(ast.unparse(expr), True)}


def _helper_write_guards(eng: Engine, helper_rel: str, plane: str,
                         kernel_rel: str) -> List[Set[Tuple[str, bool]]]:
    """Guard atom sets of the helper's writes to ``plane``, with
    parameters substituted by the kernel's call-site arguments."""
    out: List[Set[Tuple[str, bool]]] = []
    for site in write_sites(eng, helper_rel, frozenset({plane})):
        parts = _where_parts(site.node)
        if parts is None:
            continue
        atoms = _guard_atoms(parts[0])
        params = eng._params(site.fn)
        resolved: Set[Tuple[str, bool]] = set()
        for text, pol in atoms:
            if text in params:
                for cs in eng.callers(helper_rel, site.fn.name):
                    if cs.caller_rel != kernel_rel:
                        continue
                    argmap = dict(zip(params, cs.call.args))
                    for kw in cs.call.keywords:
                        if kw.arg:
                            argmap[kw.arg] = kw.value
                    a = argmap.get(text)
                    if a is None:
                        continue
                    sub = _guard_atoms(a)
                    if pol:
                        resolved |= sub
                    elif len(sub) == 1:
                        # same rule as _guard_atoms: ~ distributes
                        # over a single substituted atom only
                        ((t2, p2),) = sub
                        resolved.add((t2, not p2))
                    else:
                        resolved.add((f"~({ast.unparse(a)})", True))
            else:
                resolved.add((text, pol))
        out.append(resolved)
    return out


def _disjoint(a: Set[Tuple[str, bool]],
              b: Set[Tuple[str, bool]]) -> bool:
    return any((t, not p) in b for t, p in a)


# ---------------------------------------------------------------------------
# PXF803/804: cross-module quorum flow
# ---------------------------------------------------------------------------

_P1_HINTS = ("p1", "phase1", "prepare", "elect", "recover", "read")
_P2_HINTS = ("p2", "accept", "commit", "write")


@dataclass
class ThresholdParam:
    """One helper parameter compared as a quorum threshold."""

    fn_name: str
    param: str
    index: int
    strict: bool                  # `>` vs `>=`
    phase: str                    # "p1" | "p2" | ""


def threshold_params(eng: Engine, rel: str) -> List[ThresholdParam]:
    info = eng.index.module(rel)
    if info is None:
        return []
    out: List[ThresholdParam] = []
    for name, fns in info.functions.items():
        for fn in fns:
            params = eng._params(fn)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Compare)
                        and len(node.ops) == 1):
                    continue
                op = node.ops[0]
                # both orientations: ``tally > param`` and the
                # flipped ``param <= tally`` (Lt/LtE, param left)
                if isinstance(op, (ast.Gt, ast.GtE)):
                    cand = node.comparators[0]
                    strict = isinstance(op, ast.Gt)
                elif isinstance(op, (ast.Lt, ast.LtE)):
                    cand = node.left
                    strict = isinstance(op, ast.Lt)
                else:
                    continue
                rhs = cand
                if not (isinstance(rhs, ast.Name)
                        and rhs.id in params):
                    continue
                if not any(h in rhs.id for h in QUORUM_PARAM_HINTS):
                    continue
                lname = name.lower()
                phase = ("p1" if any(h in lname for h in _P1_HINTS)
                         else "p2" if any(h in lname for h in _P2_HINTS)
                         else "")
                out.append(ThresholdParam(
                    fn_name=name, param=rhs.id,
                    index=params.index(rhs.id),
                    strict=strict, phase=phase))
    return out


def _sim_prop_exprs(root: Path) -> Dict[str, ast.expr]:
    path = root / SIM_TYPES
    if not path.is_file():
        return {}
    tree, _ = astutil.parse_file(path)
    out: Dict[str, ast.expr] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "SimConfig"):
            continue
        for item in node.body:
            if isinstance(item, astutil.FuncNode) and \
                    "property" in astutil.decorator_names(item):
                rets = [s for s in ast.walk(item)
                        if isinstance(s, ast.Return)]
                if len(rets) == 1 and rets[0].value is not None:
                    out[item.name] = rets[0].value
    return out


def _threshold_fn(arg: ast.expr, resolver: Resolver,
                  props: Dict[str, ast.expr], strict: bool):
    def size(n: int) -> Optional[int]:
        def resolve(key: str) -> Optional[ast.expr]:
            hit = resolver(key)
            if hit is not None:
                return hit
            tail = key.split(".")[-1]
            if key.split(".")[0] in ("cfg", "self") and tail in props:
                return props[tail]
            return None

        env = {"self.n_replicas": Fraction(n),
               "cfg.n_replicas": Fraction(n), "n": Fraction(n)}
        v = flow.SymEval(env, resolve=resolve).eval(arg)
        if v is None:
            return None
        if strict:
            return int(v.__floor__()) + 1
        return int(-((-v).__floor__()))
    return size


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _analyzed_files(root: Path,
                    files: Optional[Sequence[Path]]) -> List[Path]:
    if files is not None:
        return list(files)
    return list(astutil.iter_py(root, TARGETS))


_ENGINES: Dict[int, Engine] = {}


def _engine_for(index: ProjectIndex) -> Engine:
    """One Engine per shared index: its assignment/fixpoint caches key
    off the index's parsed trees, so they stay valid exactly as long
    as the index itself."""
    eng = _ENGINES.get(id(index))
    if eng is None:
        eng = _ENGINES[id(index)] = Engine(index)
    return eng


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    paths = _analyzed_files(root, files)
    index = shared_index(root, extra_files=paths)
    eng = _engine_for(index)
    rels = [astutil.rel(Path(p).resolve(), root) for p in paths]
    out: List[Violation] = []
    props = _sim_prop_exprs(root)

    for rel in rels:
        info = index.module(rel)
        if info is None:
            continue
        # ---- PXF801/PXF804: epoch-write domination ----
        for site in write_sites(eng, rel, EPOCH_PLANES):
            classify(eng, site)
            if site.verdict == "unproven":
                out.append(Violation(
                    rule=RULE, code="PXF801", path=rel,
                    line=site.line, col=site.col,
                    message=(
                        f"epoch-plane write `{site.plane}` in "
                        f"`{site.fn.name}` has no dominating ballot "
                        f"comparison ({site.detail}) — a lower-ballot "
                        "message can overwrite promised state")))
            elif site.verdict == "unresolved":
                out.append(Violation(
                    rule=RULE, code="PXF804", path=rel,
                    line=site.line, col=site.col,
                    message=(
                        f"epoch-plane write `{site.plane}` in "
                        f"`{site.fn.name}` cannot be proven or refuted "
                        f"({site.detail}) — resolve or baseline it")))

        # ---- PXF802: shared-plane interference ----
        helper_rels = {e.relpath for e in info.imports.values()
                       if e.kind == "module"}
        for helper_rel in sorted(helper_rels):
            owned = _owned_planes(eng, helper_rel)
            if not owned or helper_rel == rel:
                continue
            for site in write_sites(eng, rel, owned):
                parts = _where_parts(site.node)
                mine = (_guard_atoms(parts[0]) if parts is not None
                        else set())
                theirs = _helper_write_guards(eng, helper_rel,
                                              site.plane, rel)
                if not theirs:
                    continue
                if all(_disjoint(mine, t) for t in theirs):
                    continue
                out.append(Violation(
                    rule=RULE, code="PXF802", path=rel,
                    line=site.line, col=site.col,
                    message=(
                        f"`{site.plane}` is owned by {helper_rel} "
                        f"(KEYS) but written directly in "
                        f"`{site.fn.name}` with a guard not disjoint "
                        "from the helper's writes — two modules "
                        "masking one carry plane can interleave "
                        "updates")))

        # ---- PXF803/PXF804: cross-module quorum flow ----
        by_phase: Dict[str, List[Tuple[ast.Call, str, object]]] = {}
        resolver = Resolver(info.tree)
        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            tgt = index.resolve_call(rel, node)
            if tgt is None or tgt[0] == rel:
                continue
            for tp in threshold_params(eng, tgt[0]):
                if tp.fn_name != tgt[1]:
                    continue
                # the callee signature includes no `self`; count args
                arg: Optional[ast.expr] = None
                if tp.index < len(node.args):
                    arg = node.args[tp.index]
                for kw in node.keywords:
                    if kw.arg == tp.param:
                        arg = kw.value
                if arg is None:
                    continue
                fn = _threshold_fn(arg, resolver, props, tp.strict)
                if fn(5) is None and fn(29) is None:
                    out.append(Violation(
                        rule=RULE, code="PXF804", path=rel,
                        line=node.lineno, col=node.col_offset,
                        message=(
                            f"threshold `{ast.unparse(arg)}` passed to "
                            f"`{tp.fn_name}({tp.param}=...)` does not "
                            "evaluate symbolically — the cross-module "
                            "quorum proof cannot run; resolve or "
                            "baseline it")))
                    continue
                by_phase.setdefault(tp.phase, []).append(
                    (node, ast.unparse(arg), fn))
        for a_call, a_text, a_fn in by_phase.get("p1", []):
            for b_call, b_text, b_fn in by_phase.get("p2", []):
                bad = None
                for n in range(2, MAX_N + 1):
                    sa, sb = a_fn(n), b_fn(n)
                    if sa is None or sb is None:
                        continue
                    if 0 < sa <= n and 0 < sb <= n and sa + sb <= n:
                        bad = (n, sa, sb)
                        break
                if bad is not None:
                    n, sa, sb = bad
                    out.append(Violation(
                        rule=RULE, code="PXF803", path=rel,
                        line=a_call.lineno, col=a_call.col_offset,
                        message=(
                            f"cross-module quorum thresholds "
                            f"`{a_text}` (line {a_call.lineno}, p1) "
                            f"and `{b_text}` (line {b_call.lineno}, "
                            f"p2) can fail to intersect: at n={n} the "
                            f"sizes are {sa}+{sb} <= {n}")))
    return out


def coverage(root: Path) -> Dict[str, Dict[str, object]]:
    """Per-module proof summary: how many epoch-plane writes each sim
    kernel (and the shared helper) carries and how each was proven —
    the artifact the tier-1 test pins so the five ballot-ring
    consumers can never silently fall out of the proof."""
    paths = _analyzed_files(root, None)
    index = shared_index(root, extra_files=paths)
    eng = _engine_for(index)
    out: Dict[str, Dict[str, object]] = {}
    helper_writes: Dict[str, List[WriteSite]] = {}
    for p in paths:
        rel = astutil.rel(Path(p).resolve(), root)
        sites = [classify(eng, s)
                 for s in write_sites(eng, rel, EPOCH_PLANES)]
        entry = {
            "writes": len(sites),
            "proven": sum(1 for s in sites
                          if s.verdict not in ("unproven",
                                               "unresolved")),
            "via": sorted({s.verdict for s in sites}),
            "call_site_proofs": [
                s.detail for s in sites if s.verdict == "call-site"],
        }
        out[rel] = entry
        helper_writes[rel] = sites
    # attribute helper writes to the kernels whose call sites carry the
    # proof obligations (the "covers all consumers" half)
    for rel, sites in helper_writes.items():
        consumers: Set[str] = set()
        info = index.module(rel)
        if info is None:
            continue
        from paxi_tpu.analysis.project import _iter_defs
        for _qual, fn in _iter_defs(info):
            for cs in index.callers_of(rel, fn.name):
                consumers.add(cs.caller_rel)
        out[rel]["consumers"] = sorted(consumers)
    return out
