"""Workload purity rule family (PXW12x).

The workload engine's contract (paxi_tpu/workload/) is that every
draw is a *counter-based pure function* of ``(group, slot, channel,
seed)`` — that is what makes one ``Workload`` spec compile onto both
runtimes with bit-identical pinned sim command planes AND lets the
host sampler replay the same sequence per stream.  One stray
``random.random()`` (or a jax.random key threaded into a plane
function, or a wall-clock read) silently breaks pinned replay: runs
stop being reproducible, the lane-major vs per-group parity tests
stop meaning anything, and sim/host splits drift apart.

This family pins that contract statically over the workload package:

- **PXW121** a workload module imports a nondeterminism source
  (``random``, ``secrets``, ``uuid``, ``numpy.random``) — draws must
  come from the counter hash (``_draw_u``/``_draw_ui``).
- **PXW122** a workload module *calls* a stateful random source
  (``random.*``, ``np.random.*``, ``numpy.random.*``, ``jr.*``,
  ``jax.random.*``, ``secrets.*``, ``uuid.*``) — even via a module
  imported elsewhere.
- **PXW123** a workload module reads the wall clock (``time.*`` /
  ``datetime.*`` calls) — schedules are step/ramp indexed, never
  wall-clock indexed, or replay breaks across machines.

Purely syntactic (imports + attribute calls), so it runs in
milliseconds and never needs jax.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.model import Violation

RULE = "workload-purity"

TARGETS = ("paxi_tpu/workload/*.py",)

# import-time contraband (PXW121): modules whose mere presence in a
# workload file means draws are about to leave the counter hash
BANNED_IMPORTS = frozenset({"random", "secrets", "uuid"})
BANNED_IMPORT_FROMS = frozenset({"random", "secrets", "uuid",
                                 "numpy.random"})

# call-time contraband roots (PXW122): attribute-call base paths that
# name a stateful random source regardless of how they were imported
RANDOM_ROOTS = ("random", "np.random", "numpy.random", "jr",
                "jax.random", "secrets", "uuid")

# wall-clock roots (PXW123)
CLOCK_ROOTS = ("time", "datetime")


def _dotted(node) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _matches(base: str, roots) -> bool:
    return any(base == r or base.startswith(r + ".") for r in roots)


def _check_file(path: Path, root: Path) -> List[Violation]:
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return []
    rel = astutil.rel(path, root)
    out: List[Violation] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                top = a.name.split(".")[0]
                if top in BANNED_IMPORTS or a.name == "numpy.random":
                    out.append(Violation(
                        rule=RULE, code="PXW121", path=rel,
                        line=node.lineno, col=node.col_offset,
                        message=f"workload module imports "
                                f"nondeterminism source {a.name!r} — "
                                f"draws must come from the counter "
                                f"hash (compile._draw_u)"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in BANNED_IMPORT_FROMS:
                out.append(Violation(
                    rule=RULE, code="PXW121", path=rel,
                    line=node.lineno, col=node.col_offset,
                    message=f"workload module imports from "
                            f"nondeterminism source {mod!r} — draws "
                            f"must come from the counter hash "
                            f"(compile._draw_u)"))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            base = _dotted(node.func.value)
            if not base:
                continue
            full = f"{base}.{node.func.attr}"
            if _matches(base, RANDOM_ROOTS):
                out.append(Violation(
                    rule=RULE, code="PXW122", path=rel,
                    line=node.lineno, col=node.col_offset,
                    message=f"workload draw path calls stateful "
                            f"random source {full}() — replay across "
                            f"lowerings breaks; derive from "
                            f"(group, slot, channel, seed) instead"))
            elif _matches(base, CLOCK_ROOTS):
                out.append(Violation(
                    rule=RULE, code="PXW123", path=rel,
                    line=node.lineno, col=node.col_offset,
                    message=f"workload module reads the wall clock "
                            f"via {full}() — schedules are step/ramp "
                            f"indexed, never wall-clock indexed"))
    return out


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (files if files is not None
                 else astutil.iter_py(root, TARGETS)):
        out.extend(_check_file(Path(path), root))
    return sorted(out, key=lambda v: (v.path, v.line, v.code))
