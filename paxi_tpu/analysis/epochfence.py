"""Shard-epoch fence rule family (PXE15x).

The ROADMAP's next big build — online migration behind
``move_range`` — opens a double-write window the moment any router
path acts on a stale ``ShardMap``.  This family proves, before that
window can land, the swap discipline ``shard/router.py`` documents:
``_map`` and the pending queues live behind one lock; every
ShardMap-dependent forward, pending-queue epoch stamp, 2PC
partitioning, and writeback acts on a *fenced* map value; and the only
mutation is a version-advancing reference swap.  It is the ballot-
domination proof (PXB) at shard granularity: PXB proves no acceptor
acts on a stale ballot, PXE proves no router path acts on a stale
epoch.

A map value is **fenced** when it is:

- a ``._map`` attribute read *inside* a lock region (a ``with`` whose
  context expression ends in ``lock``) — the atomic snapshot;
- the ``shard_map`` property (which takes the lock itself), read as
  ``<obj>.shard_map``;
- a function parameter (the caller owed us a fenced value — this is
  how ``txn.partition_ops(shard_map, ops)`` stays in the proof);
- a name assigned from any fenced value, a ``.move_range(...)`` /
  ``.with_migration(...)`` / ``.complete_migration(...)`` result
  (pure derivations of a fenced map), or another fenced name —
  closed over the function by a two-pass propagation, so the
  snapshot-then-use-outside-the-lock idiom (``flush``) proves clean.

Checks:

- **PXE151** unfenced map read: a ``._map`` attribute load outside
  any lock region, or a ``group_of(...)`` / ``migration_of(...)`` /
  ``ranges_of(...)`` / ``partition_ops(...)`` whose map operand is
  not a fenced value — each one is a key that can resolve against a
  routing table mid-swap;
- **PXE152** non-monotone map write: a store to ``._map`` outside
  ``__init__`` that is not inside a lock region *and* dominated by a
  strict version-advance comparison (``new.version > current.version``
  in either spelling, including the ``if new.version <= cur.version:
  raise`` early-exit form) with the stored name's ``.version`` on one
  side — the guard shape :func:`flow.dominating_guards` extracts.

:func:`coverage` reports the per-module proof surface (map reads
seen/fenced, swaps seen/guarded) so tests can pin that the rule is
actually looking at the sites the docstring claims.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paxi_tpu.analysis import astutil, flow
from paxi_tpu.analysis.model import Violation

RULE = "epoch-fence"

TARGETS = (
    "paxi_tpu/shard/router.py",
    "paxi_tpu/shard/txn.py",
    "paxi_tpu/shard/migrate.py",
)

# attribute names that ARE the guarded routing table
_MAP_ATTRS = ("_map",)
# attribute reads that are fenced by construction (the property takes
# the lock; reading it yields an immutable snapshot)
_FENCED_ATTRS = ("shard_map",)
# calls that consume a map operand which must be fenced; the
# method-style ones (receiver IS the map) vs. the function-style ones
# (map is the first argument) are told apart in _check_consumer
_MAP_CONSUMERS = ("group_of", "migration_of", "ranges_of",
                  "partition_ops")
_METHOD_CONSUMERS = ("group_of", "migration_of", "ranges_of")
# calls whose result is a fenced map derivation
_FENCED_DERIVATIONS = ("move_range", "with_migration",
                       "complete_migration")

_NEGATE = {ast.Lt: ast.GtE, ast.LtE: ast.Gt, ast.Gt: ast.LtE,
           ast.GtE: ast.Lt}


def _is_lock_ctx(expr: ast.expr) -> bool:
    dotted = astutil.dotted_name(expr)
    if dotted is None and isinstance(expr, ast.Call):
        dotted = astutil.dotted_name(expr.func)
    return dotted is not None and dotted.split(".")[-1].endswith("lock")


def _version_side(expr: ast.expr) -> Optional[str]:
    """The dotted base of a ``<base>.version`` read, else None."""
    if isinstance(expr, ast.Attribute) and expr.attr == "version":
        return astutil.dotted_name(expr.value) or "<expr>"
    return None


class _FnCheck:
    """One function's fence proof: lock regions, fenced-name closure,
    then the read/write checks."""

    def __init__(self, rel: str, fn, out: List[Violation],
                 stats: Dict[str, int]):
        self.rel = rel
        self.fn = fn
        self.out = out
        self.stats = stats
        self.guards = flow.dominating_guards(fn)
        self.in_lock: Set[int] = set()     # id(stmt) inside a lock With
        self._mark_lock(fn.body, False)
        self.fenced: Set[str] = {
            a.arg for a in (list(fn.args.posonlyargs)
                            + list(fn.args.args)
                            + list(fn.args.kwonlyargs))}
        if fn.args.vararg:
            self.fenced.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            self.fenced.add(fn.args.kwarg.arg)
        # two passes close use-before-textual-def chains
        for _ in range(2):
            self._propagate(fn.body)

    # -- lock regions -----------------------------------------------------
    def _mark_lock(self, body: Sequence[ast.stmt], locked: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if locked:
                self.in_lock.add(id(stmt))
            inner = locked
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = locked or any(_is_lock_ctx(i.context_expr)
                                      for i in stmt.items)
            for field in ("body", "orelse", "finalbody"):
                self._mark_lock(getattr(stmt, field, []) or [], inner)
            for h in getattr(stmt, "handlers", []) or []:
                self._mark_lock(h.body, inner)

    # -- fenced-name closure ----------------------------------------------
    def _is_fenced_expr(self, expr: ast.expr, stmt: ast.stmt) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.fenced
        if isinstance(expr, ast.Attribute):
            if expr.attr in _FENCED_ATTRS:
                return True
            if expr.attr in _MAP_ATTRS:
                return id(stmt) in self.in_lock
        if isinstance(expr, ast.Call):
            name = astutil.dotted_name(expr.func) or ""
            if name.split(".")[-1] in _FENCED_DERIVATIONS:
                return True
        return False

    def _propagate(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and stmt.value is not None:
                if self._is_fenced_expr(stmt.value, stmt):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.fenced.add(t.id)
            for field in ("body", "orelse", "finalbody"):
                self._propagate(getattr(stmt, field, []) or [])
            for h in getattr(stmt, "handlers", []) or []:
                self._propagate(h.body)

    # -- checks -----------------------------------------------------------
    def _flag(self, code: str, node: ast.AST, msg: str) -> None:
        self.out.append(Violation(
            rule=RULE, code=code, path=self.rel, line=node.lineno,
            col=node.col_offset, message=msg))

    def _monotone_guarded(self, stmt: ast.stmt,
                          stored: ast.expr) -> bool:
        """Is ``stmt`` dominated by a strict ``stored.version >
        <other>.version`` comparison (any spelling)?"""
        if not isinstance(stored, ast.Name):
            return False
        want = stored.id
        for test, polarity in self.guards.get(id(stmt), frozenset()):
            if not (isinstance(test, ast.Compare)
                    and len(test.ops) == 1):
                continue
            op = type(test.ops[0])
            if op not in _NEGATE:
                continue
            if not polarity:
                op = _NEGATE[op]
            left = _version_side(test.left)
            right = _version_side(test.comparators[0])
            if left is None or right is None:
                continue
            if left == want and op is ast.Gt:
                return True                 # new.version > cur.version
            if right == want and op is ast.Lt:
                return True                 # cur.version < new.version
        return False

    def run(self) -> None:
        for stmt in self._stmts(self.fn.body):
            self._check_stmt(stmt)

    def _stmts(self, body: Sequence[ast.stmt]):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield stmt
            for field in ("body", "orelse", "finalbody"):
                yield from self._stmts(getattr(stmt, field, []) or [])
            for h in getattr(stmt, "handlers", []) or []:
                yield from self._stmts(h.body)

    @staticmethod
    def _own_exprs(stmt: ast.stmt):
        """The statement's OWN expressions — compound statements yield
        only their header (test/iter/items); their bodies are separate
        statements the caller visits with their own lock membership."""
        if isinstance(stmt, (ast.If, ast.While)):
            yield stmt.test
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield stmt.target
            yield stmt.iter
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                yield item.context_expr
        elif not isinstance(stmt, ast.Try):
            yield stmt

    def _check_stmt(self, stmt: ast.stmt) -> None:
        for top in self._own_exprs(stmt):
            self._check_nodes(stmt, top)

    def _check_nodes(self, stmt: ast.stmt, top: ast.AST) -> None:
        for node in ast.walk(top):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _MAP_ATTRS:
                if isinstance(node.ctx, ast.Store):
                    self._check_swap(stmt, node)
                elif isinstance(node.ctx, ast.Load):
                    self.stats["map_reads"] += 1
                    if id(stmt) in self.in_lock:
                        self.stats["fenced_reads"] += 1
                    else:
                        self._flag(
                            "PXE151", node,
                            "unfenced routing-map read: `._map` "
                            "accessed outside the lock can observe a "
                            "mid-swap table; snapshot it under the "
                            "lock (or via the shard_map property) "
                            "first")
            elif isinstance(node, ast.Call):
                self._check_consumer(stmt, node)

    def _check_consumer(self, stmt: ast.stmt, call: ast.Call) -> None:
        name = (astutil.dotted_name(call.func) or "").split(".")[-1]
        if name not in _MAP_CONSUMERS:
            return
        if name in _METHOD_CONSUMERS:
            assert isinstance(call.func, ast.Attribute)
            operand: Optional[ast.expr] = call.func.value
        else:
            operand = call.args[0] if call.args else None
        if operand is None:
            return
        self.stats["map_reads"] += 1
        if self._is_fenced_expr(operand, stmt):
            self.stats["fenced_reads"] += 1
            return
        if isinstance(operand, ast.Attribute) \
                and operand.attr in _MAP_ATTRS:
            return   # the raw ._map load above already flagged it
        self._flag(
            "PXE151", call,
            f"map-dependent `{name}(...)` on an unfenced operand: "
            f"resolve keys against one locked snapshot (shard_map "
            f"property / in-lock `._map` bind) so a concurrent "
            f"install_map cannot split the epoch")

    def _check_swap(self, stmt: ast.stmt, target: ast.Attribute) -> None:
        self.stats["swaps"] += 1
        if self.fn.name == "__init__":
            self.stats["guarded_swaps"] += 1
            return                          # initial install
        value = getattr(stmt, "value", None)
        ok = (id(stmt) in self.in_lock and value is not None
              and self._monotone_guarded(stmt, value))
        if ok:
            self.stats["guarded_swaps"] += 1
            return
        if id(stmt) not in self.in_lock:
            why = "outside the lock"
        else:
            why = ("without a dominating strict version-advance "
                   "comparison (new.version > installed.version)")
        self._flag(
            "PXE152", target,
            f"routing-map swap {why}: a regressing or racing install "
            f"re-opens the stale-epoch window the flush re-resolution "
            f"depends on closing")


def _new_stats() -> Dict[str, int]:
    return {"map_reads": 0, "fenced_reads": 0, "swaps": 0,
            "guarded_swaps": 0}


def _run(root: Path, files: Optional[Sequence[Path]]
         ) -> Tuple[List[Violation], Dict[str, Dict[str, int]]]:
    paths = list(files if files is not None
                 else astutil.iter_py(root, TARGETS))
    out: List[Violation] = []
    per_module: Dict[str, Dict[str, int]] = {}
    for path in paths:
        try:
            tree = ast.parse(Path(path).read_text())
        except (OSError, SyntaxError):
            continue
        rel = astutil.rel(Path(path).resolve(), root)
        stats = per_module.setdefault(rel, _new_stats())
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                _FnCheck(rel, node, out, stats).run()
    return (sorted(out, key=lambda v: (v.path, v.line, v.code)),
            per_module)


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    return _run(root, files)[0]


def coverage(root: Path,
             files: Optional[Sequence[Path]] = None
             ) -> Dict[str, Dict[str, int]]:
    """Per-module proof surface: how many map reads/swaps the rule
    actually examined and proved fenced/guarded — the tests pin these
    so a refactor cannot silently move the map out from under the
    rule."""
    return _run(root, files)[1]
