"""Quorum-safety rule family (PXQ5xx) — static intersection proofs.

The framework's safety story (SIGMOD'19 "Dissecting...", and the
Bipartisan Paxos decomposition in PAPERS.md) reduces every protocol to
quorum arithmetic plus ballot-guarded handlers.  The quorum half of
that obligation is *statically checkable*: every quorum a protocol
waits on is declared in source as either a :class:`core.quorum.Quorum`
predicate call (``majority()``, ``fast_quorum()``, ``grid_q1(q)``...)
or an explicit size comparison (``len(e.acked) >= self.fast``,
``op.quorum.size() >= self.W``), and every threshold is a small
floor-linear expression of the cluster size.  This rule symbolically
evaluates those thresholds (analysis/flow.SymEval — exact rational
arithmetic, so ``-(-3*n//4)`` and ``math.ceil(3*n/4)`` agree) and
proves the pairwise intersection obligations over all config sizes:

- a **phase-1 quorum** (election/prepare/recovery) must intersect
  every **phase-2 quorum** (accept/commit) on the same id universe;
- a **read quorum** must intersect every **write quorum** likewise;
- flexible grid quorums (WPaxos) intersect when ``q1 + q2 > Z``.

"All config n" means every n in ``2..MAX_N`` (and every zone count /
grid knob up to ``MAX_Z``): the thresholds this repo can express are
floor-linear with denominator <= 4, so any non-intersection has a
counterexample far below the bound; the bound is generous rather than
clever on purpose.

Scope notes (also in README "Static analysis"): analysis is
module-local; a quorum's id *universe* is the text of its constructor
argument (``Quorum(self.cfg.ids)`` vs ``Quorum(self.zone_ids)``), and
only same-universe pairs owe each other intersection.  Bare
``len(...)``-comparison sites default to the whole-cluster universe.
Sites whose thresholds the evaluator cannot resolve are *reported*
(PXQ502) rather than skipped — silence is a proof here, so it must be
earned.

Checks:

- **PXQ501** a host-runtime phase-1 x phase-2 (or read x write) quorum
  pair on one universe can fail to intersect; the message carries the
  counterexample size
- **PXQ502** a quorum site whose threshold or receiver the analyzer
  cannot resolve symbolically
- **PXQ503** a sim-kernel quorum threshold pair (``cfg.majority`` /
  ``cfg.fast_size`` aliases, zone-grid thresholds) can fail to
  intersect
- **PXQ505** the switchnet in-fabric tier's recovery obligation
  (paxi_tpu/switchnet): a module that commits on the in-network vote
  (calls ``apply_fast_commits``/``fast_commit_mask``, or — host form
  — registers a ``SwitchVote`` handler) runs a write quorum of
  {switch register}; the ONLY recovery quorum intersecting it is one
  that reads the register file, so the module must also consult it
  (sim: a ``recovery_fold`` call on the phase-1 win path; host: a
  registered ``SwitchSnap`` handler).  Skipping the read is the
  lost-fast-commit bug: a value whose only durable copy is the
  bounded register file vanishes across a leader failover.  The
  replica fall-back quorum (``cfg.majority`` aliases) x recovery
  majority pairs are enumerated for all n by the PXQ503 machinery as
  usual — together the two cover every write-path x recovery pair of
  the tier.

- **PXQ504** a rectangular-grid (rowcol) read x write pair can fail
  to intersect — the BPaxos quorum system, and the first non-majority
  system this rule proves.  The grid is also the *thrifty* variant
  (messages go to exactly the quorum), so this check subsumes the
  thrifty-quorum obligation PR 5 left open: a thrifty write is safe
  iff the minimal sets themselves intersect, which is precisely what
  is enumerated here.  Two forms:

  - sim kernels: write sites compare a ``*_row_quorums`` tally, read
    sites a ``*_col_quorums`` tally; the per-line *fullness* threshold
    is DERIVED from the tally helper's own body (``per >= GC``) and
    must demand complete lines — a full row and a full column of one
    grid always share exactly one cell, but a row short one cell can
    dodge a column, which is the counterexample the message carries;
  - host replicas: ``Quorum.grid_row(cols)`` x ``Quorum.grid_col(cols)``
    call pairs on one universe; the predicates are modeled as
    complete-line tests (their bodies — core/quorum.py — are covered
    by a runtime structural test), so the proof obligation is that
    both sites derive the grid from the SAME ``cols`` expression for
    every geometry; a mismatch re-shapes the grid between read and
    write and loses the shared cell.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from fractions import Fraction
from pathlib import Path
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, \
    Set, Tuple

from paxi_tpu.analysis import astutil, flow
from paxi_tpu.analysis.model import Violation

RULE = "quorum-safety"

TARGETS = (
    "paxi_tpu/protocols/*/host.py",
    "paxi_tpu/protocols/*/sim.py",
    "paxi_tpu/protocols/*/sim_pg.py",
)

QUORUM_MODULE = "paxi_tpu/core/quorum.py"
SIM_TYPES = "paxi_tpu/sim/types.py"

MAX_N = 48     # cluster sizes the "for all n" proof enumerates
MAX_Z = 8      # zone counts / grid knobs likewise

PHASE1 = frozenset({"p1"})
PHASE2 = frozenset({"p2"})
ANY_PHASE = frozenset({"p1", "p2", "read", "write"})

_AMBIG = object()


# ---------------------------------------------------------------------------
# the predicate model (core/quorum.py) and SimConfig thresholds
# ---------------------------------------------------------------------------


@dataclass
class Predicates:
    """What each ``Quorum`` method means, derived from its source."""

    # name -> threshold fn: universe size n -> min acks, or None
    count: Dict[str, Callable[[int], Optional[int]]]
    # zone-structured predicates (modeled, not derived): name -> phase
    grid: Dict[str, FrozenSet[str]]
    # rectangular-grid predicates (modeled as complete-line tests;
    # core/quorum.py's bodies are covered by a runtime structural
    # test): name -> phase ("write" = row, "read" = column)
    rowcol: Dict[str, str]
    # module-level size helpers usable in thresholds:
    # name -> (params, return expr)
    funcs: Dict[str, Tuple[List[str], ast.expr]]


def _single_return(fn: ast.AST) -> Optional[ast.expr]:
    rets = [s for s in ast.walk(fn) if isinstance(s, ast.Return)]
    return rets[0].value if len(rets) == 1 else None


def load_predicates(root: Path) -> Predicates:
    """Derive each count predicate's threshold from its own body: the
    smallest ack count satisfying the returned comparison (so a quorum
    refactor in core/quorum.py re-derives the model for free)."""
    tree, _ = astutil.parse_file(root / QUORUM_MODULE)
    count: Dict[str, Callable[[int], Optional[int]]] = {}
    funcs: Dict[str, Tuple[List[str], ast.expr]] = {}
    for node in tree.body:
        if isinstance(node, astutil.FuncNode):
            expr = _single_return(node)
            if expr is not None:
                funcs[node.name] = (
                    [a.arg for a in node.args.args], expr)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if not isinstance(item, astutil.FuncNode):
                continue
            expr = _single_return(item)
            if expr is None:
                continue
            # the ack-count term is the len(...) call in the predicate
            lens = [n for n in ast.walk(expr)
                    if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "len"]
            if len(lens) != 1 or not isinstance(expr, (ast.Compare,
                                                       ast.BoolOp)):
                continue
            key = ast.unparse(lens[0])

            def mk(pred_expr=expr, count_key=key):
                def thresh(n: int) -> Optional[int]:
                    ev = flow.SymEval({"self.n": Fraction(n)}, funcs=funcs)
                    return flow.min_satisfying(pred_expr, count_key,
                                               ev, n)
                return thresh

            count[item.name] = mk()
    grid = {"grid_q1": PHASE1, "grid_q2": PHASE2}
    rowcol = {"grid_row": "write", "grid_col": "read"}
    return Predicates(count=count, grid=grid, rowcol=rowcol, funcs=funcs)


def load_sim_props(root: Path) -> Dict[str, Callable[[int],
                                                     Optional[int]]]:
    """SimConfig's derived quorum sizes (``majority``, ``fast_size``):
    property name -> size fn of n_replicas."""
    tree, _ = astutil.parse_file(root / SIM_TYPES)
    out: Dict[str, Callable[[int], Optional[int]]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "SimConfig"):
            continue
        for item in node.body:
            if not isinstance(item, astutil.FuncNode):
                continue
            if "property" not in astutil.decorator_names(item):
                continue
            expr = _single_return(item)
            if expr is None:
                continue

            def mk(e=expr):
                def size(n: int) -> Optional[int]:
                    v = flow.SymEval(
                        {"self.n_replicas": Fraction(n)}).eval(e)
                    return int(v) if v is not None and v.denominator == 1 \
                        else None
                return size

            out[item.name] = mk()
    return out


# ---------------------------------------------------------------------------
# per-module symbol resolution
# ---------------------------------------------------------------------------


class Resolver:
    """Chase names through their (unique) assignments, module-wide.

    ``self.X`` resolves through any class's single ``self.X = expr``
    assignment; a bare name through module-level then unique
    function-local single assignments.  Conflicting assignments make a
    name unresolvable (the rule then reports PXQ502 rather than
    guessing which definition a site sees)."""

    def __init__(self, tree: ast.Module):
        self.attr: Dict[str, object] = {}
        self.local: Dict[str, object] = {}
        self.modlvl: Dict[str, object] = {}

        def put(table: Dict[str, object], key: str,
                expr: ast.expr) -> None:
            old = table.get(key)
            if old is None:
                table[key] = expr
            elif old is not _AMBIG and ast.unparse(old) != \
                    ast.unparse(expr):
                table[key] = _AMBIG

        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                put(self.modlvl, node.targets[0].id, node.value)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            targets = node.targets
            values: List[Tuple[ast.expr, ast.expr]] = []
            if len(targets) == 1 and isinstance(targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) and \
                    len(targets[0].elts) == len(node.value.elts):
                values = list(zip(targets[0].elts, node.value.elts))
            else:
                values = [(t, node.value) for t in targets]
            for t, v in values:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    put(self.attr, t.attr, v)
                elif isinstance(t, ast.Name):
                    put(self.local, t.id, v)

    def __call__(self, key: str) -> Optional[ast.expr]:
        if key.startswith("self."):
            hit = self.attr.get(key[5:])
        else:
            hit = self.modlvl.get(key)
            if hit is None:
                hit = self.local.get(key)
        return None if hit is _AMBIG else hit


# ---------------------------------------------------------------------------
# quorum sites
# ---------------------------------------------------------------------------


@dataclass
class Site:
    kind: str                 # "count" | "grid" | "rowcol"
    line: int
    col: int
    text: str
    universe: str
    phases: FrozenSet[str]
    # count: universe size n -> min quorum size
    size_fn: Optional[Callable[[int], Optional[int]]] = None
    # grid: (zones, grid_q2 knob) -> zone-majorities required
    # rowcol/sim: (rows, cols) -> complete lines required
    # rowcol/host: (rows, cols) -> the site's resolved ``cols`` arg
    zones_fn: Optional[Callable[[int, int], Optional[int]]] = None
    # rowcol/sim only: (rows, cols) -> cells per counted line, derived
    # from the ``*_row_quorums``/``*_col_quorums`` helper body — the
    # fullness the intersection proof hinges on
    fill_fn: Optional[Callable[[int, int], Optional[int]]] = None
    resolved: bool = True
    why_unresolved: str = ""


_P1_HINTS = ("p1", "phase1", "prepare", "become_leader", "elect",
             "recover", "seq1")
_P2_HINTS = ("p2", "accept", "commit")


def _phases(fn_name: str, recv: str, pred: str) -> FrozenSet[str]:
    name = f"{fn_name} {recv} {pred}".lower()
    out: Set[str] = set()
    if any(h in name for h in _P1_HINTS):
        out.add("p1")
    if any(h in name for h in _P2_HINTS):
        out.add("p2")
    if "read" in name:
        out.add("read")
    if "write" in name:
        out.add("write")
    return frozenset(out) or ANY_PHASE


def _norm_universe(expr: ast.expr) -> str:
    text = ast.unparse(expr)
    return text[5:] if text.startswith("self.") else text


def _enclosing_functions(tree: ast.Module) -> Dict[int, str]:
    """id(node) -> name of the innermost def containing it."""
    out: Dict[int, str] = {}

    def walk(node: ast.AST, fn: str) -> None:
        for child in ast.iter_child_nodes(node):
            here = child.name if isinstance(child, astutil.FuncNode) \
                else fn
            out[id(child)] = here
            walk(child, here)

    walk(tree, "<module>")
    return out


def _universes(tree: ast.Module) -> Dict[str, Set[str]]:
    """quorum-holding name (local name or attribute tail) -> universe
    texts of the ``Quorum(...)`` constructions flowing into it."""
    local: Dict[str, Set[str]] = {}
    attr: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                (astutil.dotted_name(node.value.func) or ""
                 ).split(".")[-1] == "Quorum" and node.value.args:
            univ = _norm_universe(node.value.args[0])
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local.setdefault(t.id, set()).add(univ)
                elif isinstance(t, ast.Attribute):
                    attr.setdefault(t.attr, set()).add(univ)
        # Entry(..., quorum=q): the local's universe flows to the field
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and isinstance(kw.value, ast.Name) and \
                        kw.value.id in local:
                    attr.setdefault(kw.arg, set()).update(
                        local[kw.value.id])
    merged = dict(attr)
    for k, v in local.items():
        merged.setdefault(k, set()).update(v)
    return merged


def _size_term(node: ast.expr) -> Optional[Tuple[str, ast.expr]]:
    """(receiver-name, receiver-expr) when ``node`` is a quorum size
    term: ``X.size()`` or ``len(X)``."""
    if isinstance(node, ast.Call) and not node.args and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "size":
        recv = node.func.value
        tail = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None)
        if tail:
            return tail, recv
    if isinstance(node, ast.Call) and len(node.args) == 1 and \
            isinstance(node.func, ast.Name) and node.func.id == "len":
        # only ack/vote collections count — `len(order)`-style list
        # bookkeeping is not a quorum tally
        arg = node.args[0]
        name = (arg.attr if isinstance(arg, ast.Attribute)
                else arg.id if isinstance(arg, ast.Name) else "")
        if any(h in name.lower() for h in ("ack", "vote", "quorum",
                                           "promis", "replies")):
            return "len", arg
    return None


def _count_env(n: int) -> Dict[str, Fraction]:
    f = Fraction(n)
    return {"self.n": f, "cfg.n": f, "self.cfg.n": f, "n": f,
            "len(cfg.ids)": f, "len(self.cfg.ids)": f,
            "len(self.ids)": f, "len(ids)": f}


def _grid_env(z: int, q2: int) -> Dict[str, Fraction]:
    fz = Fraction(z)
    return {"cfg.n_zones": fz, "self.cfg.n_zones": fz,
            "len(cfg.zones())": fz, "len(self.cfg.zones())": fz,
            "z": fz, "cfg.grid_q2": Fraction(q2),
            "self.cfg.grid_q2": Fraction(q2)}


def _rowcol_env(rows: int, cols: int) -> Dict[str, Fraction]:
    fr, fc = Fraction(rows), Fraction(cols)
    return {"cfg.grid_rows": fr, "self.cfg.grid_rows": fr,
            "cfg.grid_cols": fc, "self.cfg.grid_cols": fc}


def host_sites(tree: ast.Module, preds: Predicates,
               resolver: Resolver) -> List[Site]:
    universes = _universes(tree)
    owner = _enclosing_functions(tree)
    sites: List[Site] = []

    def threshold_fn(expr: ast.expr,
                     strict: bool) -> Callable[[int], Optional[int]]:
        def size(n: int) -> Optional[int]:
            ev = flow.SymEval(_count_env(n), resolve=resolver,
                              funcs=preds.funcs)
            v = ev.eval(expr)
            if v is None:
                return None
            # min integer size passing the comparison: `size > T` is
            # floor(T)+1 (NOT ceil(T)+1 — for fractional T like n/3
            # those differ), `size >= T` is ceil(T)
            if strict:
                return int(v.__floor__()) + 1
            return int(-((-v).__floor__()))
        return size

    def grid_fn(expr: ast.expr) -> Callable[[int, int], Optional[int]]:
        def zones(z: int, q2: int) -> Optional[int]:
            ev = flow.SymEval(dict(_grid_env(z, q2), **_count_env(z)),
                              resolve=resolver, funcs=preds.funcs)
            v = ev.eval(expr)
            return int(v) if v is not None and v.denominator == 1 \
                else None
        return zones

    def rowcol_fn(expr: ast.expr) -> Callable[[int, int], Optional[int]]:
        def cols(rows: int, cols_: int) -> Optional[int]:
            ev = flow.SymEval(dict(_rowcol_env(rows, cols_),
                                   **_count_env(rows * cols_)),
                              resolve=resolver, funcs=preds.funcs)
            v = ev.eval(expr)
            return int(v) if v is not None and v.denominator == 1 \
                else None
        return cols

    for node in ast.walk(tree):
        # predicate calls: X.majority(), e.quorum.grid_q2(self.q2), ...
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            pred = node.func.attr
            recv = node.func.value
            tail = recv.attr if isinstance(recv, ast.Attribute) else (
                recv.id if isinstance(recv, ast.Name) else "")
            fn_name = owner.get(id(node), "")
            if pred in preds.rowcol:
                site = Site(kind="rowcol", line=node.lineno,
                            col=node.col_offset,
                            text=ast.unparse(node),
                            universe=" | ".join(sorted(
                                universes.get(tail, {"cfg.ids"}))),
                            phases=frozenset({preds.rowcol[pred]}))
                if node.args:
                    site.zones_fn = rowcol_fn(node.args[0])
                    if site.zones_fn(2, 3) is None:
                        site.resolved = False
                        site.why_unresolved = (
                            f"grid `cols` argument "
                            f"`{ast.unparse(node.args[0])}` does not "
                            "evaluate symbolically")
                else:
                    site.resolved = False
                    site.why_unresolved = "grid predicate without a " \
                                          "cols argument"
                sites.append(site)
                continue
            if pred in preds.grid:
                site = Site(kind="grid", line=node.lineno,
                            col=node.col_offset,
                            text=ast.unparse(node),
                            universe=" | ".join(sorted(
                                universes.get(tail, {"cfg.ids"}))),
                            phases=preds.grid[pred])
                if node.args:
                    site.zones_fn = grid_fn(node.args[0])
                else:
                    site.resolved = False
                    site.why_unresolved = "grid predicate without a " \
                                          "zone-count argument"
                sites.append(site)
                continue
            if pred in preds.count:
                univs = universes.get(tail)
                site = Site(kind="count", line=node.lineno,
                            col=node.col_offset,
                            text=ast.unparse(node),
                            universe=" | ".join(sorted(univs))
                            if univs else "?",
                            phases=_phases(fn_name, tail, pred),
                            size_fn=preds.count[pred])
                if not univs:
                    site.resolved = False
                    site.why_unresolved = (
                        f"receiver `{tail or ast.unparse(recv)}` binds "
                        "to no Quorum(...) construction in this module")
                sites.append(site)
                continue
        # explicit size comparisons: len(e.acked) >= self.fast,
        # op.quorum.size() >= self.W, ...
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            left, op, right = node.left, node.ops[0], node.comparators[0]
            for a, b, opc in ((left, right, op), (right, left, op)):
                term = _size_term(a)
                if term is None:
                    continue
                if not isinstance(opc, (ast.Gt, ast.GtE, ast.Lt,
                                        ast.LtE)):
                    continue
                # normalize to the pass-side threshold `size >= k`:
                # `size > T` and `T >= size` (fail side) both mean the
                # quorum is satisfied from T+1; `size >= T` / `size < T`
                # (early return) from ceil(T)
                if a is left:
                    strict = isinstance(opc, (ast.Gt, ast.LtE))
                else:
                    strict = isinstance(opc, (ast.Lt, ast.GtE))
                tail, recv_expr = term
                fn = threshold_fn(b, strict)
                a5, b29 = fn(5), fn(29)
                if a5 is not None and a5 == b29:
                    continue   # a resolvable CONSTANT is not a quorum
                univs = (universes.get(tail)
                         if tail != "len" else None) or {"cfg.ids"}
                fn_name = owner.get(id(node), "")
                site = Site(
                    kind="count", line=node.lineno, col=node.col_offset,
                    text=ast.unparse(node),
                    universe=" | ".join(sorted(univs)),
                    phases=_phases(fn_name, ast.unparse(recv_expr), ""),
                    size_fn=fn)
                if a5 is None and b29 is None:
                    site.resolved = False
                    site.why_unresolved = (
                        f"threshold `{ast.unparse(b)}` does not "
                        "evaluate symbolically")
                sites.append(site)
                break
    return sites


# ---------------------------------------------------------------------------
# pair checking
# ---------------------------------------------------------------------------


def _owes_intersection(a: Site, b: Site) -> bool:
    """p1 x p2 or read x write across the two sites (in either order).
    Same-phase pairs owe nothing: two phase-2 quorums of one ballot
    never disagree (same leader), and FPaxos explicitly drops the
    p1 x p1 requirement."""
    def cross(x: FrozenSet[str], y: FrozenSet[str]) -> bool:
        return ("p1" in x and "p2" in y) or ("read" in x and "write" in y)
    return cross(a.phases, b.phases) or cross(b.phases, a.phases)


def _check_count_pair(a: Site, b: Site) -> Optional[Tuple[int, int, int]]:
    for n in range(2, MAX_N + 1):
        sa, sb = a.size_fn(n), b.size_fn(n)
        if sa is None or sb is None:
            continue
        if 0 < sa <= n and 0 < sb <= n and sa + sb <= n:
            return n, sa, sb
    return None


def _check_grid_pair(a: Site, b: Site) -> Optional[Tuple[int, int, int]]:
    for z in range(1, MAX_Z + 1):
        for q2 in range(1, z + 1):
            za, zb = a.zones_fn(z, q2), b.zones_fn(z, q2)
            if za is None or zb is None:
                continue
            if 0 < za <= z and 0 < zb <= z and za + zb <= z:
                return z, za, zb
    return None


def _check_rowcol_pair(a: Site, b: Site) -> Optional[Tuple[int, int, str]]:
    """Grid read x write intersection over every rows x cols geometry.

    A set of COMPLETE rows and a set of COMPLETE columns of one grid
    always share a cell (row i x column j meet at (i, j)), so the
    obligations are: at least one line on each side, derived fullness
    (sim tallies must count only full lines), and — host form — both
    predicates shaping the grid with the same ``cols``.  Returns
    (rows, cols, why) for the first geometry that breaks one."""
    w, r = (a, b) if "write" in a.phases else (b, a)
    for gr in range(1, MAX_Z + 1):
        for gc in range(1, MAX_Z + 1):
            if w.fill_fn is not None and r.fill_fn is not None:
                tw, fw = w.zones_fn(gr, gc), w.fill_fn(gr, gc)
                tr, fr = r.zones_fn(gr, gc), r.fill_fn(gr, gc)
                if None in (tw, fw, tr, fr):
                    continue
                if tw < 1 or tr < 1:
                    return (gr, gc, "a quorum satisfiable with ZERO "
                            f"complete lines ({tw} rows / {tr} columns "
                            "required)")
                if tw > gr or tr > gc:
                    continue   # unsatisfiable: nothing ever commits
                if fw < gc:
                    return (gr, gc, f"write rows count as complete at "
                            f"{fw}/{gc} cells — a short row dodges "
                            "column " f"{fw}")
                if fr < gr:
                    return (gr, gc, f"read columns count as complete "
                            f"at {fr}/{gr} cells — a short column "
                            f"dodges row {fr}")
            else:
                cw, cr = w.zones_fn(gr, gc), r.zones_fn(gr, gc)
                if cw is None or cr is None:
                    continue
                if cw != cr:
                    return (gr, gc, "grid geometry mismatch: "
                            f"grid_row(cols={cw}) vs "
                            f"grid_col(cols={cr}) re-shape the grid "
                            "between write and read")
    return None


def _pair_violations(sites: List[Site], relpath: str,
                     code: str, scope: str) -> List[Violation]:
    out: List[Violation] = []
    by_universe: Dict[str, List[Site]] = {}
    for s in sites:
        if s.resolved:
            by_universe.setdefault(s.universe, []).append(s)
    seen: Set[Tuple[int, int]] = set()
    for univ, group in by_universe.items():
        for i, a in enumerate(group):
            for b in group[i + 1:]:
                if a.kind != b.kind or not _owes_intersection(a, b):
                    continue
                key = (a.line, b.line)
                if key in seen:
                    continue
                if a.kind == "rowcol":
                    bad_rc = _check_rowcol_pair(a, b)
                    if bad_rc is None:
                        continue
                    seen.add(key)
                    gr, gc, why = bad_rc
                    out.append(Violation(
                        rule=RULE, code="PXQ504", path=relpath,
                        line=a.line, col=a.col,
                        message=(
                            f"{scope} grid quorums `{a.text}` (line "
                            f"{a.line}, {'/'.join(sorted(a.phases))}) "
                            f"and `{b.text}` (line {b.line}, "
                            f"{'/'.join(sorted(b.phases))}) on "
                            f"universe `{univ}` can fail to intersect "
                            f"at a {gr}x{gc} grid: {why}")))
                    continue
                if a.kind == "count":
                    bad = _check_count_pair(a, b)
                    unit = "sizes"
                else:
                    bad = _check_grid_pair(a, b)
                    unit = "zone-quorums"
                if bad is None:
                    continue
                seen.add(key)
                n, sa, sb = bad
                out.append(Violation(
                    rule=RULE, code=code, path=relpath,
                    line=a.line, col=a.col,
                    message=(
                        f"{scope} quorums `{a.text}` (line {a.line}, "
                        f"phases {'/'.join(sorted(a.phases))}) and "
                        f"`{b.text}` (line {b.line}, phases "
                        f"{'/'.join(sorted(b.phases))}) on universe "
                        f"`{univ}` can fail to intersect: at "
                        f"{'Z' if a.kind == 'grid' else 'n'}={n} the "
                        f"{unit} are {sa}+{sb} <= {n}")))
    return out


# ---------------------------------------------------------------------------
# sim kernels
# ---------------------------------------------------------------------------


def _line_fullness(tree: ast.Module, helper: str, resolver: Resolver
                   ) -> Optional[Callable[[int, int], Optional[int]]]:
    """Derive a ``*_row_quorums``/``*_col_quorums`` helper's per-line
    fullness threshold from its own body: the single ``per >= K``
    comparison deciding when a line counts as complete.  Returns None
    when the body has no unique derivable comparison — the site is
    then reported (PXQ502), not silently trusted."""
    fn = next((n for n in tree.body
               if isinstance(n, astutil.FuncNode) and n.name == helper),
              None)
    if fn is None:
        return None
    cmps = [n for n in ast.walk(fn)
            if isinstance(n, ast.Compare) and len(n.ops) == 1
            and isinstance(n.ops[0], (ast.GtE, ast.Gt))]
    if len(cmps) != 1:
        return None
    thr = cmps[0].comparators[0]
    strict = isinstance(cmps[0].ops[0], ast.Gt)

    def fill(rows: int, cols: int) -> Optional[int]:
        ev = flow.SymEval(_rowcol_env(rows, cols), resolve=resolver)
        v = ev.eval(thr)
        if v is None or v.denominator != 1:
            return None
        return int(v) + (1 if strict else 0)

    return fill


def sim_sites(tree: ast.Module,
              props: Dict[str, Callable[[int], Optional[int]]],
              resolver: Resolver) -> List[Site]:
    """Quorum thresholds a sim kernel consumes: aliases of the
    SimConfig-derived sizes (``MAJ = cfg.majority``), zone-grid
    thresholds compared against ``*_zone_quorums(...)`` tallies, and
    rectangular-grid thresholds compared against ``*_row_quorums``/
    ``*_col_quorums`` tallies (the BPaxos quorum system)."""
    sites: List[Site] = []
    zone_locals: Set[str] = set()
    rowcol_locals: Dict[str, Tuple[str, str]] = {}  # name -> (phase, helper)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        pairs: List[Tuple[ast.expr, ast.expr]] = []
        if len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Tuple) and \
                isinstance(node.value, ast.Tuple) and \
                len(node.targets[0].elts) == len(node.value.elts):
            pairs = list(zip(node.targets[0].elts, node.value.elts))
        else:
            pairs = [(t, node.value) for t in node.targets]
        for t, v in pairs:
            if not isinstance(t, ast.Name):
                continue
            dn = astutil.dotted_name(v) or ""
            prop = dn.split(".")[-1]
            if dn.startswith("cfg.") and prop in props:
                sites.append(Site(
                    kind="count", line=node.lineno, col=node.col_offset,
                    text=f"{t.id} = {dn}", universe="replicas",
                    phases=ANY_PHASE, size_fn=props[prop]))
            if isinstance(v, ast.Call):
                callee = (astutil.dotted_name(v.func) or ""
                          ).split(".")[-1]
                if callee.endswith("zone_quorums"):
                    zone_locals.add(t.id)
                elif callee.endswith("row_quorums"):
                    rowcol_locals[t.id] = ("write", callee)
                elif callee.endswith("col_quorums"):
                    rowcol_locals[t.id] = ("read", callee)
    # compares of rowcol tallies against line-count thresholds: the
    # lines-needed side comes from the compare, the per-line fullness
    # from the tally helper's own body
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.GtE, ast.Gt))):
            continue
        lhs_names = {n.id for n in ast.walk(node.left)
                     if isinstance(n, ast.Name)}
        hit = sorted(lhs_names & set(rowcol_locals))
        if not hit:
            continue
        phase, helper = rowcol_locals[hit[0]]
        thr = node.comparators[0]
        strict = isinstance(node.ops[0], ast.Gt)

        def lines_fn(e=thr, s=strict):
            def lines(rows: int, cols: int) -> Optional[int]:
                ev = flow.SymEval(_rowcol_env(rows, cols),
                                  resolve=resolver)
                v = ev.eval(e)
                if v is None or v.denominator != 1:
                    return None
                return int(v) + (1 if s else 0)
            return lines

        site = Site(kind="rowcol", line=node.lineno,
                    col=node.col_offset, text=ast.unparse(node),
                    universe="grid", phases=frozenset({phase}),
                    zones_fn=lines_fn(),
                    fill_fn=_line_fullness(tree, helper, resolver))
        if site.fill_fn is None:
            site.resolved = False
            site.why_unresolved = (
                f"tally helper `{helper}` has no unique derivable "
                "per-line completeness comparison")
        elif site.zones_fn(2, 3) is None:
            site.resolved = False
            site.why_unresolved = (f"line-count threshold "
                                   f"`{ast.unparse(thr)}` does not "
                                   "evaluate symbolically")
        sites.append(site)
    # compares of zone tallies against grid thresholds
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.GtE, ast.Gt))):
            continue
        lhs_names = {n.id for n in ast.walk(node.left)
                     if isinstance(n, ast.Name)}
        if not (lhs_names & zone_locals):
            continue
        thr = node.comparators[0]
        thr_name = (thr.id if isinstance(thr, ast.Name)
                    else ast.unparse(thr)).lower()
        phases = (PHASE1 if "1" in thr_name
                  else PHASE2 if "2" in thr_name else ANY_PHASE)

        def zfn(e=thr, strict=isinstance(node.ops[0], ast.Gt)):
            def zones(z: int, q2: int) -> Optional[int]:
                ev = flow.SymEval(dict(_grid_env(z, q2)),
                                  resolve=resolver)
                v = ev.eval(e)
                if v is None or v.denominator != 1:
                    return None
                return int(v) + (1 if strict else 0)
            return zones

        sites.append(Site(
            kind="grid", line=node.lineno, col=node.col_offset,
            text=ast.unparse(node), universe="zones", phases=phases,
            zones_fn=zfn()))
    return sites


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


# switchnet structural obligation (PXQ505): fast-path commit sites and
# the register reads that keep them recoverable, by callable name
_SWITCH_FAST = frozenset({"apply_fast_commits", "fast_commit_mask"})
_SWITCH_RECOVER = "recovery_fold"


def check_switchnet(tree: ast.Module, relpath: str,
                    is_sim: bool) -> List[Violation]:
    """The in-network vote register x recovery quorum intersection
    (module docstring, PXQ505): presence of the fast path obliges
    presence of the register read on the recovery path."""
    called: Set[str] = set()
    registered: Set[str] = set()
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = (astutil.dotted_name(node.func) or "").split(".")[-1]
        called.add(name)
        lines.setdefault(name, node.lineno)
        if name == "register" and node.args:
            arg0 = astutil.dotted_name(node.args[0])
            if arg0:
                registered.add(arg0.split(".")[-1])
    out: List[Violation] = []
    if is_sim:
        fast = sorted(called & _SWITCH_FAST)
        if fast and _SWITCH_RECOVER not in called:
            out.append(Violation(
                rule=RULE, code="PXQ505", path=relpath,
                line=lines[fast[0]], col=0,
                message=(
                    f"in-network fast-path commit (`{fast[0]}`) without "
                    f"a `{_SWITCH_RECOVER}` register read on the "
                    "phase-1 win path — the {switch} write quorum "
                    "intersects no recovery quorum, so a vote-only "
                    "commit is lost across leader failover")))
    elif "SwitchVote" in registered and "SwitchSnap" not in registered:
        out.append(Violation(
            rule=RULE, code="PXQ505", path=relpath,
            line=lines.get("register", 1), col=0,
            message=(
                "host replica commits on SwitchVote but registers no "
                "SwitchSnap handler — recovery never reads the switch "
                "register file, so a vote-only commit is lost across "
                "leader failover")))
    return out


def _is_sim_module(tree: ast.Module) -> bool:
    """Sim kernels all export a top-level ``mailbox_spec``; host
    modules never do — steadier than filename matching (fixtures)."""
    return any(isinstance(n, astutil.FuncNode)
               and n.name == "mailbox_spec" for n in tree.body)


def check_file(path: Path, root: Path, preds: Predicates,
               props: Dict[str, Callable[[int],
                                         Optional[int]]]) -> List[Violation]:
    relpath = astutil.rel(path, root)
    tree, _ = astutil.parse_file(path)
    resolver = Resolver(tree)
    out: List[Violation] = []
    out.extend(check_switchnet(tree, relpath, _is_sim_module(tree)))
    if not _is_sim_module(tree):
        sites = host_sites(tree, preds, resolver)
        for s in sites:
            if s.resolved and s.kind == "count" and \
                    not any(s.size_fn(n) is not None
                            for n in range(2, MAX_N + 1)):
                s.resolved = False
                s.why_unresolved = "threshold expression does not " \
                    "evaluate for any cluster size"
        for s in sites:
            if not s.resolved:
                out.append(Violation(
                    rule=RULE, code="PXQ502", path=relpath,
                    line=s.line, col=s.col,
                    message=f"unresolvable quorum site `{s.text}`: "
                            f"{s.why_unresolved} — intersection cannot "
                            "be proven, resolve or baseline it"))
        out.extend(_pair_violations(
            [s for s in sites if s.resolved], relpath, "PXQ501", "host"))
    else:
        sites = sim_sites(tree, props, resolver)
        for s in sites:
            if not s.resolved:
                out.append(Violation(
                    rule=RULE, code="PXQ502", path=relpath,
                    line=s.line, col=s.col,
                    message=f"unresolvable quorum site `{s.text}`: "
                            f"{s.why_unresolved} — intersection cannot "
                            "be proven, resolve or baseline it"))
        out.extend(_pair_violations(
            [s for s in sites if s.resolved], relpath, "PXQ503",
            "sim kernel"))
    return out


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    preds = load_predicates(root)
    props = load_sim_props(root)
    paths = (list(files) if files is not None
             else list(astutil.iter_py(root, TARGETS)))
    out: List[Violation] = []
    for p in paths:
        out.extend(check_file(p, root, preds, props))
    return out
