"""Async-atomicity rule family (PXA9xx) — interleaving races the
lockset rules cannot see.

The host serving path (PR 7/8) is a heavily-async pipeline: one event
loop, hundreds of coroutines, almost no locks — asyncio code takes no
locks because *between* suspension points a coroutine is atomic.  The
flip side is the whole safety argument: any read-modify-write on
shared ``self`` state that SPANS a suspension point is a race, because
another task can run at the ``await`` and change the state under the
saved value or the already-taken branch.  PXC's lockset analysis is
blind to this (there is no lock to drop); the hunt engine finds these
only dynamically, one witness at a time.  This family is the static
closure of that bug class.

Model (one linear walk per method, loop bodies walked twice so
wrap-around staleness is seen):

- a **suspension point** is an ``await`` expression, an ``async for``
  iteration or an ``async with`` entry;
- an observation of ``self.X`` (a guard test, or a local snapshot
  ``v = self.X``) goes **stale** when a suspension point passes;
- a write to ``self.X`` (assignment, augmented assignment, item write,
  ``del``, or a mutating container call) **fires** when its value uses
  a stale snapshot of ``X`` or its taken branch is a stale guard on
  ``X`` — unless ``self.X`` was re-read after the suspension
  (re-validation makes the decision fresh again).

Checks:

- **PXA901** a read-modify-write on ``self`` state spans an ``await``
  without re-validation (the lost-update / check-then-act shapes);
- **PXA902** the same split across a *deferral*: a nested
  def/lambda handed to ``call_soon``/``call_later``/``create_task``/
  ``add_done_callback`` (or stored on ``self``) writes ``self.X``
  from a captured pre-scheduling snapshot of ``X`` without re-reading
  it — the resumption point is the deferral boundary;
- **PXA903** a suspension point inside ``with self.<threading lock>``:
  holding a sync lock across an ``await`` stalls the entire event loop
  (asyncio locks are exempt — awaiting under them is their purpose).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.concurrency import MUTATORS
from paxi_tpu.analysis.model import Violation

RULE = "async-atomicity"

TARGETS = (
    "paxi_tpu/host/*.py",
)

# sinks whose callable argument runs at a later event-loop tick
_DEFER_RE = re.compile(
    r"(call_soon|call_later|call_at|create_task|ensure_future|"
    r"add_done_callback|run_in_executor|submit)$")

# sync lock factories (asyncio.Lock is exempt: awaiting under it is
# the point; threading locks held across an await block the loop)
_SYNC_LOCKS = frozenset({"threading.Lock", "threading.RLock",
                         "threading.Condition", "Lock", "RLock",
                         "Condition"})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``x`` for ``self.x`` (through subscripts)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _walk_live(node: ast.AST):
    """``ast.walk`` minus the bodies of nested defs/lambdas — code
    that runs at a later tick, not when this statement executes.  (A
    bare ``continue`` on the def node inside an ``ast.walk`` loop does
    NOT prune: walk queues children before yielding.  Unpruned walks
    both over-report — an ``await`` inside a deferred ``async def``
    read as suspending under a lock — and under-report — a
    ``self.X`` load inside a stored lambda counted as re-validation.)"""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, (ast.Lambda, *astutil.FuncNode)):
                stack.append(c)


def _attr_loads(expr: ast.AST) -> Set[str]:
    """Every ``self.X`` loaded anywhere in an expression (nested
    def/lambda bodies excluded — those loads happen at call time)."""
    out: Set[str] = set()
    for n in _walk_live(expr):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.ctx, ast.Load) and \
                isinstance(n.value, ast.Name) and n.value.id == "self":
            out.add(n.attr)
    return out


def _has_await(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Await) for n in _walk_live(node))


def _sync_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            if astutil.dotted_name(node.value.func) in _SYNC_LOCKS:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out.add(attr)
    return out


# ---------------------------------------------------------------------------
# the linear walker
# ---------------------------------------------------------------------------


@dataclass
class _Guard:
    attrs: Set[str]               # self attrs the test mentions
    crossed: bool = False         # a suspension passed since the test


@dataclass
class _State:
    """Mutable walk state.  ``fresh`` holds attrs whose last
    observation is on this side of every suspension; ``local_src``
    maps locals to the self attrs their value snapshots; ``crossed``
    holds locals whose snapshot predates a suspension."""

    fresh: Set[str] = field(default_factory=set)
    local_src: Dict[str, Set[str]] = field(default_factory=dict)
    crossed: Set[str] = field(default_factory=set)

    def copy(self) -> "_State":
        return _State(set(self.fresh),
                      {k: set(v) for k, v in self.local_src.items()},
                      set(self.crossed))

    def merge(self, other: "_State") -> None:
        self.fresh &= other.fresh          # stale on either path wins
        for k, v in other.local_src.items():
            self.local_src.setdefault(k, set()).update(v)
        self.crossed |= other.crossed


class _MethodWalk:
    def __init__(self, relpath: str, cls: str, method: str,
                 code: str = "PXA901"):
        self.relpath = relpath
        self.cls = cls
        self.method = method
        self.code = code
        self.guards: List[_Guard] = []
        self.out: List[Violation] = []
        self._seen: Set[Tuple[int, str]] = set()

    # -- reporting --------------------------------------------------------
    def _add(self, node: ast.AST, attr: str, why: str) -> None:
        key = (node.lineno, attr)
        if key in self._seen:
            return
        self._seen.add(key)
        boundary = ("an `await`" if self.code == "PXA901"
                    else "the deferral boundary")
        self.out.append(Violation(
            rule=RULE, code=self.code, path=self.relpath,
            line=node.lineno, col=node.col_offset,
            message=(
                f"read-modify-write on `self.{attr}` in "
                f"`{self.cls}.{self.method}` spans {boundary} "
                f"({why}) without re-reading `self.{attr}` — another "
                "task can change it at the suspension point")))

    # -- suspension -------------------------------------------------------
    def _suspend(self, st: _State) -> None:
        st.fresh.clear()
        st.crossed.update(st.local_src)
        for g in self.guards:
            g.crossed = True

    # -- per-statement ----------------------------------------------------
    def _observe(self, expr: ast.AST, st: _State) -> None:
        st.fresh |= _attr_loads(expr)

    def _bind_locals(self, stmt: ast.stmt, st: _State) -> None:
        if isinstance(stmt, ast.Assign):
            srcs = _attr_loads(stmt.value)
            # transitive: a local built from another snapshot local —
            # and copying a CROSSED snapshot keeps it crossed
            # (``w = v`` after the await must not launder v's
            # staleness into a fresh-looking name)
            tainted = False
            for n in _walk_live(stmt.value):
                if isinstance(n, ast.Name) and n.id in st.local_src:
                    srcs |= st.local_src[n.id]
                    if n.id in st.crossed:
                        tainted = True
            for t in stmt.targets:
                names = [t] if isinstance(t, ast.Name) else (
                    [e for e in t.elts if isinstance(e, ast.Name)]
                    if isinstance(t, (ast.Tuple, ast.List)) else [])
                for n in names:
                    if srcs:
                        st.local_src[n.id] = set(srcs)
                        if tainted:
                            st.crossed.add(n.id)
                        else:
                            st.crossed.discard(n.id)
                    else:
                        st.local_src.pop(n.id, None)
                        st.crossed.discard(n.id)

    def _check_write(self, target: ast.AST, value: Optional[ast.AST],
                     stmt: ast.stmt, st: _State,
                     mutator: bool = False) -> None:
        attr = _self_attr(target)
        if attr is None:
            # mutator through a snapshot alias of a self attr (an
            # assignment to a plain local is just a local)
            if mutator and isinstance(target, ast.Name) and \
                    target.id in st.local_src and \
                    len(st.local_src[target.id]) == 1:
                attr = next(iter(st.local_src[target.id]))
            else:
                return
        if attr in st.fresh:
            return                     # re-validated after the await
        # (i) the written value uses a stale snapshot of the same attr
        if value is not None:
            for n in ast.walk(value):
                if isinstance(n, ast.Name) and n.id in st.crossed and \
                        attr in st.local_src.get(n.id, ()):
                    self._add(stmt, attr,
                              f"the value reuses `{n.id}`, a snapshot "
                              "taken before the suspension")
                    return
            # (i') single-statement lost update: a load of the attr
            # that evaluates BEFORE the value's await — inside the
            # awaited operand (``self.x = await f(self.x)``), or
            # positioned left of the last await (operands evaluate
            # left to right: ``self.x = self.x + await f()``), or the
            # implicit target read of an augmented assignment
            # (``self.x += await f()`` loads x before the RHS runs).
            # Loads after the last await evaluate post-resumption and
            # stay clean.
            awaits = [n for n in ast.walk(value)
                      if isinstance(n, ast.Await)]
            if awaits:
                if isinstance(stmt, ast.AugAssign) and \
                        _self_attr(stmt.target) == attr:
                    self._add(stmt, attr,
                              f"`self.{attr}`'s old value loads "
                              "before the awaited right-hand side "
                              "runs")
                    return
                for a in awaits:
                    if attr in _attr_loads(a.value):
                        self._add(stmt, attr,
                                  f"the value reads `self.{attr}` "
                                  "inside the awaited expression, "
                                  "before the suspension")
                        return
                last = max((a.lineno, a.col_offset) for a in awaits)
                for n in _walk_live(value):
                    if isinstance(n, ast.Attribute) and \
                            isinstance(n.ctx, ast.Load) and \
                            isinstance(n.value, ast.Name) and \
                            n.value.id == "self" and n.attr == attr \
                            and (n.lineno, n.col_offset) < last:
                        self._add(stmt, attr,
                                  f"the value reads `self.{attr}` "
                                  "left of the awaited expression, "
                                  "before the suspension")
                        return
        # (ii) the taken branch tested the attr before the suspension
        for g in self.guards:
            if g.crossed and attr in g.attrs:
                self._add(stmt, attr,
                          "the guarding test ran before the "
                          "suspension")
                return

    def _writes_of(self, stmt: ast.stmt
                   ) -> List[Tuple[ast.AST, Optional[ast.AST], bool]]:
        out: List[Tuple[ast.AST, Optional[ast.AST], bool]] = []

        def flat(t: ast.AST) -> List[ast.AST]:
            if isinstance(t, (ast.Tuple, ast.List)):
                return [x for e in t.elts for x in flat(e)]
            return [t]

        if isinstance(stmt, ast.Assign):
            out.extend((t, stmt.value, False)
                       for tgt in stmt.targets for t in flat(tgt))
        elif isinstance(stmt, ast.AugAssign):
            out.append((stmt.target, stmt.value, False))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            out.append((stmt.target, stmt.value, False))
        elif isinstance(stmt, ast.Delete):
            out.extend((t, None, False) for t in stmt.targets)
        for n in _walk_live(stmt):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in MUTATORS:
                args = ast.Tuple(elts=list(n.args), ctx=ast.Load())
                out.append((n.func.value, args, True))
        return out

    def _stmt(self, stmt: ast.stmt, st: _State) -> None:
        if isinstance(stmt, astutil.FuncNode) or \
                isinstance(stmt, ast.ClassDef):
            return                     # deferred body: PXA902's job
        if isinstance(stmt, ast.If):
            self._observe(stmt.test, st)
            g = _Guard(attrs={a for a in _attr_loads(stmt.test)} | {
                a for n in ast.walk(stmt.test)
                if isinstance(n, ast.Name)
                for a in st.local_src.get(n.id, ())})
            if _has_await(stmt.test):
                self._suspend(st)
            other = st.copy()
            self.guards.append(g)
            self._body(stmt.body, st)
            self._body(stmt.orelse, other)
            self.guards.pop()
            st.merge(other)
            return
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._observe(stmt.test, st)
            else:
                self._observe(stmt.iter, st)
                if isinstance(stmt, ast.AsyncFor):
                    self._suspend(st)
            # two passes: wrap-around staleness (a suspension late in
            # the body stales reads early in it on iteration 2)
            for _ in range(2):
                self._body(stmt.body, st)
                if isinstance(stmt, ast.AsyncFor):
                    self._suspend(st)
            self._body(stmt.orelse, st)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._observe(item.context_expr, st)
            if isinstance(stmt, ast.AsyncWith) or _has_await(stmt):
                self._suspend(st)
            self._body(stmt.body, st)
            return
        if isinstance(stmt, ast.Try):
            self._body(stmt.body, st)
            for h in stmt.handlers:
                hs = st.copy()
                self._body(h.body, hs)
                st.merge(hs)
            self._body(stmt.orelse, st)
            self._body(stmt.finalbody, st)
            return
        # simple statement: loads first, then (if it awaits) the
        # suspension, then its writes — matching evaluation order for
        # the ``self.x = await f(self.x)`` shape
        self._observe(stmt, st)
        self._bind_locals(stmt, st)
        if _has_await(stmt):
            # value loads happened before the suspension: their
            # snapshots are already crossed
            self._suspend(st)
        writes = self._writes_of(stmt)
        for target, value, mutator in writes:
            self._check_write(target, value, stmt, st, mutator)
        # a write makes the attr known-current again
        for target, _v, _m in writes:
            attr = _self_attr(target)
            if attr is not None:
                st.fresh.add(attr)

    def _body(self, stmts: Sequence[ast.stmt], st: _State) -> None:
        for s in stmts:
            self._stmt(s, st)

    def run(self, fn: ast.AST,
            seed: Optional[_State] = None) -> List[Violation]:
        st = seed if seed is not None else _State()
        self._body(fn.body, st)
        return self.out


# ---------------------------------------------------------------------------
# PXA902: deferred-callback RMW
# ---------------------------------------------------------------------------


def _method_snapshots(method: ast.AST) -> Dict[str, Set[str]]:
    """Order-insensitive local -> self-attr snapshot map for the whole
    method (what a nested callback can capture)."""
    src: Dict[str, Set[str]] = {}
    for _ in range(2):
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign):
                continue
            attrs = _attr_loads(node.value)
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id in src:
                    attrs |= src[n.id]
            if not attrs:
                continue
            for t in node.targets:
                names = [t] if isinstance(t, ast.Name) else (
                    [e for e in t.elts if isinstance(e, ast.Name)]
                    if isinstance(t, (ast.Tuple, ast.List)) else [])
                for nm in names:
                    src.setdefault(nm.id, set()).update(attrs)
    return src


def _deferred_callbacks(method: ast.AST) -> List[ast.AST]:
    """Nested defs/lambdas that run at a later tick: passed to a
    deferral sink, stored on ``self``, or returned."""
    nested = {n.name: n for n in ast.walk(method)
              if isinstance(n, astutil.FuncNode) and n is not method}
    out: List[ast.AST] = []
    deferred_names: Set[str] = set()
    for node in ast.walk(method):
        if isinstance(node, ast.Call):
            tail = (astutil.dotted_name(node.func) or "").split(".")[-1]
            if _DEFER_RE.search(tail):
                for arg in [*node.args,
                            *(kw.value for kw in node.keywords)]:
                    if isinstance(arg, ast.Lambda):
                        out.append(arg)
                    elif isinstance(arg, ast.Name) and \
                            arg.id in nested:
                        deferred_names.add(arg.id)
        elif isinstance(node, (ast.Assign, ast.Return)) and \
                getattr(node, "value", None) is not None:
            v = node.value
            if isinstance(v, ast.Lambda):
                out.append(v)
            elif isinstance(v, ast.Name) and v.id in nested:
                deferred_names.add(v.id)
    out.extend(nested[n] for n in sorted(deferred_names))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_file(path: Path, root: Path) -> List[Violation]:
    relpath = astutil.rel(path, root)
    tree, _ = astutil.parse_file(path)
    out: List[Violation] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        sync_locks = _sync_lock_attrs(cls)
        for item in cls.body:
            if not isinstance(item, astutil.FuncNode):
                continue
            if item.name == "__init__":
                continue
            # PXA901: RMW across awaits in async methods
            if isinstance(item, ast.AsyncFunctionDef):
                out.extend(_MethodWalk(relpath, cls.name,
                                       item.name).run(item))
                out.extend(_check_lock_spans(relpath, cls.name, item,
                                             sync_locks))
            # PXA902: RMW split across a deferral boundary
            snaps = _method_snapshots(item)
            for cb in _deferred_callbacks(item):
                name = getattr(cb, "name", "<lambda>")
                walk = _MethodWalk(relpath, cls.name,
                                   f"{item.name}.{name}",
                                   code="PXA902")
                seed = _State(fresh=set(),
                              local_src={k: set(v)
                                         for k, v in snaps.items()},
                              crossed=set(snaps))
                if isinstance(cb, ast.Lambda):
                    body = [ast.Expr(value=cb.body)]
                    ast.fix_missing_locations(ast.Module(
                        body=body, type_ignores=[]))
                    walk._body(body, seed)
                    out.extend(walk.out)
                else:
                    out.extend(walk.run(cb, seed))
    return out


def _check_lock_spans(relpath: str, cls: str, fn: ast.AST,
                      sync_locks: Set[str]) -> List[Violation]:
    """PXA903: a suspension point under ``with self.<sync lock>``."""
    if not sync_locks:
        return []
    out: List[Violation] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        held = None
        for it in node.items:
            expr = it.context_expr
            attr = _self_attr(expr)
            if attr is None and isinstance(expr, ast.Call):
                attr = _self_attr(expr.func)
            if attr in sync_locks:
                held = attr
        if held is None:
            continue
        for sub in _walk_live(node):
            if isinstance(sub, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                out.append(Violation(
                    rule=RULE, code="PXA903", path=relpath,
                    line=sub.lineno, col=sub.col_offset,
                    message=(
                        f"suspension point inside `with self.{held}` "
                        f"in `{cls}.{fn.name}` — a threading lock held "
                        "across an await blocks the entire event loop "
                        "and deadlocks against any other task that "
                        "takes it")))
                break
    return out


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    paths = (list(files) if files is not None
             else list(astutil.iter_py(root, TARGETS)))
    out: List[Violation] = []
    for p in paths:
        out.extend(check_file(p, root))
    return out
