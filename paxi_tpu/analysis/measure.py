"""Measurement-isolation rule family (PXM10x).

The on-device observability layer (metrics/lathist, sim/inscan) rides
in ``m_``-prefixed *measurement planes* inside protocol state.  The
architecture promises two things about them:

- they are **excluded from the trace witness hash**
  (``trace/replay.state_hash`` strips ``m_`` keys), so traces captured
  before a kernel grew an instrumentation plane replay hash-clean; and
- they are **write-only from the protocol's point of view**: a
  transition may *accumulate into* them, but no protocol decision —
  state write, message plane, guard — may ever *depend on* one.
  Otherwise "adding a histogram" could change commit behavior, and the
  hash exclusion would hide exactly the divergence it introduced.

This family enforces the second promise statically with a forward
taint walk over every ``step``/``_step`` function in the sim kernels
(the protocol logic; ``metrics``/``invariants`` are read-side exports
and oracles, where reading measurement planes is the whole point):

- a read of ``<anything>["m_..."]`` taints the expression;
- taint propagates through assignments, tuple unpacking, augmented
  assignments, and calls (any tainted argument taints the result);
- a dict construction **quarantines** taint carried under ``m_`` keys
  (the sanctioned store-back) but stays tainted if a tainted value
  sits under a non-``m_`` key.

Checks:

- **PXM101** a tainted value is stored under a non-``m_`` dict key —
  a measurement plane feeding protocol state or an outbox plane.
- **PXM102** a tainted value escapes through a ``return`` (outside the
  quarantined dict form) — e.g. ``return m_hist`` from a transition.

Loop bodies are walked twice (wrap-around taint), mirroring the
asyncflow walker.  The walk is intentionally conservative: a false
positive is an invitation to restructure the write so the quarantine
is syntactically evident, which is what keeps the property auditable.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence, Set

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.model import Violation

RULE = "measurement-isolation"

TARGETS = (
    "paxi_tpu/protocols/*/sim*.py",
    "paxi_tpu/trace/demo.py",
)

def _is_step_name(name: str) -> bool:
    """Transition functions: ``step``, ``_step``, and ``*_step``
    variants (seeded twins / fixtures follow the same convention)."""
    return name in ("step", "_step") or name.endswith("_step")


def _is_m_key(node: ast.expr) -> Optional[bool]:
    """True/False for a constant-string dict key; None when dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.startswith("m_")
    return None


class _Taint(ast.NodeVisitor):
    """Expression-taint query against a set of tainted names."""

    def __init__(self, tainted: Set[str]):
        self.tainted = tainted
        self.hit = False

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.tainted:
            self.hit = True

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # the taint SOURCE: state["m_..."] (any base expression)
        if _is_m_key(node.slice) is True:
            self.hit = True
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        # quarantine: values under m_ keys do not taint the dict
        for k, v in zip(node.keys, node.values):
            if k is not None and _is_m_key(k) is True:
                continue
            if k is None:                      # **expansion
                self.visit(v)
                continue
            self.visit(k)
            self.visit(v)

    def visit_Call(self, node: ast.Call) -> None:
        if (isinstance(node.func, ast.Name) and node.func.id == "dict"):
            for a in node.args:
                self.visit(a)
            for kw in node.keywords:
                if kw.arg is not None and kw.arg.startswith("m_"):
                    continue                   # quarantined kwarg
                self.visit(kw.value)
            return
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:  # nested defs: opaque
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _tainted(expr: ast.expr, tainted: Set[str]) -> bool:
    t = _Taint(tainted)
    t.visit(expr)
    return t.hit


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    return []


class _StepWalker:
    """Forward taint walk over one step function's body."""

    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.tainted: Set[str] = set()
        self.reported: Set[tuple] = set()

    def _flag(self, code: str, node: ast.AST, msg: str) -> None:
        key = (node.lineno, code)
        if key in self.reported:
            return
        self.reported.add(key)
        self.out.append(Violation(
            rule=RULE, code=code, path=self.rel, line=node.lineno,
            col=node.col_offset, message=msg))

    def _check_dicts(self, expr: ast.expr) -> None:
        """PXM101 at every dict construction with a tainted non-m_
        value, anywhere inside ``expr``."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    mk = None if k is None else _is_m_key(k)
                    if mk is not True and _tainted(v, self.tainted):
                        key = (k.value if isinstance(k, ast.Constant)
                               else "<dynamic>")
                        self._flag(
                            "PXM101", v,
                            f"measurement-plane value stored under "
                            f"non-m_ key {key!r}: protocol state/"
                            f"messages must never depend on m_ planes")
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id == "dict"):
                for kw in node.keywords:
                    if kw.arg is not None and kw.arg.startswith("m_"):
                        continue
                    if _tainted(kw.value, self.tainted):
                        self._flag(
                            "PXM101", kw.value,
                            f"measurement-plane value stored under "
                            f"non-m_ key {kw.arg or '**'!r}: protocol "
                            f"state/messages must never depend on m_ "
                            f"planes")

    def _walk(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                        # nested defs: opaque
            if isinstance(stmt, ast.Assign):
                self._check_dicts(stmt.value)
                names = [n for t in stmt.targets
                         for n in _target_names(t)]
                if _tainted(stmt.value, self.tainted):
                    self.tainted.update(names)
                else:
                    self.tainted.difference_update(names)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._check_dicts(stmt.value)
                if _tainted(stmt.value, self.tainted):
                    self.tainted.update(_target_names(stmt.target))
                continue
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._check_dicts(stmt.value)
                names = _target_names(stmt.target)
                if _tainted(stmt.value, self.tainted):
                    self.tainted.update(names)
                else:
                    self.tainted.difference_update(names)
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._check_dicts(stmt.value)
                    if _tainted(stmt.value, self.tainted):
                        self._flag(
                            "PXM102", stmt,
                            "measurement-plane value escapes through "
                            "return outside an m_-keyed dict entry")
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                # two passes for wrap-around taint (asyncflow precedent)
                self._walk(stmt.body)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, ast.If):
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, ast.With):
                self._walk(stmt.body)
                continue
            if isinstance(stmt, ast.Expr):
                self._check_dicts(stmt.value)
                continue
        # other statement kinds carry no interesting dataflow here


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (files if files is not None
                 else astutil.iter_py(root, TARGETS)):
        try:
            tree = ast.parse(Path(path).read_text())
        except (OSError, SyntaxError):
            continue
        rel = astutil.rel(Path(path), root)
        for node in ast.walk(tree):
            if (isinstance(node, ast.FunctionDef)
                    and _is_step_name(node.name)):
                walker = _StepWalker(rel, out)
                # two passes over the whole body: a later stamp into a
                # name read earlier (scan-carry style) still taints
                walker._walk(node.body)
                walker._walk(node.body)
    return sorted(out, key=lambda v: (v.path, v.line, v.code))
