"""Replay-determinism rule family (PXD14x).

Every headline claim of the replay stack — byte-identical trace
replay, deterministic span timelines, hunt witness reproduction —
rests on one discipline: replay-reachable host code derives time only
from the fabric-resolved logical clock and ordering only from
deterministic structures.  The documented resolution pattern is
``host/node.py``'s "resolved fabric under replay": a component holds
``self.fabric = fabric if fabric is not None else current_fabric()``
and every time read goes through the ``obs/collect.py`` ``now()``
shape::

    if self.fabric is not None:
        return self.fabric.clock()      # logical step under replay
    return time.perf_counter()          # live serving

This family is an interprocedural taint proof of that discipline over
``host/``, ``shard/``, ``switchnet/`` and ``obs/``:

**Taint roots**

- wall clocks: ``time.time`` / ``time.monotonic`` / ``time.perf_counter``
  (+ ``_ns`` variants, naive ``datetime.now``), through ``import``
  aliases, plus any *clock helper* — a function of the analyzed set
  that returns a raw clock value on a replay-reachable path (found by
  a pre-pass; call sites of such helpers are roots, the
  interprocedural step, resolved over the shared ProjectIndex);
- unordered iteration: ``for x in set(...)`` / set literals /
  ``.union()``-family results / comprehensions over them (dict/key
  iteration is insertion-ordered in the supported Pythons and does
  not taint; ``sorted(...)`` launders);
- ambient reads: ``os.environ`` / ``os.getenv`` / module-level
  ``random.*`` calls / unseeded ``random.Random()`` / ``uuid.uuid4``
  / ``secrets.*``.  A *seeded* ``random.Random(seed)`` is clean.

**Sinks** (where host state meets the replayed world)

- wire-frame emission: constructor arguments of any
  ``@register_message`` class or ``core/command.py`` wire type (the
  sink model comes from :func:`project.message_fields`), and stores
  into stamp-named fields (``timestamp``/``t0``/``t1``/``seq``/
  ``sess``/``epoch``) — sequencer stamps and span timestamps included;
- control flow: a tainted ``if``/``while``/``assert``/ternary test —
  fault-window comparisons and quorum decisions alike;
- state stamps: a tainted value stored into instance state
  (``self.x = ...`` / ``self.x[k] = ...``) — the fault-window
  ``*_until`` registers are the canonical case.

**Sanctioning** — the fabric-resolution discipline itself: statements
dominated by a "no fabric attached" guard (``flow.live_only`` over
``flow.dominating_guards``) are the live serving path replay never
reaches, including the early-return and short-circuit spellings.
Clock reads that feed only local measurement (metrics latency
observation) hit no sink and do not flag.

Checks:

- **PXD141** wall-clock taint reaches a sink on a replay-reachable
  path (frame field, fault-window/branch decision, state stamp);
- **PXD142** unordered-iteration taint reaches frame emission or a
  branch decision;
- **PXD143** ambient env/RNG read on a replay-reachable path (flagged
  at the root: the read itself is the nondeterminism).

Genuinely live-only code that the guard proof cannot see (open-loop
benchmark pacing, the fault-injection setters consulted only when no
fabric is attached) is baselined with reasons in
``analysis/baseline.toml`` — the contract is that the baseline only
shrinks.
"""

from __future__ import annotations

import ast
import builtins
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paxi_tpu.analysis import astutil, flow, project
from paxi_tpu.analysis.model import Violation

RULE = "replay-determinism"

TARGETS = (
    "paxi_tpu/host/*.py",
    "paxi_tpu/shard/*.py",
    "paxi_tpu/switchnet/*.py",
    "paxi_tpu/obs/*.py",
)

# canonical dotted names of raw wall-clock reads
CLOCK_CALLS = frozenset((
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
))

# canonical dotted names of ambient environment/entropy reads
AMBIENT_CALLS = frozenset((
    "os.getenv", "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice",
))

# stdlib modules whose import aliases the canonicalizer tracks
_STDLIB_MODULES = ("time", "datetime", "os", "random", "uuid", "secrets")

# frame/span/sequencer stamp fields: a tainted store into one of these
# on any object is frame emission even outside a constructor call
STAMP_ATTRS = ("timestamp", "t0", "t1", "seq", "sess", "epoch")

# set-producing method names whose results iterate in hash order
_SET_METHODS = ("union", "intersection", "difference",
                "symmetric_difference")

_CODE_OF = {"clock": "PXD141", "order": "PXD142"}

_BUILTIN_NAMES = frozenset(dir(builtins))


def _module_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> canonical dotted prefix for the tracked stdlib
    modules (``import time as t`` / ``from time import monotonic``)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] in _STDLIB_MODULES:
                    out[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module in _STDLIB_MODULES:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _unordered(expr: ast.expr) -> bool:
    """Does ``expr`` produce a hash-ordered iterable?  ``set``/
    ``frozenset`` constructors, set literals/comprehensions and the
    ``.union()`` method family; ``sorted(...)`` never matches."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) \
                and expr.func.id in ("set", "frozenset"):
            return True
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in _SET_METHODS:
            return True
        if isinstance(expr.func, ast.BinOp):
            return False
    return False


class _FnWalker:
    """Forward kind-tracking taint walk over one function's body."""

    def __init__(self, rel: str, aliases: Dict[str, str],
                 frames: Dict[str, List[str]], helpers: Set[str],
                 guards: Dict[int, flow.GuardSet],
                 out: Optional[List[Violation]]):
        self.rel = rel
        self.aliases = aliases
        self.frames = frames
        self.helpers = helpers
        self.guards = guards
        self.out = out                      # None: scout (helper) mode
        self.tainted: Dict[str, str] = {}
        self.reported: Set[tuple] = set()
        self.clock_return = False           # scout-mode result

    # -- canonicalization / roots ----------------------------------------
    def _canon(self, expr: ast.AST) -> Optional[str]:
        dotted = astutil.dotted_name(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        base = self.aliases.get(head)
        if base is not None:
            return base + ("." + rest if rest else "")
        return dotted

    def _root_kind(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            c = self._canon(node.func)
            if c in CLOCK_CALLS:
                return "clock"
            if c in AMBIENT_CALLS:
                return "ambient"
            if c == "random.Random":
                # unseeded only: Random(seed) is the sanctioned form
                return "ambient" if not node.args and not node.keywords \
                    else None
            if c is not None and (c.startswith("random.")
                                  or c.startswith("secrets.")):
                return "ambient"
            if c is not None and c.split(".")[-1] in self.helpers:
                # interprocedural helper root; a BARE name shared with
                # a builtin (e.g. a method named `next`) resolves to
                # the builtin at bare call sites, not the helper
                if "." in c or c not in _BUILTIN_NAMES:
                    return "clock"
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("list", "tuple", "iter") \
                    and len(node.args) == 1 and _unordered(node.args[0]):
                return "order"
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            if self._canon(node) == "os.environ":
                return "ambient"
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                               ast.SetComp)):
            if any(_unordered(g.iter) for g in node.generators):
                return "order"
        return None

    # -- reporting --------------------------------------------------------
    def _flag(self, code: str, node: ast.AST, msg: str) -> None:
        if self.out is None:
            return
        key = (node.lineno, code)
        if key in self.reported:
            return
        self.reported.add(key)
        self.out.append(Violation(
            rule=RULE, code=code, path=self.rel, line=node.lineno,
            col=node.col_offset, message=msg))

    # -- expression scanning ----------------------------------------------
    def _scan(self, expr: ast.expr) -> Set[str]:
        """Taint kinds of ``expr``; ambient roots flag in place (the
        read is the violation), respecting short-circuit sanctioning."""
        hits: List[Tuple[ast.AST, str]] = []

        def root_of(node: ast.AST) -> Optional[str]:
            kind = self._root_kind(node)
            if kind is not None:
                hits.append((node, kind))
            return kind

        kinds = flow.expr_taint(expr, self.tainted, root_of)
        for node, kind in hits:
            if kind == "ambient":
                self._flag(
                    "PXD143", node,
                    "ambient env/RNG read on a replay-reachable path: "
                    "seed it, resolve it at construction, or gate it "
                    "on `fabric is None`")
        return kinds

    def _frame_sinks(self, expr: ast.expr) -> None:
        """PXD141/142 at every wire-frame constructor receiving a
        tainted argument anywhere inside ``expr``."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = (astutil.dotted_name(node.func) or "").split(".")[-1]
            if name not in self.frames:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                kinds = flow.expr_taint(arg, self.tainted,
                                        self._root_kind)
                for kind in ("clock", "order"):
                    if kind in kinds:
                        what = ("wall-clock value" if kind == "clock"
                                else "hash-ordered iteration value")
                        self._flag(
                            _CODE_OF[kind], arg,
                            f"{what} flows into wire frame "
                            f"{name}(...): replay-visible fields must "
                            f"derive from the resolved fabric clock "
                            f"(spans.now() / fabric.clock())")

    def _sinks_in(self, expr: ast.expr) -> Set[str]:
        kinds = self._scan(expr)
        self._frame_sinks(expr)
        return kinds

    # -- statement sinks --------------------------------------------------
    @staticmethod
    def _state_target(target: ast.expr) -> Optional[str]:
        """'state' for instance-state stores, 'stamp' for stamp-field
        stores on any object, None otherwise."""
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute):
            if isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                return "state"
            if base.attr in STAMP_ATTRS:
                return "stamp"
        return None

    def _flag_store(self, kind: str, target_kind: str,
                    stmt: ast.stmt) -> None:
        what = ("wall-clock value" if kind == "clock"
                else "hash-ordered iteration value")
        where = ("instance state (a replay-divergent register, e.g. a "
                 "fault window)" if target_kind == "state"
                 else "a stamp field (frame/span/sequencer surface)")
        self._flag(_CODE_OF[kind], stmt,
                   f"{what} stored into {where}: derive it from the "
                   f"resolved fabric clock or gate it on "
                   f"`fabric is None`")

    def _flag_branch(self, kind: str, node: ast.AST) -> None:
        what = ("wall-clock value" if kind == "clock"
                else "hash-ordered iteration value")
        self._flag(_CODE_OF[kind], node,
                   f"{what} steers replay-reachable control flow "
                   f"(fault-window comparison / protocol decision): "
                   f"use the resolved fabric clock or gate on "
                   f"`fabric is None`")

    # -- the walk ---------------------------------------------------------
    def _live(self, stmt: ast.stmt) -> bool:
        guards = self.guards.get(id(stmt))
        return guards is not None and flow.live_only(guards)

    def _walk(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                    # nested defs: opaque
            if self._live(stmt):
                continue                    # the live serving path
            if isinstance(stmt, ast.Expr):
                self._sinks_in(stmt.value)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                value = stmt.value
                if value is None:
                    continue
                kinds = self._sinks_in(value)
                if not kinds and isinstance(value, (ast.Attribute,
                                                    ast.Name)):
                    if self._canon(value) in CLOCK_CALLS:
                        kinds = {"clock"}   # clock-function alias
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                kind = ("clock" if "clock" in kinds
                        else "order" if "order" in kinds else None)
                names = [n for t in targets
                         for n in _target_names(t)]
                if kind is not None:
                    for t in targets:
                        tk = self._state_target(t)
                        if tk is not None:
                            self._flag_store(kind, tk, stmt)
                    self.tainted.update({n: kind for n in names})
                else:
                    if not isinstance(stmt, ast.AugAssign):
                        for n in names:
                            self.tainted.pop(n, None)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                kinds = self._sinks_in(stmt.test)
                for kind in ("clock", "order"):
                    if kind in kinds:
                        self._flag_branch(kind, stmt.test)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, ast.Assert):
                kinds = self._scan(stmt.test)
                for kind in ("clock", "order"):
                    if kind in kinds:
                        self._flag_branch(kind, stmt.test)
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    kinds = self._sinks_in(stmt.value)
                    if "clock" in kinds:
                        self.clock_return = True
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._sinks_in(stmt.iter)
                if _unordered(stmt.iter):
                    self.tainted.update(
                        {n: "order" for n in _target_names(stmt.target)})
                # two passes for wrap-around taint (measure precedent)
                self._walk(stmt.body)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._sinks_in(item.context_expr)
                self._walk(stmt.body)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(stmt.body)
                for h in stmt.handlers:
                    self._walk(h.body)
                self._walk(stmt.orelse)
                self._walk(stmt.finalbody)
                continue
        # other statement kinds carry no interesting dataflow here


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    return []


def _parse_all(root: Path, paths: Sequence[Path]
               ) -> List[Tuple[str, ast.Module, Dict[str, str]]]:
    out = []
    for path in paths:
        try:
            tree = ast.parse(Path(path).read_text())
        except (OSError, SyntaxError):
            continue
        out.append((astutil.rel(Path(path).resolve(), root), tree,
                    _module_aliases(tree)))
    return out


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _clock_helpers(mods, frames) -> Set[str]:
    """Names of analyzed functions that return a raw clock value on a
    replay-reachable path — their call sites become taint roots.  Two
    rounds close helper-of-helper chains one level deep; the sanctioned
    ``now()`` resolver never qualifies because its raw-clock return is
    live-only dominated."""
    helpers: Set[str] = set()
    for _ in range(2):
        found: Set[str] = set(helpers)
        for rel, tree, aliases in mods:
            for fn in _functions(tree):
                scout = _FnWalker(rel, aliases, frames, helpers,
                                  flow.dominating_guards(fn), out=None)
                scout._walk(fn.body)
                scout._walk(fn.body)
                if scout.clock_return:
                    found.add(fn.name)
        if found == helpers:
            break
        helpers = found
    return helpers


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    paths = list(files if files is not None
                 else astutil.iter_py(root, TARGETS))
    index = project.shared_index(root, extra_files=files)
    frames = project.message_fields(index)
    mods = _parse_all(root, paths)
    helpers = _clock_helpers(mods, frames)
    out: List[Violation] = []
    for rel, tree, aliases in mods:
        for fn in _functions(tree):
            walker = _FnWalker(rel, aliases, frames, helpers,
                               flow.dominating_guards(fn), out)
            # two passes over the whole body: a later clock bind read
            # earlier still taints (measure precedent)
            walker._walk(fn.body)
            walker._walk(fn.body)
    return sorted(out, key=lambda v: (v.path, v.line, v.code))
