"""Trace-map coverage rule family (PXT3xx).

Cross-runtime replay (trace/host.py) projects a sim trace's
per-mailbox fault schedule onto host ``Socket`` directives through the
protocol's ``TRACE_MSG_MAP`` (sim mailbox name -> host message class).
Every *unmapped* mailbox degrades to a coarse time-window drop — the
projection still runs, but the witness loses its occurrence-indexed
precision, which is exactly the ROADMAP divergence-hunting item.  A
*missing* map disables the projection entirely.

This rule closes the loop statically, without importing jax or any
protocol module:

- the protocol registry (``protocols/__init__.py``) is parsed for the
  ``_SIM_MODULES`` / ``_HOST_MODULES`` dict literals, applying the same
  variant-derivation rule as ``trace/host.py:trace_msg_map`` (a sim
  protocol not in ``_HOST_MODULES`` projects through its base
  protocol's host module — e.g. ``paxos_pg`` and
  ``wankeeper_nofloor``);
- the sim module's ``mailbox_spec`` supplies the mailbox names (dict
  literal keys — constant strings even where the field tuples are
  computed);
- the host module supplies ``TRACE_MSG_MAP`` and its
  ``@register_message`` classes.

Checks:

- **PXT301** a protocol with both runtimes whose host module exports
  no ``TRACE_MSG_MAP``
- **PXT302** a sim mailbox absent from the map's keys (projection
  falls back to coarse windows for that message type)
- **PXT303** a map key that names no sim mailbox (stale after a
  kernel refactor — it will never match a recorded fault)
- **PXT304** a map value that names no ``@register_message`` class in
  the host module (``Socket.drop_next`` matches on
  ``type(msg).__name__``, so a typo never fires)
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.model import Violation

RULE = "trace-map"

REGISTRY = "paxi_tpu/protocols/__init__.py"
MAP_NAME = "TRACE_MSG_MAP"


def _module_to_path(module: str, root: Path) -> Path:
    return root / (module.replace(".", "/") + ".py")


def _twin_of(root: Path, module: str) -> Optional[str]:
    """A host twin module's ``TWIN_OF = "pkg.base_host"`` marker: the
    module subclasses its base replica to seed a bug (e.g.
    protocols/bpaxos/noread.py) and declares that its message classes,
    maps and state vocabulary live in the base — so the map rules
    analyze the base module instead of re-litigating the shim."""
    path = _module_to_path(module, root)
    if not path.exists():
        return None
    tree, _ = astutil.parse_file(path)
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "TWIN_OF" \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            return node.value.value
    return None


def registry_pairs(root: Path) -> List[Tuple[str, str, str]]:
    """(protocol, sim module, host module) for every sim protocol whose
    trace projection resolves a host module — base protocols and
    variants alike, deduplicated on (sim module, host module).
    Host modules carrying a ``TWIN_OF`` marker resolve to their base
    module first (seeded-bug twins dedup onto the base pair)."""
    tree, _ = astutil.parse_file(root / REGISTRY)
    sims = astutil.parse_module_dict(tree, "_SIM_MODULES")
    hosts = astutil.parse_module_dict(tree, "_HOST_MODULES")
    if sims is None or hosts is None:
        raise ValueError(f"{REGISTRY}: _SIM_MODULES/_HOST_MODULES dict "
                         "literals not found — registry layout changed?")
    sim_map = {k: v for k, v, _, _ in astutil.str_dict_items(sims)
               if v is not None}
    host_map = {k: v for k, v, _, _ in astutil.str_dict_items(hosts)
                if v is not None}
    out: List[Tuple[str, str, str]] = []
    seen = set()
    for proto, sim_mod in sim_map.items():
        sim_mod = sim_mod.partition(":")[0]
        base = proto
        if base not in host_map:
            # trace/host.py:trace_msg_map's variant rule: derive the
            # base protocol from the sim module's package name
            parts = sim_mod.rsplit(".", 2)
            base = parts[-2] if len(parts) >= 2 else proto
        host_mod = host_map.get(base)
        if host_mod is None:
            continue   # sim-only protocol (e.g. fragile_counter)
        host_mod = _twin_of(root, host_mod) or host_mod
        key = (sim_mod, host_mod)
        if key not in seen:
            seen.add(key)
            out.append((proto, sim_mod, host_mod))
    return sorted(out, key=lambda t: t[0])


def sim_mailboxes(sim_path: Path) -> List[Tuple[str, int]]:
    """(mailbox name, line) from the sim module's ``mailbox_spec``."""
    tree, _ = astutil.parse_file(sim_path)
    for node in tree.body:
        if isinstance(node, astutil.FuncNode) and \
                node.name == "mailbox_spec":
            return astutil.string_keys_of_returned_dicts(node)
    return []


def host_map(host_path: Path) -> Optional[Tuple[Dict[str, str], int]]:
    """(TRACE_MSG_MAP as dict, its line) or None when absent."""
    tree, _ = astutil.parse_file(host_path)
    d = astutil.parse_module_dict(tree, MAP_NAME)
    if d is None:
        return None
    out = {}
    for key, val, _, _ in astutil.str_dict_items(d):
        out[key] = val or ""
    return out, d.lineno


def host_message_classes(host_path: Path) -> set:
    tree, _ = astutil.parse_file(host_path)
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            decs = astutil.decorator_names(node)
            if any(d.split(".")[-1] == "register_message" for d in decs):
                out.add(node.name)
    return out


def check_pair(protocol: str, sim_path: Path, host_path: Path,
               root: Path) -> List[Violation]:
    rel_host = astutil.rel(host_path, root)
    out: List[Violation] = []
    boxes = sim_mailboxes(sim_path)
    if not boxes:
        return out   # no mailbox_spec — not a sim protocol module
    found = host_map(host_path)
    if found is None:
        out.append(Violation(
            rule=RULE, code="PXT301", path=rel_host, line=1, col=0,
            message=f"protocol `{protocol}` has a sim twin "
                    f"({astutil.rel(sim_path, root)}) but its host "
                    f"module exports no {MAP_NAME} — sim witnesses "
                    "cannot project onto host fault directives"))
        return out
    mapping, line = found
    box_names = {name for name, _ in boxes}
    for name, bline in boxes:
        if name not in mapping:
            out.append(Violation(
                rule=RULE, code="PXT302", path=rel_host, line=line, col=0,
                message=f"sim mailbox `{name}` of protocol `{protocol}` "
                        f"is not covered by {MAP_NAME} — its recorded "
                        "faults degrade to coarse drop windows"))
    classes = host_message_classes(host_path)
    for key, val in mapping.items():
        if key not in box_names:
            out.append(Violation(
                rule=RULE, code="PXT303", path=rel_host, line=line, col=0,
                message=f"{MAP_NAME} key `{key}` names no sim mailbox of "
                        f"protocol `{protocol}` (stale after a kernel "
                        "refactor?)"))
        if val not in classes:
            out.append(Violation(
                rule=RULE, code="PXT304", path=rel_host, line=line, col=0,
                message=f"{MAP_NAME} value `{val}` (key `{key}`) names no "
                        "@register_message class in the host module — "
                        "drop_next matches type names, a typo never "
                        "fires"))
    return out


def _matches(path: Path, dirs: List[Path], files: set) -> bool:
    rp = path.resolve()
    return rp in files or any(str(rp).startswith(str(d) + "/")
                              for d in dirs)


def analyzed_pairs(root: Path,
                   restrict: Optional[Sequence[Path]] = None
                   ) -> List[Tuple[str, Path, Path]]:
    """(protocol, sim path, host path) for every pair this rule will
    analyze.  ``restrict`` (files or directories) keeps a pair when its
    sim OR host module falls inside — so both ``lint
    paxi_tpu/protocols`` and ``lint .../wankeeper/host.py`` exercise
    the coverage rule rather than silently skipping it."""
    dirs = [p.resolve() for p in restrict or [] if p.is_dir()]
    files = {p.resolve() for p in restrict or [] if p.is_file()}
    out: List[Tuple[str, Path, Path]] = []
    for protocol, sim_mod, host_mod in registry_pairs(root):
        sim_path = _module_to_path(sim_mod, root)
        host_path = _module_to_path(host_mod, root)
        if not sim_path.exists() or not host_path.exists():
            continue
        if restrict is not None and not (
                _matches(sim_path, dirs, files)
                or _matches(host_path, dirs, files)):
            continue
        out.append((protocol, sim_path, host_path))
    return out


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    """``files``, when given, restricts the check to pairs whose sim or
    host module is in the set (CLI ``-paths`` filtering; directories
    match everything beneath them)."""
    out: List[Violation] = []
    for protocol, sim_path, host_path in analyzed_pairs(root, files):
        out.extend(check_pair(protocol, sim_path, host_path, root))
    return out
