"""Shared violation/reporting model for paxi-lint (paxi_tpu/analysis).

Every rule family emits :class:`Violation` records; the engine
(``__init__.run_lint``) filters them through two suppression layers:

- **inline**: a ``# paxi-lint: disable=CODE[,CODE...]`` comment on the
  flagged line (or ``disable-all``) silences that line only;
- **baseline**: ``analysis/baseline.toml`` records *intentional*
  exceptions — places where a rule is right in general but wrong about
  one specific construct — so the repo-wide lint can be kept at zero
  without weakening any rule.  Each entry must carry a ``reason``.

The baseline format is a TOML subset (``[[suppress]]`` tables of
string/int scalars) parsed by :func:`load_baseline` — the container
runs Python 3.10, which has no stdlib ``tomllib``, and paxi-lint must
not grow third-party dependencies.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Violation:
    """One finding: ``path:line:col CODE message`` (path repo-relative)."""

    rule: str      # family name, e.g. "kernel-purity"
    code: str      # stable id, e.g. "PXK102"
    path: str      # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col} {self.code} {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "code": self.code, "path": self.path,
                "line": self.line, "col": self.col, "message": self.message}


@dataclass
class Suppression:
    """One baseline entry.  ``code`` matches the violation code exactly
    (or a whole family via its ``PXK``-style prefix); ``path`` matches
    the repo-relative path exactly; ``match``, when set, must be a
    substring of the violation message.  ``used`` tracks whether any
    violation consumed the entry, so stale baseline rows surface."""

    code: str
    path: str
    match: str = ""
    reason: str = ""
    used: bool = field(default=False, compare=False)

    def matches(self, v: Violation) -> bool:
        if v.path != self.path:
            return False
        if not (v.code == self.code or v.code.startswith(self.code)):
            return False
        return self.match in v.message


@dataclass
class LintReport:
    violations: List[Violation]          # unsuppressed, the lint's verdict
    suppressed: List[Tuple[Violation, str]]   # (violation, why)
    unused_baseline: List[Suppression]
    checked_files: int = 0
    # per-family wall time in seconds, insertion-ordered by run order
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self, verbose: bool = False) -> str:
        lines = [v.render() for v in
                 sorted(self.violations, key=lambda v: (v.path, v.line,
                                                        v.col, v.code))]
        if verbose:
            for v, why in self.suppressed:
                lines.append(f"# suppressed ({why}): {v.render()}")
        for s in self.unused_baseline:
            lines.append(f"# warning: unused baseline entry "
                         f"{s.code} {s.path} match={s.match!r}")
        tail = (f"{len(self.violations)} violation(s), "
                f"{len(self.suppressed)} suppressed, "
                f"{self.checked_files} file(s) checked")
        return "\n".join(lines + [tail])

    def to_json(self) -> str:
        return json.dumps({
            "ok": self.ok,
            "violations": [v.to_json() for v in self.violations],
            "suppressed": [dict(v.to_json(), suppressed_by=why)
                           for v, why in self.suppressed],
            "unused_baseline": [
                {"code": s.code, "path": s.path, "match": s.match}
                for s in self.unused_baseline],
            "checked_files": self.checked_files,
            "timings": {k: round(t, 4)
                        for k, t in self.timings.items()},
        }, indent=2)

    def to_sarif(self) -> str:
        """SARIF 2.1.0 export, one run: kept findings as ``error``
        results, suppressed ones as ``note`` results carrying a
        ``suppressions`` record — the shape CI annotators ingest."""
        def _result(v: Violation, level: str,
                    why: Optional[str] = None) -> dict:
            out = {
                "ruleId": v.code,
                "level": level,
                "message": {"text": f"[{v.rule}] {v.message}"},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": v.path},
                        "region": {"startLine": v.line,
                                   "startColumn": v.col + 1},
                    },
                }],
            }
            if why is not None:
                out["suppressions"] = [{"kind": "inSource"
                                        if why == "inline"
                                        else "external",
                                        "justification": why}]
            return out

        everything = ([(v, "error", None) for v in self.violations]
                      + [(v, "note", why)
                         for v, why in self.suppressed])
        rules = sorted({(v.code, v.rule) for v, _, _ in everything})
        return json.dumps({
            "$schema": ("https://json.schemastore.org/"
                        "sarif-2.1.0.json"),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": {
                    "name": "paxi-lint",
                    "informationUri":
                        "https://example.invalid/paxi_tpu/analysis",
                    "rules": [{"id": code,
                               "shortDescription": {"text": family}}
                              for code, family in rules],
                }},
                "results": [_result(v, level, why)
                            for v, level, why in everything],
            }],
        }, indent=2)


# ---- baseline (mini-TOML) -----------------------------------------------
_KV_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_-]*)\s*=\s*(.+?)\s*$")


def _parse_scalar(raw: str, path: Path, lineno: int):
    if raw[:1] in ('"', "'"):
        quote = raw[0]
        end = raw.find(quote, 1)
        tail = raw[end + 1:].strip() if end != -1 else None
        # a trailing `# comment` after the closing quote is valid TOML
        if end != -1 and (not tail or tail.startswith("#")):
            return raw[1:end]
        raise ValueError(f"{path}:{lineno}: malformed string {raw!r}")
    if re.fullmatch(r"-?[0-9]+", raw):
        return int(raw)
    if raw in ("true", "false"):
        return raw == "true"
    raise ValueError(f"{path}:{lineno}: unsupported TOML value {raw!r} "
                     "(baseline.toml uses quoted strings only)")


def load_baseline(path: Path) -> List[Suppression]:
    """Parse the ``[[suppress]]`` tables of a baseline file.  Subset
    grammar: comments, blank lines, ``[[suppress]]`` headers, and
    ``key = "value"`` scalar pairs — enough for a suppression list,
    with no tomllib dependency (Python 3.10 container)."""
    if not path.exists():
        return []
    entries: List[Dict] = []
    current: Optional[Dict] = None
    for lineno, line in enumerate(path.read_text().splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped == "[[suppress]]":
            current = {}
            entries.append(current)
            continue
        if stripped.startswith("["):
            raise ValueError(f"{path}:{lineno}: unsupported table "
                             f"{stripped!r} (only [[suppress]] is known)")
        m = _KV_RE.match(stripped)
        if m is None:
            raise ValueError(f"{path}:{lineno}: cannot parse {stripped!r}")
        if current is None:
            raise ValueError(f"{path}:{lineno}: key outside [[suppress]]")
        # strip trailing comments outside quotes
        raw = m.group(2)
        if "#" in raw and not (raw.startswith('"') or raw.startswith("'")):
            raw = raw.split("#", 1)[0].strip()
        current[m.group(1)] = _parse_scalar(raw, path, lineno)
    out = []
    for e in entries:
        if "code" not in e or "path" not in e:
            raise ValueError(f"{path}: [[suppress]] entry needs at least "
                             f"'code' and 'path': {e}")
        if not str(e.get("reason", "")).strip():
            raise ValueError(f"{path}: [[suppress]] entry for {e['code']} "
                             f"{e['path']} must carry a 'reason'")
        out.append(Suppression(code=str(e["code"]), path=str(e["path"]),
                               match=str(e.get("match", "")),
                               reason=str(e.get("reason", ""))))
    return out


# ---- inline suppressions -------------------------------------------------
_INLINE_RE = re.compile(r"#\s*paxi-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


def inline_disables(source: str) -> Dict[int, set]:
    """``line -> {codes}`` for ``# paxi-lint: disable=PXK102[,...]``
    comments; the special token ``all`` silences every rule on the
    line."""
    out: Dict[int, set] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _INLINE_RE.search(line)
        if m:
            out[i] = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return out


def apply_suppressions(
        violations: Iterable[Violation],
        baseline: Sequence[Suppression],
        inline: Dict[str, Dict[int, set]],
) -> Tuple[List[Violation], List[Tuple[Violation, str]]]:
    """Split raw findings into (kept, suppressed-with-reason).
    ``inline`` maps repo-relative path -> line -> codes."""
    kept: List[Violation] = []
    dropped: List[Tuple[Violation, str]] = []
    for v in violations:
        codes = inline.get(v.path, {}).get(v.line, set())
        if "all" in codes or v.code in codes:
            dropped.append((v, "inline"))
            continue
        hit = next((s for s in baseline if s.matches(v)), None)
        if hit is not None:
            hit.used = True
            dropped.append((v, f"baseline: {hit.reason}"))
            continue
        kept.append(v)
    return kept, dropped
