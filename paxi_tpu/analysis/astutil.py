"""AST plumbing shared by the paxi-lint rule families.

Everything here is *purely static*: rules parse source files and never
import the modules under analysis, so the linter runs in milliseconds,
needs no jax, and can analyze broken or heavyweight modules safely.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple


def parse_file(path: Path) -> Tuple[ast.Module, str]:
    source = path.read_text()
    return ast.parse(source, filename=str(path)), source


def rel(path: Path, root: Path) -> str:
    return path.relative_to(root).as_posix()


def iter_py(root: Path, patterns: Sequence[str]) -> Iterator[Path]:
    """Sorted union of glob matches under ``root`` (deterministic
    reports)."""
    seen = set()
    for pat in patterns:
        for p in root.glob(pat):
            if p.suffix == ".py" and p not in seen:
                seen.add(p)
    yield from sorted(seen)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def collect_functions(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """Every function/async def in the module (any nesting depth),
    keyed by bare name.  Name collisions keep all defs — reachability
    over-approximates, which for a linter errs toward sensitivity."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, FuncNode):
            out.setdefault(node.name, []).append(node)
    return out


def referenced_names(fn: ast.AST) -> set:
    """Bare names referenced inside a function body (calls, aliases,
    partial() arguments alike) — the edge relation for reachability."""
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            names.add(node.id)
    return names


def reachable_functions(roots: Sequence[ast.AST],
                        funcs: Dict[str, List[ast.AST]]) -> List[ast.AST]:
    """Closure of ``roots`` over the references-a-function-name
    relation, module-local.  Lambdas count as anonymous members of the
    function they appear in (ast.walk descends into them)."""
    seen: List[ast.AST] = []
    seen_ids = set()
    work = list(roots)
    while work:
        fn = work.pop()
        if id(fn) in seen_ids:
            continue
        seen_ids.add(id(fn))
        seen.append(fn)
        for name in referenced_names(fn):
            for target in funcs.get(name, []):
                if id(target) not in seen_ids:
                    work.append(target)
    return seen


def parse_module_dict(tree: ast.Module, name: str) -> Optional[ast.Dict]:
    """The dict literal bound to a module-level ``name = {...}``."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == name
                        and isinstance(node.value, ast.Dict)):
                    return node.value
        elif (isinstance(node, ast.AnnAssign)
              and isinstance(node.target, ast.Name)
              and node.target.id == name
              and isinstance(node.value, ast.Dict)):
            return node.value
    return None


def str_dict_items(d: ast.Dict) -> List[Tuple[str, Optional[str],
                                              int, int]]:
    """(key, value-if-string, line, col) for every constant-string key
    of a dict literal; non-string values come back as None."""
    out = []
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            val = (v.value if isinstance(v, ast.Constant)
                   and isinstance(v.value, str) else None)
            out.append((k.value, val, k.lineno, k.col_offset))
    return out


def decorator_names(node: ast.AST) -> List[str]:
    out = []
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name:
            out.append(name)
        # @functools.partial(jax.jit, ...) — surface the wrapped callee
        if isinstance(dec, ast.Call) and name and \
                name.split(".")[-1] == "partial" and dec.args:
            inner = dotted_name(dec.args[0])
            if inner:
                out.append(inner)
    return out


def string_keys_of_returned_dicts(fn: ast.AST) -> List[Tuple[str, int]]:
    """Constant-string keys of every dict literal inside ``fn`` —
    how the trace-map rule reads a sim module's ``mailbox_spec``
    without executing it (specs are dict literals with computed
    values but constant keys)."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.append((k.value, k.lineno))
    return out
