"""Span-isolation rule family (PXO13x).

The causal tracing layer (paxi_tpu/obs) instruments protocol host code
through the SpanCollector's **statement tier**: ``self.spans.open(key,
kind, ctx)`` / ``close(key)`` / ``close_group(prefix)`` are bare
expression statements that return ``None`` and no-op when the command
is unsampled.  The architecture promises that spans are **write-only
from protocol code**: a handler may emit span opens/closes, but no
span value — the collector itself, an open Span, its count — may ever
feed a protocol decision.  Otherwise "turning sampling on" could
change commit behavior, and the fabric-deterministic replay would mask
exactly the divergence the sampling introduced (the same contract the
PXM10x measurement-isolation family pins for the sim kernels, ported
to the host tier).

Enforced with a forward taint walk over every function of the protocol
host modules:

- a read of the ``.spans`` attribute (or the result of any
  ``.spans.<method>()`` call in expression position) taints;
- taint propagates through assignment to local names;
- three forms are **sanctioned** and carry no taint:
  a bare expression statement calling a collector method
  (``self.spans.open(...)`` — the statement tier), passing the
  collector through a ``spans=`` keyword (wiring it into a
  BatchBuffer or sub-component), and the resolved-clock read
  ``spans.now()`` — its value is a plain timestamp (fabric clock
  under replay, perf_counter live), not span state, and the lease
  machinery MUST read time through exactly this spelling (PXR165),
  so timestamping entries or lease deadlines with it is not a span
  leak.

Checks:

- **PXO131** a span value is stored into protocol state (attribute or
  subscript target, or a non-``_sp*`` local name) or passed as a
  non-``spans=`` argument to a non-collector call.
- **PXO132** a span value steers control flow (``if``/``while``/
  ``assert``/ternary test) — the "no protocol decision" core.
- **PXO133** a span value escapes through ``return``.

Local names prefixed ``_sp`` are quarantined for storage (PXO131) —
the sanctioned spelling for a helper that must hold a span briefly —
but branching on or returning them still flags: quarantine marks the
value as span-typed, it does not launder it.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Sequence, Set

from paxi_tpu.analysis import astutil
from paxi_tpu.analysis.model import Violation

RULE = "span-isolation"

TARGETS = (
    "paxi_tpu/protocols/*/host*.py",
)

# SpanCollector surface; a call through `.spans.<one of these>` in
# statement position is the sanctioned write
_COLLECTOR_METHODS = ("open", "close", "close_group", "start",
                      "finish", "clear", "export", "now")


def _is_spans_base(node: ast.expr) -> bool:
    """``<expr>.spans`` or a bare name ``spans``."""
    return ((isinstance(node, ast.Attribute) and node.attr == "spans")
            or (isinstance(node, ast.Name) and node.id == "spans"))


def _is_collector_call(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute)
            and f.attr in _COLLECTOR_METHODS
            and _is_spans_base(f.value))


class _Taint(ast.NodeVisitor):
    """Does this expression carry a span value?  ``.spans`` reads and
    quarantined ``_sp*`` names hit; ``spans=`` keyword values do not
    (the wiring quarantine)."""

    def __init__(self, tainted: Set[str]):
        self.tainted = tainted
        self.hit = False

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.tainted:
            self.hit = True

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "spans" and isinstance(node.ctx, ast.Load):
            self.hit = True
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "now" \
                and _is_spans_base(f.value):
            return          # resolved clock: a timestamp, not a span
        self.visit(node.func)
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            if kw.arg == "spans":
                continue                        # sanctioned wiring
            self.visit(kw.value)

    def visit_FunctionDef(self, node) -> None:  # nested defs: opaque
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _tainted(expr: ast.expr, tainted: Set[str]) -> bool:
    t = _Taint(tainted)
    t.visit(expr)
    return t.hit


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    return []


class _FnWalker:
    """Forward taint walk over one host function's body."""

    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.tainted: Set[str] = set()          # incl. quarantined _sp*
        self.reported: Set[tuple] = set()

    def _flag(self, code: str, node: ast.AST, msg: str) -> None:
        key = (node.lineno, code)
        if key in self.reported:
            return
        self.reported.add(key)
        self.out.append(Violation(
            rule=RULE, code=code, path=self.rel, line=node.lineno,
            col=node.col_offset, message=msg))

    def _check_args(self, expr: ast.expr) -> None:
        """PXO131 at every non-collector call receiving a span value
        through a non-``spans=`` argument, anywhere in ``expr``; also
        PXO132 at every ternary whose test is span-tainted."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and not _is_collector_call(node):
                for a in node.args:
                    if _tainted(a, self.tainted):
                        self._flag(
                            "PXO131", a,
                            "span value passed into a non-collector "
                            "call: spans are write-only from protocol "
                            "code (use the spans= wiring keyword)")
                for kw in node.keywords:
                    if kw.arg == "spans":
                        continue
                    if _tainted(kw.value, self.tainted):
                        self._flag(
                            "PXO131", kw.value,
                            f"span value passed as keyword "
                            f"{kw.arg or '**'!r} into a non-collector "
                            f"call: spans are write-only from "
                            f"protocol code")
            elif isinstance(node, ast.IfExp):
                if _tainted(node.test, self.tainted):
                    self._flag(
                        "PXO132", node.test,
                        "span value steers a ternary: no protocol "
                        "decision may depend on span state")

    def _walk(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                        # nested defs: opaque
            if isinstance(stmt, ast.Expr):
                if (isinstance(stmt.value, ast.Call)
                        and _is_collector_call(stmt.value)):
                    continue                    # the statement tier
                self._check_args(stmt.value)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                 ast.AugAssign)):
                value = stmt.value
                if value is None:
                    continue
                self._check_args(value)
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if _tainted(value, self.tainted):
                    names = [n for t in targets
                             for n in _target_names(t)]
                    stored = [t for t in targets
                              if not isinstance(t, (ast.Name, ast.Tuple,
                                                    ast.List))]
                    bad = [n for n in names if not n.startswith("_sp")]
                    if stored:
                        self._flag(
                            "PXO131", stmt,
                            "span value stored into protocol state "
                            "(attribute/subscript target): spans are "
                            "write-only from protocol code")
                    elif bad:
                        self._flag(
                            "PXO131", stmt,
                            f"span value bound to {bad[0]!r}: hold "
                            f"spans only in _sp*-quarantined locals")
                    self.tainted.update(names)
                else:
                    self.tainted.difference_update(
                        n for t in targets for n in _target_names(t))
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                if _tainted(stmt.test, self.tainted):
                    self._flag(
                        "PXO132", stmt.test,
                        "span value steers a branch: no protocol "
                        "decision may depend on span state")
                self._check_args(stmt.test)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, ast.Assert):
                if _tainted(stmt.test, self.tainted):
                    self._flag(
                        "PXO132", stmt.test,
                        "span value steers an assert: no protocol "
                        "decision may depend on span state")
                continue
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    if _tainted(stmt.value, self.tainted):
                        self._flag(
                            "PXO133", stmt,
                            "span value escapes through return: spans "
                            "leave protocol code only via the "
                            "collector's export path")
                    self._check_args(stmt.value)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._check_args(stmt.iter)
                # two passes for wrap-around taint (measure precedent)
                self._walk(stmt.body)
                self._walk(stmt.body)
                self._walk(stmt.orelse)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(stmt.body)
                for h in stmt.handlers:
                    self._walk(h.body)
                self._walk(stmt.orelse)
                self._walk(stmt.finalbody)
                continue
        # other statement kinds carry no interesting dataflow here


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    out: List[Violation] = []
    for path in (files if files is not None
                 else astutil.iter_py(root, TARGETS)):
        try:
            tree = ast.parse(Path(path).read_text())
        except (OSError, SyntaxError):
            continue
        rel = astutil.rel(Path(path), root)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                walker = _FnWalker(rel, out)
                # two passes over the whole body: a later span bind
                # read earlier still taints (measure precedent)
                walker._walk(node.body)
                walker._walk(node.body)
    return sorted(out, key=lambda v: (v.path, v.line, v.code))
