"""Lease / read-staleness rule family (PXR16x).

The ROADMAP's next subsystem is a layered read tier (leaseholder local
reads, follower reads, a router read cache).  Every layer of it leans
on ONE invariant the write-path proofs never covered: a replica may
answer a read from local state *without consulting the log* only while
a leader lease vouches that no rival quorum can have committed writes
the local state misses.  This family proves that invariant over the
serving stack before the read tier is built on it — the same
precondition move PXE15x made for the migration double-write window.

The proof surface, per module:

- **read serving** — a statement that replies to a client from
  ``db.get`` local state (a ``.reply(...)`` / ``_response(...)``
  carrying a ``<x>.db.get(...)`` value, alias-chased through the
  ``db_get = self.db.get`` hot-path bind).  In a *lease-bearing*
  class (one that owns a ``_lease_until`` deadline) every such
  statement must be dominated by a ``_lease_ok()``-shaped guard
  (:func:`flow.dominating_guards` atoms, early-return polarity
  included).  Modules with NO lease state cannot serve lease reads;
  their local-state answers (the blockchain host's documented
  eventually-consistent read, the HTTP ``/local`` raw probe) are
  *declared non-linearized* and show up in :func:`coverage` as
  ``nonlinearized_reads`` — pinned by tests, so a future read cache
  cannot dodge the proof by simply not declaring a lease.
- **lease-deadline writes** — every store to ``_lease_until`` outside
  ``__init__`` is either the revocation (``= 0``, shrinking is always
  safe) or the monotone renewal ``max(_lease_until, round_start +
  lease_s)`` whose ``round_start`` is a helper parameter; every call
  site of such a helper must pass a recorded quorum-round start
  (``_p1_start``, ``entry.timestamp``), never a clock read — a lease
  renewed from "now" outlives the quorum round that justified it.
- **election fencing** — a function that flips ``active = True`` in a
  lease-bearing class must stamp the takeover fence
  (``_fence_until = now + lease_s``) and the module must consult it
  (a comparison against ``_fence_until``) before proposing, so a
  fresh leader cannot commit writes while a deposed leader's lease
  may still be serving reads.
- **recovery fencing** — a ``recover`` method in a class carrying
  ``lease_s`` (the 2PC coordinator, shard/txn.py) must await a sleep
  of exactly that bound (alias-chased) — the same envelope that
  fences ``cfg.leader_reads``.
- **resolved clocks** — any function touching the lease machinery
  (lease/fence/round-start attrs, ``_lease_ok``, renewal helpers,
  the recovery fence) must read time through the resolved clock
  (``spans.now()``: fabric clock under replay), never ``time.time``
  and friends — the PXD14x obligation extended onto the protocol
  lease surface its TARGETS never covered.

Checks:

- **PXR161** unleased local read: read served from local state in a
  lease-bearing class without a dominating ``_lease_ok()`` guard;
- **PXR162** non-monotone or clock-derived lease renewal: a
  ``_lease_until`` store that is not ``max(old, start + lease_s)``,
  or a renewal-helper call whose round-start argument is a clock
  read;
- **PXR163** unfenced election: no takeover-fence stamp on the
  election path, a fence bound not derived from ``lease_s``, or a
  fence that is stamped but never consulted;
- **PXR164** unfenced recovery: a lease-carrying ``recover`` without
  an awaited ``sleep(lease_s)`` (alias-chased);
- **PXR165** wall-clock lease arithmetic: a raw wall-clock call
  inside the lease machinery (lease expiry would then depend on host
  wall time during a virtual-clock replay).

:func:`coverage` reports the per-module proof surface so tests pin
every lease check, renewal, fence and declared-non-linearized read
the rule examined — the coming follower-read/read-cache code must
extend the proof, not dodge it.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paxi_tpu.analysis import astutil, flow
from paxi_tpu.analysis.model import Violation

RULE = "lease-flow"

TARGETS = (
    "paxi_tpu/protocols/*/host.py",
    "paxi_tpu/host/*.py",
    "paxi_tpu/shard/*.py",
)

# the lease state vocabulary (protocols/paxos/host.py)
_LEASE_ATTRS = ("_lease_until",)
_FENCE_ATTRS = ("_fence_until",)
_ROUND_ATTRS = ("_p1_start",)
_LEASE_CHECKS = ("_lease_ok",)
_RECOVER_BOUND = "lease_s"

_WALL_CLOCKS = ("time.time", "time.monotonic", "time.perf_counter")


def _is_clock_call(call: ast.Call) -> bool:
    name = astutil.dotted_name(call.func) or ""
    tail = name.split(".")[-1]
    return (name in _WALL_CLOCKS or name.endswith(".time")
            or name == "time"
            or tail in ("monotonic", "perf_counter", "time_ns",
                        "monotonic_ns"))


def _clock_calls(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _is_clock_call(n):
            yield n


def _call_tail(call: ast.Call) -> str:
    return (astutil.dotted_name(call.func) or "").split(".")[-1]


def _stmts(body: Sequence[ast.stmt]):
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            yield from _stmts(getattr(stmt, field, []) or [])
        for h in getattr(stmt, "handlers", []) or []:
            yield from _stmts(h.body)


def _own_exprs(stmt: ast.stmt):
    """The statement's OWN expressions (epochfence discipline):
    compound statements yield only their header; their bodies are
    separate statements with their own guard sets."""
    if isinstance(stmt, (ast.If, ast.While)):
        yield stmt.test
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield stmt.iter
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield item.context_expr
    elif not isinstance(stmt, ast.Try):
        yield stmt


def _fn_params(fn) -> List[str]:
    args = (list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs))
    return [a.arg for a in args]


def _new_stats() -> Dict[str, int]:
    return {"local_read_serves": 0, "lease_guarded_reads": 0,
            "nonlinearized_reads": 0, "lease_checks": 0,
            "renewals": 0, "monotone_renewals": 0, "revocations": 0,
            "renewal_calls": 0, "elections": 0, "fences": 0,
            "fence_checks": 0, "recovery_fences": 0, "lease_fns": 0}


class _Module:
    """One parsed module's lease facts."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.model = flow.ModuleModel(tree)
        # classes that OWN a lease deadline — the lease contract scope
        self.lease_classes: Set[str] = {
            name for name, ci in self.model.classes.items()
            if any(a in ci.attrs for a in _LEASE_ATTRS)}
        # classes carrying the recovery bound (the 2PC coordinator)
        self.bound_classes: Set[str] = {
            name for name, ci in self.model.classes.items()
            if _RECOVER_BOUND in ci.attrs}

    def functions(self):
        """(class-name-or-None, FunctionDef) for every def."""
        for name, ci in self.model.classes.items():
            for fi in ci.methods.values():
                yield name, fi.node
        for fi in self.model.functions.values():
            yield None, fi.node

    def renewal_helpers(self) -> Dict[str, int]:
        """fn name -> round-start arg position, for every function
        containing a monotone lease renewal parameterized on one of
        its own arguments."""
        out: Dict[str, int] = {}
        for _cls, fn in self.functions():
            params = _fn_params(fn)
            for stmt in _stmts(fn.body):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Attribute) \
                            and t.attr in _LEASE_ATTRS:
                        start = _monotone_start(stmt.value)
                        if isinstance(start, ast.Name) \
                                and start.id in params:
                            pos = params.index(start.id)
                            if params and params[0] == "self":
                                pos -= 1
                            out[fn.name] = max(pos, 0)
        return out


def _monotone_start(value: ast.expr) -> Optional[ast.expr]:
    """The round-start operand of a ``max(_lease_until, start +
    lease_s)``-shaped renewal, else None."""
    if not (isinstance(value, ast.Call)
            and _call_tail(value) == "max"
            and len(value.args) == 2 and not value.keywords):
        return None
    old = [a for a in value.args
           if isinstance(a, ast.Attribute) and a.attr in _LEASE_ATTRS]
    add = [a for a in value.args
           if isinstance(a, ast.BinOp) and isinstance(a.op, ast.Add)]
    if len(old) != 1 or len(add) != 1:
        return None
    left, right = add[0].left, add[0].right
    for bound, start in ((left, right), (right, left)):
        name = astutil.dotted_name(bound) or ""
        if name.endswith("." + _RECOVER_BOUND) or name == _RECOVER_BOUND:
            return start
    return None


class _FileCheck:
    def __init__(self, mod: _Module, helpers: Dict[str, int],
                 out: List[Violation], stats: Dict[str, int]):
        self.mod = mod
        self.helpers = helpers
        self.out = out
        self.stats = stats

    def _flag(self, code: str, node: ast.AST, msg: str) -> None:
        self.out.append(Violation(
            rule=RULE, code=code, path=self.mod.rel, line=node.lineno,
            col=node.col_offset, message=msg))

    # -- per-function fact helpers ----------------------------------------
    @staticmethod
    def _db_get_aliases(fn) -> Set[str]:
        out: Set[str] = set()
        for stmt in _stmts(fn.body):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Attribute) \
                    and (astutil.dotted_name(stmt.value) or ""
                         ).endswith(".db.get"):
                out.update(t.id for t in stmt.targets
                           if isinstance(t, ast.Name))
        return out

    @staticmethod
    def _serves_local_read(expr: ast.AST, aliases: Set[str]) -> bool:
        """Does this expression both read local db state and emit a
        client-facing answer (reply / _response)?"""
        has_get = has_answer = False
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            name = astutil.dotted_name(n.func) or ""
            if name.endswith(".db.get") or \
                    (isinstance(n.func, ast.Name)
                     and n.func.id in aliases):
                has_get = True
            if name.split(".")[-1] in ("reply", "_response"):
                has_answer = True
        return has_get and has_answer

    @staticmethod
    def _lease_guarded(guards: flow.GuardSet) -> bool:
        for test, polarity in guards:
            if polarity and isinstance(test, ast.Call) \
                    and _call_tail(test) in _LEASE_CHECKS:
                return True
        return False

    def _is_lease_fn(self, cls: Optional[str], fn) -> bool:
        """Does ``fn`` touch the lease machinery at all?  (The PXR165
        resolved-clock obligation's scope.)"""
        if fn.name in self.helpers:
            return True
        if fn.name == "recover" and cls in self.mod.bound_classes:
            return True
        watched = set(_LEASE_ATTRS) | set(_FENCE_ATTRS) \
            | set(_ROUND_ATTRS)
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr in watched:
                return True
            if isinstance(node, ast.Call) \
                    and _call_tail(node) in (set(_LEASE_CHECKS)
                                             | set(self.helpers)):
                return True
        return False

    # -- the checks -------------------------------------------------------
    def run(self) -> None:
        fence_stores: List[Tuple[ast.stmt, ast.Attribute]] = []
        fence_checks = 0
        for cls, fn in self.mod.functions():
            in_lease_class = cls in self.mod.lease_classes
            guards = flow.dominating_guards(fn)
            aliases = self._db_get_aliases(fn)
            elected = False
            fn_fence: List[Tuple[ast.stmt, ast.Attribute]] = []
            for stmt in _stmts(fn.body):
                for top in _own_exprs(stmt):
                    # lease-check call sites
                    for n in ast.walk(top):
                        if isinstance(n, ast.Call) \
                                and _call_tail(n) in _LEASE_CHECKS:
                            self.stats["lease_checks"] += 1
                        if isinstance(n, ast.Compare) and any(
                                isinstance(s, ast.Attribute)
                                and s.attr in _FENCE_ATTRS
                                for s in ast.walk(n)):
                            fence_checks += 1
                    # PXR161: local-state read serving
                    if self._serves_local_read(top, aliases):
                        self.stats["local_read_serves"] += 1
                        if not in_lease_class:
                            self.stats["nonlinearized_reads"] += 1
                        elif self._lease_guarded(
                                guards.get(id(stmt), frozenset())):
                            self.stats["lease_guarded_reads"] += 1
                        else:
                            self._flag(
                                "PXR161", stmt,
                                "read served from local state without "
                                "a dominating _lease_ok() guard: a "
                                "deposed leader would answer from a "
                                "snapshot a rival quorum has already "
                                "overwritten — gate on the lease or "
                                "order the read through the log")
                # PXR162: lease-deadline stores
                if isinstance(stmt, ast.Assign):
                    self._check_lease_store(fn, stmt)
                    for t in stmt.targets:
                        if isinstance(t, ast.Attribute) \
                                and t.attr in _FENCE_ATTRS \
                                and fn.name != "__init__":
                            fn_fence.append((stmt, t))
                        if isinstance(t, ast.Attribute) \
                                and t.attr == "active" \
                                and isinstance(stmt.value, ast.Constant) \
                                and stmt.value.value is True:
                            elected = True
                if isinstance(stmt, ast.AugAssign) \
                        and isinstance(stmt.target, ast.Attribute) \
                        and stmt.target.attr in _LEASE_ATTRS:
                    self.stats["renewals"] += 1
                    self._flag(
                        "PXR162", stmt,
                        "lease deadline mutated in place: the only "
                        "sound shapes are the monotone "
                        "max(_lease_until, round_start + lease_s) "
                        "renewal and the shrink-to-zero revocation")
                # PXR162: renewal-helper call sites
                for top in _own_exprs(stmt):
                    self._check_renewal_calls(top)
            fence_stores.extend(fn_fence)
            # PXR163: election fencing
            if elected and in_lease_class:
                self.stats["elections"] += 1
                if not fn_fence:
                    self._flag(
                        "PXR163", fn,
                        f"election path `{fn.name}` flips active=True "
                        f"without stamping the takeover fence "
                        f"(_fence_until = now + lease_s): first "
                        f"proposals could commit while a deposed "
                        f"leader's lease is still serving reads")
                for fstmt, ftarget in fn_fence:
                    value = getattr(fstmt, "value", None)
                    if self._lease_bound_sum(value):
                        self.stats["fences"] += 1
                    else:
                        self._flag(
                            "PXR163", ftarget,
                            "takeover fence bound is not lease_s-"
                            "derived (want <now> + lease_s): a "
                            "shorter fence under-waits the deposed "
                            "leader's live lease")
            # PXR164: recovery fencing
            if fn.name == "recover" and cls in self.mod.bound_classes:
                if self._recover_fenced(fn):
                    self.stats["recovery_fences"] += 1
                else:
                    self._flag(
                        "PXR164", fn,
                        "2PC recovery without awaiting the lease_s "
                        "fence: recovery's decide(abort) could race a "
                        "live coordinator still inside its lease "
                        "envelope — await asyncio.sleep(self.lease_s) "
                        "first")
            # PXR165: wall clocks in lease machinery
            if self._is_lease_fn(cls, fn):
                self.stats["lease_fns"] += 1
                for call in _clock_calls(fn):
                    self._flag(
                        "PXR165", call,
                        "wall-clock read inside the lease machinery: "
                        "lease expiry would depend on host wall time "
                        "during a virtual-clock replay — route "
                        "through the resolved clock (spans.now())")
        self.stats["fence_checks"] += fence_checks
        if fence_stores and fence_checks == 0:
            self._flag(
                "PXR163", fence_stores[0][1],
                "takeover fence is stamped but never consulted: no "
                "comparison against _fence_until guards the proposal "
                "path, so the fence fences nothing")

    def _check_lease_store(self, fn, stmt: ast.Assign) -> None:
        targets = [t for t in stmt.targets
                   if isinstance(t, ast.Attribute)
                   and t.attr in _LEASE_ATTRS]
        if not targets or fn.name == "__init__":
            return
        value = stmt.value
        if isinstance(value, ast.Constant) \
                and value.value in (0, 0.0):
            self.stats["revocations"] += 1
            return                      # shrinking the lease is safe
        self.stats["renewals"] += 1
        start = _monotone_start(value)
        if start is None:
            self._flag(
                "PXR162", targets[0],
                "non-monotone lease-deadline write: want "
                "max(_lease_until, round_start + lease_s) so a "
                "reordered stale renewal can never extend the lease "
                "past what its quorum round justified")
            return
        if any(True for _ in _clock_calls(start)) \
                or (isinstance(start, ast.Call)
                    and _call_tail(start) == "now"):
            self._flag(
                "PXR162", targets[0],
                "lease renewed from a clock read: the deadline must "
                "derive from a recorded quorum-round START "
                "(_p1_start / entry.timestamp), not from \"now\"")
            return
        self.stats["monotone_renewals"] += 1

    def _check_renewal_calls(self, top: ast.AST) -> None:
        for n in ast.walk(top):
            if not (isinstance(n, ast.Call)
                    and _call_tail(n) in self.helpers):
                continue
            self.stats["renewal_calls"] += 1
            pos = self.helpers[_call_tail(n)]
            arg = n.args[pos] if pos < len(n.args) else None
            if arg is None:
                continue
            bad = any(True for _ in _clock_calls(arg)) \
                or (isinstance(arg, ast.Call)
                    and _call_tail(arg) == "now")
            if bad:
                self._flag(
                    "PXR162", n,
                    "lease renewal passed a clock read as the round "
                    "start: \"now\" outlives the quorum round that "
                    "justified the lease — pass the recorded round "
                    "start (_p1_start / entry.timestamp)")

    @staticmethod
    def _lease_bound_sum(value: Optional[ast.expr]) -> bool:
        if not (isinstance(value, ast.BinOp)
                and isinstance(value.op, ast.Add)):
            return False
        for side in (value.left, value.right):
            name = astutil.dotted_name(side) or ""
            if name.endswith("." + _RECOVER_BOUND) \
                    or name == _RECOVER_BOUND:
                return True
        return False

    def _recover_fenced(self, fn) -> bool:
        aliases: Set[str] = set()
        for stmt in _stmts(fn.body):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Attribute) \
                    and stmt.value.attr == _RECOVER_BOUND:
                aliases.update(t.id for t in stmt.targets
                               if isinstance(t, ast.Name))
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Await)
                    and isinstance(node.value, ast.Call)
                    and _call_tail(node.value) == "sleep"
                    and node.value.args):
                continue
            arg = node.value.args[0]
            if isinstance(arg, ast.Attribute) \
                    and arg.attr == _RECOVER_BOUND:
                return True
            if isinstance(arg, ast.Name) and arg.id in aliases:
                return True
        return False


def _run(root: Path, files: Optional[Sequence[Path]]
         ) -> Tuple[List[Violation], Dict[str, Dict[str, int]]]:
    root = root.resolve()
    defaults = list(astutil.iter_py(root, TARGETS))
    requested = list(files) if files is not None else defaults
    # parse the full universe once: renewal helpers are a whole-
    # program fact (the switchnet subclass renews a lease its base
    # class defines), so a scoped run must see the same helper set a
    # full run would
    universe: Dict[Path, _Module] = {}
    for path in [*defaults, *requested]:
        rp = Path(path).resolve()
        if rp in universe:
            continue
        try:
            tree = ast.parse(rp.read_text())
        except (OSError, SyntaxError):
            continue
        universe[rp] = _Module(astutil.rel(rp, root), tree)
    helpers: Dict[str, int] = {}
    for mod in universe.values():
        helpers.update(mod.renewal_helpers())

    out: List[Violation] = []
    per_module: Dict[str, Dict[str, int]] = {}
    for path in requested:
        mod = universe.get(Path(path).resolve())
        if mod is None:
            continue
        stats = per_module.setdefault(mod.rel, _new_stats())
        _FileCheck(mod, helpers, out, stats).run()
    return (sorted(out, key=lambda v: (v.path, v.line, v.code)),
            per_module)


def check(root: Path,
          files: Optional[Sequence[Path]] = None) -> List[Violation]:
    return _run(root, files)[0]


def coverage(root: Path,
             files: Optional[Sequence[Path]] = None
             ) -> Dict[str, Dict[str, int]]:
    """Per-module proof surface: every lease check, guarded/declared
    read, renewal, fence and recovery fence the rule examined — tests
    pin these so the read tier cannot grow out from under the proof."""
    return _run(root, files)[1]
