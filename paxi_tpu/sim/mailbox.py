"""Dense lock-step message exchange with a randomized fault schedule.

This is the TPU-native replacement for the reference's in-process ``chan``
transport + socket fault injection (transport.go scheme "chan",
socket.go Crash/Drop/Slow/Flaky) [driver].  Per message type there is one
``(src, dst)`` plane of int32 fields plus a validity mask; in-flight
messages live in a *timing wheel* ``(delay, src, dst)`` so arbitrary
per-edge delays (=> reordering across edges), drops, duplicates, crashes
and partitions are all cheap masked array ops inside the jitted step.

Collision semantics: a newly sent message overwrites an undelivered one in
the same wheel slot for the same (type, src, dst) edge — i.e. extra loss,
which the fuzzing oracle tolerates by design.  In fault-free mode
(max_delay=1) each sender emits at most one message per type per edge per
step, so no collisions occur.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.sim.types import FuzzConfig, Mailboxes

MailSpec = Dict[str, Tuple[str, ...]]


def empty_mailboxes(spec: MailSpec, n: int) -> Mailboxes:
    """One zeroed (src, dst) plane per message type."""
    out = {}
    for name, fields in spec.items():
        box = {"valid": jnp.zeros((n, n), bool)}
        for f in fields:
            box[f] = jnp.zeros((n, n), jnp.int32)
        out[name] = box
    return out


def empty_wheel(spec: MailSpec, n: int, fuzz: FuzzConfig) -> Mailboxes:
    """Timing wheel: slot d holds messages arriving in d+1 steps."""
    d = fuzz.wheel
    out = {}
    for name, fields in spec.items():
        box = {"valid": jnp.zeros((d, n, n), bool)}
        for f in fields:
            box[f] = jnp.zeros((d, n, n), jnp.int32)
        out[name] = box
    return out


def wheel_deliver(wheel: Mailboxes) -> Tuple[Mailboxes, Mailboxes]:
    """Pop slot 0 as this step's inbox; rotate the wheel forward."""
    inbox, rolled = {}, {}
    for name, box in wheel.items():
        inbox[name] = {k: v[0] for k, v in box.items()}
        rolled[name] = {
            k: jnp.concatenate([v[1:], jnp.zeros_like(v[:1])], axis=0)
            for k, v in box.items()
        }
    return inbox, rolled


def fault_state_init(n: int) -> Dict[str, jax.Array]:
    """Connectivity + crash masks carried in the scan."""
    return {
        "conn": jnp.ones((n, n), bool),   # can (src -> dst) deliver?
        "crashed": jnp.zeros((n,), bool),  # comms-crashed replicas
    }


def fault_state_refresh(fs, rng, t, fuzz: FuzzConfig, n: int):
    """Resample partition/crash schedule every ``fuzz.window`` steps.

    Partition: a random bipartition of replicas; messages across the cut
    are dropped (socket.go Drop generalized).  Crash: a replica's sends
    and receives are suppressed (socket.go Crash — the node keeps its
    state, matching the reference where Crash only stops the transport).

    A scenario's churn/outage/reconfig kills (paxi_tpu/scenarios) OR
    into the crash plane EVERY step, like ``perm_crash`` — held
    overlays deterministic in t, never resampled away — so they
    materialize into the recorded schedule like any drawn fault.
    """
    scn = fuzz.scenario
    scn_kills = scn is not None and scn.kills_nodes()
    if not (fuzz.p_partition > 0 or fuzz.p_crash > 0
            or fuzz.perm_crash >= 0 or scn_kills):
        return fs
    k1, k2, k3 = jr.split(rng, 3)
    side = jr.bernoulli(k1, 0.5, (n,))
    cut = jr.bernoulli(k2, fuzz.p_partition, ())
    conn = jnp.where(cut, side[:, None] == side[None, :],
                     jnp.ones((n, n), bool))
    crashed = jr.bernoulli(k3, fuzz.p_crash, (n,))
    fresh = (t % fuzz.window) == 0
    new = {
        "conn": jnp.where(fresh, conn, fs["conn"]),
        "crashed": jnp.where(fresh, crashed, fs["crashed"]),
    }
    if fuzz.perm_crash >= 0:
        # held, never resampled: a permanently dead replica stays dead
        forced = ((jnp.arange(n) == fuzz.perm_crash)
                  & (t >= fuzz.perm_crash_at))
        new["crashed"] = new["crashed"] | forced
    if scn_kills:
        from paxi_tpu.scenarios.schedule import forced_crash
        # the carried crash plane includes LAST step's overlay; the
        # scenario is deterministic in t, so un-stick yesterday's
        # overlay before OR-ing today's — that is what makes revivals
        # (churn's whole point) actually happen.  A window-drawn crash
        # coinciding with a scenario kill revives with it (and is
        # redrawn at the next window boundary) — scenario revival wins.
        new["crashed"] = ((new["crashed"] & ~forced_crash(scn, t - 1, n))
                          | forced_crash(scn, t, n))
    return new


def draw_edge_faults(rng, outbox: Mailboxes, fuzz: FuzzConfig):
    """Draw the per-edge fault planes wheel_insert consumes — one
    ``{"drop", "delay", "dup"}`` triple per message type, each plane
    shaped like the outbox validity plane ((src, dst) per-group or
    (src, dst, G) lane-major, so one implementation serves both
    layouts).  Factored out of wheel_insert so the trace subsystem can
    materialize the schedule (capture) or substitute a recorded one
    (pinned replay); the key-split structure is unchanged from the old
    inline draws, so existing runs stay bit-for-bit identical."""
    d = fuzz.wheel
    scn = fuzz.scenario
    geo = scn is not None and scn.zones is not None
    names = sorted(outbox.keys())
    keys = jr.split(rng, 3 * len(names))
    faults = {}
    for i, name in enumerate(names):
        shape = outbox[name]["valid"].shape
        kd, kdel, kdup = keys[3 * i], keys[3 * i + 1], keys[3 * i + 2]
        drop = (jr.bernoulli(kd, fuzz.p_drop, shape)
                if fuzz.p_drop > 0 else jnp.zeros(shape, bool))
        if geo:
            # WAN latency plane (paxi_tpu/scenarios): the per-edge zone
            # matrix replaces the uniform delay distribution — base
            # latency per (src_zone, dst_zone) plus uniform jitter,
            # clipped to the wheel (which FuzzConfig.wheel sized to the
            # matrix).  Same key-split structure as the uniform draw,
            # so scenario-free runs stay bit-for-bit identical.
            from paxi_tpu.scenarios.schedule import delay_base
            base = jnp.asarray(delay_base(scn, shape[0]))
            base = base.reshape(base.shape + (1,) * (len(shape) - 2))
            if scn.zones.jitter > 0:
                base = base + jr.randint(kdel, shape, 0,
                                         scn.zones.jitter + 1)
            delay = jnp.clip(base, 1, d).astype(jnp.int32)
        elif d > 1:
            delay = jr.randint(kdel, shape, 1, d + 1)  # arrive in 1..d steps
        else:
            delay = jnp.ones(shape, jnp.int32)
        dup = (jr.bernoulli(kdup, fuzz.p_dup, shape)
               if fuzz.p_dup > 0 else jnp.zeros(shape, bool))
        faults[name] = {"drop": drop, "delay": delay, "dup": dup}
    return faults


def live_mask(fs, valid_ndim: int, n: int):
    """The delivery-validity predicate (no self-edges, conn intact,
    both endpoints alive) — ONE definition shared by wheel_insert and
    the runner's record path, so the recorded-event neutralization can
    never drift from what delivery actually masks (drift would make a
    fresh capture replay to a different state hash).  Rank-generic:
    ``valid_ndim`` is 3 for lane-major (src, dst, G) planes with
    crashed (R, G), 2 for per-group (src, dst) with crashed (R,)."""
    no_self = ~jnp.eye(n, dtype=bool)
    if valid_ndim == 3:
        no_self = no_self[:, :, None]
        alive = ~fs["crashed"][:, None, :] & ~fs["crashed"][None, :, :]
    else:
        alive = ~fs["crashed"][:, None] & ~fs["crashed"][None, :]
    return no_self & fs["conn"] & alive


def wheel_insert(wheel: Mailboxes, outbox: Mailboxes, fs,
                 fuzz: FuzzConfig, faults: Mailboxes) -> Mailboxes:
    """Push this step's outbox into the wheel under the fault schedule.

    ``faults`` comes from draw_edge_faults — or is a recorded schedule
    during pinned replay; planes are applied unconditionally so a
    replayed schedule can carry drops/dups even when the FuzzConfig
    probabilities are zero.  Deliberately no internal draw fallback:
    one draw site (the runner) keeps the capture/replay bit-for-bit
    guarantee auditable.

    Rank-generic over the two layouts (ONE implementation so the
    replay guarantee can't drift between them): per-group planes are
    (src, dst) with crashed (R,); lane-major planes are (src, dst, G)
    with crashed (R, G) — the eye and crash masks grow a trailing
    group axis, everything else is shape-polymorphic."""
    d = fuzz.wheel
    new_wheel = {}
    for name in sorted(outbox.keys()):
        box, wbox = outbox[name], wheel[name]
        n = box["valid"].shape[0]
        f = faults[name]
        valid = (box["valid"] & live_mask(fs, box["valid"].ndim, n)
                 & ~f["drop"])
        delay, dup = f["delay"], f["dup"]
        dup_delay = jnp.minimum(delay + 1, d)

        wvalid = wbox["valid"]
        wfields = {k: v for k, v in wbox.items() if k != "valid"}
        for slot in range(d):
            put = valid & ((delay == slot + 1)
                           | (dup & (dup_delay == slot + 1)))
            wvalid = wvalid.at[slot].set(wvalid[slot] | put)
            for f_ in wfields:
                wfields[f_] = wfields[f_].at[slot].set(
                    jnp.where(put, box[f_], wfields[f_][slot]))
        new_wheel[name] = {"valid": wvalid, **wfields}
    return new_wheel
