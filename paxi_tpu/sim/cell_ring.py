"""Shared Multi-Paxos FIXED-CELL core for lane-major sim kernels.

The ``sim/ballot_ring.py`` consensus machinery rebuilt on the
fixed-cell layout (``sim/cell.py``: absolute slot ``a`` lives at cell
``a % S`` forever), so the per-step ``ring.shift_window`` alignment
gathers — the dominant cost of the old layout on XLA:CPU — disappear:
window slides and snapshot adoptions become masked clears, and the
phase-1 log merge a pure elementwise mask over the ``(ldr, src, S, G)``
ack cube (leader cell ``c`` and acker cell ``c`` hold the SAME absolute
slot exactly when that slot is inside the acker's window).

Drivers: the paxos kernel (self-generated client commands), sdpaxos
(sequencer-ordered owner tokens) and wankeeper (root token-transfer
log).  The function surface mirrors ``ballot_ring`` one-for-one —
layout-free helpers (``promise_p1a``/``tally_p1b``/``election_tick``/
``depose``/``own_bal_mask``/``propose_write``) are re-exported from it
(one audited copy), layout-dependent ones are rebuilt here.  Each
consumer kernel is proven BIT-CANONICALLY equal to its frozen
sliding-window reference (``protocols/*/sim_sw.py``) on pinned fuzz
seeds: identical PRNG draws, outboxes and counters, and identical
state after ``cell.window_view_np`` (tests/test_fixed_cell_equiv.py).

Measurement-plane contract (``m_prop_t`` and friends, never passed in
here): these helpers no longer shift anything, so after every
base-moving call the kernel re-arms its ring-shaped ``m_`` planes with
``cell.advance_clear(plane, base_before, base_after, 0)`` — the exact
fixed-cell equivalent of the old re-alignment shift.

Conventions: as ``ballot_ring`` — ``st`` carries the 13 standard keys
(``KEYS``), ``extras`` travel with state transfer by reference, mailbox
planes are ``(src, dst, G)`` consumed via masked selects.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# one audited copy of the layout-free machinery (promise/tally/election
# touch only scalar-per-lane planes; propose_write is given its one-hot)
from paxi_tpu.sim.ballot_ring import (KEYS, NO_CMD, NOOP, depose,
                                      election_tick, own_bal_mask,
                                      promise_p1a, propose_write,
                                      tally_p1b)
from paxi_tpu.sim.cell import cell_abs, cell_onehot, in_window
from paxi_tpu.sim.ring import pick_src
from paxi_tpu.sim.ring import take_replica as _take_replica

__all__ = ["KEYS", "NO_CMD", "NOOP", "depose", "election_tick",
           "own_bal_mask", "promise_p1a", "propose_write", "tally_p1b",
           "adopt_best_acker", "merge_acker_logs", "accept_p2a",
           "tally_p2b", "apply_p3", "repropose_target", "p3_out",
           "retry_stuck", "slide_window"]

BIG = jnp.int32(2 ** 30)


def _ridx(st):
    R = st["log_bal"].shape[0]
    return jnp.arange(R, dtype=jnp.int32)


def _clear_ring(st, drop):
    """Reset recycled cells in place (the no-copy window move)."""
    return {**st,
            "log_bal": jnp.where(drop, 0, st["log_bal"]),
            "log_cmd": jnp.where(drop, NO_CMD, st["log_cmd"]),
            "log_commit": st["log_commit"] & ~drop,
            "proposed": st["proposed"] & ~drop,
            "log_acks": jnp.where(drop, 0, st["log_acks"])}


def adopt_best_acker(st, amask, p1_win, extras):
    """Phase-1 win, step 1: a laggard winner adopts the most advanced
    acker's (extras, execute, base) by reference.  Fixed cell mapping:
    raising my base recycles the cells that fell below it — a masked
    clear, where the old layout shifted every plane.  Returns
    (st', extras')."""
    el_exec = jnp.where(amask, st["execute"][None, :, :], -1)
    f_src = jnp.argmax(el_exec, axis=1).astype(jnp.int32)
    front = jnp.max(el_exec, axis=1)
    el_ad = p1_win & (front > st["execute"])
    ex = {k: jnp.where(el_ad[(slice(None),)
                             + (None,) * (v.ndim - 2) + (slice(None),)],
                       _take_replica(v, f_src), v)
          for k, v in extras.items()}
    execute = jnp.where(el_ad, front, st["execute"])
    next_slot = jnp.where(el_ad, jnp.maximum(st["next_slot"], front),
                          st["next_slot"])
    # never adopt a LOWER base: dropping my own top-of-window entries
    # (possibly committed via P3) is never safe; the merge tolerates
    # ackers whose base is below mine (front-fill only)
    f_base = _take_replica(st["base"], f_src)
    S = st["log_bal"].shape[1]
    A_old = cell_abs(st["base"], S)
    base = jnp.where(el_ad, jnp.maximum(f_base, st["base"]), st["base"])
    st = _clear_ring({**st, "execute": execute, "next_slot": next_slot,
                      "base": base}, A_old < base[:, None, :])
    return st, ex


def merge_acker_logs(st, amask, p1_win):
    """Phase-1 win, step 2: merge the ackers' current logs — per slot
    adopt any committed value, else the highest-ballot accepted value,
    else NOOP-fill below the frontier; own the window under my ballot.
    Fixed cell mapping: leader cell c and acker cell c hold the SAME
    absolute slot exactly when the leader's slot A[ldr, c] is inside
    the acker's window — a pure mask over the (ldr, src, S, G) cube,
    no base-alignment gathers.  Returns st' (active set for
    winners)."""
    S = st["log_bal"].shape[1]
    ridx = _ridx(st)
    self_bit3 = (jnp.int32(1) << ridx)[:, None, None]
    base = st["base"]
    A = cell_abs(base, S)                                # (ldr, S, G)
    Al = A[:, None]                                      # (ldr, 1, S, G)
    in_src = (Al >= base[None, :, None, :]) \
        & (Al < base[None, :, None, :] + S)
    sel = amask[:, :, None, :] & in_src                  # (ldr, src, S, G)
    lb = jnp.where(sel, st["log_bal"][None], -1)
    src_best = jnp.argmax(lb, axis=1)                    # first max src
    best_bal = jnp.max(lb, axis=1)                       # (ldr, S, G)
    oh_best = ridx[None, :, None, None] == src_best[:, None]
    merged_cmd = jnp.sum(jnp.where(oh_best, st["log_cmd"][None], 0),
                         axis=1)
    cmask = sel & st["log_commit"][None]
    merged_commit = jnp.any(cmask, axis=1)
    csrc = jnp.argmax(cmask, axis=1)                     # first committed
    oh_csrc = ridx[None, :, None, None] == csrc[:, None]
    committed_cmd = jnp.sum(jnp.where(oh_csrc, st["log_cmd"][None], 0),
                            axis=1)
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, A + 1, 0), axis=1)  # (ldr, G) abs
    new_next = jnp.maximum(st["next_slot"], top)
    in_win = A < new_next[:, None, :]
    w = p1_win[:, None, :]
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    return {**st,
            "log_cmd": jnp.where(w & in_win, adopt_cmd, st["log_cmd"]),
            "log_bal": jnp.where(w & in_win, st["ballot"][:, None, :],
                                 st["log_bal"]),
            "log_commit": jnp.where(w & in_win,
                                    merged_commit | st["log_commit"],
                                    st["log_commit"]),
            "proposed": jnp.where(w, in_win
                                  & (merged_commit | st["log_commit"]),
                                  st["proposed"]),
            "log_acks": jnp.where(w, jnp.where(in_win, self_bit3, 0),
                                  st["log_acks"]),
            "next_slot": jnp.where(p1_win, new_next, st["next_slot"]),
            "active": st["active"] | p1_win}


def accept_p2a(st, m):
    """P2a handler: accept from the highest-ballot proposer; ack ONLY
    what was durably stored in-window.  Returns (st', out_p2b, acc_ok,
    demote)."""
    R = st["log_bal"].shape[0]
    S = st["log_bal"].shape[1]
    ridx = _ridx(st)
    G = st["ballot"].shape[-1]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = pick_src(m["slot"], a_src)                  # absolute
    a_cmd = pick_src(m["cmd"], a_src)
    acc_ok = a_has & (a_bal >= st["ballot"])
    demote = acc_ok & (a_bal > st["ballot"])
    st = depose(st, demote, a_bal)
    a_inw = in_window(a_slot, st["base"], S)
    oh = (acc_ok & a_inw)[:, None, :] & cell_onehot(a_slot, S)
    writable = oh & (st["log_bal"] <= a_bal[:, None, :]) \
        & ~st["log_commit"]
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None, :]
        & (ridx[None, :, None] == a_src[:, None, :]),
        "bal": jnp.broadcast_to(a_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(a_slot[:, None, :], (R, R, G)),
    }
    st = {**st,
          "log_bal": jnp.where(writable, a_bal[:, None, :], st["log_bal"]),
          "log_cmd": jnp.where(writable, a_cmd[:, None, :], st["log_cmd"])}
    return st, out_p2b, acc_ok, demote


def tally_p2b(st, m, majority, stride):
    """P2b handler: the leader tallies acks per (slot) bitmask and
    commits at majority.  Returns (st', newly)."""
    R = st["log_bal"].shape[0]
    S = st["log_bal"].shape[1]
    ob = own_bal_mask(st, stride)
    okb = m["valid"] & (m["bal"] == st["ballot"][None, :, :]) \
        & (st["active"] & ob)[None, :, :]                # (src, ldr, G)
    base = st["base"]
    log_acks = st["log_acks"]
    for s in range(R):
        ok_s = okb[s] & in_window(m["slot"][s], base, S)
        oh_s = ok_s[:, None, :] & cell_onehot(m["slot"][s], S)
        log_acks = log_acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    acks_n = jax.lax.population_count(log_acks)
    newly = ((st["active"] & ob)[:, None, :] & (acks_n >= majority)
             & ~st["log_commit"] & (st["log_cmd"] != NO_CMD)
             & st["proposed"])
    return {**st, "log_acks": log_acks,
            "log_commit": st["log_commit"] | newly}, newly


def apply_p3(st, m, extras):
    """P3 handler: adopt the commit notification, frontier-commit below
    ``upto`` at the sender's exact ballot, and snapshot-adopt (extras,
    execute, base) when my frontier fell below the sender's window.
    Returns (st', extras', c_has, c_bal).

    Zombie fences as in ``ballot_ring.apply_p3`` (higher-ballot P3
    deposes; frontier-commit only at ``bal >= my promised ballot``).
    Fixed cell mapping: under snapshot adoption the sender's cells are
    already aligned with mine, so the overlay is elementwise — my cells
    still inside the sender's window (``A >= src_base``) are kept where
    the sender has no commit, everything below was recycled."""
    S = st["log_bal"].shape[1]
    c_src = jnp.argmax(jnp.where(m["valid"], m["bal"], -1), axis=0) \
        .astype(jnp.int32)
    c_bal = jnp.max(jnp.where(m["valid"], m["bal"], -1), axis=0)
    c_has = c_bal > 0
    c_slot = pick_src(m["slot"], c_src)
    c_cmd = pick_src(m["cmd"], c_src)
    c_upto = pick_src(m["upto"], c_src)
    fresh3 = c_has & (c_bal >= st["ballot"])             # fence (2)
    promote3 = c_has & (c_bal > st["ballot"])            # fence (1)
    st = depose(st, promote3, c_bal)
    base = st["base"]
    A = cell_abs(base, S)
    c_inw = in_window(c_slot, base, S)
    oh = (c_has & c_inw)[:, None, :] & cell_onehot(c_slot, S)
    log_cmd = jnp.where(oh, c_cmd[:, None, :], st["log_cmd"])
    log_bal = jnp.where(oh, jnp.maximum(st["log_bal"],
                                        c_bal[:, None, :]), st["log_bal"])
    log_commit = st["log_commit"] | oh
    ohu = (fresh3[:, None, :] & (A < c_upto[:, None, :])
           & (log_bal == c_bal[:, None, :]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # snapshot catch-up for deep laggards
    src_base = _take_replica(base, c_src)
    adopt = c_has & (st["execute"] < src_base)
    keep = A >= src_base[:, None, :]     # my cells still in the new window
    my_bal = jnp.where(keep, log_bal, 0)
    my_cmd = jnp.where(keep, log_cmd, NO_CMD)
    my_com = keep & log_commit
    s_bal = _take_replica(log_bal, c_src)
    s_cmd = _take_replica(log_cmd, c_src)
    s_com = _take_replica(log_commit, c_src)
    a2 = adopt[:, None, :]
    ex = {k: jnp.where(adopt[(slice(None),)
                             + (None,) * (v.ndim - 2) + (slice(None),)],
                       _take_replica(v, c_src), v)
          for k, v in extras.items()}
    execute = jnp.where(adopt, _take_replica(st["execute"], c_src),
                        st["execute"])
    st = {**st,
          "log_bal": jnp.where(a2, jnp.where(s_com, s_bal, my_bal),
                               log_bal),
          "log_cmd": jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd),
                               log_cmd),
          "log_commit": jnp.where(a2, s_com | my_com, log_commit),
          "proposed": jnp.where(a2, False, st["proposed"]),
          "log_acks": jnp.where(a2, 0, st["log_acks"]),
          "execute": execute,
          "next_slot": jnp.where(adopt,
                                 jnp.maximum(st["next_slot"], execute),
                                 st["next_slot"]),
          "base": jnp.where(adopt, src_base, base)}
    return st, ex, c_has, c_bal


def repropose_target(st):
    """Shared proposal targeting: the lowest unproposed-uncommitted
    absolute slot below next_slot (re-proposal), else the next fresh
    slot (window flow control).  Returns (has_re, can_new, prop_cell,
    prop_slot, oh_p, re_cmd)."""
    S = st["log_bal"].shape[1]
    base, next_slot = st["base"], st["next_slot"]
    A = cell_abs(base, S)
    mask_re = (~st["log_commit"]) & (~st["proposed"]) \
        & (A < next_slot[:, None, :])
    re_abs = jnp.min(jnp.where(mask_re, A, BIG), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S
    prop_slot = jnp.where(has_re, re_abs, next_slot)     # absolute
    prop_cell = jnp.remainder(prop_slot, S)
    oh_p = cell_onehot(prop_slot, S)
    re_cmd = jnp.sum(jnp.where(oh_p, st["log_cmd"], 0), axis=1)
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    return has_re, can_new, prop_cell, prop_slot, oh_p, re_cmd


def p3_out(st, newly, new_execute, is_leader, t):
    """Emit P3: the lowest newly committed absolute slot, else
    round-robin retransmit through the committed prefix (laggards
    behind the window heal via snapshot adoption)."""
    R = st["log_bal"].shape[0]
    S = st["log_bal"].shape[1]
    G = st["ballot"].shape[-1]
    A = cell_abs(st["base"], S)
    low_new = jnp.min(jnp.where(newly, A, BIG), axis=1)  # abs
    any_new = jnp.any(newly, axis=1)
    span = jnp.maximum(new_execute - st["base"], 1)
    rr = t % span
    p3_abs = jnp.where(any_new, low_new, st["base"] + rr)
    oh_3 = cell_onehot(p3_abs, S)
    p3_committed = jnp.any(oh_3 & st["log_commit"], axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, st["log_cmd"], 0), axis=1)
    p3_do = is_leader & p3_committed
    return {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(st["ballot"][:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(p3_abs[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(new_execute[:, None, :], (R, R, G)),
    }


def retry_stuck(st, new_execute, is_leader, retry_timeout):
    """Stuck-frontier retry, go-back-N: on a stall re-open EVERY
    uncommitted in-flight slot so the proposer re-proposes one per step
    (see ballot_ring.retry_stuck)."""
    S = st["log_bal"].shape[1]
    A = cell_abs(st["base"], S)
    stalled = is_leader & (new_execute == st["execute"]) \
        & (st["next_slot"] > new_execute)
    stuck = jnp.where(stalled, st["stuck"] + 1, 0)
    retry = stuck >= retry_timeout
    ohr = (retry[:, None, :] & ~st["log_commit"]
           & (A >= new_execute[:, None, :])
           & (A < st["next_slot"][:, None, :]))
    return {**st, "proposed": st["proposed"] & ~ohr,
            "stuck": jnp.where(retry, 0, stuck)}


def slide_window(st, new_execute, retain):
    """Slide the window past the executed prefix, retaining ``retain``
    executed slots for P3 retransmits.  Fixed cell mapping: recycled
    cells are cleared in place, nothing moves."""
    S = st["log_bal"].shape[1]
    new_base = jnp.maximum(st["base"], new_execute - retain)
    drop = cell_abs(st["base"], S) < new_base[:, None, :]
    return _clear_ring({**st, "base": new_base, "execute": new_execute},
                       drop)
