"""In-scan linearizability spot-checker (the vectorized lincheck slice).

``sim/lincheck.py``'s stale/future-read oracle and the host precedence
checker run post-hoc over materialized op histories — they cannot keep
up with the 100k-group lane-major kernels, so every bench number above
the post-hoc scale was trusted on counters alone.  This module is the
slice of those invariants that CAN run inside the scan body at full
speed, as pure elementwise reductions over the ring-log planes every
instrumented kernel already carries:

1. **Monotone commit frontier** — ``execute`` and ``base`` never
   regress per lane.  (Deliberately NOT in the protocol oracles:
   ``proto.invariants`` checks ``execute >= base``, not monotonicity.)
2. **Committed-value stability, same-cell** — a committed cell whose
   absolute slot is unchanged between steps must keep its commit bit
   and value.  (Cells recycled by a window slide are covered by the
   protocol oracle's shifted check; this is the alignment-free spot
   version that costs no gathers.)
3. **Per-slot agreement across lanes** — committed cells holding the
   SAME absolute slot at different replicas must hold the same value;
   checked on the cells aligned with the most-advanced replica's frame
   (``abs == max_r abs``), which is every cell in the steady state.
4. **Register condition** (the lincheck projection): two replicas with
   the same execute frontier have executed the same committed prefix,
   so their state-machine registers must be bitwise equal — the
   "a read must see the latest completed write" condition, evaluated
   on the materialized registers instead of an op history.

All checks are elementwise / small-pair reductions — no per-step
gathers — so the spot-checker rides inside the 100k-group scan with
single-digit-percent overhead.  Results accumulate into each kernel's
``m_inscan_viol`` measurement plane (excluded from the witness hash,
surfaced as the ``inscan_violations`` metric): an independent oracle
beside ``proto.invariants``, not a replacement.

Layout conventions: lane axis 0 = replicas, slot axis = -2 (lane-major,
trailing group axis) or -1 (per-group kernels).  Callers pass the
ABSOLUTE-slot plane (``abs_``) for their cell layout — ``base + sidx``
for ring-position kernels, ``_cell_abs`` for fixed-cell — which is
what makes one implementation serve both.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def spot_check(old_exec, new_exec, old_base, new_base,
               old_abs, new_abs, old_cmd, new_cmd,
               old_commit, new_commit,
               kv: Optional[jnp.ndarray] = None, *,
               lane_major: bool):
    """One step's spot-check violation count.

    Shapes: ``*_exec``/``*_base`` are ``(R, ..., G?)`` lane planes,
    ``*_abs``/``*_cmd``/``*_commit`` add a slot axis before the
    (optional, lane-major) trailing group axis.  ``kv``, when given,
    is the register plane for check 4 — either shaped like ``new_exec``
    (one register per frontier, e.g. wpaxos objects) or with one extra
    value axis at position 1 (e.g. the (R, K, G) KV stores).  Returns
    int32 counts: ``(G,)`` lane-major, scalar otherwise.
    """
    def red(x):
        if lane_major:
            return jnp.sum(x, axis=tuple(range(x.ndim - 1)),
                           dtype=jnp.int32)
        return jnp.sum(x, dtype=jnp.int32)

    # 1. monotone commit frontier
    v = red(new_exec < old_exec) + red(new_base < old_base)

    # 2. same-cell committed-value stability
    v = v + red(old_commit & (old_abs == new_abs)
                & (~new_commit | (new_cmd != old_cmd)))

    # 3. per-slot agreement on the most-advanced replica's frame.
    # Sentinels are the full int32 extremes: encode_cmd can legally
    # reach 0x7FFFFFFF once ballots pass 0x4000, so a 2^30-style
    # sentinel would read as a disagreeing lane on a safe run
    # (committed values are NOOP(-2)/NO_CMD(-1)/non-negative ids, so
    # iinfo.min can never collide with a real value, and an iinfo.max
    # value agrees with the mn fill exactly when all lanes hold it)
    vis = new_commit & (new_abs == jnp.max(new_abs, axis=0,
                                           keepdims=True))
    info = jnp.iinfo(jnp.int32)
    mx = jnp.max(jnp.where(vis, new_cmd, info.min), axis=0)
    mn = jnp.min(jnp.where(vis, new_cmd, info.max), axis=0)
    v = v + red(jnp.any(vis, axis=0) & (mx != mn))

    # 4. register condition: equal frontier => equal registers
    if kv is not None:
        R = new_exec.shape[0]
        eq = new_exec[:, None] == new_exec[None, :]       # (R, R, ...)
        if kv.ndim == new_exec.ndim + 1:
            diff = jnp.any(kv[:, None] != kv[None, :], axis=2)
        else:
            diff = kv[:, None] != kv[None, :]
        pair = (jnp.arange(R)[:, None] < jnp.arange(R)[None, :]).reshape(
            (R, R) + (1,) * (eq.ndim - 2))
        v = v + red(eq & diff & pair)
    return v
