"""Checkpoint/resume for long simulation runs.

The reference has no checkpointing (SURVEY §5: in-memory store, no
snapshots — a conscious gap).  The TPU sim runtime makes it trivial:
the entire simulation state is one pytree carry (protocol state, the
in-flight message wheel, fault masks, and the PRNG key(s) — one run key
for lane-major kernels, per-group keys for vmapped ones), so a
checkpoint is an exact bit-for-bit resume point — ``run(60 steps)``
equals ``run(30); save; load; run(30)``.

Format: a single ``.npz`` with path-flattened arrays plus a JSON meta
blob (protocol name, geometry, step counter).  numpy is the container
so checkpoints are portable across hosts/devices; arrays land back on
the default device on load (orbax can be slotted in for sharded
multi-host checkpoints later without changing callers).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

_META_KEY = "__paxi_tpu_meta__"
_SEP = "|"
# bump when a kernel's carry layout changes incompatibly (e.g. the
# r4 group-major -> lane-major migration): load_carry turns a mismatch
# into a clear "incompatible layout" error instead of a bare shape error
LAYOUT_VERSION = 2


def layout_version(meta: dict) -> int:
    return int(meta.get("layout_version", 1))


def _flatten(carry: Any) -> Dict[str, np.ndarray]:
    flat = {}
    leaves = jax.tree_util.tree_flatten_with_path(carry)[0]
    for path, leaf in leaves:
        key = _SEP.join(str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _norm(path: str) -> str:
    """np.savez appends .npz when missing — normalize on both ends."""
    return path if path.endswith(".npz") else path + ".npz"


def save_carry(path: str, carry: Any, meta: Optional[dict] = None) -> None:
    """Write a resumable checkpoint of a simulation carry."""
    flat = _flatten(carry)
    meta = dict(meta or {})
    meta.setdefault("layout_version", LAYOUT_VERSION)
    flat[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    np.savez_compressed(_norm(path), **flat)


def load_carry(path: str, like: Any) -> Tuple[Any, dict]:
    """Load a checkpoint into the structure of ``like`` (a carry built by
    ``init_carry`` with the same geometry); returns (carry, meta)."""
    with np.load(_norm(path)) as z:
        meta = json.loads(bytes(z[_META_KEY]).decode()) if _META_KEY in z \
            else {}
        flat = {k: z[k] for k in z.files if k != _META_KEY}
    if layout_version(meta) != LAYOUT_VERSION:
        raise ValueError(
            f"checkpoint layout v{layout_version(meta)} is incompatible "
            f"with this build (v{LAYOUT_VERSION}): kernel carry layouts "
            "changed; re-run the simulation from scratch")
    leaves = jax.tree_util.tree_flatten_with_path(like)
    out_leaves = []
    for path_k, leaf in leaves[0]:
        key = _SEP.join(str(p) for p in path_k)
        if key not in flat:
            raise KeyError(f"checkpoint missing array {key!r}")
        arr = flat[key]
        if arr.shape != leaf.shape:
            raise ValueError(f"{key!r}: checkpoint shape {arr.shape} != "
                             f"expected {leaf.shape}")
        out_leaves.append(jax.numpy.asarray(arr).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(leaves[1], out_leaves), meta
