"""TPU simulation runtime (the `transport=tpu-sim` backend)."""

from paxi_tpu.sim.types import (FAULT_FREE, FuzzConfig, SimConfig,
                                SimProtocol, StepCtx)
from paxi_tpu.sim.runner import (SimResult, continue_run, make_pinned_run,
                                 make_recorded_run, make_run, simulate)
from paxi_tpu.sim.checkpoint import load_carry, save_carry

__all__ = ["SimConfig", "FuzzConfig", "FAULT_FREE", "SimProtocol",
           "StepCtx", "SimResult", "make_run", "simulate",
           "continue_run", "make_recorded_run", "make_pinned_run",
           "save_carry", "load_carry"]
