"""TPU simulation runtime (the `transport=tpu-sim` backend)."""

from paxi_tpu.sim.types import (FAULT_FREE, FuzzConfig, SimConfig,
                                SimProtocol, StepCtx)
from paxi_tpu.sim.runner import SimResult, make_run, simulate

__all__ = ["SimConfig", "FuzzConfig", "FAULT_FREE", "SimProtocol",
           "StepCtx", "SimResult", "make_run", "simulate"]
