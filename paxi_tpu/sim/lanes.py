"""Lane-major (G-last) exchange machinery for batched sim kernels.

TPU layout note (the whole point of this module): the vector unit tiles
the **last two** array dimensions onto (8 sublanes x 128 lanes).  The
vmap-over-groups path in sim/mailbox.py produces group-major arrays
like ``(G, R, S, R)`` whose trailing dims (64, 5) occupy <5% of each
tile — measured on a real v5e this ran *slower than one CPU core* with
wall time linear in G (zero parallel speedup) and faulted the device at
>=32k groups from padded-buffer blowup.  Here the group axis is the
**minor** dimension everywhere — state ``(R, S, G)``, mailbox planes
``(src, dst, G)``, wheel ``(delay, src, dst, G)`` — so G feeds the
lanes and every tile is full.

Boolean ack planes are additionally bit-packed by the kernels that use
this layout (``(R, S, G)`` int32 bitmask + ``lax.population_count``
instead of ``(G, R, S, R)`` bool) — the reference's ``Quorum.ACK`` /
``Majority()`` (quorum.go [driver]) as a bitwise-or and popcount.

Randomness: one PRNG key per run with *shaped* draws ``(R, G)`` /
``(src, dst, G)`` — per-group key splitting (a vmapped threefry per
group per step) is both slower and group-major.

Semantics match sim/mailbox.py exactly (same fault schedule surface:
drop/dup/delay/partition/crash/perm_crash — socket.go Crash/Drop/Slow/
Flaky [driver], same collision rule: a newly sent message overwrites an
undelivered one in the same wheel slot for the same (type, src, dst)
edge), so protocols can migrate kernel-by-kernel.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.sim.mailbox import wheel_deliver  # noqa: F401  (layout-
# agnostic: pops/rotates the leading delay axis; re-exported so batched
# and per-group paths share one delivery implementation)
from paxi_tpu.sim.mailbox import draw_edge_faults  # noqa: F401  (shape-
# generic: planes take each outbox validity plane's shape, so the same
# draw serves (src, dst) and lane-major (src, dst, G) layouts)
from paxi_tpu.sim.mailbox import wheel_insert  # noqa: F401  (rank-
# generic: the eye and crash masks grow a trailing group axis when the
# outbox validity plane is (src, dst, G) — one implementation for both
# layouts so the trace subsystem's replay guarantee can't drift)
from paxi_tpu.sim.types import FuzzConfig, Mailboxes

MailSpec = Dict[str, Tuple[str, ...]]


def empty_wheel(spec: MailSpec, n: int, g: int,
                fuzz: FuzzConfig) -> Mailboxes:
    """Timing wheel, lane-major: slot d holds messages arriving in d+1
    steps; planes are (delay, src, dst, G)."""
    d = fuzz.wheel
    out = {}
    for name, fields in spec.items():
        box = {"valid": jnp.zeros((d, n, n, g), bool)}
        for f in fields:
            box[f] = jnp.zeros((d, n, n, g), jnp.int32)
        out[name] = box
    return out


def fault_state_init(n: int, g: int) -> Dict[str, jax.Array]:
    """Connectivity + crash masks carried in the scan, lane-major."""
    return {
        "conn": jnp.ones((n, n, g), bool),    # can (src -> dst) deliver?
        "crashed": jnp.zeros((n, g), bool),   # comms-crashed replicas
    }


def fault_state_refresh(fs, rng, t, fuzz: FuzzConfig, n: int):
    """Resample partition/crash schedule every ``fuzz.window`` steps —
    shaped draws give every group an independent schedule from one key
    (semantics of mailbox.fault_state_refresh, G-last).  Scenario
    churn/outage/reconfig kills OR in every step like ``perm_crash``
    (identical for every group: a scenario is the environment, not a
    draw — see paxi_tpu/scenarios/schedule.py)."""
    scn = fuzz.scenario
    scn_kills = scn is not None and scn.kills_nodes()
    if not (fuzz.p_partition > 0 or fuzz.p_crash > 0
            or fuzz.perm_crash >= 0 or scn_kills):
        return fs
    g = fs["crashed"].shape[-1]
    k1, k2, k3 = jr.split(rng, 3)
    side = jr.bernoulli(k1, 0.5, (n, g))
    cut = jr.bernoulli(k2, fuzz.p_partition, (g,))
    conn = jnp.where(cut[None, None, :],
                     side[:, None, :] == side[None, :, :],
                     True)
    crashed = jr.bernoulli(k3, fuzz.p_crash, (n, g))
    fresh = (t % fuzz.window) == 0
    new = {
        "conn": jnp.where(fresh, conn, fs["conn"]),
        "crashed": jnp.where(fresh, crashed, fs["crashed"]),
    }
    if fuzz.perm_crash >= 0:
        # held, never resampled: a permanently dead replica stays dead
        forced = ((jnp.arange(n)[:, None] == fuzz.perm_crash)
                  & (t >= fuzz.perm_crash_at))
        new["crashed"] = new["crashed"] | forced
    if scn_kills:
        from paxi_tpu.scenarios.schedule import forced_crash
        # un-stick yesterday's deterministic overlay before OR-ing
        # today's, so churn revivals happen (see mailbox twin)
        new["crashed"] = (
            (new["crashed"] & ~forced_crash(scn, t - 1, n)[:, None])
            | forced_crash(scn, t, n)[:, None])
    return new


