"""Vectorized (batched) linearizability oracle for big sim histories.

The precedence-graph checker in ``paxi_tpu.host.history`` is exact but
O(n^3)-ish per key — right for benchmark-sized histories.  For the sim
runtime's scale (100k groups) this module provides the vectorized
**stale/future-read** check over dense op arrays, which is the register
condition the reference's checker enforces in practice: a read must not
return a value whose write was already overwritten by a write that
completed before the read started, nor a value written only after the
read ended.

Arrays (ops flattened per group; pad with valid=False):
- ``valid   (B, N) bool``
- ``key     (B, N) int32``
- ``is_read (B, N) bool``
- ``val     (B, N) int32``  unique per write within (group, key)
- ``start, end (B, N) float/int`` — any monotonic clock (sim step ids)

Returns per-group anomaly counts ``(B,) int32``.  Pure numpy so the
oracle also runs while no accelerator is attached; shapes are dense so
the same code jits under jax.numpy if handed jax arrays.
"""

from __future__ import annotations

import numpy as np


def stale_read_anomalies(valid, key, is_read, val, start, end,
                         max_elems: int = 10_000_000):
    """Chunks the batch axis so the (chunk, N, N) intermediates stay
    around ``max_elems`` booleans regardless of B."""
    valid = np.asarray(valid)
    B, N = valid.shape
    chunk = max(1, max_elems // max(N * N, 1))
    if B > chunk:
        return np.concatenate([
            stale_read_anomalies(valid[i:i + chunk],
                                 np.asarray(key)[i:i + chunk],
                                 np.asarray(is_read)[i:i + chunk],
                                 np.asarray(val)[i:i + chunk],
                                 np.asarray(start)[i:i + chunk],
                                 np.asarray(end)[i:i + chunk],
                                 max_elems)
            for i in range(0, B, chunk)])
    key = np.asarray(key)
    is_read = np.asarray(is_read)
    val = np.asarray(val)
    start = np.asarray(start)
    end = np.asarray(end)

    w_ok = valid & ~is_read                      # (B, N) writes
    r_ok = valid & is_read

    # match reads to their writes: same (key, val)
    same_key = key[:, :, None] == key[:, None, :]        # (B, r, w)
    same_val = val[:, :, None] == val[:, None, :]
    rw = r_ok[:, :, None] & w_ok[:, None, :] & same_key & same_val

    has_src = rw.any(axis=2)                              # (B, r)
    # a read of a non-initial value with no matching write is anomalous
    no_src = r_ok & (val != 0) & ~has_src

    src = rw.argmax(axis=2)                               # (B, r)
    bidx = np.arange(B)[:, None]
    w_start = np.where(has_src, start[bidx, src], 0)
    w_end = np.where(has_src, end[bidx, src], 0)

    # future read: the sourcing write started only after the read ended
    future = has_src & (w_start > end)

    # stale read: some OTHER write to the same key began after the
    # sourcing write ended and completed before the read started
    other = w_ok[:, None, :] & same_key & ~same_val       # (B, r, w)
    overw = other & (start[:, None, :] > w_end[:, :, None]) \
                  & (end[:, None, :] < start[:, :, None])
    stale = has_src & overw.any(axis=2)

    # initial-value read (val == 0): stale if ANY write to the key
    # completed before the read started
    init_r = r_ok & (val == 0)
    any_w = w_ok[:, None, :] & same_key & (end[:, None, :]
                                           < start[:, :, None])
    init_stale = init_r & any_w.any(axis=2)

    bad = no_src | future | stale | init_stale
    return bad.sum(axis=1).astype(np.int32)
