"""Static configs and the protocol-plugin interface for the TPU sim runtime.

The reference's plugin boundary is ``Node.Register(msgType, handler)`` +
``Replica.Run()`` (node.go) [driver].  The sim runtime's equivalent: a
protocol provides

- a *mailbox spec* (message types and their int32 fields — the gob-
  registration analog, codec.go),
- ``init_state(cfg, rng)`` building a per-group struct-of-arrays pytree,
- a pure ``step(state, inbox, ctx) -> (state, outbox)`` transition
  (all handlers fused, fully masked — no data-dependent control flow),
- per-step ``invariants`` (the safety oracle; generalizes history.go's
  linearizability check), and ``metrics``.

The runner vmaps ``step`` over the group axis, drives a lock-step
message exchange with a fuzz schedule, and scans over steps.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax

Array = jax.Array
State = Dict[str, Array]
Mailboxes = Dict[str, Dict[str, Array]]


@dataclass(frozen=True)
class SimConfig:
    """Static (hashable) per-protocol group geometry; jit static arg.

    Mirrors the knobs of config.go that matter inside the kernel.
    """

    n_replicas: int = 3
    n_slots: int = 64          # log window (reference log is unbounded map)
    n_keys: int = 16           # KV key-space inside the sim
    n_zones: int = 1           # zone grid rows (WPaxos); R % zones == 0
    exec_window: int = 4       # max slots executed per replica per step
    ballot_stride: int = 64    # ballot = round*stride + replica_idx
    election_timeout: int = 8  # steps without leader activity before P1a
    backoff: int = 8           # randomized extra timeout (anti-dueling)
    retry_timeout: int = 6     # steps with a stuck frontier before re-propose
    # protocol-specific extras (ignored by protocols that don't use them)
    n_objects: int = 8         # WPaxos: per-key paxos objects per group
    steal_threshold: int = 3   # WPaxos policy.go threshold analog
    grid_q2: int = 1           # WPaxos: zones in a phase-2 grid quorum
    locality: float = 0.8      # WPaxos workload: P(demand home-zone object)
    fast_quorum: bool = True   # EPaxos fast path enabled
    # BPaxos compartmentalized tier (protocols/bpaxos): node-index role
    # split — the first ``n_proxies`` nodes are proxy leaders, the next
    # ``grid_rows * grid_cols`` are the acceptor grid (write quorum =
    # one full row, read quorum = one full column), the rest are
    # replica executors; ``batch_max`` bounds the HT-Paxos batch a
    # proxy amortizes over one grid round (commands per slot)
    n_proxies: int = 2
    grid_rows: int = 2
    grid_cols: int = 2
    batch_max: int = 4
    # switchnet in-fabric consensus tier (paxi_tpu/switchnet): the
    # switchpaxos kernel mirrors the programmable-switch acceptor +
    # NOPaxos-style sequencer as carry planes.  ``sw_window`` is the
    # switch's bounded per-slot register file (fixed size, no heap —
    # slots outside it overflow to the replica fall-back path);
    # ``sw_down_*`` is the sequencer-churn schedule compiled from a
    # Scenario's SwitchChurn (scenarios/compile.apply_switch): during
    # down windows the switch neither votes nor stamps (register state
    # persists — the failover model migrates it), and each window end
    # bumps the ordered-multicast session epoch.  Static, so the same
    # trace meta that pins the geometry pins the churn schedule.
    sw_window: int = 16
    sw_down_start: int = -1    # first down window start (-1: never)
    sw_down_period: int = 0    # steps between window starts (0: one-shot)
    sw_down_for: int = 0       # steps each window lasts
    # traffic workload (paxi_tpu/workload/spec.Workload; Any-typed to
    # keep this module import-cycle-free, like FuzzConfig.scenario
    # below).  When set, kernels that serve a command stream derive
    # per-slot key ids / read flags / key classes from the spec's
    # counter-based draws (workload/compile.py) instead of hashing the
    # command word, and accumulate per-class latency histograms.
    # Frozen + hashable, so it rides the jit static arg and the trace
    # ``sim_cfg`` meta exactly like the geometry knobs.
    workload: Any = None

    @property
    def majority(self) -> int:
        return self.n_replicas // 2 + 1

    @property
    def fast_size(self) -> int:
        return -(-3 * self.n_replicas // 4)  # ceil(3N/4)

    def with_(self, **kw) -> "SimConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class FuzzConfig:
    """Randomized fault schedule applied at the message exchange.

    Vectorized generalization of socket.go's fault injection surface
    (Crash/Drop/Slow/Flaky) [driver: drop/dup/reorder/partition].
    ``max_delay=1`` and all probabilities 0 => fault-free lock-step.
    """

    max_delay: int = 1         # messages arrive after 1..max_delay steps
    p_drop: float = 0.0        # per-message drop probability (Flaky)
    p_dup: float = 0.0         # per-message duplication probability
    p_crash: float = 0.0       # per-replica comms-crash prob per window
    p_partition: float = 0.0   # prob a window has a random bipartition
    window: int = 16           # steps between fault-schedule resamples
    # permanent failure (never heals, unlike the resampled p_crash
    # windows): replica ``perm_crash`` goes comms-dead at step
    # ``perm_crash_at`` and stays dead — the schedule that forces
    # protocols to exercise real recovery/takeover, not just retries
    perm_crash: int = -1
    perm_crash_at: int = 0
    # WAN topology / churn / reconfiguration scenario
    # (paxi_tpu/scenarios/spec.Scenario; Any-typed to keep this module
    # import-cycle-free — scenarios/compile.py imports FuzzConfig).
    # Folded into the schedule draws by sim/mailbox.py + sim/lanes.py:
    # the zone matrix replaces the uniform delay draw, churn/outage/
    # reconfig kills OR into the crash plane — both still materialize
    # into the recorded schedule, so capture/replay/shrink work
    # unchanged.
    scenario: Any = None

    @property
    def wheel(self) -> int:
        d = max(self.max_delay, 1)
        if self.scenario is not None:
            d = max(d, self.scenario.max_latency())
        return d

    @property
    def faulty(self) -> bool:
        return (self.p_drop > 0 or self.p_dup > 0 or self.p_crash > 0
                or self.p_partition > 0 or self.max_delay > 1
                or self.perm_crash >= 0 or self.scenario is not None)


FAULT_FREE = FuzzConfig()


class StepCtx(NamedTuple):
    """Per-step context handed to protocol transition functions."""

    rng: Array      # per-group PRNG key for this step
    t: Array        # step index (traced scalar)
    cfg: SimConfig  # static geometry


@dataclass(frozen=True)
class SimProtocol:
    """A protocol plugin for the TPU sim runtime (see module docstring).

    Two kernel layouts are supported (see sim/lanes.py for why):

    - ``batched=False`` (legacy): per-group functions — ``init_state``
      builds one group's state, ``step`` sees (R, ...) state and
      (src, dst) mailbox planes; the runner vmaps over a leading group
      axis.  Group-major arrays starve the TPU vector lanes.
    - ``batched=True`` (lane-major): the kernel IS the batch — state
      arrays carry the group axis as their **last** dimension
      ((R, G), (R, S, G), ...), mailbox planes are (src, dst, G),
      ``init_state(cfg, rng, n_groups)`` takes the group count,
      ``metrics``/``invariants`` return already-aggregated scalars.
      This is the layout that actually feeds the 8x128 vector unit.
    """

    name: str
    mailbox_spec: Callable[[SimConfig], Dict[str, Tuple[str, ...]]]
    # two accepted signatures, keyed on ``batched`` below:
    #   batched=False -> init_state(cfg, rng) builds ONE group's state
    #   batched=True  -> init_state(cfg, rng, n_groups) builds the whole
    #                    lane-major batch (group axis LAST)
    init_state: Callable[..., State]
    step: Callable[[State, Mailboxes, StepCtx], Tuple[State, Mailboxes]]
    metrics: Callable[[State, SimConfig], Dict[str, Array]]
    invariants: Callable[[State, State, SimConfig], Array]
    batched: bool = False
