"""Sliding-window ring primitives shared by lane-major sim kernels.

The reference keeps unbounded per-slot maps (``log map[int]*entry``,
paxos.go [driver]); inside a jitted kernel the log must be a fixed-shape
ring instead (SURVEY §7 slot-recycling requirement — a 10M-slot horizon
runs in a 64-slot ring).  TWO ring-layout contracts coexist in this
tree; know which one a kernel uses before touching its slot math:

- **Sliding-window (this module)**: ring position ``i`` holds absolute
  slot ``base + i``; the window slides forward as the execute frontier
  advances via :func:`shift_window` data movements.  Every shift
  scalarizes into a gather on XLA:CPU, which is why the hot-path
  kernels left this layout.  Still used by: epaxos, kpaxos,
  switchpaxos (via sim/ballot_ring.py), and the frozen pre-rewrite
  references ``protocols/*/sim_sw.py``.
- **Fixed-cell (sim/cell.py)**: absolute slot ``a`` lives at cell
  ``a % S`` forever; window moves are masked clears of recycled cells,
  and replicas' cells align without per-pair realignment.  Used by:
  paxos (+ the per-group ``paxos_pg``), sdpaxos, wankeeper (via
  sim/cell_ring.py), wpaxos, bpaxos, and chain (fixed-cell since
  birth).  The PXL11x lint family pins the rewritten kernels to it,
  and tests/test_fixed_cell_equiv.py proves each rewrite
  bit-canonically equal to its ``sim_sw`` reference.

These helpers operate on lane-major arrays (group axis LAST, slot axis
second-to-last) so every sliding-window kernel shares one shift
implementation: epaxos (R, S, G) + deps planes, kpaxos (R, P, S, G),
...  The masked-select helpers (``pick_src``/``take_replica``/
``dst_major``/``diag2``) are layout-free and serve both contracts.
"""

from __future__ import annotations

import jax.numpy as jnp


def require_packable(n_replicas: int) -> None:
    """Guard for kernels that bit-pack per-replica acks into int32
    masks: bit 31 is the sign bit and XLA shifts wrap mod 32, so
    replica 32 would silently alias replica 0."""
    if n_replicas > 31:
        raise ValueError(f"n_replicas={n_replicas} > 31: packed int32 "
                         "ack masks support at most 31 replicas per group")


def dst_major(x):
    """Mailbox plane (src, dst, G) -> (me=dst, src, G) — the receiver-
    major view every lane-major handler consumes."""
    return jnp.swapaxes(x, 0, 1)


def diag2(x):
    """State plane (R, R, ...) -> (R, ...) at second-index == replica —
    a replica's own row (its own partition/instance column), unrolled
    over the tiny R axis."""
    return jnp.stack([x[p, p] for p in range(x.shape[0])], axis=0)


def shift_deps(pl, adv, fill=-1):
    """shift_window for a deps-style plane ``(..., S, R, G)`` whose slot
    axis sits third-from-last: transpose the (S, R) pair around the
    shift and back."""
    return jnp.swapaxes(
        shift_window(jnp.swapaxes(pl, -3, -2), adv[..., None, :], fill),
        -3, -2)


def shift_window(arr, adv, fill):
    """Slide ``arr (..., S, G)`` forward along the slot axis by
    ``adv (..., G)`` >= 0: out[..., i, g] = arr[..., i + adv[..., g], g]
    (``fill`` past the end).  The ring-recycling / base-alignment
    primitive."""
    S = arr.shape[-2]
    idx = jnp.arange(S, dtype=jnp.int32)[:, None] + adv[..., None, :]
    valid = (idx >= 0) & (idx < S)
    idxc = jnp.clip(idx, 0, S - 1)
    return jnp.where(valid, jnp.take_along_axis(arr, idxc, axis=-2), fill)


def shift_row(row, adv, fill):
    """Like :func:`shift_window` but from a single source plane viewed
    by R readers with per-(r, g) offsets: row ``(S, G)``, adv ``(R, G)``
    -> out[r, i, g] = row[i + adv[r, g], g]."""
    R = adv.shape[0]
    S, G = row.shape
    idx = jnp.arange(S, dtype=jnp.int32)[None, :, None] + adv[:, None, :]
    valid = (idx >= 0) & (idx < S)
    idxc = jnp.clip(idx, 0, S - 1)
    src = jnp.broadcast_to(row[None], (R, S, G))
    return jnp.where(valid, jnp.take_along_axis(src, idxc, axis=1), fill)


def pick_src(field, src_idx):
    """out[d, g] = field[src_idx[d, g], d, g] — select each
    destination's chosen sender's message from a (src, dst, G) mailbox
    plane, unrolled over the tiny src axis (masked selects instead of
    an XLA gather)."""
    acc = jnp.zeros_like(field[0])
    for s in range(field.shape[0]):
        acc = jnp.where(src_idx == s, field[s], acc)
    return acc


def take_replica(x, idx):
    """out[r, ..., g] = x[idx[r, g], ..., g] — adopt another replica's
    row of a (R, ..., G) state array, unrolled over the tiny R axis
    (masked selects instead of an XLA gather)."""
    R = x.shape[0]
    mid = x.ndim - 2
    mshape = (idx.shape[0],) + (1,) * mid + (idx.shape[-1],)
    acc = jnp.zeros(mshape[:1] + x.shape[1:], x.dtype)
    for s in range(R):
        m = (idx == s).reshape(mshape)
        acc = jnp.where(m, x[s][None], acc)
    return acc
