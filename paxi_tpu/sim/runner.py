"""The sim driver: lax.scan over steps, jit the whole run.

This lifts the reference's per-replica message loop (node.go Node.Run ->
handler dispatch -> Quorum.ACK [driver]) into a single fused kernel over an
(instance x replica) batch: every step, every group delivers its in-flight
messages, applies the protocol's pure transition, refreshes its fault
schedule, and checks safety invariants.

Two kernel layouts (see sim/lanes.py): lane-major protocols
(``proto.batched``) carry the group axis as the LAST dimension of every
array and run the whole batch natively with one PRNG key; legacy
per-group kernels are vmapped over a leading group axis with per-group
keys.  The public ``SimResult.state`` is group-major (G leading) either
way.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.metrics.simcount import counters_of, step_counts
from paxi_tpu.sim import lanes
from paxi_tpu.sim import mailbox as mb
from paxi_tpu.sim.types import (FAULT_FREE, FuzzConfig, SimConfig,
                                SimProtocol, StepCtx)


@dataclass
class SimResult:
    state: Any                   # final batched state pytree (G leading)
    metrics: Dict[str, jax.Array]  # aggregated over groups (protocol
    # metrics + the runner's ``net_*`` message/fault counters)
    violations: jax.Array        # total invariant violations (int32)
    steps: int
    groups: int
    # per-step counter time series ({name: (T,) int32}, prefix
    # stripped) — the scan's ys before the time reduction; populated
    # only when ``simulate(..., series=True)`` asked for the export
    counter_series: Optional[Dict[str, jax.Array]] = None

    @property
    def counters(self) -> Dict[str, jax.Array]:
        """Per-run message/fault counters threaded through the scan
        (see paxi_tpu/metrics/simcount.py), prefix stripped."""
        return counters_of(self.metrics)

    # ---- on-device observability (instrumented kernels only) ---------
    # The commit-latency histogram rides in state as the ``m_lat_hist``
    # measurement plane ((G, N_BUCKETS) group-major here) because the
    # metrics dict is scalar-valued by contract; these views fold it
    # over groups.  ``None``/absent on kernels without the planes.
    @property
    def latency_hist(self):
        """Whole-batch commit-latency bucket vector ((N_BUCKETS,)
        int32 numpy, metrics/lathist layout; any deltas still pending
        the deferred flush are folded in), or None."""
        from paxi_tpu.metrics import lathist
        return lathist.total_hist(self.state)

    @property
    def inscan_violations(self) -> Optional[int]:
        """Total in-scan linearizability spot-check violations
        (sim/inscan), or None when the kernel is uninstrumented."""
        v = self.metrics.get("inscan_violations")
        return None if v is None else int(v)

    def latency_summary(self) -> Optional[Dict[str, Any]]:
        """The bench-row form: p50/p99/p999 in lock-step rounds plus
        sample count, mean and sparse buckets (lathist.summarize)."""
        from paxi_tpu.metrics import lathist
        hist = self.latency_hist
        if hist is None:
            return None
        return lathist.summarize(hist,
                                 int(self.metrics.get("commit_lat_sum", 0)))

    def latency_snapshot(self, step_seconds: float = 1.0,
                         name: str = "paxi_sim_commit_latency_seconds",
                         **labels: str) -> Optional[Dict[str, Any]]:
        """Host-registry-format histogram snapshot (merges and renders
        through metrics/registry's one code path); None when
        uninstrumented."""
        from paxi_tpu.metrics import lathist
        hist = self.latency_hist
        if hist is None:
            return None
        snap = lathist.to_host_snapshot(
            hist, int(self.metrics.get("commit_lat_sum", 0)),
            step_seconds=step_seconds)
        return {"name": name, "labels": dict(labels), **snap}


def init_carry(proto: SimProtocol, cfg: SimConfig, fuzz: FuzzConfig,
               n_groups: int, rng: jax.Array):
    spec = proto.mailbox_spec(cfg)
    k_state, k_run = jr.split(rng)
    if proto.batched:
        # lane-major: state (.., G), wheel (d, src, dst, G), one run key
        state = proto.init_state(cfg, k_state, n_groups)
        wheel = lanes.empty_wheel(spec, cfg.n_replicas, n_groups, fuzz)
        fs = lanes.fault_state_init(cfg.n_replicas, n_groups)
        return (state, wheel, fs, k_run)
    state = jax.vmap(lambda k: proto.init_state(cfg, k))(
        jr.split(k_state, n_groups))
    if isinstance(state, dict) and "wl_gid" in state:
        # workload runs key their counter-based draws on the GLOBAL
        # group id; per-group init_state emits a scalar placeholder
        # (it cannot see its own batch index under vmap) — patch the
        # vmapped plane to the real ids so the per-group lowering
        # draws the exact command planes of the lane-major one
        state["wl_gid"] = jnp.arange(n_groups, dtype=jnp.int32)
    wheel = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_groups,) + x.shape),
        mb.empty_wheel(spec, cfg.n_replicas, fuzz))
    fs = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_groups,) + x.shape),
        mb.fault_state_init(cfg.n_replicas))
    rngs = jr.split(k_run, n_groups)
    return (state, wheel, fs, rngs)


def _group_step(proto: SimProtocol, cfg: SimConfig, fuzz: FuzzConfig,
                carry_g, t, sched_t=None, pin_on=None, record=False,
                exchange: str = "dense"):
    """One lock-step round: deliver -> step -> refresh faults -> insert
    -> check invariants.  ONE implementation for both layouts — only the
    exchange module differs (lane-major vs per-group planes); the caller
    vmaps this over a leading group axis for non-batched protocols.

    Trace hooks (see paxi_tpu/trace/):
    - ``sched_t``: this step's recorded single-group fault schedule
      (``{"conn", "crashed", "faults"}``); it replaces the drawn
      schedule for the pinned group — ``pin_on`` is a static group index
      under the lane-major layout, a traced per-group boolean under
      vmap.  The PRNG chain is split identically either way, so a
      replay whose recorded schedule equals the drawn one is bit-for-bit
      the original run.
    - ``record=True``: additionally emit the materialized schedule and
      (lane-major) per-group violations, so capture can slice out the
      violating group's schedule.
    """
    if exchange == "pallas" and proto.batched:
        # the fused lane-major Pallas exchange (paxi_tpu/ops/exchange):
        # same semantics, one kernel per message type instead of ~10
        # XLA ops per field — interpret-mode on CPU, compiled on TPU
        from paxi_tpu.ops import exchange as ops
    else:
        ops = lanes if proto.batched else mb
    state, wheel, fs, rng = carry_g
    rng, k_step, k_fault, k_ins = jr.split(rng, 4)
    inbox, wheel = ops.wheel_deliver(wheel)
    new_state, outbox = proto.step(state, inbox, StepCtx(k_step, t, cfg))
    fs = ops.fault_state_refresh(fs, k_fault, t, fuzz, cfg.n_replicas)
    faults = mb.draw_edge_faults(k_ins, outbox, fuzz)
    if sched_t is not None:
        if proto.batched:
            g = pin_on
            fs = dict(fs,
                      conn=fs["conn"].at[:, :, g].set(sched_t["conn"]),
                      crashed=fs["crashed"].at[:, g].set(
                          sched_t["crashed"]))
            faults = {
                name: {k: v.at[:, :, g].set(sched_t["faults"][name][k])
                       for k, v in f.items()}
                for name, f in faults.items()}
        else:
            on = pin_on

            def mix(drawn, rec):
                return jnp.where(on, rec, drawn)

            fs = dict(fs, conn=mix(fs["conn"], sched_t["conn"]),
                      crashed=mix(fs["crashed"], sched_t["crashed"]))
            faults = {
                name: {k: mix(v, sched_t["faults"][name][k])
                       for k, v in f.items()}
                for name, f in faults.items()}
    # on-device metrics carry: pure reductions over the same planes
    # delivery consumed — AFTER the sched_t substitution, so a pinned
    # replay counts the recorded schedule and reproduces the captured
    # counters exactly (see metrics/simcount.py).  Computed BEFORE the
    # insert so the pre-insert wheel exposes delay collisions (a put
    # overwriting an in-flight message on the same edge cell).
    counts = step_counts(inbox, outbox, faults, fs, cfg.n_replicas,
                         wheel=wheel)
    wheel = ops.wheel_insert(wheel, outbox, fs, fuzz, faults)
    if record and proto.batched:
        viol = per_group_invariants(proto, cfg, state, new_state)
    else:
        viol = proto.invariants(state, new_state, cfg)
    if record:
        # record only EFFECTIVE fault events: a drop/dup/delay on an
        # edge wheel_insert would mask anyway (empty outbox, self-edge,
        # severed conn, crashed endpoint) is a delivery no-op, so
        # neutralizing it keeps replay bit-for-bit while making the
        # recorded schedule sparse — which is what lets the shrinker
        # and the host-runtime projection work on real events instead
        # of PRNG noise
        live = mb.live_mask(fs, 3 if proto.batched else 2,
                            cfg.n_replicas)
        rec_faults = {
            name: {"drop": f["drop"] & outbox[name]["valid"] & live,
                   "delay": jnp.where(outbox[name]["valid"] & live,
                                      f["delay"], 1),
                   "dup": f["dup"] & outbox[name]["valid"] & live}
            for name, f in faults.items()}
        sched = {"conn": fs["conn"], "crashed": fs["crashed"],
                 "faults": rec_faults}
        return (new_state, wheel, fs, rng), (viol, counts, sched)
    return (new_state, wheel, fs, rng), (viol, counts)


def flush_measurements(proto: SimProtocol, cfg: SimConfig, carry, t):
    """Deferred commit-latency binning for per-group kernels (the
    observability layer, metrics/lathist).

    An instrumented per-group kernel stores each newly committed
    cell's propose->commit delta in an ``m_commit_dt`` pending plane
    (one masked write on the hot path) instead of binning per step;
    this hook — called by EVERY scan body that vmaps a per-group step
    (make_run / record / pinned / the sharded twins, so all runners
    bin at identical steps and capture/replay determinism holds) —
    runs the N_BUCKETS reduction fan only every ``flush_every(S)``
    steps, under a batch-level ``lax.cond`` (a real dynamic branch:
    the predicate is group-independent, so it sits OUTSIDE the vmap
    where cond does not degrade to select).  End-of-run residuals are
    folded on host by ``lathist.total_hist``.  No-op for kernels
    without the plane; lane-major kernels with one flush directly
    (their group axis is a trailing array dim, no vmap involved)."""
    state = carry[0]
    if not (isinstance(state, dict) and "m_commit_dt" in state):
        return carry
    from paxi_tpu.metrics import lathist
    every = lathist.flush_every(cfg.n_slots)

    def do(s):
        if proto.batched:
            return lathist.flush_pending(s)
        return jax.vmap(lathist.flush_pending)(s)

    state = jax.lax.cond((t + 1) % every == 0, do, lambda s: s, state)
    return (state,) + tuple(carry[1:])


def per_group_invariants(proto: SimProtocol, cfg: SimConfig, old, new):
    """Per-group invariant violations for a lane-major kernel.  Batched
    ``invariants`` return already-aggregated scalars and index arrays
    assuming a trailing G axis, so vmapping them per group is not
    possible; instead map over width-1 group slices (groups are
    independent, so the slice totals sum to the aggregate)."""
    G = jax.tree_util.tree_leaves(new)[0].shape[-1]

    def one(g):
        def sl(x):
            return jax.lax.dynamic_slice_in_dim(x, g, 1, axis=-1)
        return proto.invariants(jax.tree.map(sl, old),
                                jax.tree.map(sl, new), cfg)

    return jax.lax.map(one, jnp.arange(G))


def make_scan_body(proto: SimProtocol, cfg: SimConfig, fuzz: FuzzConfig,
                   exchange: str = "dense"):
    """The per-step transition shared by make_run, the sharded runner
    (parallel/mesh.py) and the driver entry point.  Lane-major kernels
    (proto.batched) run the whole batch natively; per-group kernels are
    vmapped over a leading group axis.  ``exchange`` selects the
    message-exchange implementation for lane-major kernels: ``dense``
    (sim/mailbox XLA ops) or ``pallas`` (the fused kernels in
    paxi_tpu/ops/exchange — bench.py's ``--backend pallas``)."""
    step1 = functools.partial(_group_step, proto, cfg, fuzz,
                              exchange=exchange)
    if proto.batched:
        def bbody(carry, t):
            carry, ys = step1(carry, t)
            return flush_measurements(proto, cfg, carry, t), ys

        return bbody

    def body(carry, t):
        carry, (viol, counts) = jax.vmap(step1, in_axes=(0, None))(carry, t)
        carry = flush_measurements(proto, cfg, carry, t)
        return carry, (jnp.sum(viol),
                       {k: jnp.sum(v) for k, v in counts.items()})

    return body


def finish_run(proto: SimProtocol, cfg: SimConfig, carry, viols,
               counts=None, group_mask=None):
    """Shared aggregation tail: per-group metrics summed over groups,
    plus the scan's per-step ``net_*`` counters summed over time and
    folded into the metrics dict.  One implementation for both the
    straight and the resumed path, so checkpointed runs can never
    diverge from uninterrupted ones — and part of the runner's
    cross-module contract (parallel/mesh.py calls it inside each
    shard).  Lane-major kernels aggregate internally; their final state
    is transposed back to the public group-major layout (one cheap
    transpose per run, outside the hot loop).

    ``group_mask`` (per-group kernels only) excludes groups from the
    metric sums — the sharded runner's inert-padding contract (a padded
    batch reports only the real groups' totals)."""
    state = carry[0]
    net = ({k: jnp.sum(v) for k, v in counts.items()}
           if counts is not None else {})
    if proto.batched:
        assert group_mask is None, "lane-major metrics aggregate in-kernel"
        metrics = {**proto.metrics(state, cfg), **net}
        state = jax.tree.map(lambda x: jnp.moveaxis(x, -1, 0), state)
        return state, metrics, jnp.sum(viols)
    per_group = jax.vmap(lambda s: proto.metrics(s, cfg))(state)
    if group_mask is not None:
        per_group = {k: jnp.where(group_mask, v, 0)
                     for k, v in per_group.items()}
    metrics = {**{k: jnp.sum(v) for k, v in per_group.items()}, **net}
    return state, metrics, jnp.sum(viols)


def make_run(proto: SimProtocol, cfg: SimConfig,
             fuzz: FuzzConfig = FAULT_FREE, series: bool = False,
             exchange: str = "dense"):
    """Build ``run(rng, n_groups, n_steps) -> SimResult`` (jitted).

    n_groups / n_steps are static; the whole simulation is one XLA
    computation (scan over steps of a vmapped group transition).

    ``series=True`` additionally returns the per-step ``net_*``
    counter stack ({name: (T,)}) as a fourth output — the scan's ys
    BEFORE the time reduction, i.e. a counter time series at zero
    extra on-device cost (the reduction output is unchanged, so the
    default signature stays three-valued for every existing caller).

    ``exchange="pallas"`` swaps the lane-major message exchange for
    the fused Pallas kernels (see make_scan_body).
    """
    body = make_scan_body(proto, cfg, fuzz, exchange=exchange)

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def run(rng, n_groups: int, n_steps: int):
        carry = init_carry(proto, cfg, fuzz, n_groups, rng)
        carry, (viols, counts) = jax.lax.scan(body, carry,
                                              jnp.arange(n_steps))
        out = finish_run(proto, cfg, carry, viols, counts)
        if series:
            return (*out, counts)
        return out

    return run


def make_recorded_run(proto: SimProtocol, cfg: SimConfig,
                      fuzz: FuzzConfig = FAULT_FREE):
    """Build the capture-mode runner (the sim runner's ``record`` mode):

    ``run(rng, n_groups, n_steps) -> (state, metrics, viols_total,
    viol_steps, sched)`` where ``viol_steps`` is the per-step, PER-GROUP
    violation matrix (T, G) — locating the violating group is the whole
    point — and ``sched`` is the materialized fault schedule for every
    group and step (conn/crashed planes plus per-message-type
    drop/delay/dup planes), stacked over time.  The PRNG chain is
    identical to make_run's, so the recorded schedule is exactly what
    the normal run consumed."""
    step1 = functools.partial(_group_step, proto, cfg, fuzz, record=True)
    if proto.batched:
        def body(carry, t):
            carry, ys = step1(carry, t)
            return flush_measurements(proto, cfg, carry, t), ys
    else:
        def body(carry, t):
            carry, (viol, counts, sched) = jax.vmap(
                step1, in_axes=(0, None))(carry, t)
            carry = flush_measurements(proto, cfg, carry, t)
            return carry, (viol,
                           {k: jnp.sum(v) for k, v in counts.items()},
                           sched)

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def run(rng, n_groups: int, n_steps: int):
        carry = init_carry(proto, cfg, fuzz, n_groups, rng)
        carry, (viols, counts, sched) = jax.lax.scan(body, carry,
                                                     jnp.arange(n_steps))
        state, metrics, total = finish_run(proto, cfg, carry, viols,
                                           counts)
        return state, metrics, total, viols, sched

    return run


def make_pinned_run(proto: SimProtocol, cfg: SimConfig,
                    fuzz: FuzzConfig, group: int):
    """Build the replay-mode runner: ``run(rng, n_groups, sched) ->
    (state, metrics, viols_g_total, viol_steps_g)``.

    ``sched`` is a time-stacked single-group schedule (a trace's
    pytree); group ``group`` consumes it INSTEAD of PRNG draws while the
    other groups keep their drawn schedules (they are scaffolding — with
    the original seed and geometry they reproduce the captured run
    exactly, so the traced group's workload is pinned too).  Violations
    are reported for the traced group only."""
    def sl(x):
        return jax.lax.dynamic_slice_in_dim(x, group, 1, axis=-1)

    def body(carry, xt):
        t, sched_t = xt
        old_state = carry[0]
        if proto.batched:
            carry, (_, counts) = _group_step(proto, cfg, fuzz, carry, t,
                                             sched_t=sched_t, pin_on=group)
            viol_g = proto.invariants(jax.tree.map(sl, old_state),
                                      jax.tree.map(sl, carry[0]), cfg)
            carry = flush_measurements(proto, cfg, carry, t)
            return carry, (viol_g, counts)
        gidx = jnp.arange(jax.tree_util.tree_leaves(old_state)[0].shape[0])
        carry, (viol, counts) = jax.vmap(
            lambda cg, on: _group_step(proto, cfg, fuzz, cg, t,
                                       sched_t=sched_t, pin_on=on),
            in_axes=(0, 0))(carry, gidx == group)
        carry = flush_measurements(proto, cfg, carry, t)
        return carry, (viol[group],
                       {k: jnp.sum(v) for k, v in counts.items()})

    @functools.partial(jax.jit, static_argnums=(1,))
    def run(rng, n_groups: int, sched):
        carry = init_carry(proto, cfg, fuzz, n_groups, rng)
        n_steps = jax.tree_util.tree_leaves(sched)[0].shape[0]
        carry, (viols, counts) = jax.lax.scan(body, carry,
                                              (jnp.arange(n_steps), sched))
        state, metrics, total = finish_run(proto, cfg, carry, viols,
                                           counts)
        return state, metrics, total, viols

    return run


def simulate(proto: SimProtocol, cfg: SimConfig, n_groups: int,
             n_steps: int, fuzz: FuzzConfig = FAULT_FREE,
             seed: int = 0, series: bool = False) -> SimResult:
    """Convenience one-shot entry (compiles on first call per shape).
    ``series=True`` also exports the per-step counter time series on
    ``SimResult.counter_series``."""
    run = make_run(proto, cfg, fuzz, series=series)
    out = run(jr.PRNGKey(seed), n_groups, n_steps)
    state, metrics, viols = out[:3]
    jax.block_until_ready(viols)
    cs = (counters_of(out[3]) if series else None)
    return SimResult(state=state, metrics=metrics, violations=viols,
                     steps=n_steps, groups=n_groups, counter_series=cs)


_CONTINUE_CACHE: dict = {}


def continue_run(proto: SimProtocol, cfg: SimConfig, carry,
                 t0: int, n_steps: int,
                 fuzz: FuzzConfig = FAULT_FREE):
    """Advance a simulation from an existing carry (checkpoint/resume
    seam — see sim/checkpoint.py).  ``t0`` is the absolute step index the
    carry was paused at (a traced operand, so resuming at a new offset
    reuses the compiled executable); resumed runs are bit-for-bit
    identical to uninterrupted ones.  Returns (SimResult, new_carry).
    Note the ``net_*`` counters are flow-per-segment (this call's
    steps), unlike the state-derived protocol metrics.

    The input carry's buffers are DONATED to the step — the multi-GB
    100k-group state advances in place instead of being copied per
    segment.  Don't reuse a carry after passing it here; resume from
    the returned one (or a checkpoint)."""
    key = (id(proto), cfg, fuzz)
    run = _CONTINUE_CACHE.get(key)
    if run is None:
        body = make_scan_body(proto, cfg, fuzz)

        @functools.partial(jax.jit, static_argnums=(2,),
                           donate_argnums=(0,))
        def run(carry, t0, n_steps: int):
            carry, (viols, counts) = jax.lax.scan(body, carry,
                                                  t0 + jnp.arange(n_steps))
            return carry, *finish_run(proto, cfg, carry, viols, counts)

        _CONTINUE_CACHE[key] = run
    carry, state, metrics, viols = run(carry, jnp.int32(t0), n_steps)
    jax.block_until_ready(viols)
    n_groups = jax.tree_util.tree_leaves(state)[0].shape[0]
    return SimResult(state=state, metrics=metrics, violations=viols,
                     steps=n_steps, groups=n_groups), carry
