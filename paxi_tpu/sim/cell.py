"""Fixed-cell ring primitives shared by lane-major sim kernels.

The OTHER ring-layout contract, next to ``sim/ring.py``'s sliding
window.  Two layouts coexist in this tree:

- **Sliding-window** (``sim/ring.py``): ring position ``i`` holds
  absolute slot ``base + i``; advancing the window is a
  ``shift_window`` data movement per plane per step.  On XLA:CPU those
  shifts scalarize into gathers and dominated the north-star bench
  (~70% of step cost pre-PR 6).
- **Fixed-cell** (this module): absolute slot ``a`` lives at ring cell
  ``a % S`` *forever*.  Advancing the window is a masked **clear** of
  the recycled cells — no data movement — and any two replicas' cells
  line up without per-pair realignment: cell ``c`` refers to the same
  absolute slot at replicas ``x`` and ``y`` exactly when that slot is
  inside both windows (all in-window slots congruent to ``c`` mod
  ``S`` coincide).

``protocols/paxos/sim_pg.py`` pioneered the mapping per-group (PR 6,
412 s -> 107 s at 100k groups x 36 steps); these helpers carry it to
the lane-major layout (group axis LAST) so the paxos / sdpaxos /
wankeeper / bpaxos / wpaxos kernels share one audited copy of the
cell-index arithmetic.  The shared fixed-cell consensus core built on
them is ``sim/cell_ring.py`` (the ``ballot_ring`` twin); each rewritten
kernel is proven BIT-CANONICALLY equal to its frozen sliding-window
reference (``protocols/*/sim_sw.py``) on pinned fuzz seeds —
``window_view_np`` below is the canonicalizer that maps a fixed-cell
state onto the window order the old layout stored directly.

Shape conventions (lane-major): ring planes ``(..., S, G)`` with the
slot axis second-to-last, ``base (..., G)`` absolute; the deps variant
serves epaxos-style ``(..., S, R, G)`` planes whose slot axis sits
third-from-last.
"""

from __future__ import annotations

import jax.numpy as jnp


def cell_abs(base, S: int):
    """The absolute slot cell ``c`` currently holds: the unique element
    of ``[base, base + S)`` congruent to ``c`` (mod S).  ``base`` is
    ``(..., G)``; returns ``(..., S, G)``.  Pure elementwise — the
    fixed-mapping replacement for ``base + ring_position``."""
    sidx = jnp.arange(S, dtype=jnp.int32)
    b = base[..., None, :]
    return b + jnp.remainder(sidx[:, None] - b, S)


def cell_abs_deps(base, S: int):
    """``cell_abs`` for deps-style planes ``(..., S, R, G)`` whose slot
    axis sits third-from-last (the ``ring.shift_deps`` shape, e.g. the
    epaxos dependency cube): returns ``(..., S, 1, G)``, broadcastable
    against the plane's per-replica axis."""
    sidx = jnp.arange(S, dtype=jnp.int32)
    b = base[..., None, None, :]
    return b + jnp.remainder(sidx[:, None, None] - b, S)


def cell_onehot(slot, S: int):
    """One-hot ``(..., S, G)`` of the cell holding absolute ``slot``
    ``(..., G)``.  Carries NO in-window validity: callers must mask
    with ``in_window`` (an out-of-window slot's cell holds a different
    absolute slot — writing there would corrupt it)."""
    sidx = jnp.arange(S, dtype=jnp.int32)
    return sidx[:, None] == jnp.remainder(slot, S)[..., None, :]


def in_window(slot, base, S: int):
    """``base <= slot < base + S`` — the frontier mask that gates every
    fixed-cell one-hot write (same shapes as ``slot``/``base``)."""
    return (slot >= base) & (slot < base + S)


def advance_clear(plane, old_base, new_base, fill):
    """The fixed-cell equivalent of
    ``ring.shift_window(plane, new_base - old_base, fill)``: cells
    whose absolute slot (under ``old_base``) fell below ``new_base``
    were recycled by the advance and reset to ``fill`` in place —
    nothing moves.  ``plane (..., S, G)``, bases ``(..., G)``."""
    S = plane.shape[-2]
    drop = cell_abs(old_base, S) < new_base[..., None, :]
    return jnp.where(drop, fill, plane)


# ring-shaped state planes per fixed-cell kernel (slot axis LAST in
# the runner's group-major final state) — the ONE registry behind the
# equivalence canonicalizer: tests/test_fixed_cell_equiv.py and the
# verify.sh --bench smoke both read it, so adding a ring plane to a
# kernel updates every consumer at once
RING_PLANES = {
    "paxos": ("log_bal", "log_cmd", "log_commit", "log_acks",
              "proposed"),
    "sdpaxos": ("log_bal", "log_cmd", "log_commit", "log_acks",
                "proposed"),
    "wankeeper": ("log_bal", "log_cmd", "log_commit", "log_acks",
                  "proposed"),
    "wpaxos": ("log_bal", "log_cmd", "log_commit", "log_acks",
               "proposed"),
    "bpaxos": ("abal", "vbal", "vcmd", "vbsz", "committed", "proposed",
               "p2_acks"),
}


def canonical_state_np(name, state):
    """Fixed-cell group-major final state -> the window-ordered view
    the sliding-window layout stores directly (numpy; ``m_`` planes
    dropped — they are excluded from the witness hash and compared via
    metrics).  The bit-canonical equivalence form: hash this against a
    ``sim_sw`` reference run's state."""
    import numpy as np
    base = np.asarray(state["base"])
    ring = RING_PLANES[name]
    return {k: (window_view_np(v, base) if k in ring
                else np.asarray(v))
            for k, v in state.items() if not k.startswith("m_")}


def window_view_np(plane, base):
    """Roll a fixed-cell ring plane to window order (numpy; tests and
    tooling only — this IS a gather, which is why it never runs inside
    a kernel).  Operates on the runner's group-major final state: slot
    axis LAST (``(G, R, S)`` / ``(G, R, O, S)``), ``base`` matching the
    leading dims.  ``out[..., i] = plane[..., (base + i) % S]`` holds
    absolute slot ``base + i`` — exactly what ring position ``i``
    stores under the sliding-window layout, so a fixed-cell kernel's
    state equals its ``sim_sw`` reference's state after this view
    (the bit-canonical equivalence proof in
    tests/test_fixed_cell_equiv.py)."""
    import numpy as np
    plane = np.asarray(plane)
    base = np.asarray(base)
    S = plane.shape[-1]
    idx = (base[..., None] + np.arange(S)) % S
    return np.take_along_axis(plane, idx, axis=-1)
