"""Shared Multi-Paxos ring machinery for lane-major sim kernels.

One audited copy of the ballot/ring consensus core that several
protocol kernels run on: the paxos kernel drives it with self-generated
client commands (protocols/paxos/sim.py), the sdpaxos kernel with
sequencer-ordered owner tokens (protocols/sdpaxos/sim.py).  Reference:
paxi paxos/paxos.go HandleP1a/P1b/P2a/P2b/P3 [driver] — see the paxos
kernel docstring for the full TPU re-design rationale (masked handlers,
bit-packed acks, sliding ring over absolute slots, by-reference P1b
merge, P3 snapshot catch-up).

Conventions:
- ``st`` is the protocol's state dict; these helpers read/write the 13
  standard keys (ballot, active, p1_acks, base, log_bal, log_cmd,
  log_commit, log_acks, proposed, next_slot, execute, timer, stuck) and
  leave every other key untouched.
- ``extras`` is a dict of additional ``(R, ..., G)`` planes that must
  travel with state transfer (election adoption and P3 snapshot
  catch-up): the KV store for paxos, KV + per-owner execution counters
  for sdpaxos.
- Mailbox planes are ``(src, dst, G)``; handlers consume them
  receiver-major via masked selects (ring.pick_src), never gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.sim.ring import pick_src
from paxi_tpu.sim.ring import shift_row as _shift_row
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.ring import take_replica as _take_replica

NO_CMD = -1    # empty log entry
NOOP = -2      # hole filled by a recovering leader

# the 13 state planes this module owns; kernels build their state dicts
# with these keys plus their protocol-specific extras
KEYS = ("ballot", "active", "p1_acks", "base", "log_bal", "log_cmd",
        "log_commit", "log_acks", "proposed", "next_slot", "execute",
        "timer", "stuck")


def _ridx(st):
    R = st["log_bal"].shape[0]
    return jnp.arange(R, dtype=jnp.int32)


def _sidx(st):
    S = st["log_bal"].shape[1]
    return jnp.arange(S, dtype=jnp.int32)


def own_bal_mask(st, stride):
    """Replicas whose current ballot is their own (ballot.ID() == me)."""
    ridx = _ridx(st)
    return (st["ballot"] > 0) & (st["ballot"] % stride == ridx[:, None])


def depose(st, mask, bal):
    """Adopt a higher ballot where ``mask``: raise the promise, drop
    leadership, void any in-flight phase-1 round — the one demotion
    rule every handler (P1a, P2a, P3) applies."""
    return {**st,
            "ballot": jnp.where(mask, bal, st["ballot"]),
            "active": st["active"] & ~mask,
            "p1_acks": jnp.where(mask, 0, st["p1_acks"])}


def promise_p1a(st, m):
    """P1a handler: promise to the highest proposer; emit P1b to it.
    Returns (st', out_p1b, promote)."""
    R = st["log_bal"].shape[0]
    ridx = _ridx(st)
    G = st["ballot"].shape[-1]
    b_in = jnp.where(m["valid"], m["bal"], 0)
    p1a_bal = jnp.max(b_in, axis=0)                      # (dst, G)
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > st["ballot"]
    st = depose(st, promote, p1a_bal)
    out_p1b = {
        "valid": promote[:, None, :] & (ridx[None, :, None]
                                        == p1a_src[:, None, :]),
        "bal": jnp.broadcast_to(st["ballot"][:, None, :], (R, R, G)),
    }
    return st, out_p1b, promote


def tally_p1b(st, m, majority, stride):
    """P1b handler: collect phase-1 acks into the bit-packed mask.
    Returns (st', p1_win, amask) where amask[ldr, s, g] marks s as an
    acker of ldr's round (self included)."""
    ridx = _ridx(st)
    src_bit = (jnp.int32(1) << ridx)[:, None, None]
    ob = own_bal_mask(st, stride)
    cond = m["valid"] & (m["bal"] == st["ballot"][None, :, :]) \
        & ob[None, :, :]                                 # (src, ldr, G)
    p1_acks = st["p1_acks"] | jnp.sum(jnp.where(cond, src_bit, 0), axis=0)
    p1_win = ob & ~st["active"] \
        & (jax.lax.population_count(p1_acks) >= majority)
    amask = ((p1_acks[:, None, :] >> ridx[None, :, None]) & 1).astype(bool)
    return {**st, "p1_acks": p1_acks}, p1_win, amask


def adopt_best_acker(st, amask, p1_win, extras):
    """Phase-1 win, step 1: a laggard winner adopts the most advanced
    acker's (extras, execute, base) by reference — the state-transfer /
    log-compaction analog of the host runtime's P1b snapshot.  Returns
    (st', extras')."""
    el_exec = jnp.where(amask, st["execute"][None, :, :], -1)
    f_src = jnp.argmax(el_exec, axis=1).astype(jnp.int32)
    front = jnp.max(el_exec, axis=1)
    el_ad = p1_win & (front > st["execute"])
    ex = {k: jnp.where(el_ad[(slice(None),)
                             + (None,) * (v.ndim - 2) + (slice(None),)],
                       _take_replica(v, f_src), v)
          for k, v in extras.items()}
    execute = jnp.where(el_ad, front, st["execute"])
    next_slot = jnp.where(el_ad, jnp.maximum(st["next_slot"], front),
                          st["next_slot"])
    # never adopt a LOWER base: a negative self-shift would drop my own
    # top-of-window entries (possibly committed via P3); the merge
    # tolerates ackers whose base is below mine (front-fill only)
    f_base = _take_replica(st["base"], f_src)
    adv_el = jnp.where(el_ad, jnp.maximum(f_base - st["base"], 0), 0)
    base = jnp.where(el_ad, jnp.maximum(f_base, st["base"]), st["base"])
    st = {**st, "execute": execute, "next_slot": next_slot, "base": base,
          "log_bal": _shift(st["log_bal"], adv_el, 0),
          "log_cmd": _shift(st["log_cmd"], adv_el, NO_CMD),
          "log_commit": _shift(st["log_commit"], adv_el, False),
          "proposed": _shift(st["proposed"], adv_el, False),
          "log_acks": _shift(st["log_acks"], adv_el, 0)}
    return st, ex


def merge_acker_logs(st, amask, p1_win):
    """Phase-1 win, step 2: merge the ackers' current logs base-aligned
    — per slot adopt any committed value, else the highest-ballot
    accepted value, else NOOP-fill below the frontier; own the window
    under my ballot.  Returns st' (active set for winners)."""
    R = st["log_bal"].shape[0]
    sidx = _sidx(st)
    ridx = _ridx(st)
    self_bit3 = (jnp.int32(1) << ridx)[:, None, None]
    base = st["base"]
    log_bal, log_cmd = st["log_bal"], st["log_cmd"]
    log_commit, proposed = st["log_commit"], st["proposed"]
    best_bal = jnp.full_like(log_bal, -1)
    merged_cmd = jnp.full_like(log_cmd, NO_CMD)
    merged_commit = jnp.zeros_like(log_commit)
    committed_cmd = jnp.full_like(log_cmd, NO_CMD)
    for s in range(R):
        sel_s = amask[:, s, :]                           # (ldr, G)
        adv_s = base - base[s][None, :]
        lb_s = _shift_row(log_bal[s], adv_s, -1)
        lc_s = _shift_row(log_cmd[s], adv_s, NO_CMD)
        lm_s = _shift_row(log_commit[s], adv_s, False)
        lb_s = jnp.where(sel_s[:, None, :], lb_s, -1)
        lm_s = lm_s & sel_s[:, None, :]
        upd = lb_s > best_bal
        best_bal = jnp.where(upd, lb_s, best_bal)
        merged_cmd = jnp.where(upd, lc_s, merged_cmd)
        committed_cmd = jnp.where(lm_s & ~merged_commit, lc_s,
                                  committed_cmd)
        merged_commit = merged_commit | lm_s
    abs_ = base[:, None, :] + sidx[None, :, None]
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, abs_ + 1, 0), axis=1)
    new_next = jnp.maximum(st["next_slot"], top)
    in_win = abs_ < new_next[:, None, :]
    w = p1_win[:, None, :]
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    return {**st,
            "log_cmd": jnp.where(w & in_win, adopt_cmd, log_cmd),
            "log_bal": jnp.where(w & in_win, st["ballot"][:, None, :],
                                 log_bal),
            "log_commit": jnp.where(w & in_win,
                                    merged_commit | log_commit,
                                    log_commit),
            "proposed": jnp.where(w, in_win
                                  & (merged_commit | log_commit),
                                  proposed),
            "log_acks": jnp.where(w, jnp.where(in_win, self_bit3, 0),
                                  st["log_acks"]),
            "next_slot": jnp.where(p1_win, new_next, st["next_slot"]),
            "active": st["active"] | p1_win}


def accept_p2a(st, m):
    """P2a handler: accept from the highest-ballot proposer; ack ONLY
    what was durably stored in-window.  Returns (st', out_p2b, acc_ok,
    demote)."""
    R = st["log_bal"].shape[0]
    S = st["log_bal"].shape[1]
    sidx = _sidx(st)
    ridx = _ridx(st)
    G = st["ballot"].shape[-1]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = pick_src(m["slot"], a_src)                  # absolute
    a_cmd = pick_src(m["cmd"], a_src)
    acc_ok = a_has & (a_bal >= st["ballot"])
    demote = acc_ok & (a_bal > st["ballot"])
    st = depose(st, demote, a_bal)
    a_rel = a_slot - st["base"]
    a_inw = (a_rel >= 0) & (a_rel < S)
    oh = acc_ok[:, None, :] & (sidx[None, :, None] == a_rel[:, None, :])
    writable = oh & (st["log_bal"] <= a_bal[:, None, :]) \
        & ~st["log_commit"]
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None, :]
        & (ridx[None, :, None] == a_src[:, None, :]),
        "bal": jnp.broadcast_to(a_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(a_slot[:, None, :], (R, R, G)),
    }
    st = {**st,
          "log_bal": jnp.where(writable, a_bal[:, None, :], st["log_bal"]),
          "log_cmd": jnp.where(writable, a_cmd[:, None, :], st["log_cmd"])}
    return st, out_p2b, acc_ok, demote


def tally_p2b(st, m, majority, stride):
    """P2b handler: the leader tallies acks per (slot) bitmask and
    commits at majority.  Returns (st', newly)."""
    R = st["log_bal"].shape[0]
    sidx = _sidx(st)
    ob = own_bal_mask(st, stride)
    okb = m["valid"] & (m["bal"] == st["ballot"][None, :, :]) \
        & (st["active"] & ob)[None, :, :]
    brel = m["slot"] - st["base"][None, :, :]
    log_acks = st["log_acks"]
    for s in range(R):
        oh_s = okb[s][:, None, :] \
            & (sidx[None, :, None] == brel[s][:, None, :])
        log_acks = log_acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    acks_n = jax.lax.population_count(log_acks)
    newly = ((st["active"] & ob)[:, None, :] & (acks_n >= majority)
             & ~st["log_commit"] & (st["log_cmd"] != NO_CMD)
             & st["proposed"])
    return {**st, "log_acks": log_acks,
            "log_commit": st["log_commit"] | newly}, newly


def apply_p3(st, m, extras):
    """P3 handler: adopt the commit notification, frontier-commit below
    ``upto`` at the sender's exact ballot, and snapshot-adopt (extras,
    execute, base) when my frontier fell below the sender's window.
    Returns (st', extras', c_has, c_bal).

    Two zombie fences (a deposed leader partitioned through later
    rounds stays active with a stale ballot): (1) a P3 with a higher
    ballot DEPOSES the receiver — so the moment a zombie adopts the
    new leader's state it stops leading, and never broadcasts an
    ``upto`` covering a frontier it did not commit itself; (2) the
    frontier-commit only fires for ``bal >= my promised ballot`` — an
    in-flight stale P3 cannot commit a receiver's same-stale-ballot
    accepted-but-never-chosen entries.  (Observed: a zombie's
    post-adoption upto committed a never-chosen proposal at a fellow
    laggard, diverging committed values across replicas.)"""
    sidx = _sidx(st)
    c_src = jnp.argmax(jnp.where(m["valid"], m["bal"], -1), axis=0) \
        .astype(jnp.int32)
    c_bal = jnp.max(jnp.where(m["valid"], m["bal"], -1), axis=0)
    c_has = c_bal > 0
    c_slot = pick_src(m["slot"], c_src)
    c_cmd = pick_src(m["cmd"], c_src)
    c_upto = pick_src(m["upto"], c_src)
    fresh3 = c_has & (c_bal >= st["ballot"])             # fence (2)
    promote3 = c_has & (c_bal > st["ballot"])            # fence (1)
    st = depose(st, promote3, c_bal)
    base = st["base"]
    abs_ = base[:, None, :] + sidx[None, :, None]
    c_rel = c_slot - base
    oh = c_has[:, None, :] & (sidx[None, :, None] == c_rel[:, None, :])
    log_cmd = jnp.where(oh, c_cmd[:, None, :], st["log_cmd"])
    log_bal = jnp.where(oh, jnp.maximum(st["log_bal"],
                                        c_bal[:, None, :]), st["log_bal"])
    log_commit = st["log_commit"] | oh
    ohu = (fresh3[:, None, :] & (abs_ < c_upto[:, None, :])
           & (log_bal == c_bal[:, None, :]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # snapshot catch-up for deep laggards
    src_base = _take_replica(base, c_src)
    adopt = c_has & (st["execute"] < src_base)
    adv_a = jnp.where(adopt, src_base - base, 0)
    my_bal = _shift(log_bal, adv_a, 0)
    my_cmd = _shift(log_cmd, adv_a, NO_CMD)
    my_com = _shift(log_commit, adv_a, False)
    s_bal = _take_replica(log_bal, c_src)
    s_cmd = _take_replica(log_cmd, c_src)
    s_com = _take_replica(log_commit, c_src)
    a2 = adopt[:, None, :]
    ex = {k: jnp.where(adopt[(slice(None),)
                             + (None,) * (v.ndim - 2) + (slice(None),)],
                       _take_replica(v, c_src), v)
          for k, v in extras.items()}
    execute = jnp.where(adopt, _take_replica(st["execute"], c_src),
                        st["execute"])
    st = {**st,
          "log_bal": jnp.where(a2, jnp.where(s_com, s_bal, my_bal),
                               log_bal),
          "log_cmd": jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd),
                               log_cmd),
          "log_commit": jnp.where(a2, s_com | my_com, log_commit),
          "proposed": jnp.where(a2, False, st["proposed"]),
          "log_acks": jnp.where(a2, 0, st["log_acks"]),
          "execute": execute,
          "next_slot": jnp.where(adopt,
                                 jnp.maximum(st["next_slot"], execute),
                                 st["next_slot"]),
          "base": jnp.where(adopt, src_base, base)}
    return st, ex, c_has, c_bal


def repropose_target(st):
    """Shared proposal targeting: the first unproposed-uncommitted slot
    below next_slot (re-proposal), else the next fresh slot (window
    flow control).  Returns (has_re, can_new, prop_rel, prop_slot,
    oh_p, re_cmd)."""
    S = st["log_bal"].shape[1]
    sidx = _sidx(st)
    base, next_slot = st["base"], st["next_slot"]
    abs_ = base[:, None, :] + sidx[None, :, None]
    mask_re = (~st["log_commit"]) & (~st["proposed"]) \
        & (abs_ < next_slot[:, None, :])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :, None], S),
                          axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S
    rel_next = jnp.clip(next_slot - base, 0, S - 1)
    prop_rel = jnp.where(has_re, first_re, rel_next).astype(jnp.int32)
    oh_p = sidx[None, :, None] == prop_rel[:, None, :]
    re_cmd = jnp.sum(jnp.where(oh_p, st["log_cmd"], 0), axis=1)
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    return has_re, can_new, prop_rel, base + prop_rel, oh_p, re_cmd


def propose_write(st, do, is_new, prop_cmd, prop_slot, oh_p):
    """Apply a proposal to the leader's own log and emit P2a.
    Returns (st', out_p2a)."""
    R = st["log_bal"].shape[0]
    ridx = _ridx(st)
    G = st["ballot"].shape[-1]
    self_bit3 = (jnp.int32(1) << ridx)[:, None, None]
    oh = do[:, None, :] & oh_p
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(st["ballot"][:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(prop_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None, :], (R, R, G)),
    }
    return {**st,
            "log_bal": jnp.where(oh, st["ballot"][:, None, :],
                                 st["log_bal"]),
            "log_cmd": jnp.where(oh & ~st["log_commit"],
                                 prop_cmd[:, None, :], st["log_cmd"]),
            "proposed": st["proposed"] | oh,
            "log_acks": st["log_acks"]
            | jnp.where(oh, self_bit3, 0),
            "next_slot": st["next_slot"] + (is_new & do)}, out_p2a


def p3_out(st, newly, new_execute, is_leader, t):
    """Emit P3: the lowest newly committed slot, else round-robin
    retransmit through the committed prefix (laggards behind the window
    heal via snapshot adoption)."""
    R = st["log_bal"].shape[0]
    S = st["log_bal"].shape[1]
    sidx = _sidx(st)
    G = st["ballot"].shape[-1]
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    span = jnp.maximum(new_execute - st["base"], 1)
    rr = t % span
    p3_rel = jnp.where(any_new, low_new, rr).astype(jnp.int32)
    p3_rel = jnp.clip(p3_rel, 0, S - 1)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_committed = jnp.any(oh_3 & st["log_commit"], axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, st["log_cmd"], 0), axis=1)
    p3_do = is_leader & p3_committed
    return {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(st["ballot"][:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to((st["base"] + p3_rel)[:, None, :],
                                 (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(new_execute[:, None, :], (R, R, G)),
    }


def retry_stuck(st, new_execute, is_leader, retry_timeout):
    """Stuck-frontier retry, go-back-N: a dropped P2a/P2b leaves its
    slot unproposable forever (P2a is sent once); on a stall re-open
    EVERY uncommitted in-flight slot so the proposer re-proposes one
    per step — a deep uncommitted backlog under sustained drops drains
    in O(N) steps, not O(N * retry_timeout)."""
    sidx = _sidx(st)
    abs_ = st["base"][:, None, :] + sidx[None, :, None]
    stalled = is_leader & (new_execute == st["execute"]) \
        & (st["next_slot"] > new_execute)
    stuck = jnp.where(stalled, st["stuck"] + 1, 0)
    retry = stuck >= retry_timeout
    ohr = (retry[:, None, :] & ~st["log_commit"]
           & (abs_ >= new_execute[:, None, :])
           & (abs_ < st["next_slot"][:, None, :]))
    return {**st, "proposed": st["proposed"] & ~ohr,
            "stuck": jnp.where(retry, 0, stuck)}


def election_tick(st, heard, rng, cfg):
    """Election timer with jittered backoff: fire a fresh higher ballot
    (P1a) when nothing leader-ish has been heard.  Returns (st',
    out_p1a)."""
    R = st["log_bal"].shape[0]
    ridx = _ridx(st)
    G = st["ballot"].shape[-1]
    self_bit2 = (jnp.int32(1) << ridx)[:, None]
    k_jit = jr.fold_in(rng, 17)
    jitter = jr.randint(k_jit, st["ballot"].shape, 0, cfg.backoff + 1)
    timer = jnp.where(heard | st["active"],
                      cfg.election_timeout + jitter,
                      st["timer"] - 1)
    fire = ~st["active"] & (timer <= 0)
    new_bal = (jnp.max(st["ballot"], axis=0)[None, :]
               // cfg.ballot_stride + 1) * cfg.ballot_stride \
        + ridx[:, None]
    ballot = jnp.where(fire, new_bal, st["ballot"])
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
    }
    return {**st, "ballot": ballot,
            "p1_acks": jnp.where(fire, self_bit2, st["p1_acks"]),
            "timer": jnp.where(fire, cfg.election_timeout + jitter,
                               timer)}, out_p1a


def slide_window(st, new_execute, retain):
    """Slide the ring past the executed prefix, retaining ``retain``
    executed slots for P3 retransmits (slot recycling)."""
    new_base = jnp.maximum(st["base"], new_execute - retain)
    adv = new_base - st["base"]
    return {**st, "base": new_base, "execute": new_execute,
            "log_bal": _shift(st["log_bal"], adv, 0),
            "log_cmd": _shift(st["log_cmd"], adv, NO_CMD),
            "log_commit": _shift(st["log_commit"], adv, False),
            "proposed": _shift(st["proposed"], adv, False),
            "log_acks": _shift(st["log_acks"], adv, 0)}
