"""Commands and client wire types.

Reference: paxi db.go (Key/Value/Command), msg.go (Request/Reply/Read/
Transaction, gob-registered in init()).  The host runtime serializes these
with ``paxi_tpu.host.codec``; the sim runtime packs Command into int32
lanes (see protocols' ``sim.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

Key = int
Value = bytes


@dataclass
class Command:
    """Reference: db.go Command{Key, Value, ClientID, CommandID}."""

    key: Key
    value: Value = b""
    client_id: str = ""
    command_id: int = 0

    def is_read(self) -> bool:
        """Reference: db.go Command.IsRead() — empty value means read."""
        return len(self.value) == 0

    def is_write(self) -> bool:
        return not self.is_read()


@dataclass
class Request:
    """A client request as seen by a replica.

    Reference: msg.go Request{Command, Properties, Timestamp, NodeID, c}.
    The reply channel ``c`` is node-local in the reference; here it is an
    optional callable / asyncio.Future set by the host runtime and never
    serialized.
    """

    command: Command
    properties: dict = field(default_factory=dict)
    timestamp: float = 0.0
    node_id: str = ""
    reply_to: Optional[Any] = None  # asyncio.Future | callable, node-local

    def reply(self, reply: "Reply") -> None:
        if self.reply_to is None:
            return
        if callable(self.reply_to):
            self.reply_to(reply)
        else:  # asyncio.Future
            if not self.reply_to.done():
                self.reply_to.set_result(reply)

    def wire(self) -> dict:
        """Serializable form (reply channel stripped, like gob encoding)."""
        return {
            "command": {
                "key": self.command.key,
                "value": self.command.value,
                "client_id": self.command.client_id,
                "command_id": self.command.command_id,
            },
            "properties": self.properties,
            "timestamp": self.timestamp,
            "node_id": self.node_id,
        }

    @staticmethod
    def from_wire(d: dict) -> "Request":
        c = d["command"]
        return Request(
            command=Command(c["key"], c["value"], c["client_id"], c["command_id"]),
            properties=d.get("properties", {}),
            timestamp=d.get("timestamp", 0.0),
            node_id=d.get("node_id", ""),
        )


@dataclass
class Reply:
    """Reference: msg.go Reply{Command, Value, Err}."""

    command: Command
    value: Value = b""
    err: Optional[str] = None


TXN_MAGIC = b"\x00txn:"
# cross-shard 2PC records (paxi_tpu/shard/txn.py): prepare / decide /
# commit / abort ride the normal replication path as opaque command
# values, so the PARTICIPANT LOG of a distributed transaction *is*
# whatever consensus protocol the group runs — one ordered command per
# 2PC state transition, interpreted by Database._execute_tpc.
TPC_MAGIC = b"\x002pc:"
# live data migration records (paxi_tpu/shard/migrate.py): begin /
# read / install / start / cutover / done / drop ride each group's
# ordered log exactly like 2PC records, so every epoch transition of a
# range handoff is one totally-ordered log entry interpreted by
# Database._execute_mig — crash recovery is replaying the log.
MIG_MAGIC = b"\x00mig:"
# the reply marker a replica returns for a key it has RELEASED to a
# new owner group (post-cutover): never stored, only returned, so a
# stale router learns the range moved and reroutes instead of serving
# stale state or losing a write
MOVED_MAGIC = b"\x00moved:"
# every value prefix the KV surface must refuse from external clients
# (a client value carrying any magic would be reinterpreted by the
# state machine at execute time on every replica)
RESERVED_PREFIXES = (TXN_MAGIC, TPC_MAGIC, MIG_MAGIC)

MIG_KINDS = ("begin", "read", "install", "start", "cutover", "done",
             "drop")


def pack_mig(kind: str, mid: str, lo: int = 0, hi: int = 0,
             span: int = 0, items=None, cursor: int = -1,
             limit: int = 0) -> Value:
    """Encode one migration record as an opaque command value
    (shard/migrate.py epoch taxonomy; interpreted by
    ``Database._execute_mig``).  ``items`` is the install chunk:
    [(key, value), ...]."""
    import json
    doc: dict = {"kind": kind, "mid": mid}
    if hi:
        doc.update(lo=int(lo), hi=int(hi), span=int(span))
    if items is not None:
        doc["items"] = [[int(k), v.decode("latin1")] for k, v in items]
    if cursor >= 0:
        doc["cursor"] = int(cursor)
    if limit:
        doc["limit"] = int(limit)
    return MIG_MAGIC + json.dumps(doc).encode()


def unpack_mig(value: Value):
    """The migration record back out of a packed value, or None for
    plain/malformed values (poison-command safety, same contract as
    unpack_tpc)."""
    import json
    if not value.startswith(MIG_MAGIC):
        return None
    try:
        doc = json.loads(value[len(MIG_MAGIC):].decode())
        if doc["kind"] not in MIG_KINDS \
                or not isinstance(doc["mid"], str):
            return None
        if "items" in doc:
            doc["items"] = [(int(k), v.encode("latin1"))
                            for k, v in doc["items"]]
        return doc
    except (ValueError, TypeError, KeyError, AttributeError,
            UnicodeDecodeError):
        return None


def pack_tpc(kind: str, txid: str, ops=None, outcome: str = "") -> Value:
    """Encode one 2PC record as an opaque command value.

    ``kind``: ``prepare`` (stage ``ops`` = [(key, value), ...]; empty
    value = read), ``decide`` (durably fix ``outcome`` in {"c", "a"} —
    FIRST write wins, the reply reports the winner), ``commit`` /
    ``abort`` (apply / drop the stage).  The record replicates and
    totally orders like any write of the group it is sent to."""
    import json
    doc = {"kind": kind, "txid": txid}
    if ops is not None:
        doc["ops"] = [[int(k), v.decode("latin1")] for k, v in ops]
    if outcome:
        doc["outcome"] = outcome
    return TPC_MAGIC + json.dumps(doc).encode()


def unpack_tpc(value: Value):
    """The 2PC record back out of a packed value, or None for plain
    values.  Malformed payloads are None (poison-command safety, same
    contract as unpack_transaction)."""
    import json
    if not value.startswith(TPC_MAGIC):
        return None
    try:
        doc = json.loads(value[len(TPC_MAGIC):].decode())
        kind, txid = doc["kind"], doc["txid"]
        if kind not in ("prepare", "decide", "commit", "abort") \
                or not isinstance(txid, str):
            return None
        if "ops" in doc:
            doc["ops"] = [(int(k), v.encode("latin1"))
                          for k, v in doc["ops"]]
        return doc
    except (ValueError, TypeError, KeyError, AttributeError,
            UnicodeDecodeError):
        return None


def pack_transaction(commands) -> Value:
    """Encode a command batch as ONE opaque write value, so a
    Transaction rides the normal per-protocol replication path as a
    single totally-ordered command and applies atomically in
    Database.execute (db.py)."""
    import json
    return TXN_MAGIC + json.dumps(
        [[c.key, c.value.decode("latin1")] for c in commands]).encode()


def unpack_transaction(value: Value):
    """The batch back out of a packed value, or None for plain values.

    A malformed payload (e.g. a client-supplied value that merely
    starts with TXN_MAGIC and slipped past the HTTP guard) is treated
    as a plain write rather than raised: an uncaught decode error here
    would be a poison command crashing every replica at execute time.
    """
    import json
    if not value.startswith(TXN_MAGIC):
        return None
    try:
        batch = json.loads(value[len(TXN_MAGIC):].decode())
        return [Command(int(k), v.encode("latin1")) for k, v in batch]
    except (ValueError, TypeError, KeyError, AttributeError,
            UnicodeDecodeError):
        return None


def pack_values(values) -> Value:
    import json
    return json.dumps([v.decode("latin1") for v in values]).encode()


def unpack_values(payload: Value):
    import json
    return [v.encode("latin1") for v in json.loads(payload.decode())]


@dataclass
class Read:
    """Reference: msg.go Read{CommandID, Key} — a raw (non-linearized)
    read probe answered straight from a replica's local store."""

    command_id: int
    key: Key


@dataclass
class ReadReply:
    """Reference: msg.go ReadReply{CommandID, Value}."""

    command_id: int
    value: Value = b""


@dataclass
class Transaction:
    """Reference: msg.go Transaction{Commands, ClientID, CommandID,
    Timestamp} — a batch of commands applied atomically by the replica
    that executes it (paxi's transactions are a node/db-layer surface;
    protocols order the batch as one unit)."""

    commands: list = field(default_factory=list)   # List[Command]
    client_id: str = ""
    command_id: int = 0
    timestamp: float = 0.0


@dataclass
class TransactionReply:
    """Reference: msg.go TransactionReply{OK, CommandID, LeaderID,
    Timestamp}."""

    ok: bool
    command_id: int = 0
    leader_id: str = ""
    timestamp: float = 0.0
    values: list = field(default_factory=list)     # List[Value]
