"""Commands and client wire types.

Reference: paxi db.go (Key/Value/Command), msg.go (Request/Reply/Read/
Transaction, gob-registered in init()).  The host runtime serializes these
with ``paxi_tpu.host.codec``; the sim runtime packs Command into int32
lanes (see protocols' ``sim.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

Key = int
Value = bytes


@dataclass
class Command:
    """Reference: db.go Command{Key, Value, ClientID, CommandID}."""

    key: Key
    value: Value = b""
    client_id: str = ""
    command_id: int = 0

    def is_read(self) -> bool:
        """Reference: db.go Command.IsRead() — empty value means read."""
        return len(self.value) == 0

    def is_write(self) -> bool:
        return not self.is_read()


@dataclass
class Request:
    """A client request as seen by a replica.

    Reference: msg.go Request{Command, Properties, Timestamp, NodeID, c}.
    The reply channel ``c`` is node-local in the reference; here it is an
    optional callable / asyncio.Future set by the host runtime and never
    serialized.
    """

    command: Command
    properties: dict = field(default_factory=dict)
    timestamp: float = 0.0
    node_id: str = ""
    reply_to: Optional[Any] = None  # asyncio.Future | callable, node-local

    def reply(self, reply: "Reply") -> None:
        if self.reply_to is None:
            return
        if callable(self.reply_to):
            self.reply_to(reply)
        else:  # asyncio.Future
            if not self.reply_to.done():
                self.reply_to.set_result(reply)

    def wire(self) -> dict:
        """Serializable form (reply channel stripped, like gob encoding)."""
        return {
            "command": {
                "key": self.command.key,
                "value": self.command.value,
                "client_id": self.command.client_id,
                "command_id": self.command.command_id,
            },
            "properties": self.properties,
            "timestamp": self.timestamp,
            "node_id": self.node_id,
        }

    @staticmethod
    def from_wire(d: dict) -> "Request":
        c = d["command"]
        return Request(
            command=Command(c["key"], c["value"], c["client_id"], c["command_id"]),
            properties=d.get("properties", {}),
            timestamp=d.get("timestamp", 0.0),
            node_id=d.get("node_id", ""),
        )


@dataclass
class Reply:
    """Reference: msg.go Reply{Command, Value, Err}."""

    command: Command
    value: Value = b""
    err: Optional[str] = None
