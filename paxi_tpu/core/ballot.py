"""Ballot numbers.

Reference: paxi's ballot (paxos/ballot.go or paxos.go) packs
``n << 16 | leaderID`` into one integer so ballots order primarily by
round and tie-break by leader [med].  Same idea here with a wider,
range-checked leader half (zone and node get 12 bits each) so large
cluster ids cannot silently corrupt leader identity.
"""

from __future__ import annotations

from paxi_tpu.core.ident import ID, new_id

_BITS = 12
_MASK = (1 << _BITS) - 1


def ballot(n: int, id: ID) -> int:
    i = ID(id)
    if not (0 < i.zone <= _MASK and 0 < i.node <= _MASK):
        raise ValueError(f"id {i} out of ballot range (1..{_MASK})")
    return (n << (2 * _BITS)) | (i.zone << _BITS) | i.node


def ballot_n(b: int) -> int:
    return b >> (2 * _BITS)


def ballot_id(b: int) -> ID:
    return new_id((b >> _BITS) & _MASK, b & _MASK)


def next_ballot(b: int, id: ID) -> int:
    """Smallest ballot owned by ``id`` greater than ``b``."""
    return ballot(ballot_n(b) + 1, id)
