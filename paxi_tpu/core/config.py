"""Cluster and benchmark configuration.

Reference: paxi config.go — ``Config{Addrs, HTTPAddrs, Policy, Threshold,
BufferSize, ChanBufferSize, MultiVersion, Benchmark}`` loaded from a shared
static ``config.json`` (no dynamic membership service).  This file keeps
the JSON schema compatible so a paxi ``config.json`` loads unchanged.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict
from typing import Dict, List

from paxi_tpu.core.ident import ID


@dataclass
class Bconfig:
    """Benchmark workload spec.

    Reference: benchmark.go Bconfig{T, N, K, W, Concurrency, Distribution,
    Conflicts, Min, Mu, Sigma, Move, Speed, Zipfian_s, Zipfian_v, Throttle,
    LinearizabilityCheck}.
    """

    T: int = 10                 # seconds to run (0 => use N ops)
    N: int = 0                  # total ops if T == 0
    K: int = 1000               # key-space size
    W: float = 0.5              # write fraction
    concurrency: int = 1        # closed-loop client streams
    distribution: str = "uniform"  # uniform|conflict|normal|zipfian
    conflicts: int = 100        # % conflicting ops (conflict distribution)
    min: int = 0                # min key (conflict distribution)
    mu: float = 0.0             # normal distribution mean
    sigma: float = 60.0         # normal distribution stddev
    move: bool = False          # move normal-mean over time
    speed: int = 500            # mean-move speed (ms)
    zipfian_s: float = 2.0      # zipf skew
    zipfian_v: float = 1.0      # zipf value shift
    throttle: int = 0           # ops/sec limit (0 = unlimited)
    linearizability_check: bool = True
    # completions inside the first ``warmup`` seconds are reported
    # separately (dial-up, leader election, batch ramp) so
    # throughput_ops_s is steady-state — the host analog of bench.py's
    # compile_s/warmup_s split (0 = no split, every op counts)
    warmup: float = 0.0

    @staticmethod
    def from_dict(d: dict) -> "Bconfig":
        aliases = {
            "t": "T", "n": "N", "k": "K", "w": "W",
            "linearizabilitycheck": "linearizability_check",
            "zipfians": "zipfian_s", "zipfianv": "zipfian_v",
        }
        out = {}
        for k, v in d.items():
            kk = aliases.get(k.lower(), k.lower())
            if kk in ("T", "N", "K", "W"):
                out[kk] = v
            elif kk in Bconfig.__dataclass_fields__:
                out[kk] = v
        return Bconfig(**out)


@dataclass
class Config:
    """Static cluster definition, JSON-compatible with paxi's config.json.

    Reference: config.go.  ``addrs`` maps ID -> peer transport URL
    (tcp://, chan://, tpu-sim://); ``http_addrs`` maps ID -> client REST URL.
    """

    addrs: Dict[ID, str] = field(default_factory=dict)
    http_addrs: Dict[ID, str] = field(default_factory=dict)
    policy: str = "consecutive"   # WPaxos stealing policy (policy.go)
    threshold: float = 3          # policy threshold
    buffer_size: int = 1024       # socket buffer (BufferSize)
    chan_buffer_size: int = 1024  # in-process chan buffer (ChanBufferSize)
    multi_version: bool = False   # per-key value history in Database
    # commit-path batching (host/batch.py): commands per slot ceiling,
    # and the flush-timer ceiling in seconds (0 = flush on the next
    # event-loop tick — near-zero added latency, bursts still batch)
    batch_size: int = 64
    batch_wait: float = 0.0
    # leader-local reads (read-index style): reads order at the
    # leader's execute barrier instead of occupying log slots — halves
    # replication work at mixed workloads.  Sound under a single
    # stable leader (the lease assumption); off by default, and the
    # benchmark's linearizability checker gates every run that uses it.
    leader_reads: bool = False
    # the lease that makes ``leader_reads`` sound across elections
    # (protocols/paxos/host.py): a leader serves barrier reads only
    # within ``lease_s`` of its last quorum round's START, and a fresh
    # leader fences its first proposals for ``lease_s`` so no write can
    # commit while a deposed leader's lease may still be live.
    # ``lease_s <= 0`` disables the lease (pre-PR-8 unfenced behavior).
    lease_s: float = 0.2
    # BPaxos compartmentalized tier (protocols/bpaxos): node-id role
    # assignment over sorted(ids) — first ``n_proxies`` proxy leaders,
    # next ``grid_rows * grid_cols`` the acceptor grid, rest replicas
    n_proxies: int = 2
    grid_rows: int = 2
    grid_cols: int = 2
    benchmark: Bconfig = field(default_factory=Bconfig)

    # ---- derived topology helpers -------------------------------------
    @property
    def ids(self) -> List[ID]:
        return sorted(self.addrs.keys())

    @property
    def n(self) -> int:
        return len(self.addrs)

    def zones(self) -> List[int]:
        return sorted({i.zone for i in self.ids})

    def npz(self) -> int:
        """Nodes per zone (assumes rectangular zone grid, like WPaxos)."""
        zs = self.zones()
        return len([i for i in self.ids if i.zone == zs[0]]) if zs else 0

    def index(self, id: ID) -> int:
        """Dense 0-based replica index used by the sim runtime."""
        return self.ids.index(ID(id))

    # ---- (de)serialization --------------------------------------------
    @staticmethod
    def from_json(path: str) -> "Config":
        with open(path) as f:
            d = json.load(f)
        return Config.from_dict(d)

    @staticmethod
    def from_dict(d: dict) -> "Config":
        lower = {k.lower(): v for k, v in d.items()}
        cfg = Config()
        cfg.addrs = {ID(k): v for k, v in lower.get("address", lower.get("addrs", {})).items()}
        cfg.http_addrs = {ID(k): v for k, v in lower.get("http_address", lower.get("http_addrs", {})).items()}
        cfg.policy = lower.get("policy", cfg.policy)
        cfg.threshold = lower.get("threshold", cfg.threshold)
        cfg.buffer_size = lower.get("buffersize", lower.get("buffer_size", cfg.buffer_size))
        cfg.chan_buffer_size = lower.get("chanbuffersize", lower.get("chan_buffer_size", cfg.chan_buffer_size))
        cfg.multi_version = lower.get("multiversion", lower.get("multi_version", cfg.multi_version))
        cfg.batch_size = lower.get("batchsize", lower.get("batch_size", cfg.batch_size))
        cfg.batch_wait = lower.get("batchwait", lower.get("batch_wait", cfg.batch_wait))
        cfg.leader_reads = lower.get("leaderreads", lower.get("leader_reads", cfg.leader_reads))
        cfg.lease_s = lower.get("leases", lower.get("lease_s", cfg.lease_s))
        cfg.n_proxies = lower.get("nproxies", lower.get("n_proxies", cfg.n_proxies))
        cfg.grid_rows = lower.get("gridrows", lower.get("grid_rows", cfg.grid_rows))
        cfg.grid_cols = lower.get("gridcols", lower.get("grid_cols", cfg.grid_cols))
        if "benchmark" in lower:
            cfg.benchmark = Bconfig.from_dict(lower["benchmark"])
        return cfg

    def to_json(self, path: str) -> None:
        d = asdict(self)
        d["address"] = {str(k): v for k, v in d.pop("addrs").items()}
        d["http_address"] = {str(k): v for k, v in d.pop("http_addrs").items()}
        with open(path, "w") as f:
            json.dump(d, f, indent=2)


def local_config(n: int, zones: int = 1, base_port: int = 1735,
                 scheme: str = "tcp") -> Config:
    """Build an n-replica localhost config (zones x nodes-per-zone grid).

    Mirrors the sample bin/config.json layouts used by paxi's run scripts.
    """
    cfg = Config()
    npz = n // zones
    k = 0
    for z in range(1, zones + 1):
        for nn in range(1, npz + 1):
            i = ID(f"{z}.{nn}")
            cfg.addrs[i] = f"{scheme}://127.0.0.1:{base_port + k}"
            cfg.http_addrs[i] = f"http://127.0.0.1:{base_port + 1000 + k}"
            k += 1
    return cfg
