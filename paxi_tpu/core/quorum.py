"""Quorum bookkeeping.

Reference: paxi quorum.go — ``Quorum{size, acks, zones}`` with ``ACK(id)``,
``Majority()``, fast quorum (ceil(3N/4), EPaxos), zone quorums
(``ZoneMajority``) and flexible grid quorums (Q1 rows x Q2 columns,
WPaxos).  This host-side class mirrors that surface; the sim runtime's
equivalent is a bit-packed int32 ack mask per quorum site (see the
protocol kernels, e.g. protocols/paxos/sim.py ``p1_acks``/``log_acks``
and protocols/wpaxos/sim.py ``_zone_quorums``) — Quorum.ACK lifts to a
bitwise-or, Majority() to a ``lax.population_count`` compare.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Set

from paxi_tpu.core.ident import ID


class Quorum:
    def __init__(self, ids: Iterable[ID]):
        self.ids = [ID(i) for i in ids]
        self.n = len(self.ids)
        self.acks: Set[ID] = set()
        self.zone_counts: Dict[int, int] = {}
        self._zone_sizes: Dict[int, int] = {}
        for i in self.ids:
            self._zone_sizes[i.zone] = self._zone_sizes.get(i.zone, 0) + 1

    # ---- recording ----------------------------------------------------
    def ack(self, id: ID) -> None:
        """Reference: quorum.go Quorum.ACK [driver]."""
        id = ID(id)
        if id not in self.acks:
            self.acks.add(id)
            self.zone_counts[id.zone] = self.zone_counts.get(id.zone, 0) + 1

    def nack(self, id: ID) -> None:
        id = ID(id)
        if id in self.acks:
            self.acks.discard(id)
            self.zone_counts[id.zone] -= 1

    def reset(self) -> None:
        self.acks.clear()
        self.zone_counts.clear()

    # ---- predicates ---------------------------------------------------
    def size(self) -> int:
        return len(self.acks)

    def majority(self) -> bool:
        return len(self.acks) > self.n // 2

    def fast_quorum(self) -> bool:
        """EPaxos fast path: ceil(3N/4) acks."""
        return len(self.acks) >= math.ceil(3 * self.n / 4)

    def all(self) -> bool:
        return len(self.acks) == self.n

    def zone_majority(self, zone: int) -> bool:
        """Majority within one zone."""
        zs = self._zone_sizes.get(zone, 0)
        return zs > 0 and self.zone_counts.get(zone, 0) > zs // 2

    def grid_q1(self, q1: int) -> bool:
        """WPaxos flexible grid phase-1: a zone-majority in each of >= q1
        zones (a 'row' of the grid)."""
        good = sum(1 for z in self._zone_sizes if self.zone_majority(z))
        return good >= q1

    def grid_q2(self, q2: int) -> bool:
        """WPaxos flexible grid phase-2: a zone-majority in each of >= q2
        zones, with q1 + q2 > #zones guaranteeing intersection."""
        return self.grid_q1(q2)

    # ---- BPaxos rectangular grid (protocols/bpaxos) -------------------
    # The id list (sorted acceptor ids) is read as a row-major
    # rows x cols grid: id index i sits at (i // cols, i % cols).  The
    # write quorum is ONE FULL ROW, the read quorum ONE FULL COLUMN —
    # any row and any column of the same grid share exactly one cell,
    # so every read/write pair intersects structurally (paxi-lint's
    # PXQ rowcol proof checks both sites derive the grid from the same
    # ``cols``, and that the predicates demand complete lines).  This
    # is also the *thrifty* grid: a proposer messages exactly the
    # quorum, never the whole acceptor set.
    def grid_row(self, cols: int) -> bool:
        """BPaxos write/accept quorum: every member of >= 1 grid row."""
        rows = [self.ids[i:i + cols] for i in range(0, self.n, cols)]
        return any(all(m in self.acks for m in row) for row in rows)

    def grid_col(self, cols: int) -> bool:
        """BPaxos read/recovery quorum: every member of >= 1 column."""
        return any(all(m in self.acks for m in self.ids[c::cols])
                   for c in range(cols))


def majority_size(n: int) -> int:
    return n // 2 + 1


def fast_quorum_size(n: int) -> int:
    return math.ceil(3 * n / 4)
