"""The replicated state machine: an in-memory KV store.

Reference: paxi db.go — ``Database`` interface with ``Execute(Command)
Value`` backed by ``map[Key]Value`` + RWMutex, optional multi-version
history.  Host-runtime replicas execute committed commands against this;
the sim runtime keeps the KV as a dense ``(replica, key)`` int32 array
(see protocols' sim kernels).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from paxi_tpu.core.command import (MIG_MAGIC, MOVED_MAGIC, TPC_MAGIC,
                                   TXN_MAGIC, Command, Key, Value,
                                   pack_values, unpack_mig, unpack_tpc,
                                   unpack_transaction)


class Database:
    """In-memory KV store with optional per-key version history."""

    def __init__(self, multi_version: bool = False):
        self._data: Dict[Key, Value] = {}
        self._history: Dict[Key, List[Value]] = {}
        self._multi_version = multi_version
        self._lock = threading.RLock()
        self._version = 0
        # cross-shard 2PC participant state (paxi_tpu/shard/txn.py):
        # every replica of a group executes the same ordered prepare/
        # decide/commit/abort records, so these dicts evolve
        # deterministically across the group — the participant log IS
        # the group's consensus log.  ``_staged``: txid -> staged ops;
        # ``_decided``: txid -> "c"|"a", FIRST decide record wins (the
        # coordinator-recovery tiebreak rides on log order).
        self._staged: Dict[str, list] = {}
        self._decided: Dict[str, str] = {}
        # live-migration state (paxi_tpu/shard/migrate.py): evolves
        # only through ordered ``mig`` records, so — like the 2PC
        # dicts — it is identical at every replica of a group and
        # crash recovery is log replay.
        #   _mig_open: destination-side install windows, mid ->
        #     {lo, hi, span, src, dirty}.  ``dirty`` holds keys this
        #     replica wrote AFTER the window opened (double-write
        #     duplicates / post-cutover traffic); ``install`` chunks
        #     skip them so a late snapshot item can never clobber a
        #     newer duplicated write.
        #   _mig_done: completed migration ids — a replayed ``begin``
        #     must not re-open a finished window.
        #   _frozen: source-side fence (the ``start`` record): 2PC
        #     prepares on the range vote NO from the fence until
        #     cutover, so no transaction can stage into the range
        #     after the catch-up stream's log position.
        #   _released: source-side post-cutover ranges: plain reads
        #     and writes of a released key return MOVED_MAGIC instead
        #     of executing — the bounce a stale router turns into a
        #     reroute.
        self._mig_open: Dict[str, dict] = {}
        self._mig_done: set = set()
        self._frozen: Dict[str, tuple] = {}
        self._released: Dict[str, tuple] = {}

    def execute(self, cmd: Command) -> Value:
        """Apply a command; returns the PREVIOUS value (read for gets,
        old-value for puts) exactly like the reference's Execute.

        A command whose value packs a Transaction (command.py
        pack_transaction) applies the whole batch atomically and returns
        the packed previous values — this is how transactions replicate:
        as one ordered command through whatever protocol runs."""
        with self._lock:
            if cmd.value.startswith(TPC_MAGIC):
                rec = unpack_tpc(cmd.value)
                if rec is not None:
                    return self._execute_tpc(rec)
            if cmd.value.startswith(MIG_MAGIC):
                mrec = unpack_mig(cmd.value)
                if mrec is not None:
                    return self._execute_mig(mrec)
            batch = unpack_transaction(cmd.value) if cmd.value else None
            if batch is not None:
                return pack_values(self.execute_transaction(batch))
            if self._released and self._moved_key(cmd.key):
                return MOVED_MAGIC
            prev = self._data.get(cmd.key, b"")
            if cmd.is_write():
                self._data[cmd.key] = cmd.value
                self._version += 1
                if self._mig_open:
                    self._note_write(cmd.key)
                if self._multi_version:
                    self._history.setdefault(cmd.key, []).append(cmd.value)
            return prev

    def apply_batch(self, cmds: List[Command],
                    ctab: Dict[str, tuple]) -> None:
        """Tight-loop state-machine application of a committed batch
        with per-client at-most-once filtering — the execute path for
        replicas holding no client connections (one lock acquisition,
        no Reply objects).  ``ctab`` is the caller's session table
        (client_id -> (highest executed command_id, its value)),
        updated exactly as the execute() path would.  Transaction-
        packed and multi-version commands fall back to execute()
        (the RLock makes that re-entrant)."""
        with self._lock:
            data = self._data
            for cmd in cmds:
                if cmd.key < 0:
                    continue   # NOOP filler
                cid = cmd.client_id
                if cid:
                    last = ctab.get(cid)
                    if last is not None and cmd.command_id <= last[0]:
                        continue   # duplicate: already executed
                v = cmd.value
                if self._multi_version or v.startswith(TPC_MAGIC) \
                        or v.startswith(MIG_MAGIC):
                    out = self.execute(cmd)
                elif v.startswith(TXN_MAGIC):
                    batch = unpack_transaction(v)
                    # same outcome as execute(): packed previous values
                    # (ctab must agree across replicas for duplicate
                    # replies after leader changes), one unpack + one
                    # inline loop instead of nested executes
                    out = (pack_values(self.execute_transaction(batch))
                           if batch is not None
                           else self.execute(cmd))
                elif self._released and self._moved_key(cmd.key):
                    out = MOVED_MAGIC
                else:
                    out = data.get(cmd.key, b"")
                    if v:
                        data[cmd.key] = v
                        self._version += 1
                        if self._mig_open:
                            self._note_write(cmd.key)
                if cid:
                    ctab[cid] = (cmd.command_id, out)

    def execute_transaction(self, commands: List[Command]) -> List[Value]:
        """Apply a command batch atomically (msg.go Transaction surface):
        all commands run under one lock acquisition, returning each
        command's previous value in order.  Plain sub-commands apply
        inline (no nested execute/lock per sub-command — with batched
        clients this loop IS the state-machine hot path); nested
        transaction-packed or multi-version sub-commands keep
        execute()'s exact semantics via the re-entrant fallback."""
        with self._lock:
            data = self._data
            out = []
            for c in commands:
                v = c.value
                if self._multi_version or v.startswith(TXN_MAGIC) \
                        or v.startswith(TPC_MAGIC) \
                        or v.startswith(MIG_MAGIC):
                    out.append(self.execute(c))
                    continue
                if self._released and self._moved_key(c.key):
                    out.append(MOVED_MAGIC)
                    continue
                prev = data.get(c.key, b"")
                if v:
                    data[c.key] = v
                    self._version += 1
                    if self._mig_open:
                        self._note_write(c.key)
                out.append(prev)
            return out

    def _execute_tpc(self, rec: dict) -> Value:
        """Apply one cross-shard 2PC record (shard/txn.py taxonomy);
        caller holds the lock.  Deterministic and idempotent per kind,
        so duplicate records (retries, leader-change re-proposals)
        converge at every replica:

        - ``prepare``: stage the ops unless a key is staged by another
          in-flight txn (vote NO — the conflict-abort that gives 2PC
          its txn-txn isolation).  Reply ``yes:`` + packed
          prepare-point previous values, or ``no``.
        - ``decide``: record the outcome ONCE; the reply is the
          winning outcome, so a racing coordinator/recovery learns the
          truth from its own (ordered) decide record.
        - ``commit``: apply the staged writes atomically, drop the
          stage.  ``abort``: drop the stage.

        The RLock re-enters for free under execute()'s hold; taking
        it here keeps the method safe for any caller.
        """
        with self._lock:
            kind, txid = rec["kind"], rec["txid"]
            if kind == "prepare":
                ops = rec.get("ops") or []
                if (self._frozen or self._released) and any(
                        self._fenced_key(k) for k, _ in ops):
                    # the range is mid-handoff (post-fence) or already
                    # released: staging here could strand a committed
                    # write at the old owner — vote NO, the
                    # presumed-abort path retries under a fresh map
                    if txid not in self._staged:
                        return b"no"
                if txid not in self._staged:
                    for other, oops in self._staged.items():
                        if other == txid:
                            continue
                        held = {k for k, _ in oops}
                        if any(k in held for k, _ in ops):
                            return b"no"
                    if self._decided.get(txid):
                        # late duplicate of a finished txn: never
                        # re-stage
                        return b"no"
                    self._staged[txid] = ops
                prev = [self._data.get(k, b"")
                        for k, _ in self._staged[txid]]
                return b"yes:" + pack_values(prev)
            if kind == "decide":
                out = self._decided.setdefault(txid,
                                               rec.get("outcome", "a"))
                return out.encode()
            # commit / abort
            ops = self._staged.pop(txid, None)
            self._decided.setdefault(
                txid, "c" if kind == "commit" else "a")
            if kind == "commit" and ops is not None:
                for k, v in ops:
                    if v:
                        self._data[k] = v
                        self._version += 1
                        if self._mig_open:
                            self._note_write(k)
                        if self._multi_version:
                            self._history.setdefault(k, []).append(v)
            return b"done"

    # ---- live-migration records (shard/migrate.py) ---------------------
    @staticmethod
    def _folds(key: Key, lo: int, hi: int, span: int) -> bool:
        return lo <= int(key) % span < hi

    def _moved_key(self, key: Key) -> bool:
        return any(self._folds(key, lo, hi, span)
                   for lo, hi, span in self._released.values())

    def _fenced_key(self, key: Key) -> bool:
        """Is ``key`` inside a post-fence (frozen) or released range?"""
        return any(self._folds(key, lo, hi, span)
                   for lo, hi, span in self._frozen.values()) \
            or self._moved_key(key)

    def _note_write(self, key: Key) -> None:
        """Mark ``key`` dirty in every open install window it folds
        into — callers gate on ``self._mig_open`` so the steady-state
        write path never pays for this."""
        for w in self._mig_open.values():
            if self._folds(key, w["lo"], w["hi"], w["span"]):
                w["dirty"].add(int(key))

    def _execute_mig(self, rec: dict) -> Value:
        """Apply one migration record (shard/migrate.py epochs);
        caller holds the lock.  Every kind is deterministic and
        idempotent, so duplicate records (retries, leader-change
        re-proposals) converge at every replica:

        - ``begin`` (dst): open the install window + dirty tracking,
          and clear released markers the window intersects (a range
          migrating back home must stop answering MOVED here).  A
          replay keeps the existing window's dirty set; a ``begin``
          for a finished migration replies ``done`` (recovery's
          already-complete signal) and never re-opens.
        - ``read`` (src): stream one chunk of committed range state,
          ordered by key from ``cursor`` — the reply is
          ``items:{"items": [...], "next": cursor|-1}``.  Read-only,
          so follower execution is a no-op with the same outcome.
        - ``install`` (dst): upsert a chunk, SKIPPING dirty keys (a
          duplicated write ordered after ``begin`` always wins over a
          snapshot item).  Ignored once the window is closed.
        - ``start`` (src): the fence — freeze 2PC prepares on the
          range (see ``_execute_tpc``); every pre-fence write is
          log-ordered before this record, which is what makes the
          post-fence catch-up stream complete.
        - ``cutover`` (src): release the range — but only once no
          in-doubt 2PC stage intersects it (reply ``busy`` until the
          coordinator's retries find it clean); from here plain
          reads/writes of the range return MOVED_MAGIC.
        - ``done`` (dst): close the window, remember the mid.
        - ``drop`` (src): delete the moved keys (the drain); the
          released marker stays so stale routers keep bouncing.
        """
        with self._lock:
            kind, mid = rec["kind"], rec["mid"]
            if kind == "begin":
                if mid in self._mig_done:
                    return b"done"
                if mid not in self._mig_open:
                    self._mig_open[mid] = {
                        "lo": rec["lo"], "hi": rec["hi"],
                        "span": rec["span"], "dirty": set()}
                    # becoming the owner again (a split migrating back
                    # home): drop released markers that intersect the
                    # incoming window, else the re-owned range would
                    # answer MOVED forever — routers that missed BOTH
                    # handoffs still reroute via map-version staleness
                    for m_ in [m_ for m_, (rlo, rhi, rspan)
                               in self._released.items()
                               if rspan == rec["span"]
                               and rlo < rec["hi"] and rec["lo"] < rhi]:
                        del self._released[m_]
                return b"open"
            if kind == "read":
                lo, hi, span = rec["lo"], rec["hi"], rec["span"]
                cursor, limit = rec.get("cursor", -1), \
                    rec.get("limit", 256) or 256
                keys = sorted(k for k in self._data
                              if k > cursor
                              and self._folds(k, lo, hi, span))
                chunk = keys[:limit]
                nxt = chunk[-1] if len(keys) > limit else -1
                doc = {"items": [[k, self._data[k].decode("latin1")]
                                 for k in chunk],
                       "next": nxt}
                import json
                return b"items:" + json.dumps(doc).encode()
            if kind == "install":
                w = self._mig_open.get(mid)
                if w is None:
                    return b"stale"   # window closed (or never opened)
                for k, v in rec.get("items", []):
                    if k not in w["dirty"]:
                        self._data[k] = v
                        self._version += 1
                        if self._multi_version:
                            self._history.setdefault(k, []).append(v)
                return b"ok"
            if kind == "start":
                if mid not in self._released:
                    self._frozen[mid] = (rec["lo"], rec["hi"],
                                         rec["span"])
                return b"fenced"
            if kind == "cutover":
                lo, hi, span = rec["lo"], rec["hi"], rec["span"]
                if mid not in self._released:
                    for ops in self._staged.values():
                        if any(self._folds(k, lo, hi, span)
                               for k, _ in ops):
                            # an in-doubt 2PC stage intersects the
                            # range: releasing now could strand its
                            # commit — the coordinator retries
                            return b"busy"
                    self._released[mid] = (lo, hi, span)
                    self._frozen.pop(mid, None)
                return b"ok"
            if kind == "done":
                self._mig_open.pop(mid, None)
                self._mig_done.add(mid)
                return b"ok"
            # drop: drain the moved keys from the old owner
            lo, hi, span = rec["lo"], rec["hi"], rec["span"]
            for k in [k for k in self._data
                      if self._folds(k, lo, hi, span)]:
                del self._data[k]
            return b"ok"

    def migration_state(self) -> dict:
        """Diagnostic view of the migration planes (tests/status)."""
        with self._lock:
            return {"open": sorted(self._mig_open),
                    "done": sorted(self._mig_done),
                    "frozen": dict(self._frozen),
                    "released": dict(self._released)}

    def staged_txns(self) -> List[str]:
        """In-doubt txids (prepared, no commit/abort executed yet) —
        the coordinator-recovery scan surface."""
        with self._lock:
            return sorted(self._staged)

    def decided(self, txid: str) -> Optional[str]:
        with self._lock:
            return self._decided.get(txid)

    def get(self, key: Key) -> Optional[Value]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: Key, value: Value) -> None:
        with self._lock:
            self._data[key] = value
            if self._multi_version:
                self._history.setdefault(key, []).append(value)

    def snapshot(self) -> Dict[Key, Value]:
        """Copy of the full KV map — the state-transfer payload for
        leader-change log compaction (P1b snap)."""
        with self._lock:
            return dict(self._data)

    def aux_snapshot(self) -> dict:
        """The non-KV replicated state riding the P1b snapshot
        (protocols/paxos/host.py): staged/decided 2PC planes and the
        migration planes.  Without this, a leader change whose
        frontier jump compacts past an in-doubt txn's prepare (or a
        migration window's begin) would drop staged ops the decide
        record still commits — the documented 2PC gap, now closed.
        Wire-friendly: sets become sorted lists, values stay bytes
        (the codec round-trips bytes like the KV snap)."""
        with self._lock:
            return {
                "staged": {t: [[int(k), v] for k, v in ops]
                           for t, ops in self._staged.items()},
                "decided": dict(self._decided),
                "mig_open": {m: {"lo": w["lo"], "hi": w["hi"],
                                 "span": w["span"],
                                 "dirty": sorted(w["dirty"])}
                             for m, w in self._mig_open.items()},
                "mig_done": sorted(self._mig_done),
                "frozen": {m: list(r)
                           for m, r in self._frozen.items()},
                "released": {m: list(r)
                             for m, r in self._released.items()},
            }

    def restore_aux(self, aux: dict) -> None:
        """Adopt an aux snapshot at a P1b frontier jump.  Upsert
        semantics like :meth:`restore`: decided outcomes merge
        first-wins-preserving (``setdefault``), stages only land for
        txns not already decided locally, windows/fences/releases
        union — so a replica that is AHEAD on any plane keeps its own
        state."""
        if not aux:
            return
        with self._lock:
            for t, o in (aux.get("decided") or {}).items():
                self._decided.setdefault(t, o)
            for t, ops in (aux.get("staged") or {}).items():
                if t not in self._decided and t not in self._staged:
                    self._staged[t] = [(int(k), v) for k, v in ops]
            for m in aux.get("mig_done") or []:
                self._mig_done.add(m)
                self._mig_open.pop(m, None)
            for m, w in (aux.get("mig_open") or {}).items():
                if m in self._mig_done:
                    continue
                mine = self._mig_open.setdefault(
                    m, {"lo": int(w["lo"]), "hi": int(w["hi"]),
                        "span": int(w["span"]), "dirty": set()})
                mine["dirty"].update(int(k) for k in w["dirty"])
            for m, r in (aux.get("released") or {}).items():
                self._released.setdefault(m, tuple(int(x) for x in r))
                self._frozen.pop(m, None)
            for m, r in (aux.get("frozen") or {}).items():
                if m not in self._released:
                    self._frozen.setdefault(
                        m, tuple(int(x) for x in r))

    def restore(self, snap: Dict[Key, Value]) -> None:
        """Adopt a snapshot (state transfer at leader change).  Upsert
        semantics: snapshots from a more-advanced replica are a
        superset of the committed state, so absent keys need no
        deletion.  Use :meth:`reset` when the new state REPLACES the
        old (e.g. a blockchain reorg replay)."""
        with self._lock:
            for k, v in snap.items():
                self.put(int(k), v)

    def reset(self) -> None:
        """Drop every key (and history): the caller is rebuilding the
        state from scratch — a chain reorg replay, not a state
        transfer."""
        with self._lock:
            self._data.clear()
            self._history.clear()

    def history(self, key: Key) -> List[Value]:
        with self._lock:
            return list(self._history.get(key, []))

    def version(self) -> int:
        return self._version

    def __len__(self) -> int:
        return len(self._data)
