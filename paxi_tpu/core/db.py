"""The replicated state machine: an in-memory KV store.

Reference: paxi db.go — ``Database`` interface with ``Execute(Command)
Value`` backed by ``map[Key]Value`` + RWMutex, optional multi-version
history.  Host-runtime replicas execute committed commands against this;
the sim runtime keeps the KV as a dense ``(replica, key)`` int32 array
(see protocols' sim kernels).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from paxi_tpu.core.command import (TPC_MAGIC, TXN_MAGIC, Command, Key,
                                   Value, pack_values, unpack_tpc,
                                   unpack_transaction)


class Database:
    """In-memory KV store with optional per-key version history."""

    def __init__(self, multi_version: bool = False):
        self._data: Dict[Key, Value] = {}
        self._history: Dict[Key, List[Value]] = {}
        self._multi_version = multi_version
        self._lock = threading.RLock()
        self._version = 0
        # cross-shard 2PC participant state (paxi_tpu/shard/txn.py):
        # every replica of a group executes the same ordered prepare/
        # decide/commit/abort records, so these dicts evolve
        # deterministically across the group — the participant log IS
        # the group's consensus log.  ``_staged``: txid -> staged ops;
        # ``_decided``: txid -> "c"|"a", FIRST decide record wins (the
        # coordinator-recovery tiebreak rides on log order).
        self._staged: Dict[str, list] = {}
        self._decided: Dict[str, str] = {}

    def execute(self, cmd: Command) -> Value:
        """Apply a command; returns the PREVIOUS value (read for gets,
        old-value for puts) exactly like the reference's Execute.

        A command whose value packs a Transaction (command.py
        pack_transaction) applies the whole batch atomically and returns
        the packed previous values — this is how transactions replicate:
        as one ordered command through whatever protocol runs."""
        with self._lock:
            if cmd.value.startswith(TPC_MAGIC):
                rec = unpack_tpc(cmd.value)
                if rec is not None:
                    return self._execute_tpc(rec)
            batch = unpack_transaction(cmd.value) if cmd.value else None
            if batch is not None:
                return pack_values(self.execute_transaction(batch))
            prev = self._data.get(cmd.key, b"")
            if cmd.is_write():
                self._data[cmd.key] = cmd.value
                self._version += 1
                if self._multi_version:
                    self._history.setdefault(cmd.key, []).append(cmd.value)
            return prev

    def apply_batch(self, cmds: List[Command],
                    ctab: Dict[str, tuple]) -> None:
        """Tight-loop state-machine application of a committed batch
        with per-client at-most-once filtering — the execute path for
        replicas holding no client connections (one lock acquisition,
        no Reply objects).  ``ctab`` is the caller's session table
        (client_id -> (highest executed command_id, its value)),
        updated exactly as the execute() path would.  Transaction-
        packed and multi-version commands fall back to execute()
        (the RLock makes that re-entrant)."""
        with self._lock:
            data = self._data
            for cmd in cmds:
                if cmd.key < 0:
                    continue   # NOOP filler
                cid = cmd.client_id
                if cid:
                    last = ctab.get(cid)
                    if last is not None and cmd.command_id <= last[0]:
                        continue   # duplicate: already executed
                v = cmd.value
                if self._multi_version or v.startswith(TPC_MAGIC):
                    out = self.execute(cmd)
                elif v.startswith(TXN_MAGIC):
                    batch = unpack_transaction(v)
                    # same outcome as execute(): packed previous values
                    # (ctab must agree across replicas for duplicate
                    # replies after leader changes), one unpack + one
                    # inline loop instead of nested executes
                    out = (pack_values(self.execute_transaction(batch))
                           if batch is not None
                           else self.execute(cmd))
                else:
                    out = data.get(cmd.key, b"")
                    if v:
                        data[cmd.key] = v
                        self._version += 1
                if cid:
                    ctab[cid] = (cmd.command_id, out)

    def execute_transaction(self, commands: List[Command]) -> List[Value]:
        """Apply a command batch atomically (msg.go Transaction surface):
        all commands run under one lock acquisition, returning each
        command's previous value in order.  Plain sub-commands apply
        inline (no nested execute/lock per sub-command — with batched
        clients this loop IS the state-machine hot path); nested
        transaction-packed or multi-version sub-commands keep
        execute()'s exact semantics via the re-entrant fallback."""
        with self._lock:
            data = self._data
            out = []
            for c in commands:
                v = c.value
                if self._multi_version or v.startswith(TXN_MAGIC) \
                        or v.startswith(TPC_MAGIC):
                    out.append(self.execute(c))
                    continue
                prev = data.get(c.key, b"")
                if v:
                    data[c.key] = v
                    self._version += 1
                out.append(prev)
            return out

    def _execute_tpc(self, rec: dict) -> Value:
        """Apply one cross-shard 2PC record (shard/txn.py taxonomy);
        caller holds the lock.  Deterministic and idempotent per kind,
        so duplicate records (retries, leader-change re-proposals)
        converge at every replica:

        - ``prepare``: stage the ops unless a key is staged by another
          in-flight txn (vote NO — the conflict-abort that gives 2PC
          its txn-txn isolation).  Reply ``yes:`` + packed
          prepare-point previous values, or ``no``.
        - ``decide``: record the outcome ONCE; the reply is the
          winning outcome, so a racing coordinator/recovery learns the
          truth from its own (ordered) decide record.
        - ``commit``: apply the staged writes atomically, drop the
          stage.  ``abort``: drop the stage.

        The RLock re-enters for free under execute()'s hold; taking
        it here keeps the method safe for any caller.
        """
        with self._lock:
            kind, txid = rec["kind"], rec["txid"]
            if kind == "prepare":
                ops = rec.get("ops") or []
                if txid not in self._staged:
                    for other, oops in self._staged.items():
                        if other == txid:
                            continue
                        held = {k for k, _ in oops}
                        if any(k in held for k, _ in ops):
                            return b"no"
                    if self._decided.get(txid):
                        # late duplicate of a finished txn: never
                        # re-stage
                        return b"no"
                    self._staged[txid] = ops
                prev = [self._data.get(k, b"")
                        for k, _ in self._staged[txid]]
                return b"yes:" + pack_values(prev)
            if kind == "decide":
                out = self._decided.setdefault(txid,
                                               rec.get("outcome", "a"))
                return out.encode()
            # commit / abort
            ops = self._staged.pop(txid, None)
            self._decided.setdefault(
                txid, "c" if kind == "commit" else "a")
            if kind == "commit" and ops is not None:
                for k, v in ops:
                    if v:
                        self._data[k] = v
                        self._version += 1
                        if self._multi_version:
                            self._history.setdefault(k, []).append(v)
            return b"done"

    def staged_txns(self) -> List[str]:
        """In-doubt txids (prepared, no commit/abort executed yet) —
        the coordinator-recovery scan surface."""
        with self._lock:
            return sorted(self._staged)

    def decided(self, txid: str) -> Optional[str]:
        with self._lock:
            return self._decided.get(txid)

    def get(self, key: Key) -> Optional[Value]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: Key, value: Value) -> None:
        with self._lock:
            self._data[key] = value
            if self._multi_version:
                self._history.setdefault(key, []).append(value)

    def snapshot(self) -> Dict[Key, Value]:
        """Copy of the full KV map — the state-transfer payload for
        leader-change log compaction (P1b snap)."""
        with self._lock:
            return dict(self._data)

    def restore(self, snap: Dict[Key, Value]) -> None:
        """Adopt a snapshot (state transfer at leader change).  Upsert
        semantics: snapshots from a more-advanced replica are a
        superset of the committed state, so absent keys need no
        deletion.  Use :meth:`reset` when the new state REPLACES the
        old (e.g. a blockchain reorg replay)."""
        with self._lock:
            for k, v in snap.items():
                self.put(int(k), v)

    def reset(self) -> None:
        """Drop every key (and history): the caller is rebuilding the
        state from scratch — a chain reorg replay, not a state
        transfer."""
        with self._lock:
            self._data.clear()
            self._history.clear()

    def history(self, key: Key) -> List[Value]:
        with self._lock:
            return list(self._history.get(key, []))

    def version(self) -> int:
        return self._version

    def __len__(self) -> int:
        return len(self._data)
