"""Core types shared by the host runtime and the TPU sim runtime.

Reference (paxi): id.go, config.go, msg.go, db.go, quorum.go.
"""

from paxi_tpu.core.ident import ID
from paxi_tpu.core.config import Config, Bconfig
from paxi_tpu.core.command import Command, Request, Reply
from paxi_tpu.core.db import Database
from paxi_tpu.core.quorum import Quorum

__all__ = ["ID", "Config", "Bconfig", "Command", "Request", "Reply",
           "Database", "Quorum"]
