"""Object-stealing policies for WPaxos.

Reference: paxi policy.go — a ``Policy`` interface that tracks per-key
access hits by zone and decides when ownership should move; the
implementations select on ``Config.Policy`` + ``Config.Threshold``:
``consecutive`` fires after N consecutive hits from the same zone,
``majority`` (EMA-style) fires when a zone's share of recent hits
crosses a ratio threshold within a time window.

Used from the requester side here: each replica records *its own* demand
for keys it does not own; when the policy fires the replica launches a
phase-1 steal (wpaxos/host.py).  The sim kernel's ``hits`` counters
(wpaxos/sim.py) are the vectorized form of the same surface.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class Policy:
    """Per-key decision: feed zone hits, fire when ownership should move."""

    def hit(self, zone: int, now: Optional[float] = None) -> Optional[int]:
        """Record an access from ``zone``; return the zone that should own
        the object now, or None to leave ownership alone."""
        raise NotImplementedError


class ConsecutivePolicy(Policy):
    """policy.go's 'consecutive': N back-to-back hits from one zone."""

    def __init__(self, threshold: float):
        self.threshold = max(int(threshold), 1)
        self.zone = -1
        self.count = 0

    def hit(self, zone: int, now: Optional[float] = None) -> Optional[int]:
        if zone == self.zone:
            self.count += 1
        else:
            self.zone = zone
            self.count = 1
        if self.count >= self.threshold:
            self.count = 0
            return zone
        return None


class MajorityPolicy(Policy):
    """policy.go's 'majority': a zone holding > threshold share of the
    hits inside a sliding time window (EMA-flavored bookkeeping)."""

    def __init__(self, threshold: float, interval_s: float = 1.0):
        # threshold given as a count (paxi uses ints) acts as a minimum
        # hit count; given as a ratio <= 1 it acts as a share
        self.threshold = threshold
        self.interval = interval_s
        self.hits: Dict[int, int] = {}
        self.t0 = None

    def hit(self, zone: int, now: Optional[float] = None) -> Optional[int]:
        now = time.time() if now is None else now
        if self.t0 is None:
            self.t0 = now
        self.hits[zone] = self.hits.get(zone, 0) + 1
        if now - self.t0 < self.interval:
            return None
        total = sum(self.hits.values())
        best = max(self.hits, key=self.hits.get)
        share = self.hits[best] / total
        need = self.threshold if self.threshold <= 1 else 0.5
        min_hits = self.threshold if self.threshold > 1 else 1
        self.hits.clear()
        self.t0 = now
        if share > need and total >= min_hits:
            return best
        return None


def new_policy(name: str, threshold: float) -> Policy:
    """Reference: policy.go's factory keyed by Config.Policy."""
    if name == "consecutive":
        return ConsecutivePolicy(threshold)
    if name in ("majority", "ema"):
        return MajorityPolicy(threshold)
    raise KeyError(f"unknown policy {name!r}; have consecutive, majority")
