"""Node identifiers.

Reference: paxi id.go (``type ID string`` with "zone.node" format,
``Zone()``, ``Node()``, ``NewID``).  Zone-awareness is the basis for
WAN quorums and ``Multicast(zone)``.
"""

from __future__ import annotations

import functools


@functools.total_ordering
class ID(str):
    """A node identifier of the form ``"zone.node"`` (both 1-based ints).

    Subclasses ``str`` so it round-trips through JSON config keys exactly
    like the reference's ``type ID string``.
    """

    __slots__ = ()

    def __new__(cls, value: "str | ID"):
        s = str(value)
        if "." not in s:
            # tolerate bare node numbers: zone defaults to 1
            s = f"1.{s}"
        inst = super().__new__(cls, s)
        inst.zone, inst.node  # validate eagerly
        return inst

    @property
    def zone(self) -> int:
        return int(self.split(".", 1)[0])

    @property
    def node(self) -> int:
        return int(self.split(".", 1)[1])

    def __lt__(self, other) -> bool:  # numeric (zone, node) order, not lexical
        o = ID(other)
        return (self.zone, self.node) < (o.zone, o.node)

    def __eq__(self, other) -> bool:
        return str(self) == str(other)

    def __hash__(self) -> int:
        return str.__hash__(self)


def new_id(zone: int, node: int) -> ID:
    """Reference: id.go NewID(zone, node)."""
    return ID(f"{zone}.{node}")
