"""Host twin of the ``fragile_counter`` demo kernel (trace/demo.py).

The same deliberately UNSAFE protocol on the asyncio runtime: the
lowest-ID replica broadcasts a sequence number every logical step (a
virtual-clock fabric driver — see ``HUNT_DRIVER``), receivers require
strict in-order delivery and count a violation on every gap.  Because
the two implementations are behaviorally identical, a sim witness
(one dropped or reordered ``seq``) MUST reproduce on the host when the
fabric replays it — making this the hunt subsystem's end-to-end
``reproduced`` fixture, and any classification other than
``reproduced`` on a fragile witness a bug in the pipeline itself.

NOT a real protocol: it serves no client requests (the hunt classifier
reads its ``HUNT_ORACLE`` instead of a linearizability history).
"""

from __future__ import annotations

from dataclasses import dataclass

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class Seq:
    """The broadcast sequence number (sim mailbox ``seq``, field v)."""

    v: int


class FragileReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.last = 0      # highest seq applied (sim state "last")
        self.gaps = 0      # out-of-order deliveries (sim state "gaps")
        self._next = 0     # broadcaster's own sequence counter
        self.register(Seq, self.handle_seq)

    def handle_seq(self, m: Seq) -> None:
        if m.v > self.last + 1:
            self.gaps += 1
        self.last = max(self.last, m.v)

    def tick(self, t: int) -> None:
        """Per-step driver (sim: replica 0 broadcasts one fresh
        sequence number per lock-step round); only the lowest-ID
        replica ticks.  Sequenced off an own counter, not ``t`` —
        fabric drivers must tolerate clock jumps (the drain phase can
        advance the logical clock past the driven window)."""
        del t
        self._next += 1
        self.socket.broadcast(Seq(v=self._next))


def new_replica(id: ID, cfg: Config) -> FragileReplica:
    return FragileReplica(id, cfg)


# sim mailbox -> host message class (total: the one mailbox maps)
TRACE_MSG_MAP = {"seq": "Seq"}


# ---- hunt-engine hooks (paxi_tpu/hunt/classify.py) ----------------------
def HUNT_DRIVER(cluster, fabric) -> None:
    """Wire the broadcaster to the fabric's logical clock — the host
    analog of the sim kernel emitting one broadcast per lock-step
    round."""
    first = sorted(cluster.ids)[0]
    fabric.on_step(lambda t: cluster[first].tick(t))


def HUNT_ORACLE(cluster) -> int:
    """Safety-violation count after a replay (sim: the ``gaps``
    invariant counter summed over replicas)."""
    return sum(cluster[i].gaps for i in cluster.ids)
