"""`fragile_counter`: a deliberately UNSAFE protocol that seeds
violations for the trace subsystem's own tests and demos.

Replica 0 broadcasts a sequence number each step; receivers require
strict in-order delivery and count a violation whenever a sequence gap
slips through — which any single drop (or reordering delay) of a
``seq`` message causes.  This is the trace pipeline's lab rat: a
violation exists under any lossy schedule, the minimal witness is ONE
fault event, and the kernel is small enough that capture -> shrink ->
replay runs in well under a second on CPU.  It runs the per-group
(vmapped) kernel layout, complementing the lane-major protocols the
soak uses, so both runner paths stay covered.

NOT a real protocol — never add it to the soak matrix as a correctness
case; its violations are the expected output.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {"seq": ("v",)}


def init_state(cfg: SimConfig, rng: jax.Array):
    del rng
    R = cfg.n_replicas
    return {
        "last": jnp.zeros((R,), jnp.int32),   # highest seq applied
        "gaps": jnp.zeros((), jnp.int32),     # out-of-order deliveries
    }


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R = cfg.n_replicas
    m = inbox["seq"]
    from0 = m["valid"][0]                     # (dst,): arrivals from 0
    v0 = m["v"][0]
    last = state["last"]
    gap = from0 & (v0 > last + 1)             # a seq number was skipped
    new_last = jnp.where(from0, jnp.maximum(last, v0), last)
    new_gaps = state["gaps"] + jnp.sum(gap.astype(jnp.int32))
    out = {"seq": {
        "valid": jnp.zeros((R, R), bool).at[0].set(True),
        "v": jnp.broadcast_to(ctx.t + 1, (R, R)).astype(jnp.int32),
    }}
    return {"last": new_last, "gaps": new_gaps}, out


def metrics(state, cfg: SimConfig):
    return {"delivered": jnp.sum(state["last"])}


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    return (new["gaps"] - old["gaps"]).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="fragile_counter",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=False,
)
