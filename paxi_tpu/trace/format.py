"""The trace file format: a violation's fault schedule as an artifact.

A trace is the complete, versioned record of the fault schedule one
simulated group experienced — per step: the connectivity plane, the
crash vector, and per message type the effective drop/delay/dup planes
(only events that coincided with an actual send are kept; everything
else is neutral by construction, see runner._group_step's record path).
Together with (protocol, geometry, fuzz config, seed, group index) it
pins a run exactly: the pinned replay path consumes these planes
INSTEAD of PRNG draws, so a captured violation reproduces bit-for-bit,
and a schedule edited by the shrinker replays deterministically too.

Container: one ``.npz`` with path-flattened arrays plus a JSON meta
blob — the same portable envelope as sim/checkpoint.py, so traces move
between hosts/devices freely.

Schedule pytree (single group, time-major)::

    {"conn":    (T, R, R) bool,   # src->dst deliverable this step
     "crashed": (T, R)    bool,   # comms-crashed replicas
     "faults":  {msg_type: {"drop":  (T, R, R) bool,
                            "delay": (T, R, R) int32,  # 1..max_delay
                            "dup":   (T, R, R) bool}}}
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass
from typing import Any, Dict

import jax
import numpy as np

from paxi_tpu.sim.types import FuzzConfig, SimConfig

_META_KEY = "__paxi_tpu_trace_meta__"
_SEP = "|"
# bump on incompatible schedule-layout changes; load() refuses a
# mismatch with a clear error instead of a downstream shape error.
# The scenario engine (paxi_tpu/scenarios) did NOT bump this: the
# schedule planes are unchanged (a zone-latency delay is just a deeper
# per-edge delay value, churn is just crash-plane occupancy) and the
# meta extension is additive — ``fuzz.scenario`` is reconstructed when
# present and defaults to None for pre-scenario traces, the same
# subset-compatibility rule the counter check follows (cli.py `trace
# replay` compares only RECORDED counter keys).
TRACE_VERSION = 1


@dataclass
class Trace:
    """A captured (or shrunk) single-group fault schedule + provenance."""

    meta: Dict[str, Any]
    sched: Dict[str, Any]     # pytree of numpy/jax arrays, time-major

    # ---- provenance accessors -----------------------------------------
    @property
    def protocol(self) -> str:
        return self.meta["protocol"]

    @property
    def group(self) -> int:
        return int(self.meta["group"])

    @property
    def n_groups(self) -> int:
        return int(self.meta["n_groups"])

    @property
    def seed(self) -> int:
        return int(self.meta["seed"])

    @property
    def n_steps(self) -> int:
        return int(jax.tree_util.tree_leaves(self.sched)[0].shape[0])

    def sim_config(self) -> SimConfig:
        return SimConfig(**self.meta["sim_cfg"])

    def fuzz_config(self) -> FuzzConfig:
        return fuzz_from_meta(self.meta["fuzz"])

    def n_events(self) -> int:
        """Total fault events in the schedule (what the shrinker
        minimizes): drops + dups + delayed sends + crashed replica-steps
        + severed edge-steps."""
        s = self.sched
        n = int(np.sum(~np.asarray(s["conn"])))
        n += int(np.sum(np.asarray(s["crashed"])))
        for f in s["faults"].values():
            n += int(np.sum(np.asarray(f["drop"])))
            n += int(np.sum(np.asarray(f["dup"])))
            n += int(np.sum(np.asarray(f["delay"]) > 1))
        return n

    def with_sched(self, sched, **meta_updates) -> "Trace":
        meta = dict(self.meta, **meta_updates)
        t = Trace(meta=meta, sched=sched)
        if "schedule_hash" in meta and "schedule_hash" not in meta_updates:
            # an inherited stamp describes the OLD schedule — refresh it
            # so corpus dedup (hunt/corpus.py) never aliases an edited
            # (e.g. shrunk) trace to its parent
            meta["schedule_hash"] = schedule_hash(t)
        return t


def fuzz_from_meta(d: Dict[str, Any]) -> FuzzConfig:
    """Rebuild a FuzzConfig from trace meta (``dataclasses.asdict``
    after a JSON round-trip).  Pre-scenario traces have no
    ``scenario`` key and reconstruct with ``scenario=None``; newer
    traces rebuild the nested Scenario spec (lists back to tuples) so
    the pinned replay sizes its delay wheel and kill overlay exactly
    like the captured run did."""
    d = dict(d)
    scn = d.pop("scenario", None)
    fz = FuzzConfig(**d)
    if scn is not None:
        from paxi_tpu.scenarios.spec import Scenario
        fz = dataclasses.replace(fz, scenario=Scenario.from_dict(scn))
    return fz


def schedule_hash(trace: "Trace") -> str:
    """Content hash of (protocol, schedule planes) — the corpus dedup
    key (hunt/corpus.py).  Deliberately independent of provenance
    (seed, group, fuzz knobs): two fuzz runs that produced the same
    effective fault schedule for the same protocol are the same
    witness."""
    h = hashlib.sha256()
    h.update(trace.protocol.encode())
    for name, arr in sorted(_flatten(trace.sched).items()):
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def make_meta(proto_name: str, cfg: SimConfig, fuzz: FuzzConfig,
              seed: int, n_groups: int, group: int,
              **extra) -> Dict[str, Any]:
    meta = {
        "trace_version": TRACE_VERSION,
        "protocol": proto_name,
        "sim_cfg": dataclasses.asdict(cfg),
        "fuzz": dataclasses.asdict(fuzz),
        "seed": int(seed),
        "n_groups": int(n_groups),
        "group": int(group),
    }
    meta.update(extra)
    return meta


def _flatten(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[_SEP.join(str(p) for p in path)] = np.asarray(leaf)
    return flat


def _norm(path: str) -> str:
    return path if path.endswith(".npz") else path + ".npz"


def save(path: str, trace: Trace) -> str:
    """Write a trace; returns the (normalized) path written."""
    flat = _flatten(trace.sched)
    meta = dict(trace.meta)
    meta.setdefault("trace_version", TRACE_VERSION)
    # every dumped trace carries its dedup identity (and `protocol` is
    # already in meta), so corpora seeded from pre-existing trace dirs
    # dedup without re-deriving anything
    meta.setdefault("schedule_hash", schedule_hash(trace))
    flat[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    path = _norm(path)
    np.savez_compressed(path, **flat)
    return path


def load(path: str) -> Trace:
    with np.load(_norm(path)) as z:
        if _META_KEY not in z:
            raise ValueError(f"{path!r} is not a paxi_tpu trace file")
        meta = json.loads(bytes(z[_META_KEY]).decode())
        flat = {k: z[k] for k in z.files if k != _META_KEY}
    v = int(meta.get("trace_version", 0))
    if v != TRACE_VERSION:
        raise ValueError(
            f"trace version v{v} is incompatible with this build "
            f"(v{TRACE_VERSION}); re-capture the trace")
    sched: Dict[str, Any] = {"faults": {}}
    for key, arr in flat.items():
        parts = [p.strip("[']") for p in key.split(_SEP)]
        node = sched
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    for req in ("conn", "crashed"):
        if req not in sched:
            raise ValueError(f"trace {path!r} missing {req!r} plane")
    return Trace(meta=meta, sched=sched)
