"""Pinned-schedule replay: run a trace, get the violation back.

The replay runner (sim/runner.make_pinned_run) re-executes the captured
run with the SAME seed and geometry; the traced group consumes the
trace's recorded planes instead of PRNG draws while the other groups
keep their drawn schedules — they are scaffolding that pins the traced
group's workload (batched kernels draw workload per step from one run
key shaped over all groups, so the batch context is part of the
reproduction).  Because the recorded schedule of an unedited trace
equals the drawn one, replaying a fresh capture is bit-for-bit the
original run; an edited (shrunk) schedule replays just as
deterministically, which is what makes the shrinker's oracle sound.

``ReplayResult.state_hash`` fingerprints the traced group's final state
pytree — two replays of the same trace must agree exactly, and a replay
of an unedited capture must match the hash recorded at capture time.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from paxi_tpu.sim.runner import make_pinned_run
from paxi_tpu.sim.types import SimProtocol
from paxi_tpu.trace.format import Trace


@dataclass
class ReplayResult:
    violations: int           # traced group's total invariant violations
    viol_steps: np.ndarray    # per-step violation counts, shape (T,)
    state_hash: str           # fingerprint of the group's final state
    metrics: Dict[str, int]   # whole-batch metrics (context, not oracle)
    # the traced group's on-device commit-latency histogram (sparse
    # {bucket: count}, metrics/lathist layout) — None for kernels
    # without the ``m_lat_hist`` plane.  An unedited capture's replay
    # must reproduce the trace's ``capture_lat_hist`` meta exactly
    # (measurement determinism; the plane is excluded from state_hash)
    lat_hist: Optional[Dict[str, int]] = None

    @property
    def violated(self) -> bool:
        return self.violations > 0

    @property
    def counters(self) -> Dict[str, int]:
        """Whole-batch message/fault counters (``net_*`` metrics, prefix
        stripped).  For an unedited capture these must equal the trace's
        ``capture_counters`` meta — the counter half of the determinism
        guarantee."""
        from paxi_tpu.metrics.simcount import counters_of
        return counters_of(self.metrics)

    def first_violation_step(self) -> Optional[int]:
        nz = np.nonzero(self.viol_steps)[0]
        return int(nz[0]) if nz.size else None


def state_hash(state) -> str:
    """Order-, dtype- and shape-sensitive fingerprint of a pytree.

    Protocol-state keys prefixed ``m_`` are EXCLUDED: they are
    measurement accumulators (e.g. the zone-latency accounting planes
    the wpaxos/wankeeper kernels carry for the scenario bench), pure
    read-side accounting that never feeds a transition — excluding
    them keeps traces captured before a kernel grew an instrumentation
    plane replaying hash-clean, the state-side twin of the counter
    subset-compare rule (trace/format.py TRACE_VERSION note).  Their
    determinism is still pinned: they land in the run metrics, which
    the replay tests compare directly."""
    if isinstance(state, dict):
        state = {k: v for k, v in state.items()
                 if not k.startswith("m_")}
    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        a = np.asarray(leaf)
        h.update(str(path).encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def resolve_protocol(name: str) -> SimProtocol:
    from paxi_tpu.protocols import sim_protocol
    return sim_protocol(name)


# one compiled pinned runner per (protocol, geometry, fuzz, group);
# distinct schedule lengths retrace under the same jit wrapper, so the
# shrinker's many same-length trials share one executable
_PIN_CACHE: dict = {}


def _pinned_run(proto: SimProtocol, trace: Trace, mesh=None):
    # id(proto) in the key (like runner._CONTINUE_CACHE): an explicitly
    # passed protocol object must never be shadowed by a same-named
    # cached compile — registry singletons still hit
    # Mesh hashes by (devices, axis_names), so two make_mesh(8) calls
    # share one compiled run — id(mesh) would recompile per Mesh object
    key = (id(proto), trace.sim_config(), trace.fuzz_config(),
           trace.group, mesh)
    run = _PIN_CACHE.get(key)
    if run is None:
        if mesh is not None:
            from paxi_tpu.parallel.mesh import make_sharded_pinned_run
            run = make_sharded_pinned_run(proto, trace.sim_config(),
                                          trace.fuzz_config(),
                                          trace.group, mesh=mesh)
        else:
            run = make_pinned_run(proto, trace.sim_config(),
                                  trace.fuzz_config(), trace.group)
        _PIN_CACHE[key] = run
    return run


def replay(trace: Trace, proto: Optional[SimProtocol] = None,
           sched=None, mesh=None) -> ReplayResult:
    """Replay ``trace`` (or an edited ``sched`` override against the
    trace's provenance) and report the traced group's violations.

    ``mesh`` shards the replay batch over a device mesh
    (``parallel/mesh.make_sharded_pinned_run``) — per-group kernels
    reproduce the same state hash and counters as the single-device
    replay, so violations found at 100k-group scale round-trip without
    leaving the mesh."""
    proto = proto or resolve_protocol(trace.protocol)
    sched = trace.sched if sched is None else sched
    sched = jax.tree.map(jnp.asarray, sched)
    run = _pinned_run(proto, trace, mesh=mesh)
    state, metrics, total, viols = run(
        jr.PRNGKey(trace.seed), trace.n_groups, sched)
    jax.block_until_ready(total)
    gstate = jax.tree.map(lambda x: x[trace.group], state)
    from paxi_tpu.metrics import lathist
    ghist = lathist.total_hist(gstate)
    lat_hist = None if ghist is None else lathist.to_sparse(ghist)
    return ReplayResult(
        violations=int(total),
        viol_steps=np.asarray(viols).reshape(-1),
        state_hash=state_hash(gstate),
        metrics={k: int(v) for k, v in metrics.items()},
        lat_hist=lat_hist)


def check_determinism(trace: Trace,
                      proto: Optional[SimProtocol] = None) -> ReplayResult:
    """Replay twice and assert identical outcomes (the determinism
    guarantee the whole subsystem rests on); returns the result."""
    a = replay(trace, proto)
    b = replay(trace, proto)
    if a.state_hash != b.state_hash or a.violations != b.violations:
        raise AssertionError(
            f"non-deterministic replay: {a.violations}@{a.state_hash[:12]}"
            f" vs {b.violations}@{b.state_hash[:12]}")
    if a.counters != b.counters:
        raise AssertionError(
            f"non-deterministic replay counters: {a.counters} "
            f"vs {b.counters}")
    return a
