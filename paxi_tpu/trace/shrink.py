"""Delta-debugging minimizer for traces: from a violating schedule to a
minimal witness.

Classic ddmin (Zeller's delta debugging) over the trace's fault events,
with the pinned replay as the oracle: a candidate schedule "passes" if
the traced group still violates its invariants.  Passes, in order:

1. **Truncate** to the first violating step + 1 — invariants are
   per-step transition checks, so the violating prefix is sufficient.
2. **Category sweeps** — try deleting whole event classes at once
   (all dups, all delays, all partition cuts, all crashes, all drops of
   one message type): cheap early wins that shrink the ddmin universe.
3. **ddmin** over the remaining individual events.
4. **Re-truncate** (removing events can move the violation earlier).

Every candidate is a full deterministic replay, so the minimizer can
never "shrink past" the bug the way a heuristic on logs could; the
output trace carries ``shrunk: True`` plus before/after stats and its
own replay state hash.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from paxi_tpu.sim.types import SimProtocol
from paxi_tpu.trace.format import Trace
from paxi_tpu.trace.replay import ReplayResult, replay

# an event is ("drop"|"dup"|"delay", msg, t, i, j), ("crash", t, i) or
# ("cut", t, i, j) — everything the schedule can express, one atom each
Event = Tuple


def list_events(sched) -> List[Event]:
    ev: List[Event] = []
    for t, i, j in np.argwhere(~np.asarray(sched["conn"])):
        ev.append(("cut", int(t), int(i), int(j)))
    for t, i in np.argwhere(np.asarray(sched["crashed"])):
        ev.append(("crash", int(t), int(i)))
    for name in sorted(sched["faults"]):
        f = sched["faults"][name]
        for t, i, j in np.argwhere(np.asarray(f["drop"])):
            ev.append(("drop", name, int(t), int(i), int(j)))
        for t, i, j in np.argwhere(np.asarray(f["dup"])):
            ev.append(("dup", name, int(t), int(i), int(j)))
        for t, i, j in np.argwhere(np.asarray(f["delay"]) > 1):
            ev.append(("delay", name, int(t), int(i), int(j)))
    return ev


def neutralize(sched, events: List[Event]):
    """A copy of ``sched`` with ``events`` replaced by fault-free
    values (conn=True, crashed=False, drop/dup=False, delay=1)."""
    out = {"conn": np.array(sched["conn"]),
           "crashed": np.array(sched["crashed"]),
           "faults": {n: {k: np.array(v) for k, v in f.items()}
                      for n, f in sched["faults"].items()}}
    for e in events:
        if e[0] == "cut":
            _, t, i, j = e
            out["conn"][t, i, j] = True
        elif e[0] == "crash":
            _, t, i = e
            out["crashed"][t, i] = False
        else:
            kind, name, t, i, j = e
            if kind == "drop":
                out["faults"][name]["drop"][t, i, j] = False
            elif kind == "dup":
                out["faults"][name]["dup"][t, i, j] = False
            else:
                out["faults"][name]["delay"][t, i, j] = 1
    return out


def _truncate(sched, t_end: int):
    import jax
    return jax.tree.map(lambda x: np.asarray(x)[:t_end], sched)


def shrink(trace: Trace, proto: Optional[SimProtocol] = None,
           max_trials: int = 200,
           log=None) -> Tuple[Trace, Dict[str, int]]:
    """Minimize ``trace``; returns (minimal trace, stats).  Raises
    ValueError if the input trace does not reproduce a violation."""
    emit = log or (lambda *_: None)
    trials = 0

    def oracle(sched) -> ReplayResult:
        nonlocal trials
        trials += 1
        return replay(trace, proto, sched=sched)

    base = oracle(trace.sched)
    if not base.violated:
        raise ValueError(
            "trace does not reproduce a violation; nothing to shrink")
    steps0, events0 = trace.n_steps, trace.n_events()

    # ---- pass 1: truncate to the violating prefix ----------------------
    sched = trace.sched
    t_end = base.first_violation_step() + 1
    if t_end < trace.n_steps:
        cand = _truncate(sched, t_end)
        res = oracle(cand)
        if res.violated:          # prefix determinism should guarantee it
            sched, base = cand, res
    emit(f"truncated {steps0} -> "
         f"{int(np.asarray(sched['crashed']).shape[0])} steps")

    # ---- pass 2: whole-category sweeps ---------------------------------
    def events_of(s):
        return list_events(s)

    cats = [lambda e: e[0] == "dup", lambda e: e[0] == "delay",
            lambda e: e[0] == "cut", lambda e: e[0] == "crash"]
    cats += [(lambda e, n=name: e[0] == "drop" and e[1] == n)
             for name in sorted(sched["faults"])]
    for cat in cats:
        if trials >= max_trials:
            break
        victims = [e for e in events_of(sched) if cat(e)]
        if not victims:
            continue
        cand = neutralize(sched, victims)
        res = oracle(cand)
        if res.violated:
            sched, base = cand, res
            emit(f"dropped category ({len(victims)} events)")

    # ---- pass 3: ddmin over the remaining events -----------------------
    kept = events_of(sched)
    n = 2
    while len(kept) >= 2 and n <= len(kept) and trials < max_trials:
        chunk = max(len(kept) // n, 1)
        reduced = False
        for lo in range(0, len(kept), chunk):
            if trials >= max_trials:
                break
            victims = kept[lo:lo + chunk]
            remaining = kept[:lo] + kept[lo + chunk:]
            cand = neutralize(sched, victims)
            res = oracle(cand)
            if res.violated:
                sched, base, kept = cand, res, remaining
                n = max(n - 1, 2)
                reduced = True
                emit(f"{len(kept)} events left")
                break
        if not reduced:
            if n >= len(kept):
                break
            n = min(len(kept), n * 2)

    # ---- pass 4: re-truncate (the violation may have moved) ------------
    t_end = base.first_violation_step() + 1
    if t_end < int(np.asarray(sched["crashed"]).shape[0]):
        cand = _truncate(sched, t_end)
        res = oracle(cand)
        if res.violated:
            sched, base = cand, res

    out = trace.with_sched(
        sched, shrunk=True,
        group_violations=base.violations,
        first_violation_step=base.first_violation_step(),
        replay_state_hash=base.state_hash,
        replay_counters=dict(base.counters),
        shrink_stats={"steps_before": steps0, "events_before": events0,
                      "replays": trials})
    stats = {
        "steps_before": steps0, "steps_after": out.n_steps,
        "events_before": events0, "events_after": out.n_events(),
        "replays": trials, "violations": base.violations,
    }
    return out, stats
