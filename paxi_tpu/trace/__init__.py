"""Deterministic trace capture, replay & shrinking.

A violation found by the fuzzing sim runner becomes a first-class,
shippable artifact: ``capture`` materializes the violating group's
fault schedule into a versioned trace file, ``replay`` re-executes it
bit-for-bit through the pinned-schedule kernel path, ``shrink``
delta-debugs it down to a minimal witness, and ``trace.host`` projects
it onto the host runtime's fault-injection surface so sim findings
drive asyncio regression tests (and divergence between the runtimes
becomes observable).
"""

from paxi_tpu.trace.capture import capture
from paxi_tpu.trace.format import (TRACE_VERSION, Trace, load, make_meta,
                                   save)
from paxi_tpu.trace.replay import (ReplayResult, check_determinism,
                                   replay, state_hash)
from paxi_tpu.trace.shrink import list_events, neutralize, shrink

__all__ = ["Trace", "TRACE_VERSION", "load", "save", "make_meta",
           "capture", "replay", "ReplayResult", "check_determinism",
           "state_hash", "shrink", "list_events", "neutralize"]
