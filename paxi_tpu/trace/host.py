"""Cross-runtime replay: project a sim trace onto the host runtime's
fault-injection surface.

The two runtimes share one fault vocabulary by construction — the sim's
drop/dup/delay/partition/crash schedule is the vectorized
generalization of socket.go's Crash/Drop/Slow/Flaky (see sim/mailbox.py
docstring) — so a captured schedule can be projected back:

- per-message-type **drops** become occurrence-indexed ``DropMsg``
  directives consumed by ``Socket.drop_next`` (deterministic: "drop the
  next N messages of class X on edge i->j"), using the protocol's
  ``TRACE_MSG_MAP`` to translate sim mailbox names to host message
  classes;
- **delays** become ``SlowWin``/``DelayMsg`` (reordering) windows;
- **crashes** and **partition cuts** become ``CrashWin``/``DropWin``
  wall-clock windows, scaled by ``step_s`` (one sim step ~ one
  watchdog tick of host time);
- **dups** have no host analog (TCP/chan never duplicate) and are
  dropped from the projection, reported in the stats.

The projection is a schedule homomorphism, not a clock-accurate
emulation: the asyncio runtime has no lock-step rounds, so recorded
message drops apply to the FIRST ``count`` matching sends (step
indices ride along as ``DropMsg.steps`` provenance; ``skip`` can
re-aim them by hand) and everything else becomes coarse time
windows.  That is exactly what is needed to turn
a minimized sim witness ("the run where THIS Grant vanished") into a
host regression test, and to surface sim<->host divergence when the
projected schedule does NOT reproduce on the host.
"""

from __future__ import annotations

import asyncio
import dataclasses
import importlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paxi_tpu.trace.format import Trace


# ---- directive vocabulary ----------------------------------------------
@dataclass
class DropMsg:
    """Drop ``count`` messages of class ``msg_type`` on src->dst (after
    ``skip`` matching ones pass); ``key`` narrows to one object.

    ``steps`` is provenance only: the sim step indices of the recorded
    drops.  The projection applies a first-N approximation (skip=0) —
    the host runtime has no lock-step rounds, so "which occurrence"
    cannot be recovered from step indices alone; when a witness hinges
    on dropping a LATER occurrence, set ``skip`` by hand (the recorded
    steps say where to look)."""

    src: str
    dst: str
    msg_type: str
    count: int = 1
    skip: int = 0
    key: Optional[int] = None
    steps: Optional[List[int]] = None


@dataclass
class DelayMsg:
    """Hold matching messages for ``delay_s`` — the reordering fault."""

    src: str
    dst: str
    msg_type: str
    delay_s: float
    count: int = 1
    skip: int = 0
    key: Optional[int] = None


@dataclass
class CrashWin:
    id: str
    t0: float
    t1: float


@dataclass
class DropWin:
    src: str
    dst: str
    t0: float
    t1: float


@dataclass
class SlowWin:
    src: str
    dst: str
    delay_s: float
    t0: float
    t1: float


@dataclass
class FlakyWin:
    src: str
    dst: str
    p: float
    t0: float
    t1: float


Directive = Any


def directives_json(dirs: Sequence[Directive]) -> List[dict]:
    return [dict(kind=type(d).__name__, **dataclasses.asdict(d))
            for d in dirs]


# ---- projection ---------------------------------------------------------
def trace_msg_map(protocol: str) -> Dict[str, str]:
    """The protocol's sim-mailbox-name -> host-message-class map
    (``TRACE_MSG_MAP`` in its host module; {} when it has none).

    Variant protocols (seeded-bug twins like ``wankeeper_nofloor``)
    register in ``_SIM_MODULES`` pointing at the base protocol's sim
    module, so the host module is derived from that registration — no
    name-suffix conventions baked in here."""
    from paxi_tpu.protocols import _HOST_MODULES, _SIM_MODULES
    base = protocol
    if base not in _HOST_MODULES:
        sim_mod = _SIM_MODULES.get(protocol, "").partition(":")[0]
        parts = sim_mod.rsplit(".", 2)
        base = parts[-2] if len(parts) >= 2 else protocol
    mod = _HOST_MODULES.get(base)
    if mod is None:
        return {}
    return dict(getattr(importlib.import_module(mod),
                        "TRACE_MSG_MAP", {}))


def _runs(ts: Sequence[int]) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi] runs of a sorted step list."""
    out: List[Tuple[int, int]] = []
    for t in ts:
        if out and t == out[-1][1] + 1:
            out[-1] = (out[-1][0], t)
        else:
            out.append((t, t))
    return out


def host_directives(trace: Trace, ids: Sequence, step_s: float = 0.05,
                    msg_map: Optional[Dict[str, str]] = None
                    ) -> Tuple[List[Directive], Dict[str, int]]:
    """Project ``trace`` onto host directives.  ``ids`` is the host
    config's replica-ID list in SIM ORDER (numerically sorted — sim
    replica r is sorted(cfg.ids)[r] under ID's (zone, node) order,
    matching the zone-block layout both runtimes derive from the id
    list; lexical order would misplace node/zone numbers >= 10).
    Returns (directives, stats)."""
    from paxi_tpu.core.ident import ID
    ids = [str(i) for i in sorted(ID(str(i)) for i in ids)]
    if msg_map is None:
        msg_map = trace_msg_map(trace.protocol)
    sched = trace.sched
    dirs: List[Directive] = []
    stats = {"drops": 0, "drops_unmapped": 0, "dups_skipped": 0,
             "delays": 0, "crashes": 0, "cuts": 0}

    # message drops -> occurrence-indexed DropMsg (mapped types) or
    # coarse DropWin windows (unmapped types)
    per_edge: Dict[Tuple[str, int, int], List[int]] = {}
    win_edge: Dict[Tuple[int, int], List[int]] = {}
    for name in sorted(sched["faults"]):
        drop = np.asarray(sched["faults"][name]["drop"])
        for t, i, j in np.argwhere(drop):
            if name in msg_map:
                per_edge.setdefault((msg_map[name], int(i), int(j)),
                                    []).append(int(t))
                stats["drops"] += 1
            else:
                win_edge.setdefault((int(i), int(j)), []).append(int(t))
                stats["drops_unmapped"] += 1
        stats["dups_skipped"] += int(
            np.sum(np.asarray(sched["faults"][name]["dup"])))
    for (mt, i, j), ts in sorted(per_edge.items()):
        dirs.append(DropMsg(ids[i], ids[j], mt, count=len(ts),
                            steps=sorted(ts)))
    for (i, j), ts in sorted(win_edge.items()):
        for lo, hi in _runs(sorted(set(ts))):
            dirs.append(DropWin(ids[i], ids[j], lo * step_s,
                                (hi + 1) * step_s))

    # delays -> SlowWin per contiguous run; the per-event magnitude is
    # the schedule's wheel depth (max_delay steps)
    lag = max(trace.fuzz_config().max_delay - 1, 1) * step_s
    slow_edge: Dict[Tuple[int, int], set] = {}
    for name in sorted(sched["faults"]):
        delay = np.asarray(sched["faults"][name]["delay"])
        for t, i, j in np.argwhere(delay > 1):
            slow_edge.setdefault((int(i), int(j)), set()).add(int(t))
            stats["delays"] += 1
    for (i, j), ts in sorted(slow_edge.items()):
        for lo, hi in _runs(sorted(ts)):
            dirs.append(SlowWin(ids[i], ids[j], lag, lo * step_s,
                                (hi + 1) * step_s))

    # crashes / partition cuts -> wall-clock windows
    crashed = np.asarray(sched["crashed"])
    for i in range(crashed.shape[1]):
        ts = np.nonzero(crashed[:, i])[0].tolist()
        stats["crashes"] += len(ts)
        for lo, hi in _runs(ts):
            dirs.append(CrashWin(ids[i], lo * step_s, (hi + 1) * step_s))
    conn = np.asarray(sched["conn"])
    for i in range(conn.shape[1]):
        for j in range(conn.shape[2]):
            if i == j:
                continue
            ts = np.nonzero(~conn[:, i, j])[0].tolist()
            stats["cuts"] += len(ts)
            for lo, hi in _runs(ts):
                dirs.append(DropWin(ids[i], ids[j], lo * step_s,
                                    (hi + 1) * step_s))
    return dirs, stats


# ---- application --------------------------------------------------------
def _socket_of(cluster, id_str: str):
    return cluster[id_str].socket


def apply_immediate(cluster, dirs: Sequence[Directive]) -> None:
    """Install the occurrence-indexed (timeless) directives now."""
    for d in dirs:
        if isinstance(d, DropMsg):
            _socket_of(cluster, d.src).drop_next(
                d.dst, d.msg_type, count=d.count, skip=d.skip, key=d.key)
        elif isinstance(d, DelayMsg):
            _socket_of(cluster, d.src).delay_next(
                d.dst, d.msg_type, d.delay_s, count=d.count,
                skip=d.skip, key=d.key)


async def _drive_windows(dirs: Sequence[Directive], apply) -> None:
    """One scheduling engine for both window surfaces: open each
    windowed directive at its ``t0`` (relative to now) by awaiting
    ``apply(directive, duration)``.  Returns once every window has been
    opened (not when it expires)."""
    timed = sorted((d for d in dirs
                    if not isinstance(d, (DropMsg, DelayMsg))),
                   key=lambda d: d.t0)
    t_start = asyncio.get_running_loop().time()
    for d in timed:
        lag = d.t0 - (asyncio.get_running_loop().time() - t_start)
        if lag > 0:
            await asyncio.sleep(lag)
        await apply(d, max(d.t1 - d.t0, 0.0))


async def drive(cluster, dirs: Sequence[Directive]) -> None:
    """Run a full directive schedule against an in-process Cluster:
    timeless directives install immediately, windowed ones fire at
    their ``t0`` via the Socket injection surface."""
    apply_immediate(cluster, dirs)

    async def apply(d, dur):
        if isinstance(d, CrashWin):
            _socket_of(cluster, d.id).crash(dur)
        elif isinstance(d, DropWin):
            _socket_of(cluster, d.src).drop(d.dst, dur)
        elif isinstance(d, SlowWin):
            _socket_of(cluster, d.src).slow(d.dst, d.delay_s * 1000.0,
                                            dur)
        elif isinstance(d, FlakyWin):
            _socket_of(cluster, d.src).flaky(d.dst, d.p, dur)

    await _drive_windows(dirs, apply)


async def drive_admin(admin, dirs: Sequence[Directive]) -> None:
    """Same schedule through the REAL AdminClient HTTP surface (the
    soak harness path) — only windowed directives exist there."""
    async def apply(d, dur):
        if isinstance(d, CrashWin):
            await admin.crash(d.id, dur)
        elif isinstance(d, DropWin):
            await admin.drop(d.src, d.dst, dur)
        elif isinstance(d, SlowWin):
            await admin.slow(d.src, d.dst, d.delay_s * 1000.0, dur)
        elif isinstance(d, FlakyWin):
            await admin.flaky(d.src, d.dst, d.p, dur)

    await _drive_windows(dirs, apply)
