"""Cross-runtime replay: project a sim trace onto the host runtime's
fault-injection surface.

The two runtimes share one fault vocabulary by construction — the sim's
drop/dup/delay/partition/crash schedule is the vectorized
generalization of socket.go's Crash/Drop/Slow/Flaky (see sim/mailbox.py
docstring) — so a captured schedule can be projected back:

- per-message-type **drops** become occurrence-indexed ``DropMsg``
  directives consumed by ``Socket.drop_next`` (deterministic: "drop the
  next N messages of class X on edge i->j"), using the protocol's
  ``TRACE_MSG_MAP`` to translate sim mailbox names to host message
  classes;
- **delays** become ``SlowWin``/``DelayMsg`` (reordering) windows;
- **crashes** and **partition cuts** become ``CrashWin``/``DropWin``
  wall-clock windows, scaled by ``step_s`` (one sim step ~ one
  watchdog tick of host time);
- **dups** have no host analog (TCP/chan never duplicate) and are
  dropped from the projection, reported in the stats.

The projection is a schedule homomorphism, not a clock-accurate
emulation: the asyncio runtime has no lock-step rounds, so recorded
message drops apply to the FIRST ``count`` matching sends (step
indices ride along as ``DropMsg.steps`` provenance; ``skip`` can
re-aim them by hand) and everything else becomes coarse time
windows.  That is exactly what is needed to turn
a minimized sim witness ("the run where THIS Grant vanished") into a
host regression test, and to surface sim<->host divergence when the
projected schedule does NOT reproduce on the host.
"""

from __future__ import annotations

import asyncio
import dataclasses
import importlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paxi_tpu.trace.format import Trace


# ---- directive vocabulary ----------------------------------------------
@dataclass
class DropMsg:
    """Drop ``count`` messages of class ``msg_type`` on src->dst (after
    ``skip`` matching ones pass); ``key`` narrows to one object.

    ``steps`` is provenance only: the sim step indices of the recorded
    drops.  The projection applies a first-N approximation (skip=0) —
    the host runtime has no lock-step rounds, so "which occurrence"
    cannot be recovered from step indices alone; when a witness hinges
    on dropping a LATER occurrence, set ``skip`` by hand (the recorded
    steps say where to look)."""

    src: str
    dst: str
    msg_type: str
    count: int = 1
    skip: int = 0
    key: Optional[int] = None
    steps: Optional[List[int]] = None


@dataclass
class DelayMsg:
    """Hold matching messages for ``delay_s`` — the reordering fault."""

    src: str
    dst: str
    msg_type: str
    delay_s: float
    count: int = 1
    skip: int = 0
    key: Optional[int] = None


@dataclass
class CrashWin:
    id: str
    t0: float
    t1: float


@dataclass
class DropWin:
    src: str
    dst: str
    t0: float
    t1: float


@dataclass
class SlowWin:
    src: str
    dst: str
    delay_s: float
    t0: float
    t1: float


@dataclass
class FlakyWin:
    src: str
    dst: str
    p: float
    t0: float
    t1: float


Directive = Any


def directives_json(dirs: Sequence[Directive]) -> List[dict]:
    return [dict(kind=type(d).__name__, **dataclasses.asdict(d))
            for d in dirs]


# ---- sequenced (virtual-clock) vocabulary -------------------------------
@dataclass
class SeqFault:
    """One occurrence-indexed fault for the virtual-clock fabric
    (host/fabric.py): act on the ``occurrence``-th (0-based) host send
    of class ``msg_type`` on src->dst.  Unlike ``DelayMsg``'s wall-clock
    window, ``delay_steps`` is an exact number of LOGICAL steps, so a
    recorded reorder replays as the same delivery order, not a time
    smear.  ``step`` is provenance (the recorded sim step)."""

    src: str
    dst: str
    msg_type: str
    occurrence: int
    action: str                # "drop" | "delay"
    delay_steps: int = 0       # extra logical steps beyond the normal 1
    step: int = 0


@dataclass
class SeqSchedule:
    """A trace projected onto the virtual-clock fabric's fault surface:
    occurrence-indexed per-message faults plus per-logical-step crash
    and partition-cut sets — the exact-order alternative to the
    windowed ``host_directives`` projection.

    ``edge_delay`` is the scenario engine's WAN plane
    (paxi_tpu/scenarios): EXTRA logical steps added to every send on
    an (src, dst) edge — a standing per-edge latency rather than an
    occurrence-indexed event.  Trace projections leave it empty (a
    recorded schedule already carries its latency inside the per-event
    ``delay_steps``); ``scenarios.compile.seq_schedule_of`` fills it
    when a Scenario drives the fabric directly."""

    n_steps: int
    faults: List[SeqFault] = dataclasses.field(default_factory=list)
    crashed: Dict[str, List[int]] = dataclasses.field(default_factory=dict)
    cut: Dict[Tuple[str, str], List[int]] = dataclasses.field(
        default_factory=dict)
    edge_delay: Dict[Tuple[str, str], int] = dataclasses.field(
        default_factory=dict)
    # fault events the fabric cannot replay exactly: planes with no
    # TRACE_MSG_MAP entry (mailbox -> event count) and duplications
    # (neither TCP nor the chan fabric ever duplicate)
    unmapped: Dict[str, int] = dataclasses.field(default_factory=dict)
    dups_skipped: int = 0

    def __post_init__(self):
        self._idx: Dict[Tuple[str, str, str], Dict[int, SeqFault]] = {}
        for f in self.faults:
            self._idx.setdefault(
                (f.src, f.dst, f.msg_type), {})[f.occurrence] = f
        self._crashed = {i: frozenset(ts) for i, ts in self.crashed.items()}
        self._cut = {e: frozenset(ts) for e, ts in self.cut.items()}

    # fabric-facing lookups (hot path: one dict probe per send)
    def fault_for(self, src: str, dst: str, msg_type: str,
                  occurrence: int) -> Optional[SeqFault]:
        m = self._idx.get((src, dst, msg_type))
        return m.get(occurrence) if m else None

    def is_crashed(self, id: str, step: int) -> bool:
        return step in self._crashed.get(id, ())

    def is_cut(self, src: str, dst: str, step: int) -> bool:
        return step in self._cut.get((src, dst), ())

    def edge_extra(self, src: str, dst: str) -> int:
        """Standing per-edge latency (extra logical steps per send)."""
        return self.edge_delay.get((src, dst), 0)

    @property
    def exact(self) -> bool:
        """True when every recorded fault event replays exactly."""
        return not self.unmapped and self.dups_skipped == 0

    def to_json(self) -> dict:
        return {
            "n_steps": self.n_steps,
            "faults": [dataclasses.asdict(f) for f in self.faults],
            "crashed": {i: list(ts) for i, ts in self.crashed.items()},
            "cut": {f"{s}->{d}": list(ts)
                    for (s, d), ts in self.cut.items()},
            "edge_delay": {f"{s}->{d}": x
                           for (s, d), x in self.edge_delay.items()},
            "unmapped": dict(self.unmapped),
            "dups_skipped": self.dups_skipped,
        }


# ---- projection ---------------------------------------------------------
def host_algorithm(protocol: str) -> Optional[str]:
    """The host-registry name a sim protocol replays against, or None
    for sim-only protocols.

    Variant protocols (seeded-bug twins like ``wankeeper_nofloor``)
    register in ``_SIM_MODULES`` pointing at the base protocol's sim
    module, so the host module is derived from that registration — no
    name-suffix conventions baked in here."""
    from paxi_tpu.protocols import _HOST_MODULES, _SIM_MODULES
    base = protocol
    if base not in _HOST_MODULES:
        sim_mod = _SIM_MODULES.get(protocol, "").partition(":")[0]
        parts = sim_mod.rsplit(".", 2)
        base = parts[-2] if len(parts) >= 2 else protocol
    return base if base in _HOST_MODULES else None


def trace_msg_map(protocol: str) -> Dict[str, str]:
    """The protocol's sim-mailbox-name -> host-message-class map
    (``TRACE_MSG_MAP`` in its host module; {} when it has none)."""
    from paxi_tpu.protocols import _HOST_MODULES
    base = host_algorithm(protocol)
    if base is None:
        return {}
    return dict(getattr(importlib.import_module(_HOST_MODULES[base]),
                        "TRACE_MSG_MAP", {}))


def _runs(ts: Sequence[int]) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi] runs of a sorted step list."""
    out: List[Tuple[int, int]] = []
    for t in ts:
        if out and t == out[-1][1] + 1:
            out[-1] = (out[-1][0], t)
        else:
            out.append((t, t))
    return out


def host_directives(trace: Trace, ids: Sequence, step_s: float = 0.05,
                    msg_map: Optional[Dict[str, str]] = None
                    ) -> Tuple[List[Directive], Dict[str, int]]:
    """Project ``trace`` onto host directives.  ``ids`` is the host
    config's replica-ID list in SIM ORDER (numerically sorted — sim
    replica r is sorted(cfg.ids)[r] under ID's (zone, node) order,
    matching the zone-block layout both runtimes derive from the id
    list; lexical order would misplace node/zone numbers >= 10).
    Returns (directives, stats)."""
    from paxi_tpu.core.ident import ID
    ids = [str(i) for i in sorted(ID(str(i)) for i in ids)]
    if msg_map is None:
        msg_map = trace_msg_map(trace.protocol)
    sched = trace.sched
    dirs: List[Directive] = []
    stats = {"drops": 0, "drops_unmapped": 0, "dups_skipped": 0,
             "delays": 0, "crashes": 0, "cuts": 0}

    # message drops -> occurrence-indexed DropMsg (mapped types) or
    # coarse DropWin windows (unmapped types)
    per_edge: Dict[Tuple[str, int, int], List[int]] = {}
    win_edge: Dict[Tuple[int, int], List[int]] = {}
    for name in sorted(sched["faults"]):
        drop = np.asarray(sched["faults"][name]["drop"])
        for t, i, j in np.argwhere(drop):
            if name in msg_map:
                per_edge.setdefault((msg_map[name], int(i), int(j)),
                                    []).append(int(t))
                stats["drops"] += 1
            else:
                win_edge.setdefault((int(i), int(j)), []).append(int(t))
                stats["drops_unmapped"] += 1
        stats["dups_skipped"] += int(
            np.sum(np.asarray(sched["faults"][name]["dup"])))
    for (mt, i, j), ts in sorted(per_edge.items()):
        dirs.append(DropMsg(ids[i], ids[j], mt, count=len(ts),
                            steps=sorted(ts)))
    for (i, j), ts in sorted(win_edge.items()):
        for lo, hi in _runs(sorted(set(ts))):
            dirs.append(DropWin(ids[i], ids[j], lo * step_s,
                                (hi + 1) * step_s))

    # delays -> SlowWin per contiguous run; the per-event magnitude is
    # the schedule's wheel depth (max_delay steps, or the scenario
    # latency matrix's deepest entry — FuzzConfig.wheel covers both)
    lag = max(trace.fuzz_config().wheel - 1, 1) * step_s
    slow_edge: Dict[Tuple[int, int], set] = {}
    for name in sorted(sched["faults"]):
        delay = np.asarray(sched["faults"][name]["delay"])
        for t, i, j in np.argwhere(delay > 1):
            slow_edge.setdefault((int(i), int(j)), set()).add(int(t))
            stats["delays"] += 1
    for (i, j), ts in sorted(slow_edge.items()):
        for lo, hi in _runs(sorted(ts)):
            dirs.append(SlowWin(ids[i], ids[j], lag, lo * step_s,
                                (hi + 1) * step_s))

    # crashes / partition cuts -> wall-clock windows
    crashed = np.asarray(sched["crashed"])
    for i in range(crashed.shape[1]):
        ts = np.nonzero(crashed[:, i])[0].tolist()
        stats["crashes"] += len(ts)
        for lo, hi in _runs(ts):
            dirs.append(CrashWin(ids[i], lo * step_s, (hi + 1) * step_s))
    conn = np.asarray(sched["conn"])
    for i in range(conn.shape[1]):
        for j in range(conn.shape[2]):
            if i == j:
                continue
            ts = np.nonzero(~conn[:, i, j])[0].tolist()
            stats["cuts"] += len(ts)
            for lo, hi in _runs(ts):
                dirs.append(DropWin(ids[i], ids[j], lo * step_s,
                                    (hi + 1) * step_s))
    return dirs, stats


def seq_schedule(trace: Trace, ids: Sequence,
                 msg_map: Optional[Dict[str, str]] = None
                 ) -> Tuple[SeqSchedule, Dict[str, int]]:
    """Project ``trace`` onto the virtual-clock fabric's sequenced
    fault surface (the exact-order sibling of ``host_directives``).

    Same occurrence approximation as ``DropMsg`` (the host runtime has
    no lock-step rounds, so the i-th recorded fault event on an
    (edge, class) aims at the i-th matching host send), but delays keep
    their exact per-event logical magnitude instead of degrading to a
    time window, and crashes/cuts become per-logical-step sets the
    fabric consults at send/delivery time — so reorder witnesses replay
    as the same delivery ORDER the sim saw."""
    from paxi_tpu.core.ident import ID
    ids = [str(i) for i in sorted(ID(str(i)) for i in ids)]
    if msg_map is None:
        msg_map = trace_msg_map(trace.protocol)
    sched = trace.sched
    stats = {"drops": 0, "delays": 0, "unmapped": 0, "dups_skipped": 0,
             "crashes": 0, "cuts": 0}
    unmapped: Dict[str, int] = {}

    # per (edge, class): fault events ordered by recorded step share one
    # occurrence counter — drop-then-delay on one edge aims at the 1st
    # and 2nd matching sends respectively
    per_edge: Dict[Tuple[str, int, int], List[Tuple[int, str, int]]] = {}
    for name in sorted(sched["faults"]):
        f = sched["faults"][name]
        drop = np.asarray(f["drop"])
        delay = np.asarray(f["delay"])
        stats["dups_skipped"] += int(np.sum(np.asarray(f["dup"])))
        if name not in msg_map:
            n_ev = int(np.sum(drop)) + int(np.sum(delay > 1))
            if n_ev:
                unmapped[name] = unmapped.get(name, 0) + n_ev
                stats["unmapped"] += n_ev
            continue
        for t, i, j in np.argwhere(drop):
            per_edge.setdefault((msg_map[name], int(i), int(j)),
                                []).append((int(t), "drop", 0))
            stats["drops"] += 1
        for t, i, j in np.argwhere(delay > 1):
            per_edge.setdefault((msg_map[name], int(i), int(j)),
                                []).append(
                                    (int(t), "delay",
                                     int(delay[t, i, j]) - 1))
            stats["delays"] += 1
    faults: List[SeqFault] = []
    for (mt, i, j), evs in sorted(per_edge.items()):
        for occ, (t, action, extra) in enumerate(sorted(evs)):
            faults.append(SeqFault(ids[i], ids[j], mt, occurrence=occ,
                                   action=action, delay_steps=extra,
                                   step=t))

    crashed = np.asarray(sched["crashed"])
    crash_map: Dict[str, List[int]] = {}
    for t, i in np.argwhere(crashed):
        crash_map.setdefault(ids[int(i)], []).append(int(t))
        stats["crashes"] += 1
    conn = np.asarray(sched["conn"])
    cut_map: Dict[Tuple[str, str], List[int]] = {}
    for t, i, j in np.argwhere(~conn):
        if i == j:
            continue
        cut_map.setdefault((ids[int(i)], ids[int(j)]), []).append(int(t))
        stats["cuts"] += 1
    out = SeqSchedule(n_steps=trace.n_steps, faults=faults,
                      crashed={k: sorted(v) for k, v in crash_map.items()},
                      cut={k: sorted(v) for k, v in cut_map.items()},
                      unmapped=unmapped,
                      dups_skipped=stats["dups_skipped"])
    return out, stats


# ---- application --------------------------------------------------------
def _socket_of(cluster, id_str: str):
    return cluster[id_str].socket


def apply_immediate(cluster, dirs: Sequence[Directive]) -> None:
    """Install the occurrence-indexed (timeless) directives now."""
    for d in dirs:
        if isinstance(d, DropMsg):
            _socket_of(cluster, d.src).drop_next(
                d.dst, d.msg_type, count=d.count, skip=d.skip, key=d.key)
        elif isinstance(d, DelayMsg):
            _socket_of(cluster, d.src).delay_next(
                d.dst, d.msg_type, d.delay_s, count=d.count,
                skip=d.skip, key=d.key)


async def _drive_windows(dirs: Sequence[Directive], apply) -> None:
    """One scheduling engine for both window surfaces: open each
    windowed directive at its ``t0`` (relative to now) by awaiting
    ``apply(directive, duration)``.  Returns once every window has been
    opened (not when it expires)."""
    timed = sorted((d for d in dirs
                    if not isinstance(d, (DropMsg, DelayMsg))),
                   key=lambda d: d.t0)
    t_start = asyncio.get_running_loop().time()
    for d in timed:
        lag = d.t0 - (asyncio.get_running_loop().time() - t_start)
        if lag > 0:
            await asyncio.sleep(lag)
        await apply(d, max(d.t1 - d.t0, 0.0))


async def drive(cluster, dirs: Sequence[Directive]) -> None:
    """Run a full directive schedule against an in-process Cluster:
    timeless directives install immediately, windowed ones fire at
    their ``t0`` via the Socket injection surface."""
    apply_immediate(cluster, dirs)

    async def apply(d, dur):
        if isinstance(d, CrashWin):
            _socket_of(cluster, d.id).crash(dur)
        elif isinstance(d, DropWin):
            _socket_of(cluster, d.src).drop(d.dst, dur)
        elif isinstance(d, SlowWin):
            _socket_of(cluster, d.src).slow(d.dst, d.delay_s * 1000.0,
                                            dur)
        elif isinstance(d, FlakyWin):
            _socket_of(cluster, d.src).flaky(d.dst, d.p, dur)

    await _drive_windows(dirs, apply)


async def drive_admin(admin, dirs: Sequence[Directive]) -> None:
    """Same schedule through the REAL AdminClient HTTP surface (the
    soak harness path) — only windowed directives exist there."""
    async def apply(d, dur):
        if isinstance(d, CrashWin):
            await admin.crash(d.id, dur)
        elif isinstance(d, DropWin):
            await admin.drop(d.src, d.dst, dur)
        elif isinstance(d, SlowWin):
            await admin.slow(d.src, d.dst, d.delay_s * 1000.0, dur)
        elif isinstance(d, FlakyWin):
            await admin.flaky(d.src, d.dst, d.p, dur)

    await _drive_windows(dirs, apply)
