"""Capture: materialize the fault schedule behind a violation.

``capture`` reruns a (protocol, cfg, fuzz, seed, groups, steps)
combination — exactly the tuple a fuzz-soak run is keyed by — in the
sim runner's record mode, which emits the per-step, per-group fault
schedule alongside a per-group violation matrix.  The first violating
group's schedule is sliced out into a single-group Trace; replaying it
through the pinned path reproduces the run bit-for-bit (the recorded
schedule IS what the original run drew).  No violation -> None.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.random as jr
import numpy as np

from paxi_tpu.sim.runner import make_recorded_run
from paxi_tpu.sim.types import FuzzConfig, SimConfig, SimProtocol
from paxi_tpu.trace import replay as _replay
from paxi_tpu.trace.format import Trace, make_meta, schedule_hash


def _slice_group(sched, g: int, batched: bool):
    """Single-group schedule out of the recorded batch.  Lane-major
    kernels stack the group axis LAST ((T, R, R, G)); vmapped kernels
    carry it right after time ((T, G, R, R))."""
    if batched:
        return jax.tree.map(lambda x: np.asarray(x[..., g]), sched)
    return jax.tree.map(lambda x: np.asarray(x[:, g]), sched)


# one compiled record-mode runner per (protocol, geometry, fuzz) —
# a soak dumping several seeds of the same case shares one executable
# (the pinned twin is replay._PIN_CACHE)
_REC_CACHE: dict = {}


def _recorded_run(proto: SimProtocol, cfg: SimConfig, fuzz: FuzzConfig):
    # id(proto), not proto.name: see replay._pinned_run
    key = (id(proto), cfg, fuzz)
    run = _REC_CACHE.get(key)
    if run is None:
        run = make_recorded_run(proto, cfg, fuzz)
        _REC_CACHE[key] = run
    return run


def capture(proto: SimProtocol, cfg: SimConfig, fuzz: FuzzConfig,
            seed: int, n_groups: int, n_steps: int,
            group: Optional[int] = None,
            proto_name: Optional[str] = None) -> Optional[Trace]:
    """Record-mode rerun; returns the violating group's Trace or None.

    ``group`` forces a specific group (useful to capture a non-violating
    group's schedule for divergence studies); by default the group with
    the earliest violation wins.
    """
    run = _recorded_run(proto, cfg, fuzz)
    state, metrics, total, viols, sched = run(
        jr.PRNGKey(seed), n_groups, n_steps)
    jax.block_until_ready(total)
    viols = np.asarray(viols)                    # (T, G)
    if group is None:
        if int(total) == 0:
            return None
        per_group = viols.sum(axis=0)
        first_step = np.where(viols > 0, np.arange(n_steps)[:, None],
                              n_steps).min(axis=0)
        # earliest-violating group; ties broken by violation count
        cands = np.nonzero(per_group > 0)[0]
        group = int(cands[np.lexsort(
            (-per_group[cands], first_step[cands]))][0])
    g = int(group)
    gsched = _slice_group(sched, g, proto.batched)
    gstate = jax.tree.map(lambda x: x[g], state)  # finish_run: G leading
    gviols = viols[:, g]
    nz = np.nonzero(gviols)[0]
    from paxi_tpu.metrics.simcount import counters_of
    extra = {}
    from paxi_tpu.metrics import lathist
    ghist = lathist.total_hist(gstate)
    if ghist is not None:
        # the traced group's on-device commit-latency histogram
        # (pending deltas folded), stamped like capture_counters:
        # excluded from the witness hash (it is an ``m_`` plane) but
        # pinned by replay tests — measurement determinism alongside
        # state/counter determinism.  Sparse {bucket: count},
        # metrics/lathist layout.
        extra["capture_lat_hist"] = lathist.to_sparse(ghist)
    meta = make_meta(
        proto_name or proto.name, cfg, fuzz, seed, n_groups, g,
        group_violations=int(gviols.sum()),
        first_violation_step=int(nz[0]) if nz.size else -1,
        capture_state_hash=_replay.state_hash(gstate),
        # whole-batch message/fault counters: a pinned replay of this
        # (unedited) trace must reproduce them exactly — the counter
        # half of the determinism check (metrics/simcount.py)
        capture_counters={k: int(v)
                          for k, v in counters_of(metrics).items()},
        shrunk=False, **extra)
    t = Trace(meta=meta, sched=gsched)
    # dedup identity (hunt corpus): stamped here so the in-memory trace
    # and its saved form carry identical meta
    meta["schedule_hash"] = schedule_hash(t)
    return t
