"""Shard the instance batch over a device mesh.

The reference scales by adding replicas/zones over TCP (transport.go,
socket.go); the TPU build's scaling axis is the *instance batch*: groups
are independent, so they shard perfectly over ICI — each device simulates
``n_groups / n_devices`` groups and only the aggregate metrics
(committed slots, invariant violations) cross devices, via
``lax.psum`` over the mesh axis.  Cross-host DCN works identically
(jax.distributed + a bigger mesh): the collective rides whatever links
the mesh spans.

WPaxos zone-sharding (zones <-> mesh axis, Multicast(zone) <->
ppermute) is a planned refinement; see paxi_tpu/protocols/wpaxos.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.5 promotes shard_map to the top level and adds the
# varying-manual-axes (vma) carry typing that needs lax.pcast; on
# older jax the experimental entry point works without either
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map
_HAS_VMA = hasattr(jax.lax, "pcast") and hasattr(jax, "typeof")

from paxi_tpu.sim.runner import finish_run, init_carry, make_scan_body
from paxi_tpu.sim.types import FAULT_FREE, FuzzConfig, SimConfig, SimProtocol


def make_mesh(n_devices: Optional[int] = None, axis: str = "i") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, only {len(devs)} available")
    return Mesh(np.array(devs[:n]), (axis,))


def make_sharded_run(proto: SimProtocol, cfg: SimConfig,
                     fuzz: FuzzConfig = FAULT_FREE,
                     mesh: Optional[Mesh] = None, axis: str = "i"):
    """Build ``run(rng, n_groups, n_steps)`` with the group axis sharded
    over ``mesh``; returns (sharded final state, psum'd metrics, psum'd
    violation count)."""
    mesh = mesh or make_mesh()
    n_dev = mesh.shape[axis]
    body = make_scan_body(proto, cfg, fuzz)

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def run(rng, n_groups: int, n_steps: int):
        if n_groups % n_dev:
            raise ValueError(f"n_groups={n_groups} not divisible by "
                             f"mesh axis {axis}={n_dev}")
        g_local = n_groups // n_dev

        @functools.partial(
            _shard_map, mesh=mesh,
            in_specs=P(axis),
            out_specs=(P(axis), P(), P()))
        def sharded(rngs):
            carry = init_carry(proto, cfg, fuzz, g_local, rngs[0])
            # zero-initialized leaves are mesh-invariant; mark them as
            # varying over the shard axis so the scan carry types match
            # (a no-op on jax builds without the vma type system)
            def _vary(x):
                if not _HAS_VMA:
                    return x
                if axis in getattr(jax.typeof(x), "vma", frozenset()):
                    return x
                return jax.lax.pcast(x, (axis,), to="varying")
            carry = jax.tree.map(_vary, carry)
            carry, (viols, counts) = jax.lax.scan(body, carry,
                                                  jnp.arange(n_steps))
            # the shared aggregation tail (group-major public state for
            # either layout), then reduce across shards — the psum
            # covers the runner's ``net_*`` counters too, so sharded
            # runs report whole-batch message/fault totals
            state, metrics, viol = finish_run(proto, cfg, carry, viols,
                                              counts)
            metrics = {k: jax.lax.psum(v, axis) for k, v in metrics.items()}
            viol = jax.lax.psum(viol, axis)
            return state, metrics, viol

        return sharded(jr.split(rng, n_dev))

    return run
