"""Shard the instance batch over a device mesh.

The reference scales by adding replicas/zones over TCP (transport.go,
socket.go); the TPU build's scaling axis is the *instance batch*: groups
are independent, so they shard perfectly over ICI — each device simulates
``n_groups / n_devices`` groups and only the aggregate metrics
(committed slots, invariant violations) cross devices, via
``lax.psum`` over the mesh axis.  Cross-host DCN works identically
(jax.distributed + a bigger mesh): the collective rides whatever links
the mesh spans.

Group counts need not divide the mesh: the batch is padded with inert
tail groups to the next multiple and their contribution is subtracted
from (per-group kernels) or masked out of (lane-major kernels) the
psum'd metrics, so arbitrary ``n_groups`` shard.

PRNG parity (per-group kernels): the carry is initialized at the REAL
group count outside ``shard_map`` — exactly the layout the
single-device runner builds — padded (if needed) with independently
keyed inert groups, and sharded along the leading group axis, so every
real group consumes the same per-group key chain it would on one
device, divisible batch or not.  Sharded runs of per-group kernels are therefore *bit-for-bit*
equal to single-device runs (metrics, ``net_*`` counters, violations),
which is what lets ``make_sharded_pinned_run`` replay a captured trace
inside a sharded batch with the state-hash + counter check intact.
Lane-major kernels draw whole-batch shaped randomness from one key, so
their shards get independent streams: aggregate behavior matches, bits
do not (and sharded pinned replay is per-group-kernel only).

WPaxos zone-sharding (zones <-> mesh axis, Multicast(zone) <->
ppermute) is a planned refinement; see paxi_tpu/protocols/wpaxos.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.5 promotes shard_map to the top level and adds the
# varying-manual-axes (vma) carry typing that needs lax.pcast; on
# older jax the experimental entry point works without either
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map
_HAS_VMA = hasattr(jax.lax, "pcast") and hasattr(jax, "typeof")

from paxi_tpu.sim.runner import (_group_step, finish_run,
                                 flush_measurements, init_carry,
                                 make_scan_body)
from paxi_tpu.sim.types import FAULT_FREE, FuzzConfig, SimConfig, SimProtocol


def make_mesh(n_devices: Optional[int] = None, axis: str = "i") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, only {len(devs)} available")
    return Mesh(np.array(devs[:n]), (axis,))


def _vary(x, axis):
    """Mark a mesh-invariant leaf as varying over the shard axis so the
    scan carry types match (no-op without the vma type system)."""
    if not _HAS_VMA:
        return x
    if axis in getattr(jax.typeof(x), "vma", frozenset()):
        return x
    return jax.lax.pcast(x, (axis,), to="varying")


def _padded_carry(proto, cfg, fuzz, n_groups: int, n_pad: int, rng):
    """Full-batch per-group carry with the real groups' key chains
    EXACTLY as the single-device runner derives them, padded with
    independently-keyed inert groups.  ``jr.split(k, g_pad)[:G]`` is
    NOT ``jr.split(k, G)`` on current jax, so the pad groups must come
    from their own fold — otherwise padding would silently change every
    real group's schedule and break the bit-parity/replay contract."""
    carry = init_carry(proto, cfg, fuzz, n_groups, rng)
    if not n_pad:
        return carry
    pad = init_carry(proto, cfg, fuzz, n_pad, jr.fold_in(rng, 0x9ad))
    return jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0),
                        carry, pad)


def make_sharded_run(proto: SimProtocol, cfg: SimConfig,
                     fuzz: FuzzConfig = FAULT_FREE,
                     mesh: Optional[Mesh] = None, axis: str = "i",
                     exchange: str = "dense"):
    """Build ``run(rng, n_groups, n_steps)`` with the group axis sharded
    over ``mesh``; returns (final state, psum'd metrics, psum'd
    violation count).  ``n_groups`` may be any positive count (see the
    module docstring for the padding contract); the returned state is
    trimmed back to ``n_groups``.

    Padding fine print: protocol metrics always exclude the pad groups.
    For per-group kernels the ``net_*`` counters and the violation
    count exclude them too (per-group masking); for lane-major kernels
    the counters/violations are whole-batch reductions inside the
    kernel, so pad groups ride along there — counters over-count pad
    traffic and a pad-group violation still trips the oracle (it would
    be a real protocol bug, just in a group nobody asked for).

    ``exchange`` selects the lane-major message-exchange backend
    (``dense`` or ``pallas``), as in ``runner.make_run``; per-group
    kernels always use the dense per-group planes."""
    mesh = mesh or make_mesh()
    n_dev = mesh.shape[axis]

    @functools.partial(jax.jit, static_argnums=(1, 2))
    def run(rng, n_groups: int, n_steps: int):
        n_pad = (-n_groups) % n_dev
        g_pad = n_groups + n_pad
        g_local = g_pad // n_dev

        if proto.batched:
            body = make_scan_body(proto, cfg, fuzz, exchange=exchange)
            # pallas_call has no shard_map replication rule; psums make
            # the outputs' replication explicit anyway, so the checker
            # adds nothing on that path
            rep_kw = {"check_rep": False} if exchange == "pallas" else {}

            @functools.partial(
                _shard_map, mesh=mesh,
                in_specs=P(axis),
                out_specs=(P(axis), P(), P()), **rep_kw)
            def sharded(rngs):
                carry = init_carry(proto, cfg, fuzz, g_local, rngs[0])
                if isinstance(carry[0], dict) and "wl_gid" in carry[0]:
                    # workload draws key on GLOBAL group ids: offset
                    # this shard's local arange by its group base so
                    # every shard derives exactly its slice of the
                    # single-device command planes (before the state0
                    # capture, so pad neutralization preserves it)
                    d0 = jax.lax.axis_index(axis)
                    carry[0]["wl_gid"] = (carry[0]["wl_gid"]
                                          + d0 * g_local)
                state0 = carry[0]
                carry = jax.tree.map(lambda x: _vary(x, axis), carry)
                carry, (viols, counts) = jax.lax.scan(body, carry,
                                                      jnp.arange(n_steps))
                if n_pad:
                    # neutralize pad groups before the metrics
                    # reduction: blend their final state back to the
                    # (metric-zero) initial state.  Group-additive
                    # metrics — the same contract the psum below
                    # already relies on — then exclude them exactly.
                    d = jax.lax.axis_index(axis)
                    real = d * g_local + jnp.arange(g_local) < n_groups
                    carry = (jax.tree.map(
                        lambda cur, ini: jnp.where(real, cur, ini),
                        carry[0], jax.tree.map(lambda x: _vary(x, axis),
                                               state0)),) + carry[1:]
                state, metrics, viol = finish_run(proto, cfg, carry,
                                                  viols, counts)
                metrics = {k: jax.lax.psum(v, axis)
                           for k, v in metrics.items()}
                viol = jax.lax.psum(viol, axis)
                return state, metrics, viol

            state, metrics, viol = sharded(jr.split(rng, n_dev))
        else:
            # per-group kernel: full-batch init OUTSIDE the shard_map
            # (single-device PRNG layout => bit-for-bit parity), then
            # shard every carry leaf along its leading group axis
            step1 = functools.partial(_group_step, proto, cfg, fuzz)
            carry = _padded_carry(proto, cfg, fuzz, n_groups, n_pad, rng)

            @functools.partial(
                _shard_map, mesh=mesh,
                in_specs=P(axis),
                out_specs=(P(axis), P(), P()))
            def sharded(carry):
                d = jax.lax.axis_index(axis)
                real = (d * g_local + jnp.arange(g_local) < n_groups
                        if n_pad else None)

                def body(c, t):
                    c, (viol, counts) = jax.vmap(
                        step1, in_axes=(0, None))(c, t)
                    # the observability layer's deferred binning: same
                    # absolute flush steps as the single-device body,
                    # so sharded runs stay bit-for-bit
                    c = flush_measurements(proto, cfg, c, t)
                    if real is not None:
                        viol = jnp.where(real, viol, 0)
                        counts = {k: jnp.sum(jnp.where(real, v, 0))
                                  for k, v in counts.items()}
                    else:
                        counts = {k: jnp.sum(v) for k, v in counts.items()}
                    return c, (jnp.sum(viol), counts)

                carry, (viols, counts) = jax.lax.scan(body, carry,
                                                      jnp.arange(n_steps))
                # the shared aggregation tail, then reduce across
                # shards — the psum covers the runner's ``net_*``
                # counters too, so sharded runs report whole-batch
                # message/fault totals
                state, metrics, viol = finish_run(proto, cfg, carry,
                                                  viols, counts,
                                                  group_mask=real)
                metrics = {k: jax.lax.psum(v, axis)
                           for k, v in metrics.items()}
                viol = jax.lax.psum(viol, axis)
                return state, metrics, viol

            state, metrics, viol = sharded(carry)
        if n_pad:
            state = jax.tree.map(lambda x: x[:n_groups], state)
        return state, metrics, viol

    return run


def make_sharded_pinned_run(proto: SimProtocol, cfg: SimConfig,
                            fuzz: FuzzConfig, group: int,
                            mesh: Optional[Mesh] = None, axis: str = "i"):
    """Sharded twin of ``sim/runner.make_pinned_run``: replay a captured
    single-group schedule inside a batch sharded over ``mesh``.

    Because the per-group carry is initialized at the full-batch
    geometry outside the shard_map (see module docstring), every group
    — traced and scaffolding alike — consumes exactly the key chain of
    the single-device pinned run, so the replay reproduces the captured
    state hash and ``net_*`` counters bit-for-bit.  Per-group kernels
    only: lane-major kernels draw whole-batch randomness that cannot be
    re-sliced per shard (their pinned replay stays single-device)."""
    if proto.batched:
        raise NotImplementedError(
            "sharded pinned replay needs per-group PRNG streams; "
            f"lane-major kernel {proto.name!r} draws whole-batch "
            "randomness — replay it with sim/runner.make_pinned_run")
    mesh = mesh or make_mesh()
    n_dev = mesh.shape[axis]

    @functools.partial(jax.jit, static_argnums=(1,))
    def run(rng, n_groups: int, sched):
        n_pad = (-n_groups) % n_dev
        g_pad = n_groups + n_pad
        g_local = g_pad // n_dev
        carry = _padded_carry(proto, cfg, fuzz, n_groups, n_pad, rng)
        n_steps = jax.tree_util.tree_leaves(sched)[0].shape[0]

        @functools.partial(
            _shard_map, mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(axis), P(), P(), P()))
        def sharded(carry, sched):
            d = jax.lax.axis_index(axis)
            gidx = d * g_local + jnp.arange(g_local)
            on_local = gidx == group
            real = gidx < n_groups

            def body(c, xt):
                t, sched_t = xt
                c, (viol, counts) = jax.vmap(
                    lambda cg, on: _group_step(proto, cfg, fuzz, cg, t,
                                               sched_t=sched_t, pin_on=on),
                    in_axes=(0, 0))(c, on_local)
                c = flush_measurements(proto, cfg, c, t)
                # violations: traced group only (the replay oracle);
                # counters: whole real batch, like make_pinned_run
                viol_g = jnp.sum(jnp.where(on_local, viol, 0))
                counts = {k: jnp.sum(jnp.where(real, v, 0))
                          for k, v in counts.items()}
                return c, (viol_g, counts)

            carry, (viols, counts) = jax.lax.scan(
                body, carry, (jnp.arange(n_steps), sched))
            state, metrics, total = finish_run(proto, cfg, carry, viols,
                                               counts, group_mask=real)
            metrics = {k: jax.lax.psum(v, axis) for k, v in metrics.items()}
            total = jax.lax.psum(total, axis)
            viols = jax.lax.psum(viols, axis)
            return state, metrics, total, viols

        state, metrics, total, viols = sharded(carry, sched)
        if n_pad:
            state = jax.tree.map(lambda x: x[:n_groups], state)
        return state, metrics, total, viols

    return run
