"""Device-mesh parallelism for the sim runtime."""

from paxi_tpu.parallel.mesh import (make_mesh, make_sharded_pinned_run,
                                    make_sharded_run)

__all__ = ["make_mesh", "make_sharded_run", "make_sharded_pinned_run"]
