"""Protocol plugin registry.

Reference: bin/server/main.go's ``switch algorithm { case "paxos": ... }``
dispatch plus each package's ``NewReplica``.  Here a name resolves to a
``SimProtocol`` (TPU sim runtime) and/or a host ``Replica`` factory
(deployment runtime); one protocol definition feeds both.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict

from paxi_tpu.sim.types import SimProtocol

_SIM_MODULES = {
    "paxos": "paxi_tpu.protocols.paxos.sim",
    "paxos_pg": "paxi_tpu.protocols.paxos.sim_pg",
    "abd": "paxi_tpu.protocols.abd.sim",
    "chain": "paxi_tpu.protocols.chain.sim",
    "wpaxos": "paxi_tpu.protocols.wpaxos.sim",
    "epaxos": "paxi_tpu.protocols.epaxos.sim",
    "kpaxos": "paxi_tpu.protocols.kpaxos.sim",
    "dynamo": "paxi_tpu.protocols.dynamo.sim",
    "sdpaxos": "paxi_tpu.protocols.sdpaxos.sim",
    "wankeeper": "paxi_tpu.protocols.wankeeper.sim",
    "blockchain": "paxi_tpu.protocols.blockchain.sim",
    "bpaxos": "paxi_tpu.protocols.bpaxos.sim",
    # the in-fabric consensus tier (paxi_tpu/switchnet): switch
    # acceptors + ordered multicast — a protocol CLASS, not a peer
    "switchpaxos": "paxi_tpu.protocols.switchpaxos.sim",
    # trace-subsystem plumbing (NOT correctness cases — all violate by
    # design): the fragile demo kernel and the seeded bug twins.
    # ":ATTR" selects a non-default protocol symbol in the module.
    "fragile_counter": "paxi_tpu.trace.demo",
    "wankeeper_nofloor": "paxi_tpu.protocols.wankeeper.sim:PROTOCOL_NOFLOOR",
    # seeded-bug twin WITH a matching host twin (noread.py): takeover
    # recovery skips the grid's column read on BOTH runtimes, so its
    # witnesses are the hunt pipeline's "reproduced" positive control
    # for a real protocol (fragile_counter covers the demo kernel)
    "bpaxos_noread": "paxi_tpu.protocols.bpaxos.sim:PROTOCOL_NOREAD",
    # scenario-engine twins (paxi_tpu/scenarios): relay_churn is the
    # CHURN-sensitive seeded pair (matching host twin in
    # scenarios/demo_host.py — the hunt's reproduced control for
    # scenario schedules); wpaxos_thinq1 thins the steal's phase-1
    # grid quorum by one zone so WAN geo-latency schedules produce
    # capturable agreement witnesses (sim-only, like wankeeper_nofloor)
    "relay_churn": "paxi_tpu.scenarios.demo",
    "wpaxos_thinq1": "paxi_tpu.protocols.wpaxos.sim:PROTOCOL_THINQ1",
    # switchnet seeded twin WITH a matching host twin (nogap.py): gap
    # agreement replaced by unilateral NOOP-commits on BOTH runtimes,
    # so its drop witnesses are the in-fabric tier's end-to-end
    # REPRODUCED control
    "switchpaxos_nogap":
        "paxi_tpu.protocols.switchpaxos.sim:PROTOCOL_NOGAP",
}

_HOST_MODULES = {
    # host twin of the trace-subsystem demo kernel: the hunt engine's
    # end-to-end reproduction fixture (see trace/demo_host.py)
    "fragile_counter": "paxi_tpu.trace.demo_host",
    "paxos": "paxi_tpu.protocols.paxos.host",
    "abd": "paxi_tpu.protocols.abd.host",
    "chain": "paxi_tpu.protocols.chain.host",
    "wpaxos": "paxi_tpu.protocols.wpaxos.host",
    "epaxos": "paxi_tpu.protocols.epaxos.host",
    "kpaxos": "paxi_tpu.protocols.kpaxos.host",
    "dynamo": "paxi_tpu.protocols.dynamo.host",
    "sdpaxos": "paxi_tpu.protocols.sdpaxos.host",
    "wankeeper": "paxi_tpu.protocols.wankeeper.host",
    "blockchain": "paxi_tpu.protocols.blockchain.host",
    "bpaxos": "paxi_tpu.protocols.bpaxos.host",
    "bpaxos_noread": "paxi_tpu.protocols.bpaxos.noread",
    "switchpaxos": "paxi_tpu.protocols.switchpaxos.host",
    "switchpaxos_nogap": "paxi_tpu.protocols.switchpaxos.nogap",
    # host twin of the scenario engine's churn-sensitive demo kernel
    "relay_churn": "paxi_tpu.scenarios.demo_host",
}


def sim_protocol(name: str) -> SimProtocol:
    """Resolve a protocol name to its TPU sim plugin (PROTOCOL symbol)."""
    if name not in _SIM_MODULES:
        raise KeyError(f"unknown sim protocol {name!r}; "
                       f"have {sorted(_SIM_MODULES)}")
    mod, _, attr = _SIM_MODULES[name].partition(":")
    return getattr(importlib.import_module(mod), attr or "PROTOCOL")


def host_replica(name: str) -> Callable:
    """Resolve a protocol name to its host Replica factory (new_replica)."""
    if name not in _HOST_MODULES:
        raise KeyError(f"unknown host protocol {name!r}; "
                       f"have {sorted(_HOST_MODULES)}")
    return importlib.import_module(_HOST_MODULES[name]).new_replica


def sim_protocols() -> Dict[str, str]:
    return dict(_SIM_MODULES)
