"""Blockchain (longest-chain toy) replica for the host runtime.

Reference: the paxi lineage's blockchain/ package (SURVEY §2.2 "others")
— the probabilistic contrast case: miners extend the longest chain they
know, blocks gossip, forks resolve by length.  Client commands ride in
blocks and are acknowledged once their block is buried ``CONFIRM``
deep on the adopted chain — eventual, not immediate, commitment (the
benchmark's linearizability checker is EXPECTED to be able to catch
this protocol under contention; that is the point of the contrast).

Host form: real block objects with parent links (the sim kernel keeps
hash chains by reference instead); a missing parent triggers an
ancestor fetch; adoption replays the chain into the KV store (reorgs
rebuild — chains in the test workloads are short).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

GENESIS = "genesis"
CONFIRM = 1          # blocks of burial before a command is acknowledged


@register_message
@dataclass
class BlockMsg:
    id: str
    parent: str
    height: int
    miner: str
    # [[key, value, client_id, command_id], ...]
    txs: List[list] = field(default_factory=list)


@register_message
@dataclass
class BlockReq:
    """Fetch a missing ancestor."""

    id: str
    asker: str


class BlockchainReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.blocks: Dict[str, BlockMsg] = {
            GENESIS: BlockMsg(GENESIS, "", 0, "")}
        self.orphans: Dict[str, List[BlockMsg]] = {}
        self.head = GENESIS
        self.mempool: List[Tuple[Command, Optional[Request]]] = []
        self.replied: set = set()
        self.inchain: set = set()   # (cid, cmid) on my adopted chain
        self.rng = random.Random(str(self.id))
        self.register(Request, self.handle_request)
        self.register(BlockMsg, self.handle_block)
        self.register(BlockReq, self.handle_blockreq)

    async def start(self) -> None:
        await super().start()
        self._tasks.append(asyncio.create_task(self._miner()))

    async def _miner(self) -> None:
        """Mining lottery: n replicas x p=1/(2n) per 0.02s tick =
        expected one block per ~0.04s cluster-wide."""
        try:
            while True:
                await asyncio.sleep(0.02)
                if self.rng.random() < 1.0 / (2 * self.cfg.n):
                    self._mine()
        except asyncio.CancelledError:
            pass

    # ---- chain bookkeeping ---------------------------------------------
    def _height(self, bid: str) -> int:
        return self.blocks[bid].height

    def _mine(self) -> None:
        parent = self.head
        h = self._height(parent) + 1
        bid = f"{self.id}:{h}:{self.rng.randrange(1 << 30)}"
        txs = [[c.key, c.value, c.client_id, c.command_id]
               for c, _ in self.mempool
               if (c.client_id, c.command_id) not in self.inchain]
        b = BlockMsg(bid, parent, h, str(self.id), txs)
        self.blocks[bid] = b
        self.socket.broadcast(b)
        self._adopt(bid)

    def handle_block(self, m: BlockMsg) -> None:
        if m.id in self.blocks:
            return
        if m.parent not in self.blocks:
            self.orphans.setdefault(m.parent, []).append(m)
            self.socket.send(ID(m.miner), BlockReq(m.parent, str(self.id)))
            return
        self.blocks[m.id] = m
        # connect EVERY orphan waiting on this block (siblings fork)
        children = self.orphans.pop(m.id, [])
        # longest chain wins; ties: lexicographically smaller head id
        cur_h = self._height(self.head)
        if m.height > cur_h or (m.height == cur_h and m.id < self.head):
            self._adopt(m.id)
        for child in children:
            self.handle_block(child)

    def handle_blockreq(self, m: BlockReq) -> None:
        b = self.blocks.get(m.id)
        if b is not None and m.id != GENESIS:
            self.socket.send(ID(m.asker), b)

    def _chain(self, bid: str) -> List[BlockMsg]:
        out = []
        while bid != GENESIS:
            b = self.blocks[bid]
            out.append(b)
            bid = b.parent
        return list(reversed(out))

    def _adopt(self, bid: str) -> None:
        # fast path: the new head EXTENDS my current chain — apply just
        # the delta blocks and scan only the blocks whose burial depth
        # crosses CONFIRM (a full genesis walk per block would decay
        # quadratically; a reorg still pays one O(chain) rebuild)
        delta: List[BlockMsg] = []
        cur = bid
        while cur != GENESIS and cur != self.head:
            delta.append(self.blocks[cur])
            cur = self.blocks[cur].parent
        extends = cur == self.head
        self.head = bid
        new_h = self._height(bid)
        conf_frontier = new_h - CONFIRM       # heights <= this confirmed
        confirmed: List[BlockMsg] = []
        if extends:
            old_frontier = conf_frontier - len(delta)
            for b in reversed(delta):
                for key, value, cid, cmid in b.txs:
                    self.db.execute(Command(int(key), value, cid,
                                            int(cmid)))
                    self.inchain.add((cid, int(cmid)))
            # newly confirmed: heights (old_frontier, conf_frontier] —
            # at most len(delta) + CONFIRM blocks from the tip
            cur = bid
            while cur != GENESIS:
                b = self.blocks[cur]
                if b.height <= old_frontier:
                    break
                if b.height <= conf_frontier:
                    confirmed.append(b)
                cur = b.parent
        else:
            # true reorg: rebuild from scratch (rare; once per fork)
            self.db.reset()
            self.inchain = set()
            for b in self._chain(bid):
                for key, value, cid, cmid in b.txs:
                    self.db.execute(Command(int(key), value, cid,
                                            int(cmid)))
                    self.inchain.add((cid, int(cmid)))
                if b.height <= conf_frontier:
                    confirmed.append(b)
        # acknowledge my own newly confirmed commands (once)
        mine_done = {(cid, int(cmid))
                     for b in confirmed if b.miner == str(self.id)
                     for _k, _v, cid, cmid in b.txs}
        still = []
        for cmd, req in self.mempool:
            tag = (cmd.client_id, cmd.command_id)
            if tag in mine_done and tag not in self.replied:
                self.replied.add(tag)
                if req is not None:
                    req.reply(Reply(cmd, value=b""))
            elif tag not in mine_done:
                still.append((cmd, req))
        self.mempool = still

    # ---- client requests -----------------------------------------------
    def handle_request(self, req: Request) -> None:
        cmd = req.command
        if cmd.is_read():
            # reads serve the adopted chain's state (eventually
            # consistent by design)
            req.reply(Reply(cmd, value=self.db.get(cmd.key) or b""))
            return
        self.mempool.append((cmd, req))


def new_replica(id: ID, cfg: Config) -> BlockchainReplica:
    return BlockchainReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim's single ``head`` plane
# announces each replica's chain head; the host announces heads by
# broadcasting the block itself (BlockMsg) — BlockReq is the pull-side
# repair with no sim analog (the sim plane carries the whole head
# state, so there is nothing to fetch).
TRACE_MSG_MAP = {
    "head": "BlockMsg",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "ring":       "blocks",  # height-ring of block ids <-> block store
    "miner_ring": "blocks",  # miner-per-height plane <-> Block.miner
    "mined":      "",  # per-replica mined counter (metrics)
    "reorgs":     "",  # rewind counter (metrics)
}
