"""Longest-chain blockchain toy as a pure TPU kernel.

Reference: the paxi lineage's blockchain/ package (SURVEY §2.2 "others")
— the longest-chain contrast case to the consensus protocols: replicas
"mine" blocks by lottery, extend the longest chain they know, gossip
heads, and adopt any longer chain they hear about; agreement is only
eventual and probabilistic (forks happen and resolve by length), which
is exactly what its oracle checks — and what distinguishes it from the
quorum protocols whose oracles demand immediate agreement.

TPU re-design (lane-major layout — see sim/lanes.py):
- A chain is its **hash chain**: block id ``id' = mix(id, miner,
  height)`` — ancestry is a pure function of the mining history, so
  blocks carry no payload and "verify the chain" IS "recompute the
  hash chain", which the per-step oracle does over the resident
  window.
- Each replica keeps the last ``n_slots`` block ids AND miner ids of
  its adopted chain (rings indexed by height), so chain verification
  and reorg accounting are windowed like every other kernel's log.
- Gossip advertises ``(height, id)``; adoption copies the offering
  replica's **live** (height, head, rings) by reference — the same
  mechanism as the paxos kernel's P1b log merge.  The advertisement
  picks WHO to adopt from; the adopted state is the sender's current,
  internally-consistent chain (which, heights being monotone, is at
  least as long as advertised — adopting it never regresses).
- Mining: a per-(replica, step) PRNG lottery with P(block) =
  ``1 / (n_replicas * difficulty)`` — ``cfg.steal_threshold`` doubles
  as the difficulty knob, keeping SimConfig untouched.
- Oracle (what a longest-chain system really promises):
  1. height never decreases (fork choice only extends);
  2. the resident window is hash-chain-consistent: every in-window
     ``(parent, miner, height)`` recomputes to the stored id;
  eventual convergence is a METRIC (``converged``), not an invariant —
  forks are legal mid-run, and flagging them would be dishonest.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.sim.ring import dst_major
from paxi_tpu.sim.ring import take_replica as _take_replica
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

GENESIS = 7


def mix(pid, miner, height):
    """Deterministic 31-bit block id from (parent id, miner, height).
    int32 multiplies wrap in XLA — that IS the scrambling."""
    h = pid * jnp.int32(0x1E3779B1) + miner * jnp.int32(0x05EBCA77) \
        + height * jnp.int32(0x42B2AE35)
    h = h ^ (h >> 13)
    return (h & jnp.int32(0x7FFFFFFF)) | jnp.int32(1)   # never 0


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {"head": ("height", "hid")}


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, G = cfg.n_replicas, cfg.n_slots, n_groups
    del rng
    i32 = jnp.int32
    at0 = (jnp.arange(S) == 0)[None, :, None]
    return dict(
        height=jnp.zeros((R, G), i32),       # my head height (genesis=0)
        head=jnp.full((R, G), GENESIS, i32),  # my head id
        ring=jnp.where(at0, GENESIS, jnp.zeros((R, S, G), i32)),
        miner_ring=jnp.zeros((R, S, G), i32),  # miner of block at height
        mined=jnp.zeros((R, G), i32),        # blocks I mined
        reorgs=jnp.zeros((R, G), i32),       # adoptions that rewound me
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S = cfg.n_replicas, cfg.n_slots
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)

    height = state["height"]
    head = state["head"]
    ring = state["ring"]
    miner_ring = state["miner_ring"]
    mined = state["mined"]
    G = height.shape[-1]

    def ring_at(rg, h):
        """rg value at absolute height h (garbage if h left the window;
        callers mask)."""
        oh = sidx[None, :, None] == (h % S)[:, None, :]
        return jnp.sum(jnp.where(oh, rg, 0), axis=1)

    # ---------------- fork choice over gossiped advertisements ----------
    m = inbox["head"]
    v = dst_major(m["valid"])                            # (me, src, G)
    gh = jnp.where(v, dst_major(m["height"]), -1)
    gid = dst_major(m["hid"])
    best_h = jnp.max(gh, axis=1)                         # (me, G)
    tie = gh == best_h[:, None, :]
    best_id = jnp.min(jnp.where(tie & v, gid, jnp.int32(0x7FFFFFFF)),
                      axis=1)
    better = (best_h > height) \
        | ((best_h == height) & (best_h >= 0) & (best_id < head))
    pick = jnp.argmax(tie & v & (gid == best_id[:, None, :]),
                      axis=1).astype(jnp.int32)
    # adopt the offerer's LIVE chain (by reference): heights are
    # monotone, so its current chain is >= the advertised one and its
    # (height, head, rings) are mutually consistent
    src_height = _take_replica(height, pick)
    src_head = _take_replica(head, pick)
    src_ring = _take_replica(ring, pick)
    src_miner = _take_replica(miner_ring, pick)
    # reorg accounting: the adopted chain's block at MY old height
    # differs from my old head (or my old height already left the
    # adopted window — a deep rewind)
    in_win = height > src_height - S
    diverged = better & (~in_win | (ring_at(src_ring, height) != head))
    height_n = jnp.where(better, src_height, height)
    head_n = jnp.where(better, src_head, head)
    ring = jnp.where(better[:, None, :], src_ring, ring)
    miner_ring = jnp.where(better[:, None, :], src_miner, miner_ring)
    height, head = height_n, head_n
    reorgs = state["reorgs"] + diverged

    # ---------------- mine: PRNG lottery, extend my chain ---------------
    diff = max(int(cfg.steal_threshold), 1)
    k = jr.fold_in(ctx.rng, 41)
    win = jr.uniform(k, (R, G)) < (1.0 / (R * diff))
    new_h = height + 1
    new_id = mix(head, ridx[:, None], new_h)
    oh_n = sidx[None, :, None] == (new_h % S)[:, None, :]
    ring = jnp.where(win[:, None, :] & oh_n, new_id[:, None, :], ring)
    miner_ring = jnp.where(win[:, None, :] & oh_n,
                           ridx[:, None, None], miner_ring)
    height = jnp.where(win, new_h, height)
    head = jnp.where(win, new_id, head)
    mined = mined + win

    # ---------------- gossip my head ------------------------------------
    out_head = {
        "valid": jnp.ones((R, R, G), bool),
        "height": jnp.broadcast_to(height[:, None, :], (R, R, G)),
        "hid": jnp.broadcast_to(head[:, None, :], (R, R, G)),
    }

    new_state = dict(height=height, head=head, ring=ring,
                     miner_ring=miner_ring, mined=mined, reorgs=reorgs)
    return new_state, {"head": out_head}


def metrics(state, cfg: SimConfig):
    h, hd = state["height"], state["head"]
    conv = jnp.all(hd == hd[:1], axis=0) & jnp.all(h == h[:1], axis=0)
    return {
        "committed_slots": jnp.sum(jnp.max(h, axis=0)),  # chain growth
        "mined": jnp.sum(state["mined"]),
        "reorgs": jnp.sum(state["reorgs"]),
        "converged": jnp.sum(conv.astype(jnp.int32)),    # groups agreed
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Height monotonicity (fork choice only extends).
    2. Windowed hash-chain verification: every resident (parent, miner,
       height) triple recomputes to the stored id — 'verify the chain'
       done literally, over the ring window.
    3. The head slot holds the head.
    Eventual convergence is a metric, not an invariant: forks are
    legal mid-run in a longest-chain system."""
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    height, head = new["height"], new["head"]
    ring, miner = new["ring"], new["miner_ring"]

    v1 = jnp.sum(new["height"] < old["height"])

    # height assigned to ring slot s (the latest cycle at or below my
    # height); verify id[h] == mix(id[h-1], miner[h], h) wherever both
    # h and h-1 are resident and h >= 1
    h_at = height[:, None, :] - ((height[:, None, :] - sidx[None, :, None])
                                 % S)                    # (R, S, G)
    checkable = (h_at >= 1) & (h_at > height[:, None, :] - S + 1)
    # parent sits at slot (s - 1) mod S: a roll, not a gather
    parent = jnp.roll(ring, 1, axis=1)
    expect = mix(parent, miner, h_at)
    v2 = jnp.sum(checkable & (ring != expect))

    oh_h = sidx[None, :, None] == (height % S)[:, None, :]
    at_head = jnp.sum(jnp.where(oh_h, ring, 0), axis=1)
    v3 = jnp.sum(at_head != head)

    return (v1 + v2 + v3).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="blockchain",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
