"""WanKeeper replica for the host (deployment) runtime.

Reference: the paxi lineage's wankeeper/ package (SURVEY §2.2 "others")
— hierarchical token/lease coordination: a **root** coordinator grants
per-key tokens to zones; a key's operations execute in the zone holding
its token (zone-majority replication, zone-local latency); cross-zone
demand triggers a revoke → flush → grant handoff through the root, with
the key's version travelling so the receiving zone resumes where the
releasing zone committed.

Host re-design (event-driven lease form; the sim kernel in ``sim.py``
runs the log-derived form):
- The root is elected with ballots (Root1a/Root1b).  Its token table is
  **soft state**: every Root1b carries the sender's zone-held tokens
  (the ground truth lives with the holders) and the rebuild MERGES
  reports over the Grant-tracked table, so a root crash costs one
  election, never exclusivity: an unreported holder keeps its entry
  (its keys stall until its leader answers a revoke — leases here have
  no expiry clock), late reports fold in unless the key was granted
  away under the new ballot, and grants/revokes are ballot-fenced so a
  deposed root cannot move tokens.
- Zone leaders are static (lowest id per zone — intra-zone failover is
  out of scope here, as in the sim kernel).  A zone leader replicates
  writes to its zone (``ZWrite``/``ZAck``, zone-majority) and serves
  reads locally while holding the token (the lease makes this
  linearizable — the WanKeeper latency argument).
- Handoff: root sends ``Revoke(key, gen)``; the holder stops, waits
  for its zone-majority flush, then reports ``Rel(key, ver, gen)``
  (retried); the root then ``Grant(key, zone, ver, gen)``s the waiting
  zone, whose leader adopts the version and drains its queued ops.
  Generations fence stale reports, exactly like the sim kernel.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from paxi_tpu.core.ballot import ballot_id, next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class Root1a:
    ballot: int


@register_message
@dataclass
class Root1b:
    ballot: int
    id: str
    # ground truth from the holders: key -> version for tokens MY ZONE
    # holds (zone leaders report; members report {})
    held: Dict[int, int] = field(default_factory=dict)


@register_message
@dataclass
class TReq:
    """Zone leader -> root: my zone wants key's token."""

    key: int
    zone: int


@register_message
@dataclass
class Revoke:
    """Root -> holding zone leader: release key's token.  Ballot-fenced
    so a deposed root's revokes are ignored."""

    key: int
    gen: int
    ballot: int = 0


@register_message
@dataclass
class Rel:
    """Holder -> root: flushed; key's final committed version AND value
    (object state travels with the token; retried until the matching
    Grant is observed)."""

    key: int
    ver: int
    value: bytes
    gen: int


@register_message
@dataclass
class Grant:
    """Root -> everyone (so every replica tracks the table): key now
    belongs to ``zone`` at ``ver`` with ``value``."""

    key: int
    zone: int
    ver: int
    value: bytes
    gen: int
    ballot: int = 0     # fence: grants from a deposed root are ignored


@register_message
@dataclass
class ZWrite:
    """Zone leader -> zone members: apply (key, ver, value) in order."""

    key: int
    ver: int
    value: bytes


@register_message
@dataclass
class ZAck:
    key: int
    ver: int
    id: str


@dataclass
class _Op:
    req: Request
    ver: Optional[int] = None          # assigned once writable


class WanKeeperReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        zs = cfg.zones()
        self.zone = self.id.zone
        self.zone_ids = [i for i in cfg.ids if i.zone == self.zone]
        self.zone_leader = self.zone_ids[0]
        self.n_zones = len(zs)
        # token table: key -> zone (every replica tracks via Grants);
        # home assignment mirrors the sim kernel (key mod zones, over
        # the sorted zone list)
        self.zs = zs
        self.tokens: Dict[int, int] = {}
        self.ver: Dict[int, int] = {}      # my applied version per key
        self.val: Dict[int, bytes] = {}
        # durable grant floor (the sim kernel's gver, host form): last
        # granted (ver, value) per key, tracked from the broadcast
        # Grants by EVERY replica.  Grants and release reports are
        # floored at it, so a dropped Grant can never make a later
        # handoff resume below a committed, client-acked version.
        self.granted: Dict[int, Tuple[int, bytes]] = {}
        # highest Grant generation seen per key: fences out delayed /
        # duplicate Grants from an earlier handoff of the same key
        self.gen_seen: Dict[int, int] = {}
        # zone-leader state
        self.flushq: Dict[int, Quorum] = {}       # key -> current quorum
        self.pending: Dict[int, List[_Op]] = {}   # key -> queued ops
        self.revoking: Dict[int, int] = {}        # key -> gen to release
        # root state
        self.ballot = 0
        self.active = False
        self.root_quorum = Quorum(cfg.ids)
        self.gen = 0
        self.transit: Dict[int, Tuple[int, int]] = {}  # key -> (gen, zone)
        self.want: Dict[int, int] = {}
        self.granted_log: Set[Tuple[int, int]] = set()  # (key, gen) dedup
        self.granted_keys: Set[int] = set()   # granted under MY ballot
        self._done = 0                        # completed-op progress
        self.register(Request, self.handle_request)
        self.register(Root1a, self.handle_root1a)
        self.register(Root1b, self.handle_root1b)
        self.register(TReq, self.handle_treq)
        self.register(Revoke, self.handle_revoke)
        self.register(Rel, self.handle_rel)
        self.register(Grant, self.handle_grant)
        self.register(ZWrite, self.handle_zwrite)
        self.register(ZAck, self.handle_zack)

    async def start(self) -> None:
        await super().start()
        self._tasks.append(asyncio.create_task(self._watchdog()))

    async def _watchdog(self) -> None:
        stall = 0
        last_done = 0
        try:
            while True:
                await asyncio.sleep(0.05)
                # retry pending token requests (root may have changed)
                if self.is_zone_leader():
                    for k, ops in list(self.pending.items()):
                        if ops and self.holder(k) != self.zone \
                                and k not in self.revoking:
                            self._ask_root(k)
                    # retry unfinished releases
                    for k, gen in list(self.revoking.items()):
                        self._try_release(k, gen)
                # a dead root leaves a stale ballot behind: work in
                # flight with NO completed-op progress elects a fresh
                # root; under normal load ops keep completing and the
                # counter resets (ballot ordering resolves duels)
                if (self.pending or self.revoking) \
                        and self._done == last_done:
                    stall += 1
                    if stall >= 6:
                        stall = 0
                        self.run_root_election()
                else:
                    stall = 0
                last_done = self._done
        except asyncio.CancelledError:
            pass

    # ---- topology helpers ----------------------------------------------
    def is_zone_leader(self) -> bool:
        return self.id == self.zone_leader

    def home_zone(self, key: int) -> int:
        return self.zs[key % self.n_zones]

    def holder(self, key: int) -> Optional[int]:
        """Current holding zone per my table; None while in transit."""
        return self.tokens.get(key, self.home_zone(key))

    @property
    def root(self) -> Optional[ID]:
        return ballot_id(self.ballot) if self.ballot else None

    def is_root(self) -> bool:
        return self.active and self.root == self.id

    # ---- root election (token table rebuilt from holders) ---------------
    def run_root_election(self) -> None:
        self.ballot = next_ballot(self.ballot, self.id)
        self.active = False
        self.root_quorum = Quorum(self.cfg.ids)
        self.root_quorum.ack(self.id)
        self._1b_tables = {self.id: self._held_payload()}
        self.socket.broadcast(Root1a(self.ballot))

    def _held_payload(self) -> Dict[int, int]:
        if not self.is_zone_leader():
            return {}
        keys = set(self.ver) | set(self.tokens)
        return {k: self.ver.get(k, 0) for k in keys
                if self.holder(k) == self.zone}

    def handle_root1a(self, m: Root1a) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
        self.socket.send(ballot_id(m.ballot),
                         Root1b(self.ballot, str(self.id),
                                self._held_payload()))

    def handle_root1b(self, m: Root1b) -> None:
        if m.ballot != self.ballot or self.active:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
            elif (m.ballot == self.ballot and self.is_root()):
                # late holder report: fold it in unless I already
                # granted the key away under this ballot
                for k in m.held:
                    if int(k) not in self.granted_keys:
                        self.tokens[int(k)] = ID(m.id).zone
            return
        self.root_quorum.ack(ID(m.id))
        self._1b_tables[ID(m.id)] = {int(k): int(v)
                                     for k, v in m.held.items()}
        if self.root_quorum.majority() and ballot_id(self.ballot) == self.id:
            self.active = True
            # rebuild: MERGE holder reports over my existing table (it
            # tracked every broadcast Grant).  A holder whose Root1b is
            # late keeps its entry — its keys stall until its leader
            # answers a revoke, rather than being re-granted into a
            # two-holders fork; late reports are folded in by
            # handle_root1b below.  A genuinely dead zone leader pins
            # its keys (leases here have no clock; the sim kernel's
            # log-derived variant has no such pin).
            for rid, held in self._1b_tables.items():
                for k in held:
                    self.tokens[k] = rid.zone
            self.transit = {}
            # generations are namespaced by ballot so a deposed root's
            # in-flight handshake can never collide with mine
            self.gen = self.ballot << 16
            self.granted_keys = set()

    # ---- client requests -------------------------------------------------
    def handle_request(self, req: Request) -> None:
        if not self.is_zone_leader():
            self.forward(self.zone_leader, req)
            return
        k = req.command.key
        self.pending.setdefault(k, []).append(_Op(req))
        if self.holder(k) == self.zone and k not in self.revoking:
            self._drain(k)
        else:
            self._ask_root(k)

    def _ask_root(self, k: int) -> None:
        if self.is_root():
            self.handle_treq(TReq(k, self.zone))
        elif self.root is not None:
            self.socket.send(self.root, TReq(k, self.zone))
        else:
            self.run_root_election()

    def _drain(self, k: int) -> None:
        """Serve queued ops for a held key, one write pipeline stage at
        a time (next write starts when the previous flushes)."""
        ops = self.pending.get(k, [])
        while ops and k not in self.revoking \
                and self.holder(k) == self.zone:
            op = ops[0]
            cmd = op.req.command
            if cmd.is_read():
                ops.pop(0)
                self._done += 1
                op.req.reply(Reply(cmd, value=self.val.get(k, b"")))
                continue
            if op.ver is None and k not in self.flushq:
                v = self.ver.get(k, 0) + 1
                op.ver = v
                q = Quorum(self.zone_ids)
                q.ack(self.id)
                self.flushq[k] = q
                self.ver[k] = v
                self.val[k] = cmd.value
                self.db.execute(cmd)
                for i in self.zone_ids:
                    if i != self.id:
                        self.socket.send(i, ZWrite(k, v, cmd.value))
                if q.majority():
                    self._write_flushed(k)
            break           # wait for the flush (or it already popped)
        if not ops:
            self.pending.pop(k, None)

    def _write_flushed(self, k: int) -> None:
        self.flushq.pop(k, None)
        ops = self.pending.get(k, [])
        if ops and ops[0].ver is not None:
            op = ops.pop(0)
            self._done += 1
            op.req.reply(Reply(op.req.command, value=b""))
        self._drain(k)
        if k in self.revoking:
            self._try_release(k, self.revoking[k])

    # ---- zone replication ------------------------------------------------
    def handle_zwrite(self, m: ZWrite) -> None:
        if m.ver > self.ver.get(m.key, 0):
            self.ver[m.key] = m.ver
            self.val[m.key] = m.value
            self.db.execute(Command(m.key, m.value))
        self.socket.send(self.zone_leader, ZAck(m.key, m.ver, str(self.id)))

    def handle_zack(self, m: ZAck) -> None:
        q = self.flushq.get(m.key)
        if q is not None and m.ver == self.ver.get(m.key, 0):
            q.ack(ID(m.id))
            if q.majority():
                self._write_flushed(m.key)

    # ---- root: token requests and handoffs -------------------------------
    def handle_treq(self, m: TReq) -> None:
        if not self.is_root():
            return
        k = m.key
        if k in self.transit:
            self.want[k] = m.zone       # latest request wins the grant
            return
        holder = self.holder(k)
        if holder == m.zone:
            # requester already owns it but may not know: re-grant
            self.gen += 1
            self._grant(k, m.zone, None, None, self.gen)
            return
        self.gen += 1
        self.transit[k] = (self.gen, m.zone)
        self.want[k] = m.zone
        hz_leader = min(j for j in self.cfg.ids if j.zone == holder)
        rv = Revoke(k, self.gen, self.ballot)
        if hz_leader == self.id:
            self.handle_revoke(rv)
        else:
            self.socket.send(hz_leader, rv)

    def handle_revoke(self, m: Revoke) -> None:
        if not self.is_zone_leader() or m.ballot < self.ballot:
            return
        # generation fence, symmetric with handle_grant's: a delayed /
        # duplicate Revoke from an EARLIER handoff must not overwrite a
        # newer pending revocation (the holder would then retry Rel at
        # the old gen forever while the root waits on the new one — a
        # permanent wedge), nor re-open a handoff whose Grant already
        # landed (gen_seen)
        if m.gen <= self.gen_seen.get(m.key, -1) \
                or m.gen < self.revoking.get(m.key, m.gen):
            return
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
        self.revoking[m.key] = m.gen
        self._try_release(m.key, m.gen)

    def _try_release(self, k: int, gen: int) -> None:
        if k in self.flushq:
            return                       # still flushing: Rel after
        # floor the report at the version the token was granted at
        # (sim kernel's rel_ver gver floor): if the Grant that carried
        # the state to my zone was lost, reporting my local ver would
        # regress the object's history at the next handoff
        ver, val = self.ver.get(k, 0), self.val.get(k, b"")
        gv, gval = self.granted.get(k, (0, b""))
        if ver < gv:
            ver, val = gv, gval
        msg = Rel(k, ver, val, gen)
        if self.is_root():
            self.handle_rel(msg)
        elif self.root is not None:
            self.socket.send(self.root, msg)

    def handle_rel(self, m: Rel) -> None:
        if not self.is_root():
            return
        t = self.transit.get(m.key)
        if t is not None:
            if t[0] != m.gen:
                return                   # stale generation: fenced off
            zone = self.want.get(m.key, t[1])
            self._grant(m.key, zone, m.ver, m.value, m.gen)
            return
        if (m.key, m.gen) in self.granted_log:
            return                       # duplicate of a completed handoff
        # no handshake in flight and an unknown generation: a holder is
        # retrying the release of a DEAD root's revoke — the Grant that
        # would answer it can never arrive, so without help the key
        # wedges whenever the holder's OWN zone wants it (no TReq is
        # sent for a held key, so no fresh Revoke re-keys the
        # handshake).  Answer with a fresh Grant under MY generation:
        # the holder resumes only via a root-issued Grant, never by
        # unilaterally dropping its revoking entry — a failed
        # candidate's Root1a bumps ballots without deposing the live
        # root, so "gen predates my ballot" alone must NOT re-open the
        # drain gate (two zones could end up draining concurrently).
        self.gen += 1
        self._grant(m.key, self.holder(m.key), m.ver, m.value, self.gen)

    def _grant(self, k: int, zone: int, ver: Optional[int],
               value: Optional[bytes], gen: int) -> None:
        if (k, gen) in self.granted_log:
            return
        self.granted_log.add((k, gen))
        self.granted_keys.add(k)
        self.transit.pop(k, None)
        self.want.pop(k, None)
        self.tokens[k] = zone
        if ver is None:
            ver, value = self.ver.get(k, 0), self.val.get(k, b"")
        # floor at the last granted (ver, value) — the sim kernel's
        # gver floor at the root.  The re-grant path (handle_treq with
        # holder == requester) lands here with my LOCAL state, which is
        # stale whenever my zone didn't hold the key last; without the
        # floor a single dropped Grant broadcast makes the re-grant
        # regress the holder below committed, client-acked writes.
        gv, gval = self.granted.get(k, (0, b""))
        if ver < gv:
            ver, value = gv, gval
        g = Grant(k, zone, ver, value, gen, self.ballot)
        self.socket.broadcast(g)
        self.handle_grant(g)

    def handle_grant(self, m: Grant) -> None:
        if m.ballot < self.ballot:
            return                       # a deposed root's grant
        # generation fence: a delayed or duplicate Grant from an
        # EARLIER handoff of this key (same ballot — the slow-link path
        # reorders) must not resurrect my holder state after a newer
        # Revoke, or two zones end up holding the token concurrently
        if m.gen < self.revoking.get(m.key, m.gen) \
                or m.gen <= self.gen_seen.get(m.key, -1):
            return
        self.gen_seen[m.key] = m.gen
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
        if m.ver >= self.granted.get(m.key, (0, b""))[0]:
            self.granted[m.key] = (m.ver, m.value)
        self.tokens[m.key] = m.zone
        self.revoking.pop(m.key, None)
        if m.zone == self.zone and m.ver > self.ver.get(m.key, 0):
            # the object state rode the token: adopt it zone-wide
            self.ver[m.key] = m.ver
            self.val[m.key] = m.value
            self.db.execute(Command(m.key, m.value))
        if self.is_zone_leader() and m.zone == self.zone:
            self._drain(m.key)


def new_replica(id: ID, cfg: Config) -> WanKeeperReplica:
    return WanKeeperReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim's root log (p2a/p3) carries the
# grant/revoke commands that the host runtime sends as explicit Grant
# messages, so log-plane faults project onto the Grant broadcast — a
# schedule homomorphism, not a wire-level identity.
TRACE_MSG_MAP = {
    "zrep": "ZWrite", "zack": "ZAck", "treq": "TReq", "rel": "Rel",
    "p1a": "Root1a", "p1b": "Root1b", "p2a": "Grant", "p3": "Grant",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    # token table + zone replication
    "token_zone": "tokens",      # holder zone per object
    "prev_zone":  "transit",     # releasing zone during a handoff
    "aver":       "flushq",      # member acked versions <-> flush Quorum
    "relv":       "revoking",    # reported release version (gen-gated)
    "pend":       "transit",     # revoke-in-flight mark at the root
    "pgen":       "gen_seen",    # executed-revoke generation fence
    "rgen":       "gen_seen",    # my zone's release generation
    "gver":       "granted",     # durable grant floor (host form)
    # root log (shared ballot-ring planes; cf. paxos/host.py)
    "p1_acks":    "root_quorum",
    "log_bal":    "granted_log", # root-log planes: the host root drives
    "log_cmd":    "granted_log", # grants off a leader lease + dedup log
    "log_commit": "granted_log", # (see the PXT302 p2b baseline entry)
    "log_acks":   "granted_log",
    "next_slot":  "gen",         # root command counter <-> generation
    "execute":    "_done",       # executed-prefix <-> progress counter
    "base":       "",  # ring-window base (kernel-only)
    "proposed":   "",  # own-ballot P2a mask (kernel-only)
    "timer":      "",  # election step-timer: host root uses wall-clock
    "stuck":      "",  # frontier-stall retry counter (kernel-only)
    "viol_acc":   "",  # invariant accumulator (oracle)
    "writes":     "",  # leader write counter (metrics)
    "transfers":  "",  # token-transfer counter (metrics)
    # zone-latency accounting (scenario bench axis) — measurement
    # planes, not protocol state; excluded from the trace witness hash
    "m_wr_t":          "",
    "m_wr_p":          "",
    "m_acq_t":         "",
    "m_acq_p":         "",
    "m_lat_local_sum": "",
    "m_lat_local_n":   "",
    "m_lat_cross_sum": "",
    "m_lat_cross_n":   "",
    # on-device commit-latency histogram + in-scan spot-check (PR 11)
    "m_prop_t":        "",
    "m_lat_hist":      "",
    "m_lat_sum":       "",
    "m_inscan_viol":   "",
}
