"""FROZEN pre-rewrite reference: the sliding-window (ring-position)
lane-major wankeeper kernel, kept verbatim from before the fixed-cell
rewrite (PR 15) as the equivalence-proof counterpart.

Ring layout contract (the OLD one): ring position ``i`` holds absolute
slot ``base + i``; every base advance is a ``ring.shift_window`` data
movement.  The live kernel in ``sim.py`` holds absolute slot ``a`` at
cell ``a % S`` forever (sim/cell.py) and must stay BIT-CANONICALLY
equal to this module on pinned fuzz seeds: same PRNG draws, same
outboxes, same counters, and a state that matches after rolling each
ring plane to window order (cell.window_view_np) —
tests/test_fixed_cell_equiv.py enforces it, and ``python -m paxi_tpu
profile --gathers`` diffs the two compiled HLOs' gather counts.  Do
not edit except to mirror a semantic (non-layout) change in sim.py.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.metrics import lathist
from paxi_tpu.sim import ballot_ring as br
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ballot_ring import NO_CMD
from paxi_tpu.sim.ring import dst_major
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

BR_KEYS = br.KEYS

# root command encoding: kind(1) | obj(7) | zone(6) | ver(16), positive
K_REVOKE = 0
K_GRANT = 1


def enc_revoke(obj):
    return (K_REVOKE << 29) | (obj << 22)


def enc_grant(obj, zone, ver):
    return (K_GRANT << 29) | (obj << 22) | (zone << 16) | ver


def dec_kind(cmd):
    return (cmd >> 29) & 1


def dec_obj(cmd):
    return (cmd >> 22) & 0x7F


def dec_zone(cmd):
    return (cmd >> 16) & 0x3F


def dec_ver(cmd):
    return cmd & 0xFFFF


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        # zone plane: in-order object writes + cumulative acks
        "zrep": ("obj", "ver"),
        "zack": ("obj", "ver"),
        # root plane: token requests and release reports; ``gen`` is
        # the root-log slot of the revoke being answered — the agreed
        # log gives every replica the same generation tag for free, and
        # it fences off stale reports from earlier transfers of the
        # same object
        "treq": ("obj",),
        "rel": ("obj", "ver", "gen"),
        # the root log (shared Multi-Paxos core)
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, O, G = (cfg.n_replicas, cfg.n_slots, cfg.n_objects, n_groups)
    Z = cfg.n_zones
    assert R % Z == 0, "wankeeper: n_replicas must be divisible by n_zones"
    # root command encoding widths (enc_revoke/enc_grant): overflowing
    # them would silently corrupt the root log, so fail fast
    assert O <= 128, "wankeeper: n_objects > 128 overflows the 7-bit field"
    assert Z <= 64, "wankeeper: n_zones > 64 overflows the 6-bit field"
    del rng
    require_packable(R)
    i32 = jnp.int32
    oidx = jnp.arange(O, dtype=i32)
    return dict(
        # ---- token table + zone replication (derived from root log) ----
        token_zone=jnp.broadcast_to((oidx % Z)[None, :, None],
                                    (R, O, G)).astype(i32),
        prev_zone=jnp.broadcast_to((oidx % Z)[None, :, None],
                                   (R, O, G)).astype(i32),
        ver=jnp.zeros((R, O, G), i32),       # my applied object versions
        aver=jnp.zeros((R, R, O, G), i32),   # [ldr, member] acked vers
        want=jnp.full((R, O, G), -1, i32),   # [root ldr] requesting zone
        relv=jnp.full((R, O, G), -1, i32),   # reported rel ver (gen-gated)
        pend=jnp.zeros((R, O, G), bool),     # [root ldr] revoke proposed
        pgen=jnp.full((R, O, G), -1, i32),   # executed-revoke generation
        rgen=jnp.full((R, O, G), -1, i32),   # my zone's release generation
        gver=jnp.zeros((R, O, G), i32),      # oracle: last granted ver
        viol_acc=jnp.zeros((G,), i32),       # oracle: grant regressions
        writes=jnp.zeros((R, G), i32),       # leader write count
        transfers=jnp.zeros((R, G), i32),
        # ---- root log (shared ballot_ring planes) ----
        ballot=jnp.zeros((R, G), i32),
        active=jnp.zeros((R, G), bool),
        p1_acks=jnp.zeros((R, G), i32),
        base=jnp.zeros((R, G), i32),
        log_bal=jnp.zeros((R, S, G), i32),
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),
        proposed=jnp.zeros((R, S, G), bool),
        next_slot=jnp.zeros((R, G), i32),
        execute=jnp.zeros((R, G), i32),
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),
        # ---- zone-latency accounting (scenario bench axis) ----------
        # measurement planes, ``m_`` prefix = excluded from the trace
        # witness hash (trace/replay.state_hash).  LOCAL latency: a
        # zone leader's write (version bump) until its zone-majority
        # commit.  CROSS latency: a token request (treq) until the
        # grant lands — the root round trips, WanKeeper's cross-zone
        # cost.  One outstanding sample per (leader, object).
        m_wr_t=jnp.zeros((R, O, G), i32),
        m_wr_p=jnp.zeros((R, O, G), bool),
        m_acq_t=jnp.zeros((R, O, G), i32),
        m_acq_p=jnp.zeros((R, O, G), bool),
        m_lat_local_sum=jnp.zeros((G,), i32),
        m_lat_local_n=jnp.zeros((G,), i32),
        m_lat_cross_sum=jnp.zeros((G,), i32),
        m_lat_cross_n=jnp.zeros((G,), i32),
        # root-log commit-latency histogram + in-scan spot-check
        # (PR-11 layer; shared bucket layout — metrics/lathist)
        m_prop_t=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx, gver_floor: bool = True):
    """``gver_floor=False`` is the SEEDED BUG twin (PROTOCOL_NOFLOOR):
    it removes both halves of the granted-version floor — release
    reports are not floored at gver and stale grants are applied
    instead of skipped — reproducing in the sim kernel exactly the
    linearizability flaw the round-5 advisor found in the host runtime
    (a single dropped Grant regressing committed writes).  It exists so
    the trace pipeline has a real, capturable violation to minimize and
    to project cross-runtime; never soak it as a correctness case."""
    cfg = ctx.cfg
    R, S, O = cfg.n_replicas, cfg.n_slots, cfg.n_objects
    Z = cfg.n_zones
    ZR = R // Z
    ZMAJ = ZR // 2 + 1
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    oidx = jnp.arange(O, dtype=jnp.int32)
    my_zone = ridx // ZR                                 # (R,)
    is_zldr = (ridx % ZR) == 0
    T = dst_major

    st = {k: state[k] for k in BR_KEYS}
    token_zone = state["token_zone"]
    prev_zone = state["prev_zone"]
    ver = state["ver"]
    aver = state["aver"]
    want = state["want"]
    relv = state["relv"]
    pend = state["pend"]
    pgen = state["pgen"]
    rgen = state["rgen"]
    gver = state["gver"]
    writes = state["writes"]
    transfers = state["transfers"]
    G = writes.shape[-1]

    same_zone = my_zone[:, None] == my_zone[None, :]     # (me, src)

    # ============ zone plane: apply leader writes, cumulative acks ======
    # members apply their zone leader's (obj, ver) strictly in order
    m = inbox["zrep"]
    zv = T(m["valid"]) & same_zone[:, :, None]           # (me, ldr, G)
    zo = jnp.clip(T(m["obj"]), 0, O - 1)
    zn = T(m["ver"])
    hit = (zv[:, :, None, :]
           & (zo[:, :, None, :] == oidx[None, None, :, None])
           & (zn[:, :, None, :] == ver[:, None, :, :] + 1))
    ver = ver + jnp.any(hit, axis=1)
    # remember what my leader just replicated (acked below)
    got_rep = jnp.any(zv, axis=1)                        # (me, G)
    rcv_obj = jnp.max(jnp.where(zv, zo, 0), axis=1)      # (me, G)

    # leaders collect acks per object (max over time = cumulative)
    m = inbox["zack"]
    av = T(m["valid"]) & same_zone[:, :, None] & is_zldr[:, None, None]
    ao = jnp.clip(T(m["obj"]), 0, O - 1)
    an = T(m["ver"])
    ahit = av[:, :, None, :] & (ao[:, :, None, :]
                                == oidx[None, None, :, None])
    aver = jnp.maximum(aver, jnp.where(ahit, an[:, :, None, :], 0))
    # my own store is always current
    self_d = (ridx[:, None, None] == ridx[None, :, None])[..., None]
    aver = jnp.where(self_d, ver[:, None], aver)
    # zone-committed version: ZMAJ-th largest over my zone's members
    zsel = same_zone[:, :, None, None]
    avz = jnp.where(zsel, aver, -1)
    committed_v = jnp.maximum(
        jnp.sort(avz, axis=1)[:, R - ZMAJ], 0)           # (ldr, O, G)

    # ---- zone-latency accounting: settle LOCAL write samples ----------
    # (write -> zone-majority commit; sampled before this step's bump)
    m_wr_t, m_wr_p = state["m_wr_t"], state["m_wr_p"]
    m_acq_t, m_acq_p = state["m_acq_t"], state["m_acq_p"]
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]
    m_lat_local_sum = state["m_lat_local_sum"]
    m_lat_local_n = state["m_lat_local_n"]
    m_lat_cross_sum = state["m_lat_cross_sum"]
    m_lat_cross_n = state["m_lat_cross_n"]
    settled = m_wr_p & (committed_v >= ver)              # (ldr, O, G)
    wdt = jnp.clip(ctx.t - m_wr_t, 0, None)
    m_lat_local_sum = m_lat_local_sum + jnp.sum(
        jnp.where(settled, wdt, 0), axis=(0, 1))
    m_lat_local_n = m_lat_local_n + jnp.sum(settled, axis=(0, 1))
    m_wr_p = m_wr_p & ~settled

    # ============ root log: shared Multi-Paxos core =====================
    st, out_p1b, promote = br.promise_p1a(st, inbox["p1a"])
    st, p1_win, amask = br.tally_p1b(st, inbox["p1b"], MAJ, STRIDE)
    # token_zone/prev_zone are derived from the applied root prefix and
    # travel with (execute) by REPLACEMENT; ver/gver are zone-local
    # monotone counters, so state transfer MAX-MERGES them (another
    # replica's view of my zone's objects may be stale — replacing
    # would regress them)
    extras = {"token_zone": token_zone, "prev_zone": prev_zone,
              "ver": ver, "want": want, "relv": relv, "pend": pend,
              "pgen": pgen, "rgen": rgen, "gver": gver}
    b0 = st["base"]
    st, ex = br.adopt_best_acker(st, amask, p1_win, extras)
    token_zone, prev_zone, want, relv, pend, pgen, rgen = (
        ex["token_zone"], ex["prev_zone"], ex["want"], ex["relv"],
        ex["pend"], ex["pgen"], ex["rgen"])
    ver = jnp.maximum(ver, ex["ver"])
    gver = jnp.maximum(gver, ex["gver"])
    # measurement plane re-alignment: ballot_ring shifts the log planes
    # by the base delta; m_prop_t (never passed in) follows suit
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)
    st = br.merge_acker_logs(st, amask, p1_win)
    # a takeover restarts the adopted slots' latency clocks
    m_prop_t = jnp.where(p1_win[:, None, :] & st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    # a fresh root starts with a clean proposal-dedup slate: a stale
    # adopted pend (for a revoke the merge lost) would block the object
    # forever, while a duplicate revoke is an idempotent no-op
    pend = jnp.where(p1_win[:, None, :], False, pend)
    st, out_p2b, acc_ok, _ = br.accept_p2a(st, inbox["p2a"])
    st, newly = br.tally_p2b(st, inbox["p2b"], MAJ, STRIDE)
    # in-kernel commit-latency histogram: propose->commit step delta of
    # every newly committed root-log (leader, slot)
    rdt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_lat_hist = lathist.hist_update(m_lat_hist, rdt, newly)
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, rdt, 0),
                                    axis=(0, 1), dtype=jnp.int32)
    extras = {"token_zone": token_zone, "prev_zone": prev_zone,
              "ver": ver, "want": want, "relv": relv, "pend": pend,
              "pgen": pgen, "rgen": rgen, "gver": gver}
    b0 = st["base"]
    st, ex, c_has, c_bal = br.apply_p3(st, inbox["p3"], extras)
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)
    token_zone, prev_zone, want, relv, pend, pgen, rgen = (
        ex["token_zone"], ex["prev_zone"], ex["want"], ex["relv"],
        ex["pend"], ex["pgen"], ex["rgen"])
    ver = jnp.maximum(ver, ex["ver"])
    gver = jnp.maximum(gver, ex["gver"])

    is_root = st["active"] & br.own_bal_mask(st, STRIDE)

    # ---------------- root intake: token requests + release reports -----
    m = inbox["treq"]
    tv = T(m["valid"])                                   # (root, src, G)
    to = jnp.clip(T(m["obj"]), 0, O - 1)
    for s in range(R):
        oh = tv[:, s, None, :] & (to[:, s, None, :] == oidx[None, :, None])
        want = jnp.where(oh, my_zone[s], want)
    m = inbox["rel"]
    rv = T(m["valid"])                                   # (root, src, G)
    ro = jnp.clip(T(m["obj"]), 0, O - 1)
    rn = T(m["ver"])
    rg = T(m["gen"])
    for s in range(R):
        oh = (rv[:, s, None, :]
              & (ro[:, s, None, :] == oidx[None, :, None])
              & (rg[:, s, None, :] == pgen) & (pgen >= 0))
        relv = jnp.where(oh, jnp.maximum(relv, rn[:, s, None, :]), relv)

    # ---------------- root proposes: revoke, then grant -----------------
    has_re, can_new, prop_rel, prop_slot, oh_p, re_cmd = \
        br.repropose_target(st)
    # grant only for the EXECUTED revoke generation with an accepted,
    # gen-matching release report (pgen/relv are log-derived and
    # broadcast-replicated: failover-safe)
    g_ready = (pgen >= 0) & (relv >= 0) & (want >= 0)
    r_need = (~pend) & (pgen < 0) & (want >= 0) \
        & (want != token_zone) & (token_zone >= 0)
    pick_g = jnp.argmax(g_ready, axis=1).astype(jnp.int32)   # (root, G)
    any_g = jnp.any(g_ready, axis=1)
    pick_r = jnp.argmax(r_need, axis=1).astype(jnp.int32)
    any_r = jnp.any(r_need, axis=1)
    pick_o = jnp.where(any_g, pick_g, pick_r)
    sel = oidx[None, :, None] == pick_o[:, None, :]      # (root, O, G)
    sel_want = jnp.sum(jnp.where(sel, want, 0), axis=1)
    sel_relv = jnp.sum(jnp.where(sel, relv, 0), axis=1)
    new_cmd = jnp.where(
        any_g, enc_grant(pick_o, jnp.clip(sel_want, 0, Z - 1),
                         jnp.clip(sel_relv, 0, 0xFFFF)),
        enc_revoke(pick_o))
    is_new = ~has_re & can_new & (any_g | any_r)
    prop_cmd = jnp.where(is_new, new_cmd, re_cmd)
    do = is_root & (has_re | is_new)
    # latency clock: a slot's FIRST propose starts it (retries keep
    # the original start; recycled cells re-arm via the shifts' 0 fill)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2a = br.propose_write(st, do, is_new, prop_cmd, prop_slot,
                                   oh_p)
    # soft bookkeeping for the entry just proposed (revoke-dedup and
    # want-consumption; the handshake itself clears at EXECUTION)
    bump = (is_new & do)[:, None, :] & sel
    pend = jnp.where(bump, ~any_g[:, None, :], pend)
    want = jnp.where(bump & any_g[:, None, :], -1, want)

    # ---------------- execute the committed root prefix -----------------
    execute = st["execute"]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(st["active"])
    viol_gv = jnp.zeros((G,), jnp.int32)
    for e in range(cfg.exec_window):
        rel_pos = execute + e - st["base"]
        oh_e = sidx[None, :, None] == rel_pos[:, None, :]
        com = jnp.any(oh_e & st["log_commit"], axis=1)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, st["log_cmd"], 0), axis=1)
        wr = running & (cmd_e >= 0)
        kind = dec_kind(cmd_e)
        obj = jnp.clip(dec_obj(cmd_e), 0, O - 1)
        zon = dec_zone(cmd_e)
        v = dec_ver(cmd_e)
        ohh = wr[:, None, :] & (oidx[None, :, None] == obj[:, None, :])
        slot_e = execute + e                             # (R, G) absolute
        # revoke: token in transit; remember the releasing zone and the
        # generation (= this revoke's agreed slot number)
        rv_ = ohh & (kind == K_REVOKE)[:, None, :]
        prev_zone = jnp.where(rv_ & (token_zone >= 0), token_zone,
                              prev_zone)
        rgen = jnp.where(rv_ & (token_zone >= 0), slot_e[:, None, :],
                         rgen)
        pgen = jnp.where(rv_ & (token_zone >= 0), slot_e[:, None, :],
                         pgen)
        token_zone = jnp.where(rv_, -1, token_zone)
        # grant: new holder zone; its members adopt the handoff version.
        # A STALE grant (version below the last applied grant) is
        # INERT: a log merge after partitions can legitimately
        # resurrect a superseded transfer's accepted grant at its
        # original (higher) slot — Paxos must re-adopt possibly-
        # committed values — and applying it would move the token
        # backward.  gver evolves identically along the agreed log at
        # every replica, so the skip is deterministic.
        gr_all = ohh & (kind == K_GRANT)[:, None, :]
        gr = gr_all & (v[:, None, :] >= gver) if gver_floor else gr_all
        token_zone = jnp.where(gr, zon[:, None, :], token_zone)
        pgen = jnp.where(gr, -1, pgen)
        relv = jnp.where(gr, -1, relv)
        in_new = gr & (my_zone[:, None, None] == zon[:, None, :])
        ver = jnp.where(in_new, jnp.maximum(ver, v[:, None, :]), ver)
        # unreachable-guard self-check: APPLIED grants regressing gver
        # is impossible while the freshness guard above stands; the
        # counter revives if a future edit weakens `gr` (the
        # independent gver-monotonicity check lives in invariants())
        viol_gv = viol_gv + jnp.sum(gr & (v[:, None, :] < gver),
                                    axis=(0, 1))
        gver = jnp.where(gr_all, jnp.maximum(gver, v[:, None, :]), gver)
        transfers = transfers + jnp.sum(gr, axis=1)
        advanced = advanced + running
    new_execute = execute + advanced
    viol_acc = state["viol_acc"] + viol_gv

    # ============ zone leaders: demand, write, request ==================
    # locality-skewed demand (same generator shape as the wpaxos kernel)
    k1 = jr.fold_in(ctx.rng, 23)
    k2 = jr.fold_in(ctx.rng, 29)
    u = jr.uniform(k1, (R, G))
    n_home = max(O // Z, 1)
    pick_local = (jr.randint(k2, (R, G), 0, n_home) * Z
                  + my_zone[:, None]) % O
    pick_any = jr.randint(k2, (R, G), 0, O)
    demand = jnp.clip(jnp.where(u < cfg.locality, pick_local, pick_any),
                      0, O - 1).astype(jnp.int32)

    dsel = oidx[None, :, None] == demand[:, None, :]     # (R, O, G)
    d_holder = jnp.sum(jnp.where(dsel, token_zone, 0), axis=1)
    held = d_holder == my_zone[:, None]
    # write: bump my demanded object's version, gated on the previous
    # version being zone-committed (pipeline never outruns acks by > 1)
    d_ver = jnp.sum(jnp.where(dsel, ver, 0), axis=1)
    d_cv = jnp.sum(jnp.where(dsel, committed_v, 0), axis=1)
    w_do = is_zldr[:, None] & held & (d_ver - d_cv < 2)
    ver = ver + (w_do[:, None, :] & dsel)
    writes = writes + w_do
    # latency clock: the OLDEST outstanding write keeps its start
    start_w = w_do[:, None, :] & dsel & ~m_wr_p
    m_wr_t = jnp.where(start_w, ctx.t, m_wr_t)
    m_wr_p = m_wr_p | start_w

    # zrep out: per-destination go-back-N (like sdpaxos's C-plane) —
    # send each zone member the NEXT version it has not acked of my
    # demanded object, not my latest: a member that dropped v would
    # otherwise never match the in-order apply rule again and the
    # object's write pipeline would wedge for the rest of the run
    z_ver = jnp.sum(jnp.where(dsel, ver, 0), axis=1)     # (ldr, G) mine
    av_d = jnp.sum(jnp.where(dsel[:, None, :, :], aver, 0), axis=2)
    send_ver = jnp.minimum(av_d + 1, z_ver[:, None, :])  # (ldr, dst, G)
    zmask_out = is_zldr[:, None, None] & same_zone[:, :, None]
    out_zrep = {
        "valid": jnp.broadcast_to(zmask_out, (R, R, G))
        & (av_d < z_ver[:, None, :]),
        "obj": jnp.broadcast_to(demand[:, None, :], (R, R, G)),
        "ver": send_ver,
    }
    # zack out: echo what my leader just replicated; otherwise rotate
    # through objects so every object's acks keep refreshing
    ack_obj = jnp.where(got_rep, rcv_obj,
                        (ctx.t + ridx[:, None]) % O).astype(jnp.int32)
    ack_sel = oidx[None, :, None] == ack_obj[:, None, :]
    ack_ver = jnp.sum(jnp.where(ack_sel, ver, 0), axis=1)
    zldr_of_mine = (my_zone * ZR)[:, None]               # (R, 1)
    out_zack = {
        "valid": jnp.broadcast_to(
            (ridx[None, :] == zldr_of_mine)[:, :, None], (R, R, G)),
        "obj": jnp.broadcast_to(ack_obj[:, None, :], (R, R, G)),
        "ver": jnp.broadcast_to(ack_ver[:, None, :], (R, R, G)),
    }

    # ---- zone-latency accounting: CROSS (token-acquisition) samples ----
    # a grant landed for an object my zone was waiting on: treq ->
    # token arrival is WanKeeper's cross-zone (root round-trip) cost
    arrived = m_acq_p & (token_zone == my_zone[:, None, None])
    adt = jnp.clip(ctx.t - m_acq_t, 0, None)
    m_lat_cross_sum = m_lat_cross_sum + jnp.sum(
        jnp.where(arrived, adt, 0), axis=(0, 1))
    m_lat_cross_n = m_lat_cross_n + jnp.sum(arrived, axis=(0, 1))
    m_acq_p = m_acq_p & ~arrived

    # treq out: a zone leader demanding a non-held object asks the root
    t_do = is_zldr[:, None] & ~held & (d_holder != my_zone[:, None])
    start_a = t_do[:, None, :] & dsel & ~m_acq_p
    m_acq_t = jnp.where(start_a, ctx.t, m_acq_t)
    m_acq_p = m_acq_p | start_a
    out_treq = {
        "valid": jnp.broadcast_to(t_do[:, None, :], (R, R, G)),
        "obj": jnp.broadcast_to(demand[:, None, :], (R, R, G)),
    }
    # rel out: the RELEASING zone's leader reports its final committed
    # version for any in-transit object it held, every step until the
    # grant lands (idempotent: the root takes the max).  The report is
    # floored at the version the token was GRANTED to this zone at
    # (gver): right after a grant the zone's ack statistic may lag
    # below the handoff version, and reporting below it would fork
    # object history at the next transfer.
    in_transit_mine = (token_zone == -1) \
        & (prev_zone == my_zone[:, None, None]) & is_zldr[:, None, None]
    rel_obj = jnp.argmax(in_transit_mine, axis=1).astype(jnp.int32)
    any_rel = jnp.any(in_transit_mine, axis=1)           # (R, G)
    rsel = oidx[None, :, None] == rel_obj[:, None, :]
    rel_ver = jnp.sum(jnp.where(rsel, committed_v, 0), axis=1)
    if gver_floor:
        rel_ver = jnp.maximum(
            rel_ver, jnp.sum(jnp.where(rsel, gver, 0), axis=1))
    rel_gen = jnp.sum(jnp.where(rsel, rgen, 0), axis=1)
    out_rel = {
        "valid": jnp.broadcast_to(any_rel[:, None, :], (R, R, G)),
        "obj": jnp.broadcast_to(rel_obj[:, None, :], (R, R, G)),
        "ver": jnp.broadcast_to(rel_ver[:, None, :], (R, R, G)),
        "gen": jnp.broadcast_to(rel_gen[:, None, :], (R, R, G)),
    }

    # self-delivery: the dense exchange has no loopback edge, and the
    # root replica can itself be a requesting/releasing zone leader —
    # fold my own treq/rel into my registries (lands next step, same as
    # a delivered message)
    self_treq = t_do[:, None, :] & dsel                  # (R, O, G)
    want = jnp.where(self_treq, my_zone[:, None, None], want)
    self_rel = any_rel[:, None, :] & rsel & (rgen == pgen) & (pgen >= 0)
    relv = jnp.where(self_rel,
                     jnp.maximum(relv, rel_ver[:, None, :]), relv)

    # ---------------- wrap-up: P3 out, retry, election, slide -----------
    out_p3 = br.p3_out(st, newly, new_execute, is_root, ctx.t)
    st = br.retry_stuck(st, new_execute, is_root, cfg.retry_timeout)
    heard = promote | acc_ok | (c_has & (c_bal >= st["ballot"]))
    st, out_p1a = br.election_tick(st, heard, ctx.rng, cfg)
    b0 = st["base"]
    st = br.slide_window(st, new_execute, RETAIN)
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)

    # in-scan linearizability spot-check over the root log (sim/inscan;
    # no register plane — WanKeeper's ver/gver tables are zone-local
    # views, not a function of the root frontier alone)
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], st["execute"], state["base"], st["base"],
        state["base"][:, None, :] + sidx[None, :, None],
        st["base"][:, None, :] + sidx[None, :, None],
        state["log_cmd"], st["log_cmd"],
        state["log_commit"], st["log_commit"],
        kv=None, lane_major=True)

    new_state = dict(
        st, token_zone=token_zone, prev_zone=prev_zone, ver=ver,
        aver=aver, want=want, relv=relv, pend=pend, pgen=pgen,
        rgen=rgen, gver=gver, viol_acc=viol_acc, writes=writes,
        transfers=transfers,
        m_wr_t=m_wr_t, m_wr_p=m_wr_p, m_acq_t=m_acq_t, m_acq_p=m_acq_p,
        m_lat_local_sum=m_lat_local_sum, m_lat_local_n=m_lat_local_n,
        m_lat_cross_sum=m_lat_cross_sum, m_lat_cross_n=m_lat_cross_n,
        m_prop_t=m_prop_t, m_lat_hist=m_lat_hist, m_lat_sum=m_lat_sum,
        m_inscan_viol=m_inscan_viol)
    outbox = {"zrep": out_zrep, "zack": out_zack, "treq": out_treq,
              "rel": out_rel, "p1a": out_p1a, "p1b": out_p1b,
              "p2a": out_p2a, "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(state["writes"]),
        "transfers": jnp.sum(jnp.max(state["transfers"], axis=0)),
        "root_execute": jnp.sum(jnp.max(state["execute"], axis=0)),
        "has_root": jnp.sum(jnp.any(state["active"], axis=0)
                            .astype(jnp.int32)),
        # zone-latency split (scenario bench axis): LOCAL = write ->
        # zone-majority commit; CROSS = treq -> grant landing (the
        # root round trip), in lock-step rounds
        "commit_lat_local_sum": jnp.sum(state["m_lat_local_sum"]),
        "commit_lat_local_n": jnp.sum(state["m_lat_local_n"]),
        "commit_lat_cross_sum": jnp.sum(state["m_lat_cross_sum"]),
        "commit_lat_cross_n": jnp.sum(state["m_lat_cross_n"]),
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": jnp.sum(state["m_lat_hist"]),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """Root-log oracle (agreement / stability / ballot / exec-committed
    — token exclusivity is a pure function of the agreed log) + object
    version monotonicity + grant monotonicity (in-kernel counter)."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    v_ver = jnp.sum(new["ver"] < old["ver"])
    v_grant = jnp.sum(new["viol_acc"] - old["viol_acc"])
    # independent of the kernel's freshness guard: the applied-grant
    # frontier itself must never regress (catches a bad state-transfer
    # merge overwriting gver)
    v_gmono = jnp.sum(new["gver"] < old["gver"])

    return (v_agree + v_stable + v_bal + v_exec
            + v_ver + v_grant + v_gmono).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="wankeeper_sw",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
