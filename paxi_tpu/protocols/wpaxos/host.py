"""WPaxos replica for the host (deployment) runtime.

Reference: paxi wpaxos/ [driver] — every key is its own Paxos object
(per-key ballot, log, and quorums); a replica whose zone's clients keep
demanding a remote key *steals* it by running phase-1 on that key's
ballot (the ballot embeds zone.node via the ballot encoding); the
``Policy`` (core/policy.py, policy.go) decides when; quorums are
flexible grids (quorum.go): phase-1 needs zone-majorities in
``Z - q2 + 1`` zones, phase-2 zone-majorities in ``q2`` zones (default
1 => steady-state commits stay zone-local — the WAN win).

The same protocol runs as a vmapped TPU kernel in ``sim.py``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from paxi_tpu.core.ballot import ballot_id, next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.policy import Policy, new_policy
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

NOOP = Command(key=-1, value=b"\x00noop")


@register_message
@dataclass
class WP1a:
    key: int
    ballot: int
    # stealer's execute frontier for the key: ackers ship their session
    # table only when ahead of it, so steady-state steals (equal
    # frontiers) pay no per-client wire cost
    execute: int = 0


@register_message
@dataclass
class WP1b:
    key: int
    ballot: int
    id: str
    # slot -> [ballot, key, value, client_id, command_id, committed]
    log: Dict[int, list] = field(default_factory=dict)
    # state transfer: sender's execute frontier + its current value for
    # the key, standing in for the executed prefix the log omits
    execute: int = 0
    snap: bytes = b""
    # at-most-once session table for this key (ADVICE r2 medium):
    # client_id -> [command_id, value] of its highest executed command
    ctab: Dict[str, list] = field(default_factory=dict)


@register_message
@dataclass
class WP2a:
    key: int
    ballot: int
    slot: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class WP2b:
    key: int
    ballot: int
    slot: int
    id: str


@register_message
@dataclass
class WP3:
    key: int
    ballot: int
    slot: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@dataclass
class Entry:
    ballot: int
    command: Command
    commit: bool = False
    request: Optional[Request] = None
    quorum: Optional[Quorum] = None


class KeyObject:
    """One per-key Paxos instance (wpaxos's paxos-object-per-key)."""

    def __init__(self):
        self.ballot = 0
        self.active = False
        self.log: Dict[int, Entry] = {}
        self.slot = -1
        self.execute = 0
        self.p1_quorum: Optional[Quorum] = None
        self.p1b_logs: Dict[ID, Dict[int, list]] = {}
        self.p1b_meta: Dict[ID, tuple] = {}   # id -> (execute, snap, ctab)
        self.pending: list = []
        # per-key at-most-once filter: a steal's frontier jump re-pends
        # uncommitted entries whose true outcome was compacted away; if
        # the old quorum in fact executed them, _exec must skip the
        # re-proposal instead of re-applying an old write over newer
        # ones.  client_id -> (highest executed command_id, value);
        # command_ids are client-monotonic, so per-key subsequences are
        # monotonic too.
        self.ctab: Dict[str, tuple] = {}


class WPaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.objs: Dict[int, KeyObject] = {}
        self.policies: Dict[int, Policy] = {}
        self.steals = 0
        z = len(cfg.zones())
        self.q2 = 1                      # phase-2 zones (paxi default)
        self.q1 = max(z - self.q2 + 1, 1)  # phase-1 zones; q1+q2 > Z
        self.register(Request, self.handle_request)
        self.register(WP1a, self.handle_p1a)
        self.register(WP1b, self.handle_p1b)
        self.register(WP2a, self.handle_p2a)
        self.register(WP2b, self.handle_p2b)
        self.register(WP3, self.handle_p3)

    def obj(self, key: int) -> KeyObject:
        if key not in self.objs:
            self.objs[key] = KeyObject()
        return self.objs[key]

    def policy(self, key: int) -> Policy:
        if key not in self.policies:
            self.policies[key] = new_policy(self.cfg.policy,
                                            self.cfg.threshold)
        return self.policies[key]

    def owner(self, o: KeyObject) -> Optional[ID]:
        return ballot_id(o.ballot) if o.ballot else None

    def owns(self, o: KeyObject) -> bool:
        return o.active and self.owner(o) == self.id

    # ---- client requests + policy --------------------------------------
    def handle_request(self, req: Request) -> None:
        k = req.command.key
        o = self.obj(k)
        if self.owns(o):
            self.propose(o, k, req)
            return
        owner = self.owner(o)
        if owner is None or owner == self.id:
            # unowned key (first toucher acquires it), or our steal is
            # already in flight: queue until phase-1 resolves
            o.pending.append(req)
            if not self.steal_in_flight(o):
                self.steal(k, o)
            return
        # owned elsewhere: my zone is demanding this key — let the policy
        # decide between forwarding and stealing (policy.go seam)
        if self.policy(k).hit(self.id.zone) == self.id.zone:
            o.pending.append(req)
            if not self.steal_in_flight(o):
                self.steal(k, o)
        else:
            self.forward(owner, req)

    def steal_in_flight(self, o: KeyObject) -> bool:
        return (o.p1_quorum is not None and not o.active
                and ballot_id(o.ballot) == self.id)

    def steal(self, k: int, o: KeyObject) -> None:
        """wpaxos steal: phase-1 on this key's ballot."""
        o.ballot = next_ballot(o.ballot, self.id)
        o.active = False
        o.p1_quorum = Quorum(self.cfg.ids)
        o.p1_quorum.ack(self.id)
        o.p1b_logs = {self.id: self._log_payload(o)}
        o.p1b_meta = {self.id: (o.execute, self.db.get(k) or b"", {})}
        self.steals += 1
        self.socket.broadcast(WP1a(k, o.ballot, o.execute))
        self._maybe_win(k, o)

    def _log_payload(self, o: KeyObject) -> Dict[int, list]:
        # O(unexecuted window): slots below the sender's execute frontier
        # are covered by the (execute, snap) state transfer in WP1b —
        # the winner adopts the max frontier's value instead of needing
        # every executed committed entry (which would otherwise let a
        # stealer NOOP over a committed, executed write)
        return {s: [e.ballot, e.command.key, e.command.value,
                    e.command.client_id, e.command.command_id, e.commit]
                for s, e in o.log.items() if s >= o.execute}

    # ---- phase 1 (steal) -----------------------------------------------
    def handle_p1a(self, m: WP1a) -> None:
        o = self.obj(m.key)
        if m.ballot > o.ballot:
            o.ballot = m.ballot
            o.active = False
            self._repend(o)
        ctab = ({c: [i, v] for c, (i, v) in o.ctab.items()}
                if o.execute > m.execute else {})  # receiver drops it else
        self.socket.send(ballot_id(m.ballot),
                         WP1b(m.key, o.ballot, str(self.id),
                              self._log_payload(o), o.execute,
                              self.db.get(m.key) or b"", ctab))

    def _repend(self, o: KeyObject) -> None:
        for e in o.log.values():
            if not e.commit and e.request is not None:
                o.pending.append(e.request)
                e.request = None
        self._drain(o)

    def handle_p1b(self, m: WP1b) -> None:
        o = self.obj(m.key)
        if m.ballot != o.ballot or o.active:
            if m.ballot > o.ballot:
                o.ballot = m.ballot
                o.active = False
            return
        if o.p1_quorum is None or ballot_id(o.ballot) != self.id:
            return
        o.p1_quorum.ack(ID(m.id))
        o.p1b_logs[ID(m.id)] = m.log
        o.p1b_meta[ID(m.id)] = (m.execute, m.snap, m.ctab)
        self._maybe_win(m.key, o)

    def _maybe_win(self, k: int, o: KeyObject) -> None:
        if o.p1_quorum is None or not o.p1_quorum.grid_q1(self.q1):
            return
        # adopted: merge P1b logs exactly like single-leader recovery
        o.active = True
        o.p1_quorum = None
        # state transfer first: any acker ahead of our execute frontier
        # has executed (hence committed) everything below its frontier —
        # adopt its KV value and jump our frontier there, so the merge
        # below never NOOP-fills an executed slot
        front, snap, ctab = max(o.p1b_meta.values(),
                                key=lambda t: t[0], default=(0, b"", {}))
        if front > o.execute:
            # adopt the acker's session table before re-pending, so a
            # skipped command the old quorum already executed is
            # filtered by _exec rather than applied a second time
            for c, (i, v) in ctab.items():
                if c not in o.ctab or o.ctab[c][0] < int(i):
                    o.ctab[c] = (int(i), v)
            # same request handling as paxos host's frontier jump:
            # re-pend skipped uncommitted entries; committed ones get
            # acks for writes, the snapshot value for reads
            for s in range(o.execute, front):
                e = o.log.get(s)
                if e is None or e.request is None:
                    continue
                if e.commit:
                    v = snap if e.command.is_read() else b""
                    e.request.reply(Reply(e.command, value=v))
                else:
                    o.pending.append(e.request)
                e.request = None
            if snap:
                self.db.put(k, snap)
            o.execute = front
            o.slot = max(o.slot, front - 1)
        merged: Dict[int, tuple] = {}
        top = o.slot
        for log in o.p1b_logs.values():
            for s_raw, (bal, key, value, cid, cmid, committed) in log.items():
                s = int(s_raw)
                top = max(top, s)
                cmd = Command(int(key), value, cid, int(cmid))
                cur = merged.get(s)
                if committed:
                    merged[s] = (bal, cmd, True)
                elif cur is None or (not cur[2] and bal > cur[0]):
                    merged[s] = (bal, cmd, False)
        for s in range(o.execute, top + 1):
            bal, cmd, committed = merged.get(s, (0, NOOP, False))
            prev = o.log.get(s)
            req = prev.request if prev else None
            if prev is not None and prev.commit:
                continue
            if committed:
                o.log[s] = Entry(bal, cmd, commit=True, request=req)
            else:
                self.propose(o, k, req, command=cmd, at_slot=s)
        o.slot = max(o.slot, top)
        self._exec(k, o)
        self._drain(o)

    def _drain(self, o: KeyObject) -> None:
        pending, o.pending = o.pending, []
        for req in pending:
            self.handle_request(req)

    # ---- phase 2 -------------------------------------------------------
    def propose(self, o: KeyObject, k: int, req: Optional[Request],
                command: Optional[Command] = None,
                at_slot: Optional[int] = None) -> None:
        cmd = command if command is not None else req.command
        if at_slot is None:
            o.slot += 1
            slot = o.slot
        else:
            slot = at_slot
            o.slot = max(o.slot, slot)
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        o.log[slot] = Entry(o.ballot, cmd, request=req, quorum=q)
        self.socket.broadcast(WP2a(k, o.ballot, slot, cmd.value,
                                   cmd.client_id, cmd.command_id))
        if q.grid_q2(self.q2):  # one-node zones
            self._commit(k, o, slot)

    def handle_p2a(self, m: WP2a) -> None:
        o = self.obj(m.key)
        if m.ballot >= o.ballot:
            if m.ballot > o.ballot:
                o.ballot = m.ballot
                o.active = False
                self._repend(o)
            e = o.log.get(m.slot)
            if e is None or (not e.commit and m.ballot >= e.ballot):
                req = e.request if e else None
                o.log[m.slot] = Entry(
                    m.ballot, Command(m.key, m.value, m.client_id,
                                      m.command_id), request=req)
            o.slot = max(o.slot, m.slot)
        self.socket.send(ballot_id(m.ballot),
                         WP2b(m.key, o.ballot, m.slot, str(self.id)))

    def handle_p2b(self, m: WP2b) -> None:
        o = self.obj(m.key)
        if m.ballot > o.ballot:
            o.ballot = m.ballot
            o.active = False
            self._repend(o)
            return
        e = o.log.get(m.slot)
        if (o.active and e is not None and not e.commit
                and m.ballot == o.ballot == e.ballot
                and e.quorum is not None):
            e.quorum.ack(ID(m.id))
            if e.quorum.grid_q2(self.q2):   # zone-local commit quorum
                self._commit(m.key, o, m.slot)

    def _commit(self, k: int, o: KeyObject, slot: int) -> None:
        e = o.log[slot]
        e.commit = True
        c = e.command
        self.socket.broadcast(WP3(k, o.ballot, slot, c.value,
                                  c.client_id, c.command_id))
        self._exec(k, o)

    def handle_p3(self, m: WP3) -> None:
        o = self.obj(m.key)
        e = o.log.get(m.slot)
        req = e.request if e else None
        o.log[m.slot] = Entry(m.ballot, Command(m.key, m.value, m.client_id,
                                                m.command_id),
                              commit=True, request=req)
        o.slot = max(o.slot, m.slot)
        self._exec(m.key, o)
        self._drain(o)

    def _exec(self, k: int, o: KeyObject) -> None:
        while True:
            e = o.log.get(o.execute)
            if e is None or not e.commit:
                break
            if e.command.key >= 0:
                cmd = e.command
                last = o.ctab.get(cmd.client_id) if cmd.client_id else None
                if last is not None and cmd.command_id <= last[0]:
                    # at-most-once: already executed (possibly in a
                    # compacted slot under a previous owner)
                    value = last[1] if cmd.command_id == last[0] else b""
                else:
                    value = self.db.execute(cmd)
                    if cmd.client_id:
                        o.ctab[cmd.client_id] = (cmd.command_id, value)
                if e.request is not None:
                    e.request.reply(Reply(e.command, value=value))
                    e.request = None
            elif e.request is not None:
                e.request.reply(Reply(e.command, err="noop"))
                e.request = None
            o.execute += 1


def new_replica(id: ID, cfg: Config) -> WPaxosReplica:
    return WPaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Wire-level identity: the sim kernel's
# five mailbox planes are the host runtime's five message classes
# (per-key ballots ride inside the payload on both sides).
TRACE_MSG_MAP = {
    "p1a": "WP1a", "p1b": "WP1b", "p2a": "WP2a", "p2b": "WP2b",
    "p3": "WP3",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.  The
# sim's per-object planes correspond to per-key ``KeyObject``
# aggregates on the host.
SIM_STATE_MAP = {
    "log_bal":     "log",        # per-object ring planes <-> KeyObject.log
    "log_cmd":     "log",
    "log_commit":  "log",
    "log_acks":    "log",        # P2b bitmask <-> Entry.quorum
    "next_slot":   "slot",
    "kv":          "db",
    "p1_acks":     "p1_quorum",  # in-flight steal ack bitmask
    "hits":        "policies",   # demand counters <-> Policy state
    "steal_obj":   "steals",     # in-flight steal target; completed count
    "base":        "",  # ring-window base: host logs are unbounded dicts
    "proposed":    "",  # own-ballot P2a mask: implied by Entry existence
    "steal_timer": "",  # steal retry step-timer: host retries are wall-clock
    # zone-latency accounting (scenario bench axis) — measurement
    # planes, not protocol state; excluded from the trace witness hash
    "m_prop_t":        "",
    "m_lat_local_sum": "",
    "m_lat_local_n":   "",
    "m_lat_cross_sum": "",
    "m_lat_cross_n":   "",
    # on-device commit-latency histogram + in-scan spot-check (PR 11)
    # — the host-side twin is the registry's live latency histograms
    # and the post-hoc linearizability checker, not node state
    "m_lat_hist":      "",
    "m_lat_sum":       "",
    "m_inscan_viol":   "",
}
