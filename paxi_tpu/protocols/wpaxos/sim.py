"""WPaxos — multi-leader WAN Paxos with object stealing, as a TPU kernel.

Reference: paxi wpaxos/ [driver] — every key is a separate Paxos object
whose ballot embeds the owning zone/node; a zone *steals* an object by
running phase-1 on that object's ballot when the access policy
(policy.go, ``Config.Policy``/``Threshold``) says its clients dominate;
quorums are flexible grids (quorum.go): phase-1 needs zone-majorities in
``Z - q2 + 1`` zones, phase-2 only in ``q2`` zones (q2=1 => steady-state
commits stay inside the owner's zone — the WAN latency win the paper
dissects).  BASELINE config: 3x3 zone grid, locality-skewed workload.

TPU re-design (not a translation):
- Replicas r in 0..R-1 are arranged in Z zones of R/Z nodes,
  ``zone(r) = r // (R/Z)``.
- Per-object per-replica log SoA: ``log_{bal,cmd,commit}[R, O, S]`` and
  a 4-D phase-2 ack matrix ``log_acks[R, O, S, R]``; quorum tests are
  zone-segmented popcounts (zone-majority per zone, then >= q1 / q2
  zones).
- The workload generator is in-kernel: each replica demands one object
  per step, drawn home-zone-biased (``cfg.locality``).  Owners propose
  for the demanded object; non-owners accumulate per-object demand
  (``hits``) — the requester-side form of policy.go's counters — and
  fire a phase-1 steal at ``steal_threshold``.
- At most one steal is in flight per replica (``steal_obj``); P1b acks
  are merged with the same by-reference log-merge argument as the
  paxos kernel (acceptor logs only grow in ballot).
- All handlers are fully masked; messages for *different* objects from
  different sources in the same step are all applied via dense
  (dst, src, O) one-hot scatters, per-(dst, obj) max-ballot selected.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1
NOOP = -2


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("obj", "bal"),
        "p1b": ("obj", "bal"),
        "p2a": ("obj", "bal", "slot", "cmd"),
        "p2b": ("obj", "bal", "slot"),
        "p3": ("obj", "bal", "slot", "cmd", "upto"),
    }


def encode_cmd(bal, slot):
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def _zone_of(ridx, npz):
    return ridx // npz


def _zone_quorums(acks, cfg: SimConfig):
    """acks: (..., R) boolean -> (...,) count of zones with a
    zone-majority of acks (the flexible-grid primitive, quorum.go)."""
    Z = cfg.n_zones
    npz = cfg.n_replicas // Z
    per_zone = jnp.sum(acks.reshape(acks.shape[:-1] + (Z, npz)), axis=-1)
    return jnp.sum(per_zone >= (npz // 2 + 1), axis=-1)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, O, S = cfg.n_replicas, cfg.n_objects, cfg.n_slots
    del rng
    ridx = jnp.arange(R, dtype=jnp.int32)
    oidx = jnp.arange(O, dtype=jnp.int32)
    owner0 = oidx % R                      # initial round-robin ownership
    return dict(
        # per-object ballots: round 1, owner0 (everyone agrees at init)
        ballot=jnp.broadcast_to(cfg.ballot_stride + owner0[None, :],
                                (R, O)).astype(jnp.int32),
        active=(ridx[:, None] == owner0[None, :]),
        log_bal=jnp.zeros((R, O, S), jnp.int32),
        log_cmd=jnp.full((R, O, S), NO_CMD, jnp.int32),
        log_commit=jnp.zeros((R, O, S), bool),
        log_acks=jnp.zeros((R, O, S, R), bool),
        proposed=jnp.zeros((R, O, S), bool),
        next_slot=jnp.zeros((R, O), jnp.int32),
        execute=jnp.zeros((R, O), jnp.int32),
        kv=jnp.zeros((R, O), jnp.int32),       # object register (last cmd)
        hits=jnp.zeros((R, O), jnp.int32),     # policy demand counters
        steal_obj=jnp.full((R,), -1, jnp.int32),
        p1_acks=jnp.zeros((R, R), bool),       # for the in-flight steal
        steal_timer=jnp.zeros((R,), jnp.int32),
        steals=jnp.zeros((), jnp.int32),       # completed steals (metric)
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, O, S = cfg.n_replicas, cfg.n_objects, cfg.n_slots
    Z, STRIDE = cfg.n_zones, cfg.ballot_stride
    npz = R // Z
    Q1 = Z - cfg.grid_q2 + 1
    Q2 = cfg.grid_q2
    ridx = jnp.arange(R, dtype=jnp.int32)
    oidx = jnp.arange(O, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)

    ballot = state["ballot"]          # (R, O)
    active = state["active"]
    log_bal = state["log_bal"]        # (R, O, S)
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]      # (R, O, S, R)
    proposed = state["proposed"]
    next_slot = state["next_slot"]    # (R, O)
    execute = state["execute"]
    kv = state["kv"]
    hits = state["hits"]
    steal_obj = state["steal_obj"]    # (R,)
    p1_acks = state["p1_acks"]        # (R, R)
    steals = state["steals"]

    def per_obj_best(m, extra=()):
        """Select, per (dst, obj), the max-ballot message among sources.

        Returns (has, bal, *extra_fields) each of shape (R, O)."""
        v = jnp.transpose(m["valid"])                  # (dst, src)
        ob = jnp.transpose(m["obj"])
        bl = jnp.transpose(m["bal"])
        onehot = v[:, :, None] & (ob[:, :, None] == oidx[None, None, :])
        b3 = jnp.where(onehot, bl[:, :, None], -1)     # (dst, src, O)
        src_best = jnp.argmax(b3, axis=1)              # (dst, O)
        bal_best = jnp.max(b3, axis=1)
        has = bal_best > 0

        def pick(f):
            f3 = jnp.broadcast_to(jnp.transpose(m[f])[:, :, None], b3.shape)
            return jnp.take_along_axis(f3, src_best[:, None, :],
                                       axis=1)[:, 0, :]

        return has, bal_best, src_best, [pick(f) for f in extra]

    # ---------------- P1a: promise to higher per-object ballots ---------
    m = inbox["p1a"]
    has1, b1, src1, _ = per_obj_best(m)
    promote = has1 & (b1 > ballot)                     # (dst, O)
    ballot = jnp.where(promote, b1, ballot)
    active = active & ~promote
    # a promoted object kills my own in-flight steal of it
    my_steal_oh = (steal_obj[:, None] == oidx[None, :])
    steal_killed = jnp.any(promote & my_steal_oh, axis=1)
    steal_obj = jnp.where(steal_killed, -1, steal_obj)
    # P1b back to the (single) best stealer per promoted object; a replica
    # can promote several objects in one step but the mailbox holds one
    # p1b per edge — reply for the highest-ballot promoted object
    # (stealers retry via steal_timer, so serializing here is safe)
    pb = jnp.where(promote, b1, -1)
    best_o = jnp.argmax(pb, axis=1)                    # (dst,)
    any_p = jnp.any(promote, axis=1)
    to_src = src1[ridx, best_o]
    out_p1b = {
        "valid": any_p[:, None] & (ridx[None, :] == to_src[:, None]),
        "obj": jnp.broadcast_to(best_o[:, None].astype(jnp.int32), (R, R)),
        "bal": jnp.broadcast_to(ballot[ridx, best_o][:, None], (R, R)),
    }

    # ---------------- P1b: stealer tallies grid-quorum acks -------------
    m = inbox["p1b"]
    v = jnp.transpose(m["valid"])                      # (me, src)
    ob = jnp.transpose(m["obj"])
    bl = jnp.transpose(m["bal"])
    my_obj = steal_obj[:, None]
    my_bal = ballot[ridx, jnp.clip(steal_obj, 0, O - 1)][:, None]
    ack = v & (ob == my_obj) & (bl == my_bal) & (steal_obj >= 0)[:, None]
    p1_acks = p1_acks | ack
    zq = _zone_quorums(p1_acks, cfg)                   # (me,)
    p1_win = (steal_obj >= 0) & (zq >= Q1)

    # ---------------- steal win: adopt object, merge ackers' logs -------
    so = jnp.clip(steal_obj, 0, O - 1)
    win_oh = p1_win[:, None] & (oidx[None, :] == so[:, None])   # (R, O)
    amask = p1_acks                                    # (me, src)
    # merge the stolen object's log across ackers (by reference)
    lb_o = log_bal[:, so, :].transpose(1, 0, 2)        # (me, src, S) ... no:
    # log_bal[src, so[me], slot] -> build via take: for each me, object so[me]
    lb = jnp.take(log_bal, so, axis=1)                 # (src, me, S)
    lb = jnp.transpose(lb, (1, 0, 2))                  # (me, src, S)
    lc = jnp.transpose(jnp.take(log_cmd, so, axis=1), (1, 0, 2))
    lk = jnp.transpose(jnp.take(log_commit, so, axis=1), (1, 0, 2))
    lbm = jnp.where(amask[:, :, None], lb, -1)
    src_best = jnp.argmax(lbm, axis=1)                 # (me, S)
    best_bal = jnp.max(lbm, axis=1)
    merged_cmd = jnp.take_along_axis(lc, src_best[:, None, :], axis=1)[:, 0]
    cmask = amask[:, :, None] & lk
    merged_commit = jnp.any(cmask, axis=1)
    csrc = jnp.argmax(cmask, axis=1)
    committed_cmd = jnp.take_along_axis(lc, csrc[:, None, :], axis=1)[:, 0]
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, sidx[None, :] + 1, 0), axis=1)  # (me,)
    my_next = next_slot[ridx, so]
    new_next = jnp.maximum(my_next, top)
    in_win = sidx[None, :] < new_next[:, None]         # (me, S)
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    w3 = win_oh[:, :, None]                            # (R, O, 1)
    iw3 = in_win[:, None, :]                           # (R, 1, S)
    my_bal2 = ballot[ridx, so]                         # (me,)
    log_cmd = jnp.where(w3 & iw3, adopt_cmd[:, None, :], log_cmd)
    log_bal = jnp.where(w3 & iw3, my_bal2[:, None, None], log_bal)
    log_commit = jnp.where(w3 & iw3,
                           merged_commit[:, None, :] | log_commit,
                           log_commit)
    keep = merged_commit[:, None, :] | jnp.take_along_axis(
        log_commit, so[:, None, None] * jnp.ones((1, 1, S), jnp.int32),
        axis=1)[:, 0][:, None, :]
    proposed = jnp.where(w3, iw3 & keep, proposed)
    self_only = ridx[None, None, None, :] == ridx[:, None, None, None]
    log_acks = jnp.where(w3[..., None], iw3[..., None] & self_only,
                         log_acks)
    next_slot = jnp.where(win_oh, new_next[:, None], next_slot)
    active = active | win_oh
    steals = steals + jnp.sum(p1_win)
    steal_obj = jnp.where(p1_win, -1, steal_obj)
    p1_acks = p1_acks & ~p1_win[:, None]

    own = (ballot % STRIDE) == ridx[:, None]           # (R, O)

    # ---------------- P2a: accept from the highest-ballot owner ---------
    m = inbox["p2a"]
    has2, b2, src2, (slot2, cmd2) = per_obj_best(m, ("slot", "cmd"))
    acc_ok = has2 & (b2 >= ballot)                     # (dst, O)
    demote = acc_ok & (b2 > ballot)
    ballot = jnp.where(acc_ok, b2, ballot)
    active = active & ~demote
    sk = jnp.any(demote & my_steal_oh, axis=1)
    steal_obj = jnp.where(sk, -1, steal_obj)
    oh = (acc_ok[:, :, None] & (sidx[None, None, :] == slot2[:, :, None]))
    writable = oh & (log_bal <= b2[:, :, None]) & ~log_commit
    log_bal = jnp.where(writable, b2[:, :, None], log_bal)
    log_cmd = jnp.where(writable, cmd2[:, :, None], log_cmd)
    # p2b back to the accepted object's owner — one per edge; pick the
    # highest-ballot accepted object per destination owner is overkill:
    # since each owner proposes one object per step, per (dst, src-owner)
    # there is at most one accepted p2a => reply on that edge directly
    v2 = jnp.transpose(m["valid"])                     # (dst, src)
    ob2 = jnp.transpose(m["obj"])
    # accepted mask per (dst, src): the p2a on this edge was the winner
    win_edge = (v2 & (jnp.take_along_axis(acc_ok, ob2, axis=1))
                & (jnp.take_along_axis(src2, ob2, axis=1)
                   == ridx[None, :]))
    out_p2b = {
        "valid": win_edge,
        "obj": ob2,
        "bal": jnp.transpose(m["bal"]),
        "slot": jnp.transpose(m["slot"]),
    }

    own = (ballot % STRIDE) == ridx[:, None]

    # ---------------- P2b: owner tallies zone-grid acks, commits --------
    m = inbox["p2b"]
    v = jnp.transpose(m["valid"])                      # (own, src)
    ob = jnp.transpose(m["obj"])
    bl = jnp.transpose(m["bal"])
    sl = jnp.transpose(m["slot"])
    my_b = jnp.take_along_axis(ballot, ob, axis=1)     # (own, src)
    my_act = jnp.take_along_axis(active & own, ob, axis=1)
    okb = v & (bl == my_b) & my_act
    add = (okb[:, :, None, None]
           & (ob[:, :, None, None] == oidx[None, None, :, None])
           & (sl[:, :, None, None] == sidx[None, None, None, :]))
    log_acks = log_acks | jnp.transpose(add, (0, 2, 3, 1))  # (own, O, S, src)
    zq2 = _zone_quorums(log_acks, cfg)                 # (own, O, S)
    newly = ((active & own)[:, :, None] & (zq2 >= Q2)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly

    # ---------------- P3: commit notifications --------------------------
    m = inbox["p3"]
    has3, b3_, src3, (slot3, cmd3, upto3) = per_obj_best(
        m, ("slot", "cmd", "upto"))
    oh = has3[:, :, None] & (sidx[None, None, :] == slot3[:, :, None])
    log_cmd = jnp.where(oh, cmd3[:, :, None], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, b3_[:, :, None]), log_bal)
    log_commit = log_commit | oh
    ohu = (has3[:, :, None] & (sidx[None, None, :] < upto3[:, :, None])
           & (log_bal == b3_[:, :, None]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- workload: demand one object per step --------------
    # locality-skewed demand: each replica mostly touches its own block
    # of "home" objects (modeling paxi's zone-routed clients; when O < R
    # several replicas share a home object, giving steady contention)
    k_d, k_loc, k_jit = jr.split(ctx.rng, 3)
    blk = max(O // R, 1)
    home = (ridx * blk + jr.randint(k_d, (R,), 0, blk)) % O
    anywhere = jr.randint(jr.fold_in(k_d, 1), (R,), 0, O)
    local = jr.bernoulli(k_loc, cfg.locality, (R,))
    demand = jnp.where(local, home, anywhere).astype(jnp.int32)

    # ---------------- owner proposes for the demanded object ------------
    d_oh = oidx[None, :] == demand[:, None]            # (R, O)
    is_owner_d = jnp.any(d_oh & active & own, axis=1)
    d = demand
    d_bal = ballot[ridx, d]
    d_next = next_slot[ridx, d]
    # re-propose the first unfinished slot if any, else a new one
    mask_re = (~jnp.take_along_axis(
        log_commit, d[:, None, None] * jnp.ones((1, 1, S), jnp.int32),
        axis=1)[:, 0]) & (~jnp.take_along_axis(
            proposed, d[:, None, None] * jnp.ones((1, 1, S), jnp.int32),
            axis=1)[:, 0]) & (sidx[None, :] < d_next[:, None])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :], S), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = d_next < S
    prop_slot = jnp.where(has_re, first_re, d_next).astype(jnp.int32)
    new_cmd = encode_cmd(d_bal, prop_slot)
    re_cmd = log_cmd[ridx, d, jnp.clip(prop_slot, 0, S - 1)]
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(has_re, re_cmd, new_cmd)
    do = is_owner_d & (has_re | can_new)
    p_oh = (do[:, None, None] & d_oh[:, :, None]
            & (sidx[None, None, :] == prop_slot[:, None, None]))
    log_bal = jnp.where(p_oh, d_bal[:, None, None], log_bal)
    log_cmd = jnp.where(p_oh & ~log_commit, prop_cmd[:, None, None], log_cmd)
    proposed = proposed | p_oh
    log_acks = log_acks | (p_oh[..., None] & self_only)
    next_slot = next_slot + (do & ~has_re)[:, None] * d_oh
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None], (R, R)),
        "obj": jnp.broadcast_to(d[:, None], (R, R)),
        "bal": jnp.broadcast_to(d_bal[:, None], (R, R)),
        "slot": jnp.broadcast_to(prop_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None], (R, R)),
    }

    # ---------------- policy: count misses, fire steals ------------------
    miss = d_oh & ~(active & own)                      # demanded, not owned
    # consecutive policy (policy.go): the counter survives only while
    # the replica keeps demanding the same unowned object
    hits = jnp.where(miss, hits + 1, 0)
    # fire a steal for the hottest over-threshold object when idle
    can_steal = (steal_obj < 0)
    hot = jnp.max(hits, axis=1)
    hot_obj = jnp.argmax(hits, axis=1).astype(jnp.int32)
    fire = can_steal & (hot >= cfg.steal_threshold)
    new_bal = (jnp.max(ballot, axis=1) // STRIDE + 1) * STRIDE + ridx
    f_oh = fire[:, None] & (oidx[None, :] == hot_obj[:, None])
    ballot = jnp.where(f_oh, new_bal[:, None], ballot)
    active = active & ~f_oh
    steal_obj = jnp.where(fire, hot_obj, steal_obj)
    p1_acks = jnp.where(fire[:, None], ridx[None, :] == ridx[:, None],
                        p1_acks)
    hits = jnp.where(f_oh, 0, hits)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None], (R, R)),
        "obj": jnp.broadcast_to(hot_obj[:, None], (R, R)),
        "bal": jnp.broadcast_to(new_bal[:, None], (R, R)),
    }
    # stalled steal: retry (rebump) after a timeout
    steal_timer = jnp.where(steal_obj >= 0, state["steal_timer"] + 1,
                            0)
    timeout = steal_timer >= cfg.election_timeout + \
        jr.randint(k_jit, (R,), 0, cfg.backoff + 1)
    steal_obj = jnp.where(timeout, -1, steal_obj)      # give up; re-fire later
    steal_timer = jnp.where(timeout, 0, steal_timer)

    # ---------------- execute committed prefixes ------------------------
    advanced = jnp.zeros((R, O), jnp.int32)
    running = jnp.ones((R, O), bool)
    for e in range(cfg.exec_window):
        idx = jnp.clip(execute + e, 0, S - 1)
        inb = (execute + e) < S
        com = jnp.take_along_axis(log_commit, idx[:, :, None],
                                  axis=2)[..., 0]
        running = running & com & inb
        cmd_e = jnp.take_along_axis(log_cmd, idx[:, :, None],
                                    axis=2)[..., 0]
        wr = running & (cmd_e >= 0)
        kv = jnp.where(wr, cmd_e, kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- P3 out: per owner, its demanded object ------------
    any_new_d = jnp.take_along_axis(jnp.any(newly, axis=2), d[:, None],
                                    axis=1)[:, 0]
    low_new = jnp.argmin(jnp.where(
        jnp.take_along_axis(newly, d[:, None, None]
                            * jnp.ones((1, 1, S), jnp.int32),
                            axis=1)[:, 0], sidx[None, :], S), axis=1)
    my_exec_d = new_execute[ridx, d]
    rr = ctx.t % jnp.maximum(my_exec_d, 1)
    p3_slot = jnp.where(any_new_d, low_new, rr).astype(jnp.int32)
    p3_slot = jnp.clip(p3_slot, 0, S - 1)
    p3_committed = log_commit[ridx, d, p3_slot]
    p3_cmd = log_cmd[ridx, d, p3_slot]
    p3_do = (active & own)[ridx, d] & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None], (R, R)),
        "obj": jnp.broadcast_to(d[:, None], (R, R)),
        "bal": jnp.broadcast_to(d_bal[:, None], (R, R)),
        "slot": jnp.broadcast_to(p3_slot[:, None], (R, R)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None], (R, R)),
        "upto": jnp.broadcast_to(my_exec_d[:, None], (R, R)),
    }

    new_state = dict(
        ballot=ballot, active=active, log_bal=log_bal, log_cmd=log_cmd,
        log_commit=log_commit, log_acks=log_acks, proposed=proposed,
        next_slot=next_slot, execute=new_execute, kv=kv, hits=hits,
        steal_obj=steal_obj, p1_acks=p1_acks, steal_timer=steal_timer,
        steals=steals,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "steals": state["steals"],
        "owned_objects": jnp.sum(state["active"]).astype(jnp.int32),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Agreement per (object, slot); 2. commit stability; 3. per-
    (replica, object) ballot monotonicity; 4. executed prefix committed;
    5. single ownership: at most one active owner per object."""
    BIG = jnp.int32(2**30)
    c, cmd = new["log_commit"], new["log_cmd"]
    mx = jnp.max(jnp.where(c, cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(c, cmd, BIG), axis=0)
    n_c = jnp.sum(c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    was = old["log_commit"]
    v_stable = jnp.sum(was & (~c | (cmd != old["log_cmd"])))

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    prefix_len = jnp.sum(jnp.cumprod(c.astype(jnp.int32), axis=2), axis=2)
    v_exec = jnp.sum(new["execute"] > prefix_len)

    # two active replicas owning the same object at the same ballot round
    # would be a stolen-twice bug; different ballots are a transient
    own = new["active"]
    bal = jnp.where(own, new["ballot"], -1)
    same = (own[:, None, :] & own[None, :, :]
            & (bal[:, None, :] == bal[None, :, :])
            & (jnp.arange(cfg.n_replicas)[:, None, None]
               != jnp.arange(cfg.n_replicas)[None, :, None]))
    v_own = jnp.sum(same) // 2

    return (v_agree + v_stable + v_bal + v_exec + v_own).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="wpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
