"""WPaxos — multi-leader WAN Paxos with object stealing, as a TPU kernel.

Reference: paxi wpaxos/ [driver] — every key is a separate Paxos object
whose ballot embeds the owning zone/node; a zone *steals* an object by
running phase-1 on that object's ballot when the access policy
(policy.go, ``Config.Policy``/``Threshold``) says its clients dominate;
quorums are flexible grids (quorum.go): phase-1 needs zone-majorities in
``Z - q2 + 1`` zones, phase-2 only in ``q2`` zones (q2=1 => steady-state
commits stay inside the owner's zone — the WAN latency win the paper
dissects).  BASELINE config: 3x3 zone grid, locality-skewed workload.

TPU re-design (not a translation):
- **Lane-major batch layout** (see sim/lanes.py): state ``(R, O, G)`` /
  ``(R, O, S, G)``, mailbox planes ``(src, dst, G)`` — the group axis
  feeds the 8x128 vector lanes.
- Replicas r in 0..R-1 are arranged in Z zones of R/Z nodes,
  ``zone(r) = r // (R/Z)``.
- Per-object per-replica log SoA over a **fixed-cell ring** of S slots
  (sim/cell.py): absolute slot ``a`` lives at cell ``a % S`` forever;
  each (replica, object) window ``[base[r, o], base[r, o] + S)`` slides
  with its execute frontier as a masked clear of recycled cells —
  no per-step ``shift_window`` alignment gathers (SURVEY §7 slot
  recycling — unbounded horizon; the frozen sliding-window kernel
  survives as ``sim_sw.py``, bit-canonical equivalence pinned in
  tests/test_fixed_cell_equiv.py).  Messages carry absolute slots;
  acceptors ack only what they durably stored.
- ``Quorum.ACK`` is a **bit-packed int32 ack mask** per (owner, object,
  slot); grid-quorum tests are per-zone popcounts over bit ranges
  (zone-majority per zone, then >= q1 / q2 zones — quorum.go).
- The workload generator is in-kernel: each replica demands one object
  per step, drawn home-zone-biased (``cfg.locality``) with one shaped
  draw per plane from the step key.  Owners propose for the demanded
  object; non-owners accumulate per-object demand (``hits``) — the
  requester-side form of policy.go's counters — and fire a phase-1
  steal at ``steal_threshold``.
- At most one steal is in flight per replica (``steal_obj``); P1b acks
  are merged with the same by-reference log-merge argument as the
  paxos kernel (acceptor logs only grow in ballot), base-aligned to
  the max acker base so no committed entry is ever dropped.
- P3 carries the owner's window base (``lowslot``): a replica whose
  frontier fell below it adopts the owner's object row (log, base,
  execute, register) by reference — snapshot catch-up for laggards.
- All handlers are fully masked; messages for *different* objects from
  different sources in the same step are all applied via dense
  (dst, obj) one-hot scatters, per-(dst, obj) max-ballot selected.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.metrics import lathist
from paxi_tpu.sim import cell, inscan
from paxi_tpu.sim.ring import dst_major, require_packable
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx
from paxi_tpu.workload import compile as wlc
from paxi_tpu.workload.spec import CLASSES

NO_CMD = -1
NOOP = -2


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("obj", "bal"),
        "p1b": ("obj", "bal"),
        "p2a": ("obj", "bal", "slot", "cmd"),
        "p2b": ("obj", "bal", "slot"),
        "p3": ("obj", "bal", "slot", "cmd", "upto", "lowslot"),
    }


def encode_cmd(bal, slot):
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def _zone_quorums(acks, cfg: SimConfig):
    """acks: (...) int32 bit-packed over replicas -> (...) count of
    zones holding a zone-majority of acks (the flexible-grid primitive,
    quorum.go)."""
    Z = cfg.n_zones
    npz = cfg.n_replicas // Z
    zmaj = npz // 2 + 1
    cnt = jnp.zeros(acks.shape, jnp.int32)
    for z in range(Z):
        zmask = jnp.int32(((1 << npz) - 1) << (z * npz))
        per = jax.lax.population_count(acks & zmask)
        cnt = cnt + (per >= zmaj)
    return cnt


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, O, S, G = cfg.n_replicas, cfg.n_objects, cfg.n_slots, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    ridx = jnp.arange(R, dtype=i32)
    oidx = jnp.arange(O, dtype=i32)
    owner0 = oidx % R                      # initial round-robin ownership
    st = dict(
        # per-object ballots: round 1, owner0 (everyone agrees at init)
        ballot=jnp.broadcast_to(
            (cfg.ballot_stride + owner0)[None, :, None], (R, O, G)
        ).astype(i32),
        active=jnp.broadcast_to(
            (ridx[:, None] == owner0[None, :])[..., None], (R, O, G)),
        log_bal=jnp.zeros((R, O, S, G), i32),
        log_cmd=jnp.full((R, O, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, O, S, G), bool),
        log_acks=jnp.zeros((R, O, S, G), i32),   # bit-packed over src
        proposed=jnp.zeros((R, O, S, G), bool),
        base=jnp.zeros((R, O, G), i32),          # abs slot of ring pos 0
        next_slot=jnp.zeros((R, O, G), i32),     # absolute
        execute=jnp.zeros((R, O, G), i32),       # absolute frontier
        kv=jnp.zeros((R, O, G), i32),      # object register (last cmd)
        hits=jnp.zeros((R, O, G), i32),    # policy demand counters
        steal_obj=jnp.full((R, G), -1, i32),
        p1_acks=jnp.zeros((R, G), i32),    # bit-packed, in-flight steal
        steal_timer=jnp.zeros((R, G), i32),
        steals=jnp.zeros((G,), i32),       # completed steals (metric)
        # ---- zone-latency accounting (scenario bench axis) ----------
        # measurement planes, ``m_`` prefix = excluded from the trace
        # witness hash (trace/replay.state_hash) — pure read-side
        # accounting that never feeds a transition.  m_prop_t records
        # each slot's FIRST propose step; commits split into
        # zone-local (the owner's own zone alone satisfied the grid
        # quorum) vs cross-zone, accumulating propose->commit step
        # latencies — the Cloud paper's headline split.
        m_prop_t=jnp.zeros((R, O, S, G), i32),
        m_lat_local_sum=jnp.zeros((G,), i32),
        m_lat_local_n=jnp.zeros((G,), i32),
        m_lat_cross_sum=jnp.zeros((G,), i32),
        m_lat_cross_n=jnp.zeros((G,), i32),
        # commit-latency histogram + in-scan spot-check (PR-11 layer;
        # same bucket layout as every kernel — metrics/lathist)
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )
    if cfg.workload is not None:
        # GLOBAL group ids for the workload's counter-based demand
        # draws (parallel/mesh.py offsets them per shard); per-class
        # latency planes labeled by the demanded OBJECT's resident key
        # class (workload/compile.obj_class_table)
        st["wl_gid"] = jnp.arange(G, dtype=i32)
        for nm in CLASSES:
            st[f"m_wl_hist_{nm}"] = lathist.empty_hist(G)
            st[f"m_wl_sum_{nm}"] = jnp.zeros((G,), i32)
    return st


def step(state, inbox, ctx: StepCtx, q1_full: bool = True):
    """``q1_full=False`` is the SEEDED BUG twin (PROTOCOL_THINQ1): the
    steal's phase-1 grid quorum is one zone too thin (``Z - q2``
    instead of ``Z - q2 + 1``), so a stealer's read set can MISS the
    old owner's write zone entirely (with q2=1 commits live in one
    zone) and re-propose over chosen entries — the flexible-quorum
    intersection break.  WAN geo-latency scenarios are exactly what
    exposes it: cross-zone delays widen the in-flight phase-1 window,
    so racing steals with disjoint-enough read sets actually happen.
    It exists so the scenario engine has a real, capturable wpaxos
    witness to minimize; never soak it as a correctness case."""
    cfg = ctx.cfg
    R, O, S = cfg.n_replicas, cfg.n_objects, cfg.n_slots
    Z, STRIDE = cfg.n_zones, cfg.ballot_stride
    Q1 = Z - cfg.grid_q2 + (1 if q1_full else 0)
    Q2 = cfg.grid_q2
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    oidx = jnp.arange(O, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    self_bit2 = (jnp.int32(1) << ridx)[:, None]          # (R, 1)

    ballot = state["ballot"]          # (R, O, G)
    active = state["active"]
    log_bal = state["log_bal"]        # (R, O, S, G)
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]      # (R, O, S, G) packed
    proposed = state["proposed"]
    base = state["base"]              # (R, O, G)
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]
    hits = state["hits"]
    steal_obj = state["steal_obj"]    # (R, G)
    p1_acks = state["p1_acks"]        # (R, G) packed
    steals = state["steals"]
    m_prop_t = state["m_prop_t"]      # (R, O, S, G) first-propose step
    m_lat_local_sum = state["m_lat_local_sum"]
    m_lat_local_n = state["m_lat_local_n"]
    m_lat_cross_sum = state["m_lat_cross_sum"]
    m_lat_cross_n = state["m_lat_cross_n"]
    G = steal_obj.shape[-1]

    T = dst_major          # mailbox (src, dst, G) -> (me=dst, src, G)

    def at_obj(plane, obj):
        """plane (R, O, G) selected at obj (R, G) -> (R, G)."""
        oh = oidx[None, :, None] == obj[:, None, :]
        return jnp.sum(jnp.where(oh, plane, 0), axis=1)

    def row_at_obj(plane, obj, zero):
        """plane (R, O, S, G) selected at obj (R, G) -> (R, S, G)."""
        oh = (oidx[None, :, None, None] == obj[:, None, None, :])
        return jnp.sum(jnp.where(oh, plane, zero), axis=1)

    def per_obj_best(m, extra=()):
        """Select, per (dst, obj), the max-ballot message among sources.

        Returns (has, bal, src_best, [extra...]) each (R, O, G)."""
        v = T(m["valid"])                              # (me, src, G)
        ob = T(m["obj"])
        bl = T(m["bal"])
        onehot = v[:, :, None, :] & (ob[:, :, None, :]
                                     == oidx[None, None, :, None])
        b4 = jnp.where(onehot, bl[:, :, None, :], -1)  # (me, src, O, G)
        bal_best = jnp.max(b4, axis=1)                 # (me, O, G)
        has = bal_best > 0
        # first (lowest-index) source achieving the max, unrolled
        src_best = jnp.zeros((R, O, G), jnp.int32)
        picks = [jnp.zeros((R, O, G), jnp.int32) for _ in extra]
        for s in range(R - 1, -1, -1):
            hit = has & (b4[:, s] == bal_best)
            src_best = jnp.where(hit, s, src_best)
            for i, f in enumerate(extra):
                picks[i] = jnp.where(hit, T(m[f])[:, s][:, None, :],
                                     picks[i])
        return has, bal_best, src_best, picks

    # ---------------- P1a: promise to higher per-object ballots ---------
    m = inbox["p1a"]
    has1, b1, src1, _ = per_obj_best(m)
    promote = has1 & (b1 > ballot)                     # (me, O, G)
    ballot = jnp.where(promote, b1, ballot)
    active = active & ~promote
    # a promoted object kills my own in-flight steal of it
    my_steal_oh = (steal_obj[:, None, :] == oidx[None, :, None])
    steal_killed = jnp.any(promote & my_steal_oh, axis=1)
    steal_obj = jnp.where(steal_killed, -1, steal_obj)
    # P1b back to the (single) best stealer per promoted object; a replica
    # can promote several objects in one step but the mailbox holds one
    # p1b per edge — reply for the highest-ballot promoted object
    # (stealers retry via steal_timer, so serializing here is safe)
    pb = jnp.where(promote, b1, -1)
    best_o = jnp.argmax(pb, axis=1).astype(jnp.int32)  # (me, G)
    any_p = jnp.any(promote, axis=1)
    to_src = at_obj(src1, best_o)
    out_p1b = {
        "valid": any_p[:, None, :] & (ridx[None, :, None]
                                      == to_src[:, None, :]),
        "obj": jnp.broadcast_to(best_o[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(at_obj(ballot, best_o)[:, None, :],
                                (R, R, G)),
    }

    # ---------------- P1b: stealer tallies grid-quorum acks -------------
    m = inbox["p1b"]
    v = T(m["valid"])                                  # (me, src, G)
    ob = T(m["obj"])
    bl = T(m["bal"])
    so = jnp.clip(steal_obj, 0, O - 1)
    my_bal = at_obj(ballot, so)                        # (me, G)
    ack = (v & (ob == steal_obj[:, None, :])
           & (bl == my_bal[:, None, :])
           & (steal_obj >= 0)[:, None, :])             # (me, src, G)
    p1_acks = p1_acks | jnp.sum(
        jnp.where(ack, (jnp.int32(1) << ridx)[None, :, None], 0), axis=1)
    zq = _zone_quorums(p1_acks, cfg)                   # (me, G)
    p1_win = (steal_obj >= 0) & (zq >= Q1)

    # ---------------- steal win: adopt object, merge ackers' logs -------
    # gather every replica's row for MY stolen object via a one-hot
    # contraction over the object axis.  Fixed cell mapping: all rows
    # (and my own) are already cell-aligned — stealer cell c and acker
    # cell c hold the SAME absolute slot exactly when the slot under
    # the merge base is inside the acker's window, so the old per-src
    # base-alignment shifts become one elementwise in-window mask
    so_oh = (oidx[None, :, None] == so[:, None, :])    # (me, O, G)
    soF = so_oh.astype(jnp.int32)
    amask = ((p1_acks[:, None, :] >> ridx[None, :, None]) & 1
             ).astype(bool)                            # (me, src, G)
    lb = jnp.einsum("rosg,mog->mrsg", log_bal, soF)
    lc = jnp.einsum("rosg,mog->mrsg", log_cmd, soF)
    lk = jnp.einsum("rosg,mog->mrsg", log_commit.astype(jnp.int32),
                    soF).astype(bool)
    b_src = jnp.einsum("rog,mog->mrg", base, soF)      # (me, src, G)
    base_so = at_obj(base, so)                         # (me, G)
    base_star = jnp.maximum(
        base_so, jnp.max(jnp.where(amask, b_src, 0), axis=1))
    A_star = cell.cell_abs(base_star, S)               # (me, S, G) abs
    in_src = (A_star[:, None] >= b_src[:, :, None, :]) \
        & (A_star[:, None] < b_src[:, :, None, :] + S)  # (me, src, S, G)
    sel = amask[:, :, None, :] & in_src
    lbm = jnp.where(sel, lb, -1)
    best_bal = jnp.max(lbm, axis=1)                    # (me, S, G)
    cmask = sel & lk
    merged_commit = jnp.any(cmask, axis=1)
    merged_cmd = jnp.full((R, S, G), NO_CMD, jnp.int32)
    committed_cmd = jnp.full((R, S, G), NO_CMD, jnp.int32)
    for s in range(R - 1, -1, -1):
        merged_cmd = jnp.where(lbm[:, s] == best_bal, lc[:, s], merged_cmd)
        committed_cmd = jnp.where(cmask[:, s], lc[:, s], committed_cmd)
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, A_star + 1, 0), axis=1)  # (me, G) abs
    my_next = at_obj(next_slot, so)
    new_next = jnp.maximum(my_next, top)
    in_win = A_star < new_next[:, None, :]             # (me, S, G)
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    win_oh = p1_win[:, None, :] & so_oh                # (me, O, G)
    # raise my stolen object's base to base_star: recycled cells (abs
    # now below it) reset in place — the fixed mapping's no-copy move
    nb_steal = jnp.where(win_oh, base_star[:, None, :], base)
    drop4 = cell.cell_abs(base, S) < nb_steal[:, :, None, :]
    log_bal = jnp.where(drop4, 0, log_bal)
    log_cmd = jnp.where(drop4, NO_CMD, log_cmd)
    log_commit = log_commit & ~drop4
    proposed = proposed & ~drop4
    log_acks = jnp.where(drop4, 0, log_acks)
    m_prop_t = jnp.where(drop4, 0, m_prop_t)
    w4 = win_oh[:, :, None, :]                         # (me, O, 1, G)
    iw4 = in_win[:, None, :, :]                        # (me, 1, S, G)
    my_bal_so = at_obj(ballot, so)                     # (me, G)
    log_cmd = jnp.where(w4 & iw4, adopt_cmd[:, None], log_cmd)
    log_bal = jnp.where(w4 & iw4, my_bal_so[:, None, None, :], log_bal)
    log_commit = jnp.where(w4 & iw4,
                           merged_commit[:, None] | log_commit,
                           log_commit)
    proposed = jnp.where(w4, iw4 & (merged_commit[:, None] | log_commit),
                         proposed)
    log_acks = jnp.where(w4, jnp.where(iw4, self_bit2[:, :, None, None], 0),
                         log_acks)
    # adopted rows restart their latency clocks at the takeover step
    m_prop_t = jnp.where(w4, jnp.where(iw4, ctx.t, 0), m_prop_t)
    base = nb_steal
    next_slot = jnp.where(win_oh, new_next[:, None, :], next_slot)
    # adopt execute/register from the max-base acker when it is ahead
    # (its frontier covers everything its base recycled)
    e_src = jnp.einsum("rog,mog->mrg", execute, soF)
    k_src = jnp.einsum("rog,mog->mrg", kv, soF)
    e_am = jnp.where(amask, e_src, -1)
    f_exec = jnp.max(e_am, axis=1)                     # (me, G)
    f_kv = jnp.full((R, G), 0, jnp.int32)
    for s in range(R - 1, -1, -1):
        f_kv = jnp.where(e_am[:, s] == f_exec, k_src[:, s], f_kv)
    my_exec_so = at_obj(execute, so)
    adv_ex = p1_win & (f_exec > my_exec_so)
    execute = jnp.where(win_oh & adv_ex[:, None, :],
                        f_exec[:, None, :], execute)
    kv = jnp.where(win_oh & adv_ex[:, None, :], f_kv[:, None, :], kv)
    active = active | win_oh
    steals = steals + jnp.sum(p1_win, axis=0)
    steal_obj = jnp.where(p1_win, -1, steal_obj)
    p1_acks = jnp.where(p1_win, 0, p1_acks)

    own = (ballot % STRIDE) == ridx[:, None, None]     # (R, O, G)

    # ---------------- P2a: accept from the highest-ballot owner ---------
    m = inbox["p2a"]
    has2, b2, src2, (slot2, cmd2) = per_obj_best(m, ("slot", "cmd"))
    acc_ok = has2 & (b2 >= ballot)                     # (me, O, G)
    demote = acc_ok & (b2 > ballot)
    ballot = jnp.where(acc_ok, b2, ballot)
    active = active & ~demote
    sk = jnp.any(demote & my_steal_oh, axis=1)
    steal_obj = jnp.where(sk, -1, steal_obj)
    inw2 = cell.in_window(slot2, base, S)              # (me, O, G)
    oh = ((acc_ok & inw2)[:, :, None, :]
          & (sidx[None, None, :, None]
             == jnp.remainder(slot2, S)[:, :, None, :]))
    writable = oh & (log_bal <= b2[:, :, None, :]) & ~log_commit
    log_bal = jnp.where(writable, b2[:, :, None, :], log_bal)
    log_cmd = jnp.where(writable, cmd2[:, :, None, :], log_cmd)
    # p2b back to the accepted object's owner — one per edge; each owner
    # proposes one object per step, so per (dst, src-owner) there is at
    # most one accepted p2a => reply on that edge directly, and ack ONLY
    # what we durably stored (in-window)
    v2 = T(m["valid"])                                 # (me, src, G)
    ob2 = jnp.clip(T(m["obj"]), 0, O - 1)
    edge_ok = []
    for s in range(R):
        o_s = ob2[:, s]                                # (me, G)
        acc_s = at_obj((acc_ok & inw2).astype(jnp.int32), o_s) > 0
        src_s = at_obj(src2, o_s)
        edge_ok.append(v2[:, s] & acc_s & (src_s == s))
    win_edge = jnp.stack(edge_ok, axis=1)              # (me, src, G)
    out_p2b = {
        "valid": win_edge,
        "obj": T(m["obj"]),
        "bal": T(m["bal"]),
        "slot": T(m["slot"]),
    }

    own = (ballot % STRIDE) == ridx[:, None, None]

    # ---------------- P2b: owner tallies zone-grid acks, commits --------
    m = inbox["p2b"]
    v = T(m["valid"])                                  # (own, src, G)
    ob = jnp.clip(T(m["obj"]), 0, O - 1)
    bl = T(m["bal"])
    sl = T(m["slot"])
    for s in range(R):
        ob_s, bl_s, sl_s = ob[:, s], bl[:, s], sl[:, s]
        ok_s = (v[:, s] & (bl_s == at_obj(ballot, ob_s))
                & (at_obj((active & own).astype(jnp.int32), ob_s) > 0))
        inw_s = cell.in_window(sl_s[:, None, :], base, S)  # (own, O, G)
        oh_s = (ok_s[:, None, None, :]
                & (ob_s[:, None, None, :] == oidx[None, :, None, None])
                & inw_s[:, :, None, :]
                & (jnp.remainder(sl_s, S)[:, None, None, :]
                   == sidx[None, None, :, None]))
        log_acks = log_acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    zq2 = _zone_quorums(log_acks, cfg)                 # (own, O, S, G)
    newly = ((active & own)[:, :, None, :] & (zq2 >= Q2)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly
    # zone-latency split (the Cloud paper's headline measurement): a
    # commit is ZONE-LOCAL when the owner's own zone's acks alone
    # satisfy the grid quorum (for q2=1, the steady-state WAN win this
    # kernel exists to show; for q2>1 own-zone-alone can never
    # suffice, so every commit is honestly cross-zone)
    ZR = R // Z
    zbits = jnp.int32((1 << ZR) - 1) << ((ridx // ZR) * ZR)   # (own,)
    own_zq = _zone_quorums(log_acks & zbits[:, None, None, None], cfg)
    local = newly & (own_zq >= Q2)
    cross = newly & ~(own_zq >= Q2)
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_lat_local_sum = m_lat_local_sum + jnp.sum(
        jnp.where(local, dt, 0), axis=(0, 1, 2))
    m_lat_local_n = m_lat_local_n + jnp.sum(local, axis=(0, 1, 2))
    m_lat_cross_sum = m_lat_cross_sum + jnp.sum(
        jnp.where(cross, dt, 0), axis=(0, 1, 2))
    m_lat_cross_n = m_lat_cross_n + jnp.sum(cross, axis=(0, 1, 2))
    # the distribution-shaped twin of the local/cross mean split: every
    # newly committed (owner, object, slot) bins its propose->commit
    # delta into the shared log2 histogram (metrics/lathist)
    m_lat_hist = lathist.hist_update(state["m_lat_hist"], dt, newly)
    m_lat_sum = state["m_lat_sum"] + jnp.sum(
        jnp.where(newly, dt, 0), axis=(0, 1, 2), dtype=jnp.int32)
    # per-key-class latency (workload runs): a commit's class is its
    # OBJECT's label — demand maps key -> object by key % O, so the
    # object's epoch-0 resident rank classes it (a static table, no
    # extra planes on the wire)
    wl = cfg.workload
    wl_planes = {}
    if wl is not None:
        clsO = jnp.asarray(wlc.obj_class_table(wl, cfg.n_keys, O),
                           jnp.int32)[None, :, None, None]
        for ci, nm in enumerate(CLASSES):
            cm = newly & (clsO == ci)
            wl_planes[f"m_wl_hist_{nm}"] = lathist.hist_update(
                state[f"m_wl_hist_{nm}"], dt, cm)
            wl_planes[f"m_wl_sum_{nm}"] = state[f"m_wl_sum_{nm}"] \
                + jnp.sum(jnp.where(cm, dt, 0), axis=(0, 1, 2),
                          dtype=jnp.int32)
        wl_planes["wl_gid"] = state["wl_gid"]

    # ---------------- P3: commit notifications --------------------------
    # Zombie fences (see sim/ballot_ring.py apply_p3): a higher-ballot
    # P3 DEPOSES the receiving object owner (a partitioned stale owner
    # that snapshot-adopts must stop broadcasting upto for a frontier
    # it never committed), and the frontier-commit only fires for
    # bal >= my promised object ballot (a stale in-flight P3 cannot
    # commit same-stale-ballot never-chosen entries at a laggard).
    m = inbox["p3"]
    has3, b3_, src3, (slot3, cmd3, upto3, low3) = per_obj_best(
        m, ("slot", "cmd", "upto", "lowslot"))
    fresh3 = has3 & (b3_ >= ballot)                    # (me, O, G)
    promote3 = has3 & (b3_ > ballot)
    ballot = jnp.where(promote3, b3_, ballot)
    active = active & ~promote3
    sk3 = jnp.any(promote3 & my_steal_oh, axis=1)
    steal_obj = jnp.where(sk3, -1, steal_obj)
    inw3 = cell.in_window(slot3, base, S)
    oh = ((has3 & inw3)[:, :, None, :]
          & (sidx[None, None, :, None]
             == jnp.remainder(slot3, S)[:, :, None, :]))
    log_cmd = jnp.where(oh, cmd3[:, :, None, :], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, b3_[:, :, None, :]),
                        log_bal)
    log_commit = log_commit | oh
    abs_ = cell.cell_abs(base, S)                      # (me, O, S, G)
    ohu = (fresh3[:, :, None, :] & (abs_ < upto3[:, :, None, :])
           & (log_bal == b3_[:, :, None, :]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    # my frontier for this object fell below the owner's window base:
    # the slots I need were recycled at the owner.  Adopt the owner's
    # object row (log, base, execute, register) by reference, keeping my
    # own still-in-window commits (as the paxos kernel does) — unrolled
    # over the owner index
    adopt = (has3 & (execute < low3)
             & ~(ridx[:, None, None] == src3))         # (me, O, G)
    s_cmd = jnp.zeros_like(log_cmd)
    s_bal = jnp.zeros_like(log_bal)
    s_com = jnp.zeros_like(log_commit)
    b_own = jnp.zeros_like(base)
    e_own = jnp.zeros_like(execute)
    k_own = jnp.zeros_like(kv)
    for s in range(R - 1, -1, -1):
        mp = adopt & (src3 == s)                       # (me, O, G)
        mp4 = mp[:, :, None, :]
        s_cmd = jnp.where(mp4, log_cmd[s][None], s_cmd)
        s_bal = jnp.where(mp4, log_bal[s][None], s_bal)
        s_com = jnp.where(mp4, log_commit[s][None], s_com)
        b_own = jnp.where(mp, base[s][None], b_own)
        e_own = jnp.where(mp, execute[s][None], e_own)
        k_own = jnp.where(mp, kv[s][None], k_own)
    # fixed cell mapping: the owner's cells are already aligned with
    # mine — keep my cells still inside the owner's window (adopt
    # requires my execute — hence my base — below the owner's base),
    # everything below was recycled
    keep4 = cell.cell_abs(base, S) >= b_own[:, :, None, :]
    my_bal_s = jnp.where(keep4, log_bal, 0)
    my_cmd_s = jnp.where(keep4, log_cmd, NO_CMD)
    my_com_s = keep4 & log_commit
    a4 = adopt[:, :, None, :]
    log_bal = jnp.where(a4, jnp.where(s_com, s_bal, my_bal_s), log_bal)
    log_cmd = jnp.where(a4, jnp.where(s_com, s_cmd, my_cmd_s), log_cmd)
    log_commit = jnp.where(a4, s_com | my_com_s, log_commit)
    proposed = jnp.where(a4, False, proposed)
    log_acks = jnp.where(a4, 0, log_acks)
    m_prop_t = jnp.where(a4, 0, m_prop_t)
    base = jnp.where(adopt, b_own, base)
    execute = jnp.where(adopt, e_own, execute)
    kv = jnp.where(adopt, k_own, kv)
    next_slot = jnp.where(adopt, jnp.maximum(next_slot, e_own), next_slot)

    # ---------------- workload: demand one object per step --------------
    # locality-skewed demand: each replica mostly touches its own block
    # of "home" objects (modeling paxi's zone-routed clients; when O < R
    # several replicas share a home object, giving steady contention)
    k_d, k_loc, k_jit = jr.split(ctx.rng, 3)   # k_jit: steal backoff below
    if wl is None:
        blk = max(O // R, 1)
        home = (ridx[:, None] * blk + jr.randint(k_d, (R, G), 0, blk)) % O
        anywhere = jr.randint(jr.fold_in(k_d, 1), (R, G), 0, O)
        local = jr.bernoulli(k_loc, cfg.locality, (R, G))
        d = jnp.where(local, home, anywhere).astype(jnp.int32)
    else:
        # workload-driven demand: each replica demands the object of a
        # spec-drawn key (key % O), on its own counter channel — a
        # Zipf spec concentrates every zone's demand on the same hot
        # objects (the steal pressure the uniform control lacks).
        # The jr.split above stays so the k_jit chain (and pinned
        # replay of it) is identical with and without a workload.
        key_d = wlc.key_plane(wl, cfg.n_keys, state["wl_gid"][None, :],
                              ctx.t, chan=wlc.CH_DEMAND + ridx[:, None])
        d = jnp.remainder(key_d, O).astype(jnp.int32)

    # ---------------- owner proposes for the demanded object ------------
    d_oh = oidx[None, :, None] == d[:, None, :]        # (R, O, G)
    is_owner_d = jnp.any(d_oh & active & own, axis=1)  # (R, G)
    d_bal = at_obj(ballot, d)
    d_next = at_obj(next_slot, d)
    d_base = at_obj(base, d)
    c_at_d = row_at_obj(log_commit, d, False)          # (R, S, G)
    p_at_d = row_at_obj(proposed, d, False)
    BIG = jnp.int32(2 ** 30)
    A_d = cell.cell_abs(d_base, S)                     # (R, S, G) abs
    mask_re = (~c_at_d) & (~p_at_d) & (A_d < d_next[:, None, :])
    re_abs = jnp.min(jnp.where(mask_re, A_d, BIG), axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = d_next - d_base < S                      # window flow control
    if wl is not None:
        # flash-crowd demand gate on NEW proposals only (re-proposals
        # are recovery, never gated — see paxos kernels)
        gate = wlc.demand_gate(wl, state["wl_gid"][None, :], ctx.t)
        if gate is not None:
            can_new = can_new & gate
    prop_slot = jnp.where(has_re, re_abs, d_next)      # absolute
    new_cmd = encode_cmd(d_bal, prop_slot)
    oh_pr = sidx[None, :, None] \
        == jnp.remainder(prop_slot, S)[:, None, :]
    re_cmd = jnp.sum(jnp.where(oh_pr, row_at_obj(log_cmd, d, 0), 0),
                     axis=1)
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(has_re, re_cmd, new_cmd)
    do = is_owner_d & (has_re | can_new)
    p_oh = (do[:, None, None, :] & d_oh[:, :, None, :]
            & oh_pr[:, None, :, :])
    log_bal = jnp.where(p_oh, d_bal[:, None, None, :], log_bal)
    log_cmd = jnp.where(p_oh & ~log_commit, prop_cmd[:, None, None, :],
                        log_cmd)
    # latency clock: a slot's FIRST propose starts it (re-proposals
    # keep the original start — the honest end-to-end commit latency)
    m_prop_t = jnp.where(p_oh & ~proposed, ctx.t, m_prop_t)
    proposed = proposed | p_oh
    log_acks = log_acks | jnp.where(p_oh, self_bit2[..., None, None], 0)
    next_slot = next_slot + ((do & ~has_re & can_new)[:, None, :] & d_oh)
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None, :], (R, R, G)),
        "obj": jnp.broadcast_to(d[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(d_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(prop_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None, :], (R, R, G)),
    }

    # ---------------- policy: count misses, fire steals ------------------
    miss = d_oh & ~(active & own)                      # demanded, not owned
    # consecutive policy (policy.go): the counter survives only while
    # the replica keeps demanding the same unowned object
    hits = jnp.where(miss, hits + 1, 0)
    # fire a steal for the hottest over-threshold object when idle
    can_steal = steal_obj < 0
    hot = jnp.max(hits, axis=1)                        # (R, G)
    hot_obj = jnp.argmax(hits, axis=1).astype(jnp.int32)
    fire = can_steal & (hot >= cfg.steal_threshold)
    new_bal = ((jnp.max(ballot, axis=1) // STRIDE + 1) * STRIDE
               + ridx[:, None])
    f_oh = fire[:, None, :] & (oidx[None, :, None] == hot_obj[:, None, :])
    ballot = jnp.where(f_oh, new_bal[:, None, :], ballot)
    active = active & ~f_oh
    steal_obj = jnp.where(fire, hot_obj, steal_obj)
    p1_acks = jnp.where(fire, self_bit2, p1_acks)
    hits = jnp.where(f_oh, 0, hits)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None, :], (R, R, G)),
        "obj": jnp.broadcast_to(hot_obj[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(new_bal[:, None, :], (R, R, G)),
    }
    # stalled steal: retry (rebump) after a timeout
    steal_timer = jnp.where(steal_obj >= 0, state["steal_timer"] + 1, 0)
    timeout = steal_timer >= cfg.election_timeout + \
        jr.randint(k_jit, (R, G), 0, cfg.backoff + 1)
    steal_obj = jnp.where(timeout, -1, steal_obj)   # give up; re-fire later
    steal_timer = jnp.where(timeout, 0, steal_timer)

    # ---------------- execute committed prefixes ------------------------
    advanced = jnp.zeros((R, O, G), jnp.int32)
    running = jnp.ones((R, O, G), bool)
    for e in range(cfg.exec_window):
        abs_e = execute + e                            # (R, O, G) absolute
        inb_e = abs_e < base + S                       # execute >= base
        oh_e = (inb_e[:, :, None, :]
                & (sidx[None, None, :, None]
                   == jnp.remainder(abs_e, S)[:, :, None, :]))
        com = jnp.any(oh_e & log_commit, axis=2)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, log_cmd, 0), axis=2)
        wr = running & (cmd_e >= 0)
        kv = jnp.where(wr, cmd_e, kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------------- P3 out: per owner, its demanded object ------------
    new_at_d = row_at_obj(newly, d, False)             # (R, S, G)
    any_new_d = jnp.any(new_at_d, axis=1)
    low_new = jnp.min(jnp.where(new_at_d, A_d, BIG), axis=1)  # abs
    my_exec_d = at_obj(new_execute, d)
    rr = ctx.t % jnp.maximum(my_exec_d - d_base, 1)
    p3_abs = jnp.where(any_new_d, low_new, d_base + rr)
    oh_3 = sidx[None, :, None] == jnp.remainder(p3_abs, S)[:, None, :]
    p3_committed = jnp.any(oh_3 & row_at_obj(log_commit, d, False), axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, row_at_obj(log_cmd, d, 0), 0), axis=1)
    p3_do = (at_obj((active & own).astype(jnp.int32), d) > 0) & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "obj": jnp.broadcast_to(d[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(d_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(p3_abs[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(my_exec_d[:, None, :], (R, R, G)),
        "lowslot": jnp.broadcast_to(d_base[:, None, :], (R, R, G)),
    }

    # ---------------- slide the ring windows (slot recycling) -----------
    # fixed cell mapping: recycled cells reset in place, nothing moves
    new_base = jnp.maximum(base, new_execute - RETAIN)
    drop_s = cell.cell_abs(base, S) < new_base[:, :, None, :]
    log_bal = jnp.where(drop_s, 0, log_bal)
    log_cmd = jnp.where(drop_s, NO_CMD, log_cmd)
    log_commit = log_commit & ~drop_s
    proposed = proposed & ~drop_s
    log_acks = jnp.where(drop_s, 0, log_acks)
    m_prop_t = jnp.where(drop_s, 0, m_prop_t)

    # in-scan linearizability spot-check (sim/inscan), per (replica,
    # object) lane over the per-object rings
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], new_execute, state["base"], new_base,
        cell.cell_abs(state["base"], S),
        cell.cell_abs(new_base, S),
        state["log_cmd"], log_cmd,
        state["log_commit"], log_commit,
        kv=kv, lane_major=True)

    new_state = dict(
        ballot=ballot, active=active, log_bal=log_bal, log_cmd=log_cmd,
        log_commit=log_commit, log_acks=log_acks, proposed=proposed,
        base=new_base, next_slot=next_slot, execute=new_execute, kv=kv,
        hits=hits, steal_obj=steal_obj, p1_acks=p1_acks,
        steal_timer=steal_timer, steals=steals,
        m_prop_t=m_prop_t, m_lat_local_sum=m_lat_local_sum,
        m_lat_local_n=m_lat_local_n, m_lat_cross_sum=m_lat_cross_sum,
        m_lat_cross_n=m_lat_cross_n, m_lat_hist=m_lat_hist,
        m_lat_sum=m_lat_sum, m_inscan_viol=m_inscan_viol,
        **wl_planes,
    )
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "steals": jnp.sum(state["steals"]),
        "owned_objects": jnp.sum(state["active"]).astype(jnp.int32),
        # zone-local vs cross-zone commit-latency split (propose ->
        # commit, in lock-step rounds) — the scenario bench axis
        "commit_lat_local_sum": jnp.sum(state["m_lat_local_sum"]),
        "commit_lat_local_n": jnp.sum(state["m_lat_local_n"]),
        "commit_lat_cross_sum": jnp.sum(state["m_lat_cross_sum"]),
        "commit_lat_cross_n": jnp.sum(state["m_lat_cross_n"]),
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": jnp.sum(state["m_lat_hist"]),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
        # per-key-class sample counts (workload runs; full histograms
        # ride in state — workload.class_split)
        **{f"wl_{nm}_n": jnp.sum(state[f"m_wl_hist_{nm}"])
           for nm in CLASSES if f"m_wl_hist_{nm}" in state},
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Agreement per absolute (object, slot) — checked on the
    base-aligned common window; 2. commit stability under the slide;
    3. per-(replica, object) ballot monotonicity; 4. executed prefix
    committed (within the window); 5. single ownership: at most one
    active owner per object."""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]
    A = cell.cell_abs(base, S)                         # (R, O, S, G)

    # agreement on the common window per object (cells align under the
    # fixed mapping — see paxos/sim.invariants)
    vis = c & (A >= jnp.max(base, axis=0)[None, :, None, :])
    mx = jnp.max(jnp.where(vis, cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(vis, cmd, BIG), axis=0)
    n_c = jnp.sum(vis, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    o_c = old["log_commit"] \
        & (cell.cell_abs(old["base"], S) >= base[:, :, None, :])
    v_stable = jnp.sum(o_c & (~c | (cmd != old["log_cmd"])))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    v_exec = jnp.sum((A < new["execute"][:, :, None, :]) & ~c)

    # two active replicas owning the same object at the same ballot round
    # would be a stolen-twice bug; different ballots are a transient
    own = new["active"]
    bal = jnp.where(own, new["ballot"], -1)
    R = cfg.n_replicas
    same = (own[:, None] & own[None, :]
            & (bal[:, None] == bal[None, :])
            & (jnp.arange(R)[:, None, None, None]
               != jnp.arange(R)[None, :, None, None]))
    v_own = jnp.sum(same) // 2

    return (v_agree + v_stable + v_bal + v_exec + v_own).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="wpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)

# the seeded thin-read-quorum bug twin (see step's docstring): the
# scenario engine's capturable wpaxos witness source — WAN geo-latency
# widens the racing-steal window until a one-zone-thin phase-1 read
# set misses the write zone and the agreement oracle fires.
# Registered as ``wpaxos_thinq1`` (sim-only, like wankeeper_nofloor);
# never a correctness case.
PROTOCOL_THINQ1 = SimProtocol(
    name="wpaxos_thinq1",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=functools.partial(step, q1_full=False),
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
