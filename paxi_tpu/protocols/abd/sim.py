"""ABD (Attiya-Bar-Noy-Dolev) atomic register as a pure TPU kernel.

Reference: paxi abd/ — crash-only **linearizable multi-writer register**
with no consensus: a read queries all replicas, waits for a majority,
picks the max-timestamp value and *writes it back* to a majority; a write
queries a majority for the current timestamp and writes ts+1 (writer id
as tiebreak) to a majority [driver: "crash-only linearizable register"].
Two ``paxi.Quorum`` rounds per op (abd/abd.go Get/Set phases).

TPU re-design (lane-major layout — see sim/lanes.py):
- The kernel operates on the whole group batch with the group axis LAST
  (state ``(R, G)`` / ``(R, K, G)``, mailbox planes ``(src, dst, G)``)
  so the group axis feeds the 8x128 vector lanes.
- Every replica is also a closed-loop client issuing alternating
  read/write ops on hashed keys (benchmark.go's generator collapsed into
  the kernel, as in the paxos kernel).
- Per-op state machine is fully masked: ``phase`` in {0 idle, 1 query
  round, 2 store round}; ``Quorum.ACK`` is a bit-packed int32 ack mask
  per replica with ``lax.population_count`` for ``Majority()``
  (quorum.go [driver]).
- Timestamps encode the writer: ``ts = round * stride + writer`` (the
  (n, id) lexicographic pair of the paper packed into one int32).
- Values are a deterministic function of ts, so "register holds
  (ts, val) with val != f(ts)" is a per-step checkable corruption
  invariant.
- The linearizability oracle is *built in*: the group tracks the max
  completed-op timestamp per key; an op snapshots it at start, and
  completing with a smaller timestamp is an atomicity violation
  (an op that starts after another completes must not see older state —
  precisely the atomic-register condition history.go checks offline).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.ring import dst_major, require_packable
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

IDLE, QUERY, STORE = 0, 1, 2


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "query": ("key", "tag"),
        "query_r": ("tag", "ts", "val"),
        "store": ("key", "tag", "ts", "val"),
        "store_r": ("tag",),
    }


def encode_val(ts):
    """Deterministic register payload for a write with timestamp ts."""
    return ts * jnp.int32(7) + jnp.int32(13)


def op_key_for(ridx, seq, n_keys):
    """Per-op key choice (uniform-ish hash of (replica, seq))."""
    return fib_key(seq * jnp.int32(31) + ridx, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, K, G = cfg.n_replicas, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        store_ts=jnp.zeros((R, K, G), i32),
        store_val=jnp.zeros((R, K, G), i32),
        phase=jnp.zeros((R, G), i32),
        op_read=jnp.zeros((R, G), bool),
        op_key=jnp.zeros((R, G), i32),
        op_tag=jnp.zeros((R, G), i32),
        op_ts=jnp.zeros((R, G), i32),
        op_val=jnp.zeros((R, G), i32),
        op_snap=jnp.zeros((R, G), i32),    # oracle snapshot at op start
        op_age=jnp.zeros((R, G), i32),     # steps in current phase (retry)
        acks=jnp.zeros((R, G), i32),       # bit-packed ack mask
        best_ts=jnp.zeros((R, G), i32),
        best_val=jnp.zeros((R, G), i32),
        seq=jnp.zeros((R, G), i32),        # per-replica op counter
        reads_done=jnp.zeros((R, G), i32),
        writes_done=jnp.zeros((R, G), i32),
        done_max_ts=jnp.zeros((K, G), i32),  # oracle: max completed ts/key
        atomic_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, K = cfg.n_replicas, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    ridx = jnp.arange(R, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    self_bit = (jnp.int32(1) << ridx)[:, None]        # (R, 1) for (R, G)
    src_bit = (jnp.int32(1) << ridx)[:, None, None]   # (src, 1, 1)

    T = dst_major          # mailbox (src, dst, G) -> (me=dst, src, G)

    def key_read(plane, key):
        """out[r, g] = plane[r, key[r, g], g] as a one-hot masked max."""
        oh = kidx[None, :, None] == key[:, None, :]   # (R, K, G)
        return jnp.sum(jnp.where(oh, plane, 0), axis=1)

    store_ts, store_val = state["store_ts"], state["store_val"]
    phase = state["phase"]
    acks = state["acks"]
    best_ts, best_val = state["best_ts"], state["best_val"]
    G = phase.shape[-1]

    # ------------- serve "query": reply with local (ts, val) -------------
    m = inbox["query"]
    qv = T(m["valid"])                      # (me, src, G)
    qkey = jnp.clip(T(m["key"]), 0, K - 1)
    qoh = kidx[None, None, :, None] == qkey[:, :, None, :]   # (me,src,K,G)
    out_query_r = {
        "valid": qv,
        "tag": T(m["tag"]),
        "ts": jnp.sum(jnp.where(qoh, store_ts[:, None], 0), axis=2),
        "val": jnp.sum(jnp.where(qoh, store_val[:, None], 0), axis=2),
    }

    # ------------- serve "store": apply max-ts write per key, ack --------
    m = inbox["store"]
    sv = T(m["valid"])                      # (me, src, G)
    skey, sts, sval = T(m["key"]), T(m["ts"]), T(m["val"])
    hit = sv[:, :, None] & (kidx[None, None, :, None]
                            == skey[:, :, None, :])          # (me,src,K,G)
    sts_h = jnp.where(hit, sts[:, :, None, :], -1)
    cand_ts = jnp.max(sts_h, axis=1)                         # (me, K, G)
    # the max-ts sender's value, unrolled over the tiny src axis
    cand_val = jnp.zeros_like(cand_ts)
    for s in range(R):
        cand_val = jnp.where(sts_h[:, s] == cand_ts,
                             sval[:, s, None, :], cand_val)
    newer = cand_ts > store_ts
    store_ts = jnp.where(newer, cand_ts, store_ts)
    store_val = jnp.where(newer, cand_val, store_val)
    out_store_r = {"valid": sv, "tag": T(m["tag"])}

    # ------------- collect replies for my in-flight op -------------------
    m = inbox["query_r"]
    ok = (T(m["valid"]) & (T(m["tag"]) == state["op_tag"][:, None, :])
          & (phase == QUERY)[:, None, :])                    # (me, src, G)
    r_ts = jnp.where(ok, T(m["ts"]), -1)
    in_best = jnp.max(r_ts, axis=1)                          # (me, G)
    in_val = jnp.zeros_like(in_best)
    rv = T(m["val"])
    for s in range(R):
        in_val = jnp.where((r_ts[:, s] == in_best) & (in_best >= 0),
                           rv[:, s], in_val)
    better = in_best > best_ts
    best_val = jnp.where(better, in_val, best_val)
    best_ts = jnp.maximum(best_ts, in_best)
    acks = acks | jnp.sum(jnp.where(jnp.swapaxes(ok, 0, 1), src_bit, 0),
                          axis=0)

    m = inbox["store_r"]
    ok2 = (T(m["valid"]) & (T(m["tag"]) == state["op_tag"][:, None, :])
           & (phase == STORE)[:, None, :])
    acks = acks | jnp.sum(jnp.where(jnp.swapaxes(ok2, 0, 1), src_bit, 0),
                          axis=0)

    n_acks = jax.lax.population_count(acks)

    # ------------- phase 1 -> 2: choose (ts, val), broadcast store -------
    q_done = (phase == QUERY) & (n_acks >= MAJ)
    w_ts = (best_ts // STRIDE + 1) * STRIDE + ridx[:, None]  # write: bump
    op_ts = jnp.where(q_done,
                      jnp.where(state["op_read"], best_ts, w_ts),
                      state["op_ts"])
    op_val = jnp.where(q_done,
                       jnp.where(state["op_read"], best_val,
                                 encode_val(w_ts)),
                       state["op_val"])
    # write-back / write applies to own store immediately (self-ack)
    oh = q_done[:, None, :] & (kidx[None, :, None]
                               == state["op_key"][:, None, :])
    upd = oh & (op_ts[:, None, :] > store_ts)
    store_ts = jnp.where(upd, op_ts[:, None, :], store_ts)
    store_val = jnp.where(upd, op_val[:, None, :], store_val)
    phase = jnp.where(q_done, STORE, phase)
    acks = jnp.where(q_done, self_bit, acks)
    n_acks = jax.lax.population_count(acks)

    # ------------- phase 2 done: op completes, oracle check --------------
    s_done = (phase == STORE) & (n_acks >= MAJ) & ~q_done
    # atomicity: completing op must not carry ts older than any op that
    # completed before it started
    viol = jnp.sum(s_done & (op_ts < state["op_snap"]), axis=0)   # (G,)
    atomic_viol = state["atomic_viol"] + viol
    reads_done = state["reads_done"] + (s_done & state["op_read"])
    writes_done = state["writes_done"] + (s_done & ~state["op_read"])
    dhit = s_done[:, None, :] & (kidx[None, :, None]
                                 == state["op_key"][:, None, :])
    done_max_ts = jnp.maximum(
        state["done_max_ts"],
        jnp.max(jnp.where(dhit, op_ts[:, None, :], -1), axis=0))
    phase = jnp.where(s_done, IDLE, phase)

    # ------------- idle: start next op (alternate write/read) ------------
    start = phase == IDLE
    seq = state["seq"] + start
    new_read = (seq % 2) == 0
    new_key = op_key_for(ridx[:, None], seq, K)
    new_tag = seq * R + ridx[:, None]  # globally unique per op
    op_read = jnp.where(start, new_read, state["op_read"])
    op_keyv = jnp.where(start, new_key, state["op_key"])
    op_tag = jnp.where(start, new_tag, state["op_tag"])
    snap_at_key = jnp.sum(
        jnp.where(kidx[None, :, None] == new_key[:, None, :],
                  state["done_max_ts"][None], 0), axis=1)     # (R, G)
    op_snap = jnp.where(start, snap_at_key, state["op_snap"])
    # local contribution to the query round
    self_ts = key_read(store_ts, op_keyv)
    self_val = key_read(store_val, op_keyv)
    best_ts = jnp.where(start, self_ts, best_ts)
    best_val = jnp.where(start, self_val, best_val)
    acks = jnp.where(start, self_bit, acks)
    phase = jnp.where(start, QUERY, phase)
    op_ts = jnp.where(start, 0, op_ts)
    op_val = jnp.where(start, 0, op_val)

    # ------------- emit my round's broadcast (with fuzz retry) -----------
    op_age = jnp.where(start | q_done | s_done, 0, state["op_age"] + 1)
    resend = op_age >= cfg.retry_timeout
    op_age = jnp.where(resend, 0, op_age)
    send_q = (phase == QUERY) & (start | resend)
    send_s = (phase == STORE) & (q_done | resend)
    out_query = {
        "valid": jnp.broadcast_to(send_q[:, None, :], (R, R, G)),
        "key": jnp.broadcast_to(op_keyv[:, None, :], (R, R, G)),
        "tag": jnp.broadcast_to(op_tag[:, None, :], (R, R, G)),
    }
    out_store = {
        "valid": jnp.broadcast_to(send_s[:, None, :], (R, R, G)),
        "key": jnp.broadcast_to(op_keyv[:, None, :], (R, R, G)),
        "tag": jnp.broadcast_to(op_tag[:, None, :], (R, R, G)),
        "ts": jnp.broadcast_to(op_ts[:, None, :], (R, R, G)),
        "val": jnp.broadcast_to(op_val[:, None, :], (R, R, G)),
    }

    new_state = dict(
        store_ts=store_ts, store_val=store_val, phase=phase,
        op_read=op_read, op_key=op_keyv, op_tag=op_tag, op_ts=op_ts,
        op_val=op_val, op_snap=op_snap, op_age=op_age, acks=acks,
        best_ts=best_ts, best_val=best_val, seq=seq,
        reads_done=reads_done, writes_done=writes_done,
        done_max_ts=done_max_ts, atomic_viol=atomic_viol,
    )
    outbox = {"query": out_query, "query_r": out_query_r,
              "store": out_store, "store_r": out_store_r}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    done = state["reads_done"] + state["writes_done"]
    return {
        "ops_done": jnp.sum(done),
        "reads_done": jnp.sum(state["reads_done"]),
        "writes_done": jnp.sum(state["writes_done"]),
        # committed_slots keeps the runner/bench metric name uniform
        "committed_slots": jnp.sum(done),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Atomicity (in-kernel oracle delta).  2. Per-replica register
    timestamps never regress.  3. Register (ts, val) pairs are always
    consistent with the writer encoding."""
    v_atomic = jnp.sum(new["atomic_viol"] - old["atomic_viol"])
    v_mono = jnp.sum(new["store_ts"] < old["store_ts"])
    held = new["store_ts"] > 0
    v_consist = jnp.sum(held
                        & (new["store_val"] != encode_val(new["store_ts"])))
    return (v_atomic + v_mono + v_consist).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="abd",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
