"""ABD atomic-register replica for the host (deployment) runtime.

Reference: paxi abd/ (abd.go, msg.go, replica.go) — a crash-only
**linearizable multi-writer register** built without consensus
[driver: "crash-only linearizable register"]:

- READ  = phase-1 query all replicas, wait for a majority of
  (timestamp, value) replies, pick the max timestamp; phase-2 *write
  back* that (ts, value) to a majority, then return the value.
- WRITE = phase-1 query a majority for the current max timestamp;
  phase-2 store (ts+1 with writer id as tiebreak, new value) at a
  majority, then ack the client.

Each op therefore runs two ``paxi.Quorum`` rounds (abd.go Get/Set).
The same protocol runs as a vmapped TPU kernel in ``sim.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

# (ts, writer_index) lexicographic pair — the (n, id) tag of the paper.
Tag = Tuple[int, int]
ZERO_TAG: Tag = (0, -1)


@register_message
@dataclass
class Query:
    """Phase-1 probe for a key's current (ts, writer, value)."""

    src: str
    tag: int          # op-local sequence number routing the reply
    key: int


@register_message
@dataclass
class QueryReply:
    src: str
    tag: int
    key: int
    ts: int
    writer: int
    value: bytes


@register_message
@dataclass
class Store:
    """Phase-2 write of (ts, writer, value) — read write-back or new write."""

    src: str
    tag: int
    key: int
    ts: int
    writer: int
    value: bytes


@register_message
@dataclass
class StoreReply:
    src: str
    tag: int


@dataclass
class _Op:
    """An in-flight client op (two quorum rounds)."""

    request: Request
    key: int
    is_read: bool
    phase: int                    # 1 = query round, 2 = store round
    quorum: Quorum
    max_ts: int = 0
    max_writer: int = -1
    max_value: bytes = b""


class ABDReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        # key -> (ts, writer, value); the register store (abd.go state)
        self.store: Dict[int, Tuple[int, int, bytes]] = {}
        self.ops: Dict[int, _Op] = {}
        self._seq = 0
        self.register(Request, self.handle_request)
        self.register(Query, self.handle_query)
        self.register(QueryReply, self.handle_query_reply)
        self.register(Store, self.handle_store)
        self.register(StoreReply, self.handle_store_reply)

    def _local(self, key: int) -> Tuple[int, int, bytes]:
        return self.store.get(key, (0, -1, b""))

    def _apply(self, key: int, ts: int, writer: int, value: bytes) -> None:
        """Install (ts, writer, value) if it beats the local tag."""
        cts, cw, _ = self._local(key)
        if (ts, writer) > (cts, cw):
            self.store[key] = (ts, writer, value)
            # mirror into the KV store on EVERY replica so /local/{key}
            # and Client.local_get see the register here too (dynamo
            # behaves the same); db.execute (not put) so a packed
            # /transaction batch unpacks and applies atomically
            self.db.execute(Command(key, value))

    # ---- client ops ----------------------------------------------------
    def handle_request(self, req: Request) -> None:
        self._seq += 1
        tag = self._seq
        op = _Op(request=req, key=req.command.key,
                 is_read=req.command.is_read(), phase=1,
                 quorum=Quorum(self.cfg.ids))
        self.ops[tag] = op
        # self-reply counts toward the quorum (broadcast excludes self)
        ts, w, v = self._local(op.key)
        op.quorum.ack(self.id)
        op.max_ts, op.max_writer, op.max_value = ts, w, v
        self.socket.broadcast(Query(str(self.id), tag, op.key))
        self._maybe_phase2(tag, op)

    # ---- phase 1 -------------------------------------------------------
    def handle_query(self, m: Query) -> None:
        ts, w, v = self._local(m.key)
        self.socket.send(ID(m.src),
                         QueryReply(str(self.id), m.tag, m.key, ts, w, v))

    def handle_query_reply(self, m: QueryReply) -> None:
        op = self.ops.get(m.tag)
        if op is None or op.phase != 1:
            return
        op.quorum.ack(ID(m.src))
        if (m.ts, m.writer) > (op.max_ts, op.max_writer):
            op.max_ts, op.max_writer, op.max_value = m.ts, m.writer, m.value
        self._maybe_phase2(m.tag, op)

    def _maybe_phase2(self, tag: int, op: _Op) -> None:
        if not op.quorum.majority():
            return
        op.phase = 2
        op.quorum = Quorum(self.cfg.ids)
        if op.is_read:
            # write-back of the max tag guarantees atomicity for readers
            ts, w, v = op.max_ts, op.max_writer, op.max_value
        else:
            ts = op.max_ts + 1
            w = self.cfg.index(self.id)
            v = op.request.command.value
        op.max_ts, op.max_writer, op.max_value = ts, w, v
        self._apply(op.key, ts, w, v)
        op.quorum.ack(self.id)
        self.socket.broadcast(Store(str(self.id), tag, op.key, ts, w, v))
        self._maybe_done(tag, op)

    # ---- phase 2 -------------------------------------------------------
    def handle_store(self, m: Store) -> None:
        self._apply(m.key, m.ts, m.writer, m.value)
        self.socket.send(ID(m.src), StoreReply(str(self.id), m.tag))

    def handle_store_reply(self, m: StoreReply) -> None:
        op = self.ops.get(m.tag)
        if op is None or op.phase != 2:
            return
        op.quorum.ack(ID(m.src))
        self._maybe_done(m.tag, op)

    def _maybe_done(self, tag: int, op: _Op) -> None:
        if not op.quorum.majority():
            return
        del self.ops[tag]
        cmd = op.request.command
        if op.is_read:
            op.request.reply(Reply(cmd, value=op.max_value))
        else:
            op.request.reply(Reply(cmd, value=b""))


def new_replica(id: ID, cfg: Config) -> ABDReplica:
    return ABDReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Wire-level identity: the sim kernel's
# four mailbox planes are exactly the host runtime's four message
# classes, so sim witnesses project onto occurrence-indexed
# Socket.drop_next directives.
TRACE_MSG_MAP = {
    "query": "Query", "query_r": "QueryReply",
    "store": "Store", "store_r": "StoreReply",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "store_ts":    "store",      # (ts, writer) tag half of the register
    "store_val":   "store",
    "op_read":     "is_read",    # in-flight op planes <-> _Op fields
    "op_key":      "request",
    "op_tag":      "tag",
    "op_ts":       "ts",
    "op_val":      "max_value",
    "acks":        "quorum",     # bit-packed ack mask <-> Quorum
    "best_ts":     "max_ts",
    "best_val":    "max_value",
    "op_snap":     "",  # linearizability-oracle snapshot at op start
    "op_age":      "",  # step-count phase timeout; host op GC is wall-clock
    "reads_done":  "",  # workload counters (metrics, not protocol state)
    "writes_done": "",
    "done_max_ts": "",  # atomicity-oracle bookkeeping
    "atomic_viol": "",  # invariant accumulator
}
