"""Chain replication as a pure TPU kernel.

Reference: paxi chain/ — a static chain (successor/predecessor from the
sorted ID list): writes enter the head, propagate down the chain, the
tail acknowledges, and reads are served at the tail [driver].  The
throughput-baseline protocol of the suite.

TPU re-design:
- Replica index IS the chain position (0 = head, R-1 = tail); the dense
  (src, dst) mailbox is used only on the two chain edges per replica.
- The head is the closed-loop client: it appends one deterministic write
  per step (val = f(seq)), so the whole pipeline sustains 1 write/step.
- Forwarding uses an optimistic go-back-N pointer per replica with
  **cumulative acks**: ``ack`` carries the sender's applied count and the
  tail-applied count (the commit frontier) — a stalled successor resets
  the pointer, so drops/dups/delays from the fuzz schedule are repaired
  without per-message bookkeeping.
- Commit = tail-applied, learned upstream via the same acks (the
  reference's tail-ack propagated to the head).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "prop": ("seq", "key", "val"),
        "ack": ("applied", "tail_n"),
    }


def encode_val(seq):
    """Deterministic write payload — lets the oracle detect any
    out-of-order or corrupted apply."""
    return seq * jnp.int32(11) + jnp.int32(5)


def key_for(seq, n_keys):
    return fib_key(seq, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array):
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    return dict(
        log_key=jnp.zeros((R, S), jnp.int32),
        log_val=jnp.zeros((R, S), jnp.int32),
        applied=jnp.zeros((R,), jnp.int32),     # in-order applied prefix
        committed=jnp.zeros((R,), jnp.int32),   # known tail-applied
        known_succ=jnp.zeros((R,), jnp.int32),  # optimistic succ progress
        seen_succ=jnp.zeros((R,), jnp.int32),   # last acked succ applied
        stall=jnp.zeros((R,), jnp.int32),
        kv=jnp.zeros((R, K), jnp.int32),
        reads_done=jnp.zeros((R,), jnp.int32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    is_head = ridx == 0
    is_tail = ridx == R - 1

    applied = state["applied"]
    log_key, log_val = state["log_key"], state["log_val"]
    kv = state["kv"]

    # ------------- receive prop from predecessor -------------------------
    m = inbox["prop"]
    pred = jnp.clip(ridx - 1, 0, R - 1)
    pv = m["valid"][pred, ridx] & ~is_head          # only the chain edge
    pseq = m["seq"][pred, ridx]
    pkey = m["key"][pred, ridx]
    pval = m["val"][pred, ridx]
    take = pv & (pseq == applied) & (applied < S)   # next expected, in order
    oh = take[:, None] & (sidx[None, :] == pseq[:, None])
    log_key = jnp.where(oh, pkey[:, None], log_key)
    log_val = jnp.where(oh, pval[:, None], log_val)
    ohk = take[:, None] & (jnp.arange(K)[None, :] == pkey[:, None])
    kv = jnp.where(ohk, pval[:, None], kv)
    applied = applied + take

    # ------------- head appends one write per step -----------------------
    h_seq = applied * is_head
    h_do = is_head & (applied < S)
    h_key, h_val = key_for(h_seq, K), encode_val(h_seq)
    oh = h_do[:, None] & (sidx[None, :] == h_seq[:, None])
    log_key = jnp.where(oh, h_key[:, None], log_key)
    log_val = jnp.where(oh, h_val[:, None], log_val)
    ohk = h_do[:, None] & (jnp.arange(K)[None, :] == h_key[:, None])
    kv = jnp.where(ohk, h_val[:, None], kv)
    applied = applied + h_do

    # ------------- receive cumulative ack from successor -----------------
    m = inbox["ack"]
    succ = jnp.clip(ridx + 1, 0, R - 1)
    av = m["valid"][succ, ridx] & ~is_tail
    a_applied = jnp.where(av, m["applied"][succ, ridx], -1)
    a_tail = jnp.where(av, m["tail_n"][succ, ridx], 0)
    progress = a_applied > state["seen_succ"]
    seen_succ = jnp.maximum(state["seen_succ"], a_applied)
    committed = jnp.maximum(state["committed"], a_tail)
    committed = jnp.where(is_tail, applied, committed)

    # go-back-N: successor stalled => rewind the optimistic pointer
    stall = jnp.where(progress | ~av, 0, state["stall"] + av)
    rewind = stall >= cfg.retry_timeout
    known_succ = jnp.where(rewind, seen_succ, state["known_succ"])
    stall = jnp.where(rewind, 0, stall)

    # ------------- forward next entry to successor -----------------------
    send = (~is_tail) & (applied > known_succ)
    s_seq = jnp.clip(known_succ, 0, S - 1)
    s_key = jnp.take_along_axis(log_key, s_seq[:, None], axis=1)[:, 0]
    s_val = jnp.take_along_axis(log_val, s_seq[:, None], axis=1)[:, 0]
    to_succ = ridx[None, :] == succ[:, None]
    out_prop = {
        "valid": send[:, None] & to_succ,
        "seq": jnp.broadcast_to(s_seq[:, None], (R, R)),
        "key": jnp.broadcast_to(s_key[:, None], (R, R)),
        "val": jnp.broadcast_to(s_val[:, None], (R, R)),
    }
    known_succ = known_succ + send

    # ------------- ack upstream every step (cumulative) ------------------
    to_pred = ridx[None, :] == pred[:, None]
    out_ack = {
        "valid": (~is_head)[:, None] & to_pred,
        "applied": jnp.broadcast_to(applied[:, None], (R, R)),
        "tail_n": jnp.broadcast_to(committed[:, None], (R, R)),
    }

    # ------------- reads are served at the tail --------------------------
    # a read is a real local lookup of the latest applied write's key;
    # counted only once the register holds data (reference: reads at
    # tail are lease-free local reads)
    r_key = key_for(jnp.maximum(applied - 1, 0), K)
    r_val = jnp.take_along_axis(kv, r_key[:, None], axis=1)[:, 0]
    served = is_tail & (applied > 0) & (r_val != 0)
    reads_done = state["reads_done"] + served

    new_state = dict(
        log_key=log_key, log_val=log_val, applied=applied,
        committed=committed, known_succ=known_succ, seen_succ=seen_succ,
        stall=stall, kv=kv, reads_done=reads_done,
    )
    return new_state, {"prop": out_prop, "ack": out_ack}


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": state["committed"][0],   # head's commit frontier
        "tail_applied": state["applied"][cfg.n_replicas - 1],
        "reads_done": jnp.sum(state["reads_done"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Every applied entry matches the head's deterministic write
    (catches out-of-order / corrupted applies).  2. applied/committed
    monotone.  3. applied is nonincreasing down the chain.  4. No commit
    beyond the tail's applied prefix."""
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    ap = new["applied"]
    in_pref = sidx[None, :] < ap[:, None]
    v_det = jnp.sum(in_pref & (new["log_val"] != encode_val(sidx)[None, :]))
    v_det += jnp.sum(in_pref
                     & (new["log_key"] != key_for(sidx, cfg.n_keys)[None, :]))
    v_mono = jnp.sum(ap < old["applied"])
    v_mono += jnp.sum(new["committed"] < old["committed"])
    v_chain = jnp.sum(ap[:-1] < ap[1:])
    v_commit = jnp.sum(new["committed"] > ap[cfg.n_replicas - 1])
    return (v_det + v_mono + v_chain + v_commit).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="chain",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
