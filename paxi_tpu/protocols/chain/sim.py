"""Chain replication as a pure TPU kernel (lane-major layout).

Reference: paxi chain/ — a static chain (successor/predecessor from the
sorted ID list): writes enter the head, propagate down the chain, the
tail acknowledges, and reads are served at the tail [driver].  The
throughput-baseline protocol of the suite.

TPU re-design:
- **Lane-major batch layout** (see sim/lanes.py): state ``(R, G)`` /
  ``(R, S, G)``, mailbox planes ``(src, dst, G)`` — the group axis
  feeds the vector lanes.
- Replica index IS the chain position (0 = head, R-1 = tail); the dense
  (src, dst) mailbox is used only on the two chain edges per replica.
- The head is the closed-loop client: it appends one deterministic write
  per step (val = f(seq)), so the whole pipeline sustains 1 write/step.
- The log is a **ring over absolute sequence numbers** (seq % S): the
  head applies window flow control (applied - committed < S), so every
  entry still in flight anywhere on the chain is ring-resident and the
  horizon is unbounded (SURVEY §7 slot recycling; sim/ring.py).
- Forwarding uses an optimistic go-back-N pointer per replica with
  **cumulative acks**: ``ack`` carries the sender's applied count and the
  tail-applied count (the commit frontier) — a stalled successor resets
  the pointer, so drops/dups/delays from the fuzz schedule are repaired
  without per-message bookkeeping.  (A successor's applied count never
  trails my commit frontier, so go-back-N targets are always resident.)
- Commit = tail-applied, learned upstream via the same acks (the
  reference's tail-ack propagated to the head).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import inscan
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx


def _seq_at(applied, S: int):
    """The absolute sequence number ring cell ``c`` holds at a replica
    with ``applied`` entries: the newest ``a`` < applied congruent to
    ``c`` (mod S); negative = never written.  The chain log is already
    fixed-cell (``seq % S`` — see the module docstring), so this is
    pure elementwise arithmetic, same as invariants() uses."""
    sidx = jnp.arange(S, dtype=jnp.int32)
    last = applied[:, None, :] - 1
    return last - ((last - sidx[None, :, None]) % S)


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "prop": ("seq", "key", "val"),
        # go-back-N repair channel: every step the sender retransmits the
        # oldest entry its successor has not cumulatively acked.  Under a
        # drop/delay schedule this refills the successor's next hole
        # within ~1 RTT instead of a stall-timeout rewind; fault-free it
        # is an ignored duplicate (pseq < applied).  A separate plane so
        # it never collides with the pipeline's new-entry sends in the
        # same wheel slot.
        "rep": ("seq", "key", "val"),
        "ack": ("applied", "tail_n"),
    }


def encode_val(seq):
    """Deterministic write payload — lets the oracle detect any
    out-of-order or corrupted apply."""
    return seq * jnp.int32(11) + jnp.int32(5)


def key_for(seq, n_keys):
    return fib_key(seq, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    i32 = jnp.int32
    return dict(
        log_key=jnp.zeros((R, S, G), i32),
        log_val=jnp.zeros((R, S, G), i32),
        applied=jnp.zeros((R, G), i32),     # in-order applied prefix (abs)
        committed=jnp.zeros((R, G), i32),   # known tail-applied
        known_succ=jnp.zeros((R, G), i32),  # optimistic succ progress
        seen_succ=jnp.zeros((R, G), i32),   # last acked succ applied
        stall=jnp.zeros((R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        reads_done=jnp.zeros((R, G), i32),
        # ---- on-device observability (PR-11 template: ``m_`` planes,
        # excluded from the trace witness hash, never read by protocol
        # logic — PXM10x).  m_prop_t stamps each write's head-append
        # step at its ring cell; when the commit frontier (tail-applied
        # learned at the head) advances, the covered writes bin their
        # append->commit step delta into the shared log2 histogram
        # (metrics/lathist) — the full-pipeline latency of chain
        # replication.  m_inscan_viol accumulates the in-scan
        # linearizability spot-check (sim/inscan).
        m_prop_t=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    is_head = (ridx == 0)[:, None]
    is_tail = (ridx == R - 1)[:, None]

    applied = state["applied"]
    log_key, log_val = state["log_key"], state["log_val"]
    kv = state["kv"]
    G = applied.shape[-1]

    def edge(plane, src):
        """plane[src[r], r, :] — read the (src -> me) mailbox edge,
        unrolled over the tiny R axis (no gather on the hot path)."""
        acc = jnp.zeros(plane.shape[1:], plane.dtype)
        for s in range(R):
            acc = jnp.where((src == s)[:, None], plane[s], acc)
        return acc

    def write_ring(plane, do, seq, value):
        """Masked write of ``value (R, G)`` at ring position seq % S."""
        oh = do[:, None, :] & (sidx[None, :, None]
                               == (seq % S)[:, None, :])
        return jnp.where(oh, value[:, None, :], plane)

    # ------------- receive prop/repair from predecessor ------------------
    pred = jnp.clip(ridx - 1, 0, R - 1)
    for box in ("prop", "rep"):
        m = inbox[box]
        pv = edge(m["valid"], pred) & ~is_head      # only the chain edge
        pseq = edge(m["seq"], pred)
        pkey = edge(m["key"], pred)
        pval = edge(m["val"], pred)
        # next expected, in order; ring has room because my applied can
        # never run more than S ahead of the commit frontier (head flow
        # control)
        take = pv & (pseq == applied)
        log_key = write_ring(log_key, take, pseq, pkey)
        log_val = write_ring(log_val, take, pseq, pval)
        ohk = take[:, None, :] & (kidx[None, :, None] == pkey[:, None, :])
        kv = jnp.where(ohk, pval[:, None, :], kv)
        applied = applied + take

    # ------------- head appends one write per step (flow control) --------
    h_seq = applied * is_head
    h_do = is_head & (applied - state["committed"] < S)
    h_key, h_val = key_for(h_seq, K), encode_val(h_seq)
    log_key = write_ring(log_key, h_do, h_seq, h_key)
    log_val = write_ring(log_val, h_do, h_seq, h_val)
    ohk = h_do[:, None, :] & (kidx[None, :, None] == h_key[:, None, :])
    kv = jnp.where(ohk, h_val[:, None, :], kv)
    applied = applied + h_do
    # latency clock: stamp the append step at the write's ring cell
    # (head lanes only; cell reuse IS the re-arm — an in-flight write
    # stays ring-resident until committed, so its stamp survives)
    m_prop_t = write_ring(state["m_prop_t"], h_do, h_seq,
                          jnp.broadcast_to(ctx.t, h_seq.shape))

    # ------------- receive cumulative ack from successor -----------------
    m = inbox["ack"]
    succ = jnp.clip(ridx + 1, 0, R - 1)
    av = edge(m["valid"], succ) & ~is_tail
    a_applied = jnp.where(av, edge(m["applied"], succ), -1)
    a_tail = jnp.where(av, edge(m["tail_n"], succ), 0)
    progress = a_applied > state["seen_succ"]
    seen_succ = jnp.maximum(state["seen_succ"], a_applied)
    committed = jnp.maximum(state["committed"], a_tail)
    committed = jnp.where(is_tail, applied, committed)

    # in-kernel commit latency, measured at the head (the proposer):
    # the commit-frontier advance [old, new) bins each covered write's
    # append->commit step delta — all covered seqs are ring-resident at
    # the head (flow control keeps applied - committed < S), so this is
    # one elementwise mask over the ring, no gathers
    seq_h = _seq_at(applied, S)
    newly = (is_head[:, None, :]
             & (seq_h >= state["committed"][:, None, :])
             & (seq_h < committed[:, None, :]) & (seq_h >= 0))
    lat_dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_lat_hist = lathist.hist_update(state["m_lat_hist"], lat_dt, newly)
    m_lat_sum = state["m_lat_sum"] + jnp.sum(
        jnp.where(newly, lat_dt, 0), axis=(0, 1), dtype=jnp.int32)

    # go-back-N: successor stalled => rewind the optimistic pointer
    stall = jnp.where(progress | ~av, 0, state["stall"] + av)
    rewind = stall >= cfg.retry_timeout
    known_succ = jnp.where(rewind, seen_succ, state["known_succ"])
    stall = jnp.where(rewind, 0, stall)

    # ------------- forward next entry to successor -----------------------
    send = (~is_tail) & (applied > known_succ)
    s_seq = known_succ                               # absolute
    oh_s = sidx[None, :, None] == (s_seq % S)[:, None, :]
    s_key = jnp.sum(jnp.where(oh_s, log_key, 0), axis=1)
    s_val = jnp.sum(jnp.where(oh_s, log_val, 0), axis=1)
    to_succ = (ridx[None, :] == succ[:, None])[:, :, None]
    out_prop = {
        "valid": send[:, None, :] & to_succ,
        "seq": jnp.broadcast_to(s_seq[:, None, :], (R, R, G)),
        "key": jnp.broadcast_to(s_key[:, None, :], (R, R, G)),
        "val": jnp.broadcast_to(s_val[:, None, :], (R, R, G)),
    }
    known_succ = known_succ + send

    # ------------- repair: retransmit the oldest unacked entry -----------
    r_send = (~is_tail) & (applied > seen_succ)
    r_seq = seen_succ
    oh_r2 = sidx[None, :, None] == (r_seq % S)[:, None, :]
    out_rep = {
        "valid": r_send[:, None, :] & to_succ,
        "seq": jnp.broadcast_to(r_seq[:, None, :], (R, R, G)),
        "key": jnp.broadcast_to(
            jnp.sum(jnp.where(oh_r2, log_key, 0), axis=1)[:, None, :],
            (R, R, G)),
        "val": jnp.broadcast_to(
            jnp.sum(jnp.where(oh_r2, log_val, 0), axis=1)[:, None, :],
            (R, R, G)),
    }

    # ------------- ack upstream every step (cumulative) ------------------
    to_pred = (ridx[None, :] == pred[:, None])[:, :, None]
    out_ack = {
        "valid": (~is_head)[:, :, None] & to_pred,
        "applied": jnp.broadcast_to(applied[:, None, :], (R, R, G)),
        "tail_n": jnp.broadcast_to(committed[:, None, :], (R, R, G)),
    }

    # ------------- reads are served at the tail --------------------------
    # a read is a real local lookup of the latest applied write's key;
    # counted only once the register holds data (reference: reads at
    # tail are lease-free local reads)
    r_key = key_for(jnp.maximum(applied - 1, 0), K)
    oh_r = kidx[None, :, None] == r_key[:, None, :]
    r_val = jnp.sum(jnp.where(oh_r, kv, 0), axis=1)
    served = is_tail & (applied > 0) & (r_val != 0)
    reads_done = state["reads_done"] + served

    # in-scan linearizability spot-check (sim/inscan): applied is the
    # execute frontier, the commit frontier is the base analog (cells
    # below it are settled), log_val the committed-value plane — the
    # chain log is already fixed-cell, so the abs plane is _seq_at
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["applied"], applied, state["committed"], committed,
        _seq_at(state["applied"], S), _seq_at(applied, S),
        state["log_val"], log_val,
        (_seq_at(state["applied"], S) >= 0)
        & (_seq_at(state["applied"], S)
           < state["committed"][:, None, :]),
        (_seq_at(applied, S) >= 0)
        & (_seq_at(applied, S) < committed[:, None, :]),
        kv=kv, lane_major=True)

    new_state = dict(
        log_key=log_key, log_val=log_val, applied=applied,
        committed=committed, known_succ=known_succ, seen_succ=seen_succ,
        stall=stall, kv=kv, reads_done=reads_done,
        m_prop_t=m_prop_t, m_lat_hist=m_lat_hist, m_lat_sum=m_lat_sum,
        m_inscan_viol=m_inscan_viol,
    )
    return new_state, {"prop": out_prop, "rep": out_rep, "ack": out_ack}


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(state["committed"][0]),  # head frontier
        "tail_applied": jnp.sum(state["applied"][cfg.n_replicas - 1]),
        "reads_done": jnp.sum(state["reads_done"]),
        # on-device observability scalars (PR-11 contract; the
        # histogram itself rides in state as m_lat_hist)
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": jnp.sum(state["m_lat_hist"]),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Every ring-resident applied entry matches the head's
    deterministic write (catches out-of-order / corrupted applies): for
    a replica with applied = a, ring position p holds absolute seq
    a-1 - ((a-1-p) mod S) when that is >= 0.  2. applied/committed
    monotone.  3. applied is nonincreasing down the chain.  4. No commit
    beyond the tail's applied prefix."""
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    ap = new["applied"]                               # (R, G)
    last = ap[:, None, :] - 1                         # (R, 1, G)
    seq_at = last - ((last - sidx[None, :, None]) % S)
    live = seq_at >= 0
    v_det = jnp.sum(live & (new["log_val"] != encode_val(seq_at)))
    v_det += jnp.sum(live & (new["log_key"] != key_for(seq_at, cfg.n_keys)))
    v_mono = jnp.sum(ap < old["applied"])
    v_mono += jnp.sum(new["committed"] < old["committed"])
    v_chain = jnp.sum(ap[:-1] < ap[1:])
    v_commit = jnp.sum(new["committed"] > ap[cfg.n_replicas - 1][None])
    return (v_det + v_mono + v_chain + v_commit).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="chain",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
