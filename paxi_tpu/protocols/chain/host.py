"""Chain replication replica for the host (deployment) runtime.

Reference: paxi chain/ — a static chain ordered head -> ... -> tail over
the sorted node IDs [driver]: writes enter at the head, are applied and
propagated down the chain, and are acknowledged once the tail applies
them; reads are served at the tail (which is why the scheme is
linearizable: the tail's state is the committed prefix).  Requests
arriving at the wrong end are forwarded (node.go Forward).

The same protocol runs as a vmapped TPU kernel in ``sim.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class Propagate:
    """A write travelling down the chain (chain/ Propagate msg)."""

    seq: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class Ack:
    """Tail -> head: the write at ``seq`` reached the end of the chain."""

    seq: int


class ChainReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        order = sorted(cfg.ids)
        self.chain = order
        self.pos = order.index(self.id)
        self.head = order[0]
        self.tail = order[-1]
        self.succ: Optional[ID] = (
            order[self.pos + 1] if self.pos + 1 < len(order) else None)
        self.seq = 0            # head: last assigned; others: last applied
        self.pending: Dict[int, Request] = {}   # head: seq -> client request
        self.buffer: Dict[int, Propagate] = {}  # out-of-order propagates
        self.register(Request, self.handle_request)
        self.register(Propagate, self.handle_propagate)
        self.register(Ack, self.handle_ack)

    def is_head(self) -> bool:
        return self.id == self.head

    def is_tail(self) -> bool:
        return self.id == self.tail

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        if req.command.is_read():
            # reads at the tail (committed prefix)
            if self.is_tail():
                value = self.db.execute(req.command)
                req.reply(Reply(req.command, value=value))
            else:
                self.forward(self.tail, req)
            return
        # writes at the head
        if not self.is_head():
            self.forward(self.head, req)
            return
        self.seq += 1
        self.pending[self.seq] = req
        self.db.execute(req.command)
        if self.succ is None:       # single-node chain: head == tail
            self._ack(self.seq)
        else:
            c = req.command
            self.socket.send(self.succ, Propagate(
                self.seq, c.key, c.value, c.client_id, c.command_id))

    # ---- down the chain ------------------------------------------------
    def handle_propagate(self, m: Propagate) -> None:
        if m.seq <= self.seq:
            return              # duplicate of an already-applied write
        self.buffer[m.seq] = m
        # apply strictly in sequence order (TCP is FIFO per edge, but a
        # restarted link may reorder across reconnects — buffer defends)
        while self.seq + 1 in self.buffer:
            m = self.buffer.pop(self.seq + 1)
            self.seq += 1
            self.db.execute(Command(m.key, m.value, m.client_id,
                                    m.command_id))
            if self.is_tail():
                self.socket.send(self.head, Ack(m.seq))
            else:
                self.socket.send(self.succ, m)

    # ---- back to the head ----------------------------------------------
    def handle_ack(self, m: Ack) -> None:
        self._ack(m.seq)

    def _ack(self, seq: int) -> None:
        req = self.pending.pop(seq, None)
        if req is not None:
            req.reply(Reply(req.command, value=b""))


def new_replica(id: ID, cfg: Config) -> ChainReplica:
    return ChainReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim's ``rep`` plane is its go-back-N
# retransmit channel for the SAME wire message a ``prop`` carries, so
# both project onto Propagate; dropping either in the sim is dropping a
# Propagate on the host.
TRACE_MSG_MAP = {
    "prop": "Propagate", "rep": "Propagate", "ack": "Ack",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "log_key":    "chain",   # slot-ring planes <-> the chain list
    "log_val":    "chain",
    "applied":    "pos",     # in-order applied prefix <-> chain position
    "committed":  "head",    # known tail-applied <-> head bookkeeping
    "known_succ": "succ",    # successor progress <-> successor link
    "seen_succ":  "succ",
    "kv":         "db",
    "stall":      "",  # retransmit ticks: host retries are wall-clock
    "reads_done": "",  # workload counter (metrics, not protocol state)
}
