"""Chain replication replica for the host (deployment) runtime.

Reference: paxi chain/ — a static chain ordered head -> ... -> tail over
the sorted node IDs [driver]: writes enter at the head, are applied and
propagated down the chain, and are acknowledged once the tail applies
them; reads are served at the tail (which is why the scheme is
linearizable: the tail's state is the committed prefix).  Requests
arriving at the wrong end are forwarded (node.go Forward).

Batched commit path (HT-Paxos, PAPERS.md — the same lever the paxos
host gained in PR 7, reusing ``BatchBuffer``): the head accumulates
write requests and ONE chain descent carries the whole batch — a
``Propagate`` holds a command *list* under one sequence number, every
link applies it atomically in order, and the tail's single ``Ack``
fans replies out to every client in the batch.  Batch atomicity rides
on message atomicity: a link either receives the entire batch or
nothing, so no fault schedule can apply half a batch.

The same protocol runs as a vmapped TPU kernel in ``sim.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.host.batch import BatchBuffer
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class Propagate:
    """A write batch travelling down the chain (chain/ Propagate msg,
    generalized to a command list under one sequence number)."""

    seq: int
    # [[key, value, client_id, command_id], ...] — wire-friendly lists
    cmds: list = field(default_factory=list)


@register_message
@dataclass
class Ack:
    """Tail -> head: the write at ``seq`` reached the end of the chain."""

    seq: int


class ChainReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        order = sorted(cfg.ids)
        self.chain = order
        self.pos = order.index(self.id)
        self.head = order[0]
        self.tail = order[-1]
        self.succ: Optional[ID] = (
            order[self.pos + 1] if self.pos + 1 < len(order) else None)
        self.seq = 0            # head: last assigned; others: last applied
        # head: seq -> the batch's client requests
        self.pending: Dict[int, List[Request]] = {}
        self.buffer: Dict[int, Propagate] = {}  # out-of-order propagates
        # the batched commit path: head-side write accumulation; wall
        # timers never fire under the virtual-clock fabric, so a
        # fabric-driven replica is forced onto tick flushes.  The head
        # is static (order[0], no elections), so only it carries the
        # buffer — non-head replicas would just export dead
        # paxi_batch_* series
        if self.id == self.head:
            self.batch = BatchBuffer(
                self._flush_batch, max_size=cfg.batch_size,
                max_wait=0.0 if self.socket.fabric is not None
                else cfg.batch_wait,
                metrics=self.metrics)
        self.register(Request, self.handle_request)
        self.register(Propagate, self.handle_propagate)
        self.register(Ack, self.handle_ack)

    def is_head(self) -> bool:
        return self.id == self.head

    def is_tail(self) -> bool:
        return self.id == self.tail

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        if req.command.is_read():
            # reads at the tail (committed prefix)
            if self.is_tail():
                value = self.db.execute(req.command)
                req.reply(Reply(req.command, value=value))
            else:
                self.forward(self.tail, req)
            return
        # writes batch at the head: one descent per burst
        if not self.is_head():
            self.forward(self.head, req)
            return
        self.batch.add(req)

    def _flush_batch(self, reqs: List[Request]) -> None:
        """BatchBuffer flush: ONE sequence number (hence one descent
        and one tail Ack) carries every write of the burst."""
        self.seq += 1
        self.pending[self.seq] = list(reqs)
        for r in reqs:
            self.db.execute(r.command)
        if self.succ is None:       # single-node chain: head == tail
            self._ack(self.seq)
        else:
            self.socket.send(self.succ, Propagate(
                self.seq,
                [[r.command.key, r.command.value, r.command.client_id,
                  r.command.command_id] for r in reqs]))

    # ---- down the chain ------------------------------------------------
    def handle_propagate(self, m: Propagate) -> None:
        if m.seq <= self.seq:
            return              # duplicate of an already-applied batch
        self.buffer[m.seq] = m
        # apply strictly in sequence order (TCP is FIFO per edge, but a
        # restarted link may reorder across reconnects — buffer defends)
        while self.seq + 1 in self.buffer:
            m = self.buffer.pop(self.seq + 1)
            self.seq += 1
            for k, v, cid, cmid in m.cmds:
                self.db.execute(Command(int(k), v, cid, int(cmid)))
            if self.is_tail():
                self.socket.send(self.head, Ack(m.seq))
            else:
                self.socket.send(self.succ, m)

    # ---- back to the head ----------------------------------------------
    def handle_ack(self, m: Ack) -> None:
        self._ack(m.seq)

    def _ack(self, seq: int) -> None:
        for req in self.pending.pop(seq, []):
            req.reply(Reply(req.command, value=b""))


def new_replica(id: ID, cfg: Config) -> ChainReplica:
    return ChainReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim's ``rep`` plane is its go-back-N
# retransmit channel for the SAME wire message a ``prop`` carries, so
# both project onto Propagate; dropping either in the sim is dropping a
# Propagate on the host.
TRACE_MSG_MAP = {
    "prop": "Propagate", "rep": "Propagate", "ack": "Ack",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "log_key":    "chain",   # slot-ring planes <-> the chain list
    "log_val":    "chain",
    "applied":    "pos",     # in-order applied prefix <-> chain position
    "committed":  "head",    # known tail-applied <-> head bookkeeping
    "known_succ": "succ",    # successor progress <-> successor link
    "seen_succ":  "succ",
    "kv":         "db",
    "stall":      "",  # retransmit ticks: host retries are wall-clock
    "reads_done": "",  # workload counter (metrics, not protocol state)
    # on-device observability (PR 11, threaded through chain in PR 15)
    # — measurement planes, excluded from the trace witness hash; the
    # host twins are the registry's live latency histograms and the
    # post-hoc linearizability checker
    "m_prop_t":      "",
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
}
