"""Dynamo-style eventually-consistent store for the host runtime.

Reference: the paxi lineage's dynamo/ package (SURVEY §2.2 "others") —
a quorum R/W store with NO consensus: any replica coordinates an op;
writes stamp a Lamport (counter, node) version, store locally, and
replicate to all peers, acking the client after W acknowledgements;
reads query all peers, wait for R replies, return the max-version value
and *read-repair* stale replicas.  W + R > N gives read-your-writes in
the failure-free case; conflicting concurrent writes resolve
last-writer-wins by version — weaker than ABD (which serializes through
two quorum phases) and exactly the contrast case the benchmark's
linearizability checker is expected to flag under concurrency.

The sim kernel (sim.py) checks the honest guarantees instead:
per-replica version monotonicity and eventual convergence.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, Tuple

from paxi_tpu.core.command import Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

Ver = Tuple[int, int]          # (lamport counter, node index)
ZERO: Ver = (0, -1)


@register_message
@dataclass
class RWrite:
    """Coordinator -> peers: replicate (key, version, value)."""

    src: str
    tag: int
    key: int
    counter: int
    node: int
    value: bytes


@register_message
@dataclass
class RWriteAck:
    src: str
    tag: int


@register_message
@dataclass
class RRead:
    src: str
    tag: int
    key: int


@register_message
@dataclass
class RReadReply:
    src: str
    tag: int
    key: int
    counter: int
    node: int
    value: bytes


@dataclass
class _Op:
    request: Request
    key: int
    is_read: bool
    quorum: Quorum
    best: Ver = ZERO
    best_value: bytes = b""
    reported: Dict[ID, Ver] = None  # per-responder versions (reads)
    done: bool = False              # replied to client; repair-only phase
    born: float = field(default_factory=time.monotonic)


class DynamoReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.store: Dict[int, Tuple[int, int, bytes]] = {}
        self.clock = 0
        self.ops: Dict[int, _Op] = {}
        self._seq = 0
        # W and R: majority each (W + R > N); the knob dynamo exposes
        self.W = cfg.n // 2 + 1
        self.R = cfg.n // 2 + 1
        # op GC runs on wall-clock age from a periodic timer (like the
        # epaxos recovery watchdog), not piggybacked on request arrivals
        # — otherwise ops wedged below quorum by a partition never get
        # their 'quorum timed out' reply once client traffic stops
        # (ADVICE r2 low)
        self.op_timeout = 1.0
        self.gc_interval = 0.25
        self.register(Request, self.handle_request)
        self.register(RWrite, self.handle_write)
        self.register(RWriteAck, self.handle_write_ack)
        self.register(RRead, self.handle_read)
        self.register(RReadReply, self.handle_read_reply)

    def _local(self, key: int) -> Tuple[int, int, bytes]:
        return self.store.get(key, (0, -1, b""))

    def _apply(self, key: int, counter: int, node: int, value: bytes) -> None:
        """Last-writer-wins merge by (counter, node) version."""
        c, n, _ = self._local(key)
        if (counter, node) > (c, n):
            self.store[key] = (counter, node, value)
            self.clock = max(self.clock, counter)
            self.db.put(key, value)

    async def start(self) -> None:
        await super().start()
        self._tasks.append(asyncio.create_task(self._gc_watchdog()))

    async def _gc_watchdog(self) -> None:
        """Expire aged ops: answered reads are kept only for straggler
        repair; ops wedged below quorum by crashed/partitioned peers get
        the 'quorum timed out' error even if client traffic has stopped."""
        while True:
            await asyncio.sleep(self.gc_interval)
            now = time.monotonic()
            stale = [t for t, op in self.ops.items()
                     if now - op.born > self.op_timeout]
            for t in stale:
                op = self.ops.pop(t)
                if not op.done:
                    op.request.reply(Reply(op.request.command,
                                           err="quorum timed out"))

    # ---- coordinator ---------------------------------------------------
    def handle_request(self, req: Request) -> None:
        self._seq += 1
        tag = self._seq
        key = req.command.key
        if req.command.is_read():
            op = _Op(req, key, True, Quorum(self.cfg.ids), reported={})
            self.ops[tag] = op
            c, n, v = self._local(key)
            op.best, op.best_value = (c, n), v
            op.reported[self.id] = (c, n)
            op.quorum.ack(self.id)
            self.socket.broadcast(RRead(str(self.id), tag, key))
            self._read_done(tag, op)
        else:
            self.clock += 1
            ver = (self.clock, self.cfg.index(self.id))
            self._apply(key, ver[0], ver[1], req.command.value)
            op = _Op(req, key, False, Quorum(self.cfg.ids))
            self.ops[tag] = op
            op.quorum.ack(self.id)
            self.socket.broadcast(RWrite(str(self.id), tag, key,
                                         ver[0], ver[1],
                                         req.command.value))
            self._write_done(tag, op)

    # ---- replication ---------------------------------------------------
    def handle_write(self, m: RWrite) -> None:
        self._apply(m.key, m.counter, m.node, m.value)
        self.socket.send(ID(m.src), RWriteAck(str(self.id), m.tag))

    def handle_write_ack(self, m: RWriteAck) -> None:
        op = self.ops.get(m.tag)
        if op is None or op.is_read:
            return
        op.quorum.ack(ID(m.src))
        self._write_done(m.tag, op)

    def _write_done(self, tag: int, op: _Op) -> None:
        if op.quorum.size() >= self.W:
            del self.ops[tag]
            op.request.reply(Reply(op.request.command, value=b""))

    # ---- reads + read repair -------------------------------------------
    def handle_read(self, m: RRead) -> None:
        c, n, v = self._local(m.key)
        self.socket.send(ID(m.src),
                         RReadReply(str(self.id), m.tag, m.key, c, n, v))

    def handle_read_reply(self, m: RReadReply) -> None:
        op = self.ops.get(m.tag)
        if op is None or not op.is_read:
            return
        src = ID(m.src)
        op.quorum.ack(src)
        op.reported[src] = (m.counter, m.node)
        newer = (m.counter, m.node) > op.best
        if newer:
            op.best, op.best_value = (m.counter, m.node), m.value
            if op.done:
                # newer version surfaced after the client reply: adopt it
                # locally so our own store is not the laggard
                self._apply(op.key, op.best[0], op.best[1], op.best_value)
        if op.done:
            # repair-only phase: the client is answered, but a straggler
            # that reports a stale version still gets the write-back —
            # exactly the laggards read repair exists to heal.  If the
            # straggler RAISED the best, everyone who reported the old
            # best is now stale too: re-repair them all.
            if newer:
                for peer in op.reported:
                    self._repair_peer(op, peer)
            else:
                self._repair_peer(op, src)
            if op.quorum.size() >= len(self.cfg.ids):
                del self.ops[m.tag]
            return
        self._read_done(m.tag, op)

    def _repair_peer(self, op: _Op, peer: ID) -> None:
        if peer != self.id and op.best > ZERO and op.reported[peer] < op.best:
            self.socket.send(peer, RWrite(
                str(self.id), 0, op.key, op.best[0], op.best[1],
                op.best_value))

    def _read_done(self, tag: int, op: _Op) -> None:
        if op.quorum.size() < self.R:
            return
        op.done = True
        if op.quorum.size() >= len(self.cfg.ids):
            del self.ops[tag]
        # read repair, targeted: only responders that reported a version
        # below the winner get the write-back (healthy clusters pay no
        # repair traffic).  The op stays alive (done=True) until all N
        # replies arrive so post-quorum stragglers are repaired too.
        if op.best > ZERO:
            self._apply(op.key, op.best[0], op.best[1], op.best_value)
            for peer in op.reported:
                self._repair_peer(op, peer)
        op.request.reply(Reply(op.request.command, value=op.best_value))


def new_replica(id: ID, cfg: Config) -> DynamoReplica:
    return DynamoReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim models replication as one
# anti-entropy gossip plane; the host's replica-to-replica value
# propagation is RWrite, so a dropped gossip edge projects onto
# dropping the write replication on that edge (read-path traffic has
# no sim plane and stays unmapped on purpose).
TRACE_MSG_MAP = {
    "gossip": "RWrite",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    "ver_c": "store",   # (counter, node) version halves of the store tag
    "ver_n": "store",
    "writes": "",  # workload counter (metrics, not protocol state)
}
