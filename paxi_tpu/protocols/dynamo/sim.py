"""Dynamo-style eventual store as a pure TPU kernel.

Reference: the paxi lineage's dynamo/ package (SURVEY §2.2 "others") —
no consensus: writes stamp Lamport (counter, node) versions, replicate
best-effort, and merge last-writer-wins; anti-entropy gossip heals
divergence.  See host.py for the deployment form.

TPU re-design (lane-major layout — see sim/lanes.py):
- The kernel operates on the whole group batch with the group axis LAST
  (version planes ``ver_c/ver_n[R, K, G]``, mailbox planes
  ``(src, dst, G)``) so the group axis feeds the 8x128 vector lanes.
- The value is a deterministic function of the version, so payloads
  never need to be carried or stored; LWW merge is a lexicographic max.
- Each step, each replica writes one hashed key while ``t <
  write_rounds`` (= cfg.n_slots — the write window), then switches to
  pure anti-entropy: broadcasting a rotating key's version.  After
  quiescence, gossip alone must converge every replica (the honest
  guarantee of an eventual store; the convergence count is a metric and
  the quiesced run's endpoint is asserted in tests).
- The always-on safety oracle checks what dynamo really promises:
  per-(replica, key) version monotonicity and Lamport-clock sanity —
  NOT linearizability, which this protocol intentionally lacks (the
  host benchmark's checker is expected to flag it under contention).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.ring import dst_major
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {"gossip": ("key", "c", "n")}


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, K, G = cfg.n_replicas, cfg.n_keys, n_groups
    del rng
    return dict(
        ver_c=jnp.zeros((R, K, G), jnp.int32),
        ver_n=jnp.full((R, K, G), -1, jnp.int32),
        clock=jnp.zeros((R, G), jnp.int32),
        writes=jnp.zeros((G,), jnp.int32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, K = cfg.n_replicas, cfg.n_keys
    ridx = jnp.arange(R, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)

    ver_c = state["ver_c"]                              # (R, K, G)
    ver_n = state["ver_n"]
    clock = state["clock"]                              # (R, G)
    G = clock.shape[-1]

    # ---------------- merge incoming gossip (LWW by (c, n)) -------------
    m = inbox["gossip"]
    v = dst_major(m["valid"])                           # (me, src, G)
    g_key = dst_major(m["key"])
    g_c = dst_major(m["c"])
    g_n = dst_major(m["n"])
    oh = v[:, :, None, :] & (g_key[:, :, None, :]
                             == kidx[None, None, :, None])  # (me,src,K,G)
    in_c = jnp.max(jnp.where(oh, g_c[:, :, None, :], -1), axis=1)
    pick = jnp.argmax(jnp.where(oh, g_c[:, :, None, :] * R
                                + jnp.maximum(g_n[:, :, None, :], 0), -1),
                      axis=1)                           # (me, K, G)
    in_n = jnp.zeros_like(in_c)                         # (me, K, G)
    for s in range(R):      # masked select over the tiny src axis
        in_n = jnp.where(pick == s, g_n[:, s, None, :], in_n)
    has = jnp.any(oh, axis=1)
    newer = has & ((in_c > ver_c)
                   | ((in_c == ver_c) & (in_n > ver_n)))
    ver_c = jnp.where(newer, in_c, ver_c)
    ver_n = jnp.where(newer, in_n, ver_n)
    clock = jnp.maximum(clock, jnp.max(ver_c, axis=1))

    # ---------------- local write while inside the write window ---------
    writing = ctx.t < cfg.n_slots
    k_w = jr.fold_in(ctx.rng, 3)
    wkey = fib_key(jr.randint(k_w, (R, G), 0, 1 << 16)
                   + ridx[:, None] * 977, K)            # (R, G)
    clock = clock + jnp.where(writing, 1, 0)
    oh_w = (kidx[None, :, None] == wkey[:, None, :]) & writing  # (R, K, G)
    bump = oh_w & ((clock[:, None, :] > ver_c)
                   | ((clock[:, None, :] == ver_c)
                      & (ridx[:, None, None] > ver_n)))
    ver_c = jnp.where(bump, clock[:, None, :], ver_c)
    ver_n = jnp.where(bump, ridx[:, None, None], ver_n)
    writes = state["writes"] + jnp.where(writing, R, 0).astype(jnp.int32)

    # ---------------- gossip out: written key, else rotate anti-entropy -
    akey = (ctx.t + ridx[:, None]) % K                  # (R, G)
    gkey = jnp.where(writing, wkey, jnp.broadcast_to(akey, (R, G))) \
        .astype(jnp.int32)
    goh = kidx[None, :, None] == gkey[:, None, :]       # (R, K, G)
    out_c = jnp.sum(jnp.where(goh, ver_c, 0), axis=1)   # (R, G)
    out_n = jnp.sum(jnp.where(goh, ver_n, 0), axis=1)
    out = {
        "valid": jnp.ones((R, R, G), bool),
        "key": jnp.broadcast_to(gkey[:, None, :], (R, R, G)),
        "c": jnp.broadcast_to(out_c[:, None, :], (R, R, G)),
        "n": jnp.broadcast_to(out_n[:, None, :], (R, R, G)),
    }

    new_state = dict(ver_c=ver_c, ver_n=ver_n, clock=clock, writes=writes)
    return new_state, {"gossip": out}


def metrics(state, cfg: SimConfig):
    c, n = state["ver_c"], state["ver_n"]
    same = (jnp.all(c == c[:1], axis=0)
            & jnp.all(n == n[:1], axis=0))              # (K, G)
    return {
        "converged_keys": jnp.sum(same),
        "total_keys": jnp.int32(cfg.n_keys) * same.shape[-1],
        "writes": jnp.sum(state["writes"]),
        "committed_slots": jnp.sum(state["writes"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """What an eventual store really promises, checked every step:
    1. per-(replica, key) versions never regress (LWW monotonicity);
    2. a replica's Lamport clock bounds every version it stores;
    3. version owner indices stay in range."""
    regress = ((new["ver_c"] < old["ver_c"])
               | ((new["ver_c"] == old["ver_c"])
                  & (new["ver_n"] < old["ver_n"])))
    v1 = jnp.sum(regress)
    v2 = jnp.sum(jnp.max(new["ver_c"], axis=1) > new["clock"])
    v3 = jnp.sum((new["ver_n"] < -1)
                 | (new["ver_n"] >= cfg.n_replicas))
    return (v1 + v2 + v3).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="dynamo",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
