"""SwitchPaxos: Multi-Paxos through the in-fabric consensus tier
(lane-major TPU kernel; host twin in host.py, tier in
paxi_tpu/switchnet/).

"Paxos Made Switch-y" + NOPaxos (PAPERS.md): the network fabric
itself runs acceptor and sequencer logic, removing one full message
round from every commit.  The sim mirrors the switch as **planes
threaded through the scan carry** (switchnet/plane.py): a frame
passes the switch at the step its outbox is built, and the vote /
sequence stamp the switch produces becomes visible one step later —
exactly one fabric delivery where the classic P2a->P2b path costs
two (and 2x the WAN edge latency under a zone matrix).

On top of the shared ballot-ring core (sim/ballot_ring.py, same as
the paxos kernel) this kernel adds:

- **in-network vote plane**: the switch registers (ballot, value) per
  slot in a bounded ``cfg.sw_window`` file; the leader fast-commits
  any slot whose register carries a vote at its own ballot
  (``fast_commit_mask``) — the classic majority-P2b tally still runs
  underneath and is the fall-back for register overflow and switch
  down windows.
- **sequencer plane**: frames are stamped with monotone
  (session, sequence) pairs; replicas track ``expect`` and DETECT
  drops from stamp gaps (NOPaxos's replica contract), triggering the
  gap-agreement slow path: a ``gapreq`` to the leader, which
  retransmits the missing frame immediately (committed -> targeted
  P3; in flight -> re-proposal carrying its ORIGINAL stamp) instead
  of waiting out ``retry_timeout``.
- **recovery through the switch**: a phase-1 winner folds the
  register file into its log before the P1b merge
  (``recovery_fold``) — the {switch} x recovery quorum intersection
  paxi-lint's PXQ505 enforces statically.
- **sequencer churn** (scenario ``SwitchChurn`` -> static
  ``cfg.sw_down_*``): down windows pause votes and stamps (registers
  and the promise persist), window ends bump the session epoch and
  replicas resync ``expect`` on the first stamp of a new session.

The seeded twin ``PROTOCOL_NOGAP`` (hunt's cross-runtime REPRODUCED
control, host twin in nogap.py) replaces gap agreement with the
classic ordered-multicast mistake: on a detected gap the replica
unilaterally NOOP-commits its empty slots below the arriving frame —
holes the leader meanwhile commits real commands into, so drops
deterministically diverge committed values across replicas.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import ballot_ring as br
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ballot_ring import NO_CMD
from paxi_tpu.sim.ring import pick_src, require_packable
from paxi_tpu.sim.ring import dst_major as T
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx
from paxi_tpu.switchnet import plane as swp
from paxi_tpu.switchnet.plane import NO_SEQ

BR_KEYS = br.KEYS
GAP_SCAN = 4   # contiguous expect-advance hops per step (bounded state)
BIG = jnp.int32(2 ** 30)


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        "p1a": ("bal",),
        "p1b": ("bal",),
        # ordered-multicast frames: the switch stamps sess/seq in
        # flight (outbox fields written from the carry planes)
        "p2a": ("bal", "slot", "cmd", "sess", "seq"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto", "sess", "seq"),
        # gap agreement: replica -> leader, "retransmit sequence n"
        "gapreq": ("n",),
    }


def encode_cmd(bal, slot):
    return ((bal & 0x7FFF) << 16) | (slot & 0xFFFF)


def cmd_key(cmd, n_keys):
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        # ---- ballot-ring core (sim/ballot_ring.py) ----
        ballot=jnp.zeros((R, G), i32),
        active=jnp.zeros((R, G), bool),
        p1_acks=jnp.zeros((R, G), i32),
        base=jnp.zeros((R, G), i32),
        log_bal=jnp.zeros((R, S, G), i32),
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),
        proposed=jnp.zeros((R, S, G), bool),
        next_slot=jnp.zeros((R, G), i32),
        execute=jnp.zeros((R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),
        # ---- the in-fabric switch (switchnet/plane.py) ----
        **swp.init_planes(cfg, G),
        # ---- sequencer bookkeeping at the replicas ----
        # the proposer's record of its frames' stamps (gap lookups, P3
        # stamps); shifted with the ring like the log planes
        seq_ring=jnp.full((R, S, G), NO_SEQ, i32),
        # stamps of frames RECEIVED per ring slot (p2a or p3) — what
        # the contiguous expect advance walks
        slot_seq=jnp.full((R, S, G), NO_SEQ, i32),
        expect=jnp.zeros((R, G), i32),   # next expected sequence
        r_sess=jnp.zeros((R, G), i32),   # session last seen
        # ---- on-device observability (PR-11 template: m_ planes,
        # witness-hash-excluded, never read by protocol logic) ----
        m_prop_t=jnp.zeros((R, S, G), i32),
        m_commit_dt=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
        # switchnet accounting: fast-path commits, detected gaps,
        # register-file overflows (fall-backs)
        m_fast_commits=jnp.zeros((G,), i32),
        m_gap_events=jnp.zeros((G,), i32),
        m_sw_overflow=jnp.zeros((G,), i32),
    )


def _step(state, inbox, ctx: StepCtx, nogap: bool):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    ridx = jnp.arange(R, dtype=jnp.int32)

    st = {k: state[k] for k in BR_KEYS}
    sw = {k: state[k] for k in swp.KEYS}
    G = state["ballot"].shape[-1]
    kv = state["kv"]
    seq_ring = state["seq_ring"]
    slot_seq = state["slot_seq"]
    expect = state["expect"]
    r_sess = state["r_sess"]
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]
    m_fast = state["m_fast_commits"]
    m_gap = state["m_gap_events"]
    m_over = state["m_sw_overflow"]

    def realign(b0):
        """Re-align the ring-shaped extras after a base move."""
        nonlocal m_prop_t, seq_ring, slot_seq
        d = st["base"] - b0
        m_prop_t = _shift(m_prop_t, d, 0)
        seq_ring = _shift(seq_ring, d, NO_SEQ)
        slot_seq = _shift(slot_seq, d, NO_SEQ)

    # ---------- phase 1 + switch-assisted recovery ----------------
    st, out_p1b, promote = br.promise_p1a(st, inbox["p1a"])
    st, p1_win, amask = br.tally_p1b(st, inbox["p1b"], MAJ, STRIDE)
    b0 = st["base"]
    st, ex = br.adopt_best_acker(st, amask, p1_win, {"kv": kv})
    kv = ex["kv"]
    realign(b0)
    # the {switch} x recovery intersection: fold the register file
    # into the winner's log BEFORE the merge (PXQ505 obligation)
    st = swp.recovery_fold(sw, st, p1_win, S)
    st = br.merge_acker_logs(st, amask, p1_win)
    m_prop_t = jnp.where(p1_win[:, None, :] & st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)

    # ---------- replicas accept frames (classic path) -------------
    m2 = inbox["p2a"]
    st, out_p2b, acc_ok, _ = br.accept_p2a(st, m2)
    b2 = jnp.where(m2["valid"], m2["bal"], -1)
    a_src = jnp.argmax(b2, axis=0).astype(jnp.int32)
    a_slot = pick_src(m2["slot"], a_src)
    f_seq = pick_src(m2["seq"], a_src)
    f_sess = pick_src(m2["sess"], a_src)
    stamped2 = acc_ok & (f_seq >= 0)

    # ---------- leader commits: fast path + fall-back -------------
    is_leader = st["active"] & br.own_bal_mask(st, STRIDE)
    # in-network acceptance: votes the switch cast LAST step (the
    # one-delivery visibility — the carry holds them)
    st, newly_fast = swp.apply_fast_commits(sw, st, is_leader, S)
    m_fast = m_fast + jnp.sum(newly_fast, axis=(0, 1),
                              dtype=jnp.int32)
    st, newly_cls = br.tally_p2b(st, inbox["p2b"], MAJ, STRIDE)
    newly = newly_fast | newly_cls
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly, dt, state["m_commit_dt"])
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, dt, 0),
                                    axis=(0, 1), dtype=jnp.int32)

    # ---------- P3 commit spread + snapshot catch-up --------------
    m3 = inbox["p3"]
    b0 = st["base"]
    st, ex, c_has, c_bal = br.apply_p3(st, m3, {"kv": kv})
    kv = ex["kv"]
    realign(b0)
    c3 = jnp.where(m3["valid"], m3["bal"], -1)
    c_src = jnp.argmax(c3, axis=0).astype(jnp.int32)
    c_slot = pick_src(m3["slot"], c_src)
    p3_seq_in = pick_src(m3["seq"], c_src)
    p3_sess_in = pick_src(m3["sess"], c_src)
    stamped3 = c_has & (p3_seq_in >= 0)

    # ---------- sequencer: session bumps, stamps, gap detect ------
    s2 = jnp.where(stamped2, f_sess, -1)
    s3 = jnp.where(stamped3, p3_sess_in, -1)
    arr_sess = jnp.maximum(s2, s3)
    newer = arr_sess > r_sess
    cand = jnp.maximum(
        jnp.where(stamped2 & (f_sess == arr_sess), f_seq, -1),
        jnp.where(stamped3 & (p3_sess_in == arr_sess), p3_seq_in,
                  -1))
    # sequencer failover: resync past the first stamp of the new
    # session (frames of the old session are healed by retry/P3).
    # max(): a P3 retransmit carries the CURRENT session over its
    # frame's ORIGINAL stamp, so a resync may only ever raise the
    # cursor — never pull it back to an already-healed hole
    expect = jnp.where(newer, jnp.maximum(expect, cand + 1), expect)
    r_sess = jnp.maximum(r_sess, arr_sess)
    gap = stamped2 & (f_sess == r_sess) & (f_seq > expect)
    m_gap = m_gap + jnp.sum(gap, axis=0, dtype=jnp.int32)
    # record received stamps at their slots, then advance expect
    # over the contiguous known prefix (bounded walk)
    oh2 = stamped2[:, None, :] \
        & (sidx[None, :, None] == (a_slot - st["base"])[:, None, :])
    slot_seq = jnp.where(oh2, f_seq[:, None, :], slot_seq)
    oh3w = stamped3[:, None, :] \
        & (sidx[None, :, None] == (c_slot - st["base"])[:, None, :])
    slot_seq = jnp.where(oh3w, p3_seq_in[:, None, :], slot_seq)
    for _ in range(GAP_SCAN):
        hit = jnp.any(slot_seq == expect[:, None, :], axis=1)
        expect = expect + hit

    if nogap:
        # the seeded twin (plane.noop_commit_holes docstring): gap
        # agreement replaced by unilateral NOOP-commits — both
        # oracles trip once the leader commits the real commands
        st = swp.noop_commit_holes(st, gap, a_slot, sidx)
        out_gapreq = {
            "valid": jnp.zeros((R, R, G), bool),
            "n": jnp.zeros((R, R, G), jnp.int32),
        }
    else:
        # the real slow path: ask the frame's sender to retransmit
        # the first missing sequence number
        out_gapreq = {
            "valid": gap[:, None, :]
            & (ridx[None, :, None] == a_src[:, None, :]),
            "n": jnp.broadcast_to(expect[:, None, :], (R, R, G)),
        }

    # ---------- leader answers gap requests -----------------------
    mg = inbox["gapreq"]
    gv = T(mg["valid"])                          # (me, src, G)
    gn = T(mg["n"])
    gr_n = jnp.min(jnp.where(gv, gn, BIG), axis=1)
    has_gr = jnp.any(gv, axis=1) & is_leader & (gr_n < BIG)
    oh_gr = (seq_ring == gr_n[:, None, :]) & (seq_ring >= 0) \
        & has_gr[:, None, :]
    com_gr = jnp.any(oh_gr & st["log_commit"], axis=1)
    gap_rel = jnp.argmax(oh_gr, axis=1).astype(jnp.int32)
    # an in-flight missing frame re-opens for immediate
    # re-proposal (it keeps its original stamp: the register
    # remembers) instead of waiting out retry_timeout
    st = swp.gap_reopen(st, oh_gr)

    # ---------- leader proposes (closed-loop client) --------------
    has_re, can_new, prop_rel, prop_slot, oh_p, re_cmd = \
        br.repropose_target(st)
    is_new = ~has_re & can_new
    prop_cmd = jnp.where(is_new, encode_cmd(st["ballot"], prop_slot),
                         re_cmd)
    do = is_leader & (has_re | can_new)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2a = br.propose_write(st, do, is_new, prop_cmd,
                                   prop_slot, oh_p)

    # ---------- the switch observes the outgoing frames -----------
    sw, stamp = swp.observe_p2a(sw, out_p2a, cfg, ctx.t)
    out_p2a = dict(
        out_p2a,
        sess=jnp.broadcast_to(stamp["sess"][:, None, :], (R, R, G)),
        seq=jnp.broadcast_to(stamp["seq"][:, None, :], (R, R, G)))
    # the proposer learns its frame's stamp (gap lookups, P3
    # stamps); in the fabric this is the vote's return leg
    seq_ring = jnp.where((stamp["seq"] >= 0)[:, None, :] & oh_p,
                         stamp["seq"][:, None, :], seq_ring)
    m_over = m_over + stamp["overflow"].astype(jnp.int32)

    # ---------- execute committed prefix, apply to KV -------------
    execute = st["execute"]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(st["active"])
    for e in range(cfg.exec_window):
        rel = execute + e - st["base"]
        oh_e = sidx[None, :, None] == rel[:, None, :]
        com = jnp.any(oh_e & st["log_commit"], axis=1)
        running = running & com
        cmd_e = jnp.sum(jnp.where(oh_e, st["log_cmd"], 0), axis=1)
        key_e = cmd_key(cmd_e, K)
        wr = running & (cmd_e >= 0)
        ohk = wr[:, None, :] & (kidx[None, :, None]
                                == key_e[:, None, :])
        kv = jnp.where(ohk, cmd_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced

    # ---------- stamped P3 out (gap-override target) --------------
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S),
                         axis=1)
    any_new = jnp.any(newly, axis=1)
    span = jnp.maximum(new_execute - st["base"], 1)
    rr = ctx.t % span
    gap_p3 = has_gr & com_gr & ~any_new
    p3_rel = jnp.where(any_new, low_new,
                       jnp.where(gap_p3, gap_rel, rr))
    p3_rel = jnp.clip(p3_rel, 0, S - 1).astype(jnp.int32)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_committed = jnp.any(oh_3 & st["log_commit"], axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, st["log_cmd"], 0), axis=1)
    p3_seq = jnp.sum(jnp.where(oh_3, seq_ring, 0), axis=1)
    p3_seq = jnp.where(
        jnp.any(oh_3 & (seq_ring >= 0), axis=1), p3_seq, NO_SEQ)
    p3_do = is_leader & p3_committed
    sess_now = swp.session_t(cfg, ctx.t)
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(st["ballot"][:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to((st["base"] + p3_rel)[:, None, :],
                                 (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(new_execute[:, None, :], (R, R, G)),
        "sess": jnp.broadcast_to(
            jnp.where(p3_seq >= 0, sess_now, NO_SEQ)[:, None, :],
            (R, R, G)),
        "seq": jnp.broadcast_to(p3_seq[:, None, :], (R, R, G)),
    }

    # ---------- wrap-up: retry, election, slide, evict ------------
    st = br.retry_stuck(st, new_execute, is_leader,
                        cfg.retry_timeout)
    heard = promote | acc_ok | (c_has & (c_bal >= st["ballot"]))
    st, out_p1a = br.election_tick(st, heard, ctx.rng, cfg)
    # phase-1 passes the switch too: the promise fence that stops
    # stale leaders collecting votes after a recovery read
    sw = swp.observe_p1a(sw, out_p1a)
    b0 = st["base"]
    st = br.slide_window(st, new_execute, RETAIN)
    realign(b0)
    sw = swp.evict(sw, st["execute"])

    # ---------- in-scan spot-check --------------------------------
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], st["execute"], state["base"], st["base"],
        state["base"][:, None, :] + sidx[None, :, None],
        st["base"][:, None, :] + sidx[None, :, None],
        state["log_cmd"], st["log_cmd"],
        state["log_commit"], st["log_commit"],
        kv=kv, lane_major=True)

    new_state = dict(st, **sw, kv=kv, seq_ring=seq_ring,
                     slot_seq=slot_seq, expect=expect, r_sess=r_sess,
                     m_prop_t=m_prop_t, m_commit_dt=m_commit_dt,
                     m_lat_hist=m_lat_hist, m_lat_sum=m_lat_sum,
                     m_inscan_viol=m_inscan_viol,
                     m_fast_commits=m_fast, m_gap_events=m_gap,
                     m_sw_overflow=m_over)
    outbox = {"p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3, "gapreq": out_gapreq}
    return new_state, outbox


def step(state, inbox, ctx: StepCtx):
    return _step(state, inbox, ctx, nogap=False)


def step_nogap(state, inbox, ctx: StepCtx):
    return _step(state, inbox, ctx, nogap=True)


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "has_leader": jnp.sum(jnp.any(state["active"], axis=0)
                              .astype(jnp.int32)),
        # switchnet accounting (m_ planes; see init_state)
        "fast_commits": jnp.sum(state["m_fast_commits"]),
        "gap_events": jnp.sum(state["m_gap_events"]),
        "sw_overflows": jnp.sum(state["m_sw_overflow"]),
        # on-device observability scalars (PR-11 contract)
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """The paxos oracle (agreement / stability / ballot monotonicity /
    executed-prefix-committed) plus the sequencer plane's monotone
    contract: ``expect`` and the seen-session never regress."""
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    v_seq = jnp.sum(new["expect"] < old["expect"]) \
        + jnp.sum(new["r_sess"] < old["r_sess"])

    return (v_agree + v_stable + v_bal + v_exec
            + v_seq).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="switchpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)

# the seeded drop-the-gap-agreement twin (module docstring): hunt's
# cross-runtime REPRODUCED control for the in-fabric tier
PROTOCOL_NOGAP = SimProtocol(
    name="switchpaxos_nogap",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step_nogap,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
