"""Host twin of the ``switchpaxos_nogap`` seeded-bug sim kernel.

The same deliberately UNSAFE ordered-multicast shortcut on the asyncio
runtime: on a detected sequence gap the replica SKIPS gap agreement
and unilaterally NOOP-commits the holes below the arriving frame —
holes the leader meanwhile commits real batches into, so a drop
schedule deterministically diverges committed values across replicas
(``HUNT_ORACLE`` counts the disagreement).  Because the sim twin and
this replica share the bug, a sim witness replayed through the
virtual-clock fabric + switch tier MUST classify ``reproduced`` — the
in-fabric tier's end-to-end hunt control.

NOT a correctness case: never add it to the fuzz-soak oracle matrix.
"""

from __future__ import annotations

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.protocols.paxos.host import Entry
from paxi_tpu.protocols.switchpaxos.host import (  # noqa: F401
    HUNT_FABRIC_SETUP, HUNT_ORACLE, HUNT_TAIL_STEPS, SIM_STATE_MAP,
    TRACE_MSG_MAP, OmP2a, SwitchPaxosReplica)

# paxi-lint (analysis/tracemap.py): analyze this module AS its base —
# the message classes, maps and state vocabulary all live in host.py
TWIN_OF = "paxi_tpu.protocols.switchpaxos.host"


class NoGapReplica(SwitchPaxosReplica):
    def _on_gap(self, m: OmP2a) -> None:
        """The seeded bug: "the multicast is ordered, so a gap must be
        a NOOP" — commit the holes instead of asking for retransmits."""
        self.gap_events += 1
        for s in range(self.execute, m.slot):
            if s not in self.log:
                self.log[s] = Entry(m.ballot, [], commit=True)
        self._exec()


def new_replica(id: ID, cfg: Config) -> NoGapReplica:
    return NoGapReplica(ID(id), cfg)
