"""SwitchPaxos replica for the host (deployment) runtime: Multi-Paxos
speaking through the in-fabric consensus tier (paxi_tpu/switchnet).

Subclasses the Paxos replica (protocols/paxos/host.py) through its
message-class hooks: every frame is a switchnet-marked subclass the
``SwitchTier`` recognizes mid-flight on the virtual-clock fabric —
P1a frames raise the switch's promise and trigger a ``SwitchSnap``
register read (recovery MUST consult the registers), P2a frames are
voted on and sequence-stamped in flight, and every frame gossips the
sender's execute frontier for the tier's execution-gated register
eviction.

The three paths this module adds on top of classic Paxos:

- **fast commit**: a ``SwitchVote`` arriving one fabric delivery
  after the P2a broadcast commits the slot immediately — the classic
  majority-P2b tally still runs underneath (register overflow, switch
  down windows, and fabric-less deployments all fall back to it; with
  no fabric installed this replica IS the paxos replica).
- **gap agreement**: replicas track the ordered-multicast ``expect``
  counter and, on a stamp gap, ask the leader to retransmit the
  missing sequence number (``GapReq``) — committed frames come back
  as a targeted stamped P3, in-flight ones as a P2a retransmit that
  keeps its original stamp (the switch register remembers).  A
  session bump (sequencer failover) resyncs ``expect`` past the first
  stamp of the new session.
- **recovery through the switch**: ``_become_leader`` waits for the
  ``SwitchSnap`` and merges the register file as a pseudo-acker log,
  so a value committed via the in-network vote alone survives leader
  failover (the PXQ505 obligation, mirrored from the sim kernel's
  ``recovery_fold``).

The seeded twin (nogap.py) replaces gap agreement with unilateral
NOOP-commits of the holes — the same bug as the sim's
``PROTOCOL_NOGAP``, so hunt witnesses classify REPRODUCED.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from paxi_tpu.core.ballot import ballot_id
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.host.codec import register_message
from paxi_tpu.protocols.paxos.host import (P1a, P1b, P2a, P2b, P3,
                                           PaxosReplica, _wire_cmds)
from paxi_tpu.switchnet import SwitchSnap, SwitchTier, SwitchVote

__all__ = ["SwitchPaxosReplica", "new_replica", "SwitchTier"]

_SNAP_KEY = "__switch__"   # pseudo-acker key for the register read


# ---- switchnet-marked frames (tier recognition is by class attr) --------
@register_message
@dataclass
class SwP1a(P1a):
    switchnet_role = "p1a"


@register_message
@dataclass
class SwP1b(P1b):
    switchnet_role = "p1b"


@register_message
@dataclass
class OmP2a(P2a):
    """The ordered-multicast frame: the switch stamps sess/seq in
    flight (all broadcast copies share the object)."""

    sess: int = -1
    seq: int = -1
    execute: int = 0
    switchnet_role = "p2a"


@register_message
@dataclass
class SwP2b(P2b):
    execute: int = 0     # frontier gossip for register eviction
    switchnet_role = "p2b"


@register_message
@dataclass
class OmP3(P3):
    sess: int = -1
    seq: int = -1
    execute: int = 0
    switchnet_role = "p3"


@register_message
@dataclass
class GapReq:
    """Gap agreement: "retransmit the frame with sequence ``n``"."""

    n: int
    id: str


class SwitchPaxosReplica(PaxosReplica):
    P1A_CLS = SwP1a
    P1B_CLS = SwP1b
    P2A_CLS = OmP2a
    P2B_CLS = SwP2b
    P3_CLS = OmP3

    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.expect = 0                    # next expected sequence
        self.sess = 0                      # session last seen
        self.slot_seq: Dict[int, int] = {}  # slot -> received stamp
        self.seq_slot: Dict[int, int] = {}  # seq -> slot (leader side)
        self.gap_events = 0
        self.fast_commits = 0
        self._switch_snap = None
        fabric = self.socket.fabric
        # with no switch on the wire this replica degrades to classic
        # paxos: no votes arrive, no stamps, majority path only
        self._sw_expected = (fabric is not None
                             and getattr(fabric, "switch", None)
                             is not None)
        # the switchnet frame classes dispatch on their exact type
        # (Node.handles is keyed by type, not by isinstance)
        self.register(SwP1a, self.handle_p1a)
        self.register(SwP1b, self.handle_p1b)
        self.register(OmP2a, self.handle_p2a)
        self.register(SwP2b, self.handle_p2b)
        self.register(OmP3, self.handle_p3)
        self.register(SwitchVote, self.handle_switch_vote)
        self.register(SwitchSnap, self.handle_switch_snap)
        self.register(GapReq, self.handle_gapreq)

    # ---- the in-network fast path ---------------------------------------
    def handle_switch_vote(self, m: SwitchVote) -> None:
        """The switch accepted my frame: commit after ONE delivery."""
        if m.seq >= 0:
            self.seq_slot[m.seq] = m.slot
            self.slot_seq[m.slot] = m.seq
        if not self.active or m.ballot != self.ballot:
            return
        e = self.log.get(m.slot)
        if e is not None and not e.commit and e.ballot == m.ballot:
            self.fast_commits += 1
            self._commit(m.slot)

    def _commit(self, slot: int) -> None:
        """Commit + stamped P3 broadcast (the stamp lets followers'
        ``expect`` advance over holes healed by P3)."""
        e = self.log[slot]
        e.commit = True
        self._renew_lease(e.timestamp)
        self.socket.broadcast(OmP3(
            self.ballot, slot, _wire_cmds(e.cmds), sess=self.sess,
            seq=self.slot_seq.get(slot, -1), execute=self.execute))
        self._exec()

    # ---- sequencer tracking + gap agreement ------------------------------
    def _note_stamp(self, sess: int, seq: int, slot: int) -> None:
        if sess > self.sess:
            # sequencer failover: resync past the new session's first
            # stamp (old-session holes heal via retry/P3).  max(): a
            # P3 retransmit carries the CURRENT session over its
            # frame's ORIGINAL stamp — resync only ever raises
            self.sess = sess
            self.expect = max(self.expect, seq + 1)
        self.slot_seq[slot] = seq
        known = set(self.slot_seq.values())
        while self.expect in known:
            self.expect += 1

    def _on_gap(self, m: OmP2a) -> None:
        """The gap-agreement slow path: ask the frame's sender to
        retransmit the first missing sequence number."""
        self.gap_events += 1
        self.socket.send(ballot_id(m.ballot),
                         GapReq(self.expect, str(self.id)))

    def _make_p2a(self, slot: int, cmds):
        return OmP2a(self.ballot, slot, _wire_cmds(cmds),
                     execute=self.execute)

    def _make_p2b(self, slot: int):
        return SwP2b(self.ballot, slot, str(self.id),
                     execute=self.execute)

    def handle_p2a(self, m: OmP2a) -> None:
        seq = getattr(m, "seq", -1)
        if seq >= 0:
            if m.sess == self.sess and seq > self.expect:
                self._on_gap(m)
            self._note_stamp(m.sess, seq, m.slot)
        super().handle_p2a(m)

    def handle_p3(self, m: OmP3) -> None:
        seq = getattr(m, "seq", -1)
        if seq >= 0:
            self._note_stamp(m.sess, seq, m.slot)
        super().handle_p3(m)

    def handle_gapreq(self, m: GapReq) -> None:
        """Leader half of gap agreement: retransmit the missing frame
        — a targeted stamped P3 when committed, a P2a re-broadcast
        (original stamp: the register remembers) when in flight."""
        if not self.is_leader():
            return
        slot = self.seq_slot.get(m.n)
        if slot is None:
            return   # recycled or never mine: retry/P3 will heal it
        e = self.log.get(slot)
        if e is None:
            return
        if e.commit:
            self.socket.send(ID(m.id), OmP3(
                self.ballot, slot, _wire_cmds(e.cmds), sess=self.sess,
                seq=self.slot_seq.get(slot, -1), execute=self.execute))
        else:
            self.socket.broadcast(OmP2a(
                e.ballot, slot, _wire_cmds(e.cmds),
                execute=self.execute))

    # ---- recovery through the switch ------------------------------------
    def handle_switch_snap(self, m: SwitchSnap) -> None:
        """The register read the P1a triggered: stash it as a
        pseudo-acker log (slot -> [vballot, frame, committed=False])
        and complete the election if the P1b quorum beat it here."""
        self._switch_snap = {
            int(s): [int(vbal), list(cmds) if cmds else [], False]
            for s, (vbal, cmds, _seq) in m.regs.items()}
        if not self.active and self._p1_complete():
            self._become_leader()

    def _become_leader(self) -> None:
        if self._sw_expected and self._switch_snap is None:
            return   # the register read is part of the recovery quorum
        if self._switch_snap is not None:
            self.p1b_logs[_SNAP_KEY] = self._switch_snap
            self.p1b_meta[_SNAP_KEY] = (0, {}, {})
            self._switch_snap = None
        super()._become_leader()


def new_replica(id: ID, cfg: Config) -> SwitchPaxosReplica:
    return SwitchPaxosReplica(ID(id), cfg)


def HUNT_FABRIC_SETUP(fabric, scfg) -> None:
    """hunt/classify hook: interpose the switch tier on the replay
    fabric, mirroring the sim kernel's static ``sw_*`` knobs (the
    trace's ``sim_cfg`` meta carries them)."""
    from paxi_tpu.scenarios.spec import SwitchChurn
    churn = None
    if scfg.sw_down_start >= 0 and scfg.sw_down_for > 0:
        churn = SwitchChurn(start=scfg.sw_down_start,
                            period=scfg.sw_down_period,
                            down_for=scfg.sw_down_for)
    fabric.install_switch(SwitchTier(window=scfg.sw_window, churn=churn,
                                     n_replicas=scfg.n_replicas))


def HUNT_ORACLE(cluster) -> int:
    """Safety-violation count after a replay: cross-replica
    disagreement on committed batches (the host analog of the sim
    kernel's agreement oracle — what the nogap twin's unilateral
    NOOP-commits diverge)."""
    bad = 0
    seen: Dict[int, list] = {}
    for i in cluster.ids:
        r = cluster[i]
        for s, e in r.log.items():
            if not e.commit:
                continue
            ident = [(c.client_id, c.command_id) for c in e.cmds]
            if s in seen:
                if seen[s] != ident:
                    bad += 1
            else:
                seen[s] = ident
    return bad


# gap agreement converges a few commits after the replayed schedule
# (detect -> GapReq -> retransmit -> P3), like bpaxos's gap strikes
HUNT_TAIL_STEPS = 30


# sim mailbox name -> host message class (trace/host.py projection).
# The in-network votes/snaps are NOT mailbox planes in the sim (they
# ride the scan carry), so the fabric replay regenerates them through
# the tier itself — nothing to map.
TRACE_MSG_MAP = {
    "p1a": "SwP1a", "p1b": "SwP1b", "p2a": "OmP2a", "p2b": "SwP2b",
    "p3": "OmP3", "gapreq": "GapReq",
}

# sim state field -> host attribute (analysis/parity.py PXS7xx).
# Empty string = kernel-internal or fabric-tier state with no replica
# analog (the switch planes live in switchnet.SwitchTier on the host).
SIM_STATE_MAP = {
    "p1_acks":    "p1_quorum",
    "log_bal":    "log",
    "log_cmd":    "log",
    "log_commit": "log",
    "log_acks":   "log",
    "next_slot":  "slot",
    "kv":         "db",
    "base":       "",   # ring-window base: the host log is a dict
    "proposed":   "",   # implied by Entry existence
    "timer":      "",   # host elections are wall-clock
    "stuck":      "",   # go-back-N retry counter (kernel-only)
    # (the switch register file — sw_bal/sw_base/sw_vbal/sw_vcmd/
    # sw_reg_seq/sw_seq — is built by switchnet.plane.init_planes and
    # lives in switchnet.SwitchTier on the host, not in any replica;
    # the parity field scanner only sees literal init_state keys, so
    # those planes carry no map entries here)
    # sequencer bookkeeping
    "seq_ring":   "seq_slot",   # my frames' stamps (leader side)
    "slot_seq":   "slot_seq",   # received stamps per slot
    "expect":     "expect",
    "r_sess":     "sess",
    # on-device observability (PR 11 contract)
    "m_prop_t":      "",
    "m_commit_dt":   "",
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
    "m_fast_commits": "fast_commits",
    "m_gap_events":   "gap_events",
    "m_sw_overflow":  "",
}
