"""SwitchPaxos: Multi-Paxos through the in-fabric consensus tier
(paxi_tpu/switchnet) — switch-accepted commits + NOPaxos-style
ordered multicast, on both runtimes (sim.py / host.py)."""
