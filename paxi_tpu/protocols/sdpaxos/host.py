"""SDPaxos replica for the host (deployment) runtime.

Reference: the paxi lineage's sdpaxos/ package (SURVEY §2.2 "others" —
the SoCC'18 semi-decentralized protocol).  Command replication is
decentralized: the replica a request arrives at is that command's
leader and replicates the body from where it is (C-instance, majority
CAck quorum).  Ordering is centralized: an elected sequencer assigns
global O-log slots naming (owner, cidx) pairs and replicates them with
ordinary Multi-Paxos (OAccept/OAck/OCommit under a ballot, Seq1a/Seq1b
election with log merge).  A command executes once its O-slot is
committed AND its body is locally stored; execution follows O-log slot
order with at-most-once (owner, cidx) dedup — a minority-accepted pair
can be re-adopted at a second slot across a sequencer change, and the
dedup makes that harmless (the sim kernel avoids it structurally with
positional owner tokens; see sim.py).

O-log compaction: every replica gossips its execute frontier
(OFrontier); slots below the cluster-wide minimum (minus a small
margin) are GC'd everywhere, together with their ordered/committed/
executed bookkeeping, so election payloads and rescans are bounded by
the live window, not the cluster's lifetime.  The per-client ``ctab``
session table (bounded by client count) remains the at-most-once
backstop for any duplicate whose pair-level record was compacted away —
the same layering as paxos/host.py.  A permanently dead replica pins
the watermark (GC pauses, memory grows); the sim kernel's gossiped
watermark has the identical documented tradeoff.

The same protocol runs as a lane-major TPU kernel in ``sim.py``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

from paxi_tpu.core.ballot import ballot_id, next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


@register_message
@dataclass
class CAccept:
    """Owner -> all: replicate the body of my command #cidx."""

    owner: str
    cidx: int
    key: int
    value: bytes
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class CAck:
    """Acceptor -> owner: stored (owner, cidx)."""

    owner: str
    cidx: int
    id: str


@register_message
@dataclass
class OReq:
    """Owner -> all (idempotent, retried): order (owner, cidx)."""

    owner: str
    cidx: int


@register_message
@dataclass
class CFetch:
    """Staller -> all: re-send me the body of (owner, cidx) — pull-side
    healing for bodies the owner stopped pushing (already majority-
    chosen, or owner dead)."""

    owner: str
    cidx: int
    id: str


@register_message
@dataclass
class Seq1a:
    ballot: int


@register_message
@dataclass
class Seq1b:
    ballot: int
    id: str
    # slot -> [ballot, owner, cidx, committed]
    olog: Dict[int, list] = field(default_factory=dict)


@register_message
@dataclass
class OAccept:
    ballot: int
    slot: int
    owner: str
    cidx: int


@register_message
@dataclass
class OAck:
    ballot: int
    slot: int
    id: str


@register_message
@dataclass
class OCommit:
    ballot: int
    slot: int
    owner: str
    cidx: int


@register_message
@dataclass
class OFrontier:
    """Sequencer heartbeat: my execute frontier — laggards compare and
    fetch what they missed (the host analog of the sim kernel's P3
    frontier retransmit).  Broadcast by EVERY replica each watchdog
    tick: the collected frontiers also drive O-log GC (see module
    docstring).  Carries the ballot so a replica that missed the
    election itself learns the sequencer from the heartbeat."""

    ballot: int
    execute: int
    id: str


@register_message
@dataclass
class OFetch:
    """Laggard -> sequencer: re-send committed slots from ``slot``."""

    slot: int
    id: str


NOOP_PAIR = ("", -1)


@dataclass
class OEntry:
    ballot: int
    pair: Tuple[str, int]
    commit: bool = False
    quorum: Optional[Quorum] = None


class SDPaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        # ---- C-plane: my own command stream -----------------------------
        self.cnext = 0
        self.cstore: Dict[Tuple[str, int], Command] = {}
        self.cquorum: Dict[int, Quorum] = {}       # my cidx -> CAck quorum
        self.creq: Dict[int, Request] = {}         # my cidx -> client req
        self.cchosen: Set[int] = set()             # my majority-stored cidxs
        # ---- O-log: sequencer-ordered (owner, cidx) pairs ---------------
        self.ballot = 0
        self.active = False
        self.olog: Dict[int, OEntry] = {}
        self.oslot = -1
        self.execute = 0
        self.ordered: Set[Tuple[str, int]] = set()  # pairs in the O-log
        self.committed: Set[Tuple[str, int]] = set()  # pairs commit-known
        self.executed: Set[Tuple[str, int]] = set()  # at-most-once dedup
        self.queue: list = []                      # pairs awaiting a slot
        self.queued: Set[Tuple[str, int]] = set()  # O(1) queue membership
        self.seq_quorum = Quorum(cfg.ids)
        self.seq1b_logs: Dict[ID, Dict[int, list]] = {}
        self.ctab: Dict[str, Tuple[int, bytes]] = {}
        self._stalled_pair: Optional[Tuple[str, int]] = None
        self._last_exec = 0
        self._stall_ticks = 0
        self.peer_front: Dict[ID, int] = {}   # OFrontier-gossiped frontiers
        self.gc_base = 0                      # slots below this are pruned
        self.GC_MARGIN = 128
        self.register(Request, self.handle_request)
        self.register(CAccept, self.handle_caccept)
        self.register(CAck, self.handle_cack)
        self.register(CFetch, self.handle_cfetch)
        self.register(OReq, self.handle_oreq)
        self.register(Seq1a, self.handle_seq1a)
        self.register(Seq1b, self.handle_seq1b)
        self.register(OAccept, self.handle_oaccept)
        self.register(OAck, self.handle_oack)
        self.register(OCommit, self.handle_ocommit)
        self.register(OFrontier, self.handle_ofrontier)
        self.register(OFetch, self.handle_ofetch)

    async def start(self) -> None:
        await super().start()
        self._tasks.append(asyncio.create_task(self._watchdog()))

    async def _watchdog(self) -> None:
        """Retry loop for both planes: un-chosen bodies are re-broadcast
        (CAccept is idempotent), chosen-but-unordered pairs re-request
        ordering (OReq is idempotent) — this is what makes command loss
        and sequencer loss heal without per-message bookkeeping."""
        try:
            while True:
                await asyncio.sleep(0.05)
                for cidx, req in list(self.creq.items()):
                    pair = (str(self.id), cidx)
                    if cidx not in self.cchosen:
                        self._bcast_caccept(cidx)
                    elif pair not in self.committed:
                        # retry until COMMITTED, not merely accepted: a
                        # tentatively-accepted pair can be displaced by
                        # a sequencer change and must be re-requested
                        self.socket.broadcast(OReq(*pair))
                        self.handle_oreq(OReq(*pair))
                # pull a body my execution is stalled on (the owner may
                # be done pushing it, or dead)
                if self._stalled_pair is not None:
                    self.socket.broadcast(
                        CFetch(*self._stalled_pair, str(self.id)))
                # no execution progress with work in flight: the
                # sequencer is gone or wedged — run for the ballot
                # (paxos host's stuck-frontier retry, lifted to the
                # O-log; ballot ordering resolves duels)
                self.socket.broadcast(
                    OFrontier(self.ballot, self.execute, str(self.id)))
                self._gc_olog()
                if self.creq and self.execute == self._last_exec:
                    self._stall_ticks += 1
                    if self._stall_ticks >= 4:
                        self._stall_ticks = 0
                        self.run_seq_phase1()
                else:
                    self._stall_ticks = 0
                self._last_exec = self.execute
        except asyncio.CancelledError:
            pass

    # ---- sequencer identity --------------------------------------------
    @property
    def sequencer(self) -> Optional[ID]:
        return ballot_id(self.ballot) if self.ballot else None

    def is_sequencer(self) -> bool:
        return self.active and self.sequencer == self.id

    # ---- client requests: I am this command's leader --------------------
    def handle_request(self, req: Request) -> None:
        cidx = self.cnext
        self.cnext += 1
        pair = (str(self.id), cidx)
        self.cstore[pair] = req.command
        self.creq[cidx] = req
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        self.cquorum[cidx] = q
        self._bcast_caccept(cidx)
        if q.majority():                    # single-replica cluster
            self._c_chosen(cidx)

    def _bcast_caccept(self, cidx: int) -> None:
        cmd = self.cstore[(str(self.id), cidx)]
        self.socket.broadcast(CAccept(str(self.id), cidx, cmd.key,
                                      cmd.value, cmd.client_id,
                                      cmd.command_id))

    def handle_caccept(self, m: CAccept) -> None:
        self.cstore[(m.owner, m.cidx)] = Command(
            m.key, m.value, m.client_id, m.command_id)
        self.socket.send(ID(m.owner), CAck(m.owner, m.cidx, str(self.id)))
        self._exec()                        # a stalled body may now be here

    def handle_cfetch(self, m: CFetch) -> None:
        cmd = self.cstore.get((m.owner, m.cidx))
        if cmd is not None:
            self.socket.send(ID(m.id), CAccept(
                m.owner, m.cidx, cmd.key, cmd.value, cmd.client_id,
                cmd.command_id))

    def handle_cack(self, m: CAck) -> None:
        q = self.cquorum.get(m.cidx)
        if q is None or m.cidx in self.cchosen:
            return
        q.ack(ID(m.id))
        if q.majority():
            self._c_chosen(m.cidx)

    def _c_chosen(self, cidx: int) -> None:
        """Body durable on a majority: request a global order slot."""
        self.cchosen.add(cidx)
        pair = (str(self.id), cidx)
        self.socket.broadcast(OReq(*pair))
        self.handle_oreq(OReq(*pair))
        if self.sequencer is None:
            self.run_seq_phase1()

    # ---- ordering requests ---------------------------------------------
    def handle_oreq(self, m: OReq) -> None:
        pair = (m.owner, m.cidx)
        if pair in self.committed or pair in self.ordered \
                or pair in self.queued:
            return
        self.queue.append(pair)
        self.queued.add(pair)
        self._drain_queue()

    def _drain_queue(self) -> None:
        if not self.is_sequencer():
            return
        queue, self.queue = self.queue, []
        self.queued.clear()
        for pair in queue:
            if pair not in self.ordered:
                self._propose_o(pair)

    def _unqueue(self, pair: Tuple[str, int]) -> None:
        """Drop a now-committed pair from a bystander's request queue —
        without this, non-sequencer queues grow with command history."""
        if pair in self.queued:
            self.queued.discard(pair)
            self.queue.remove(pair)

    def _propose_o(self, pair: Tuple[str, int],
                   at_slot: Optional[int] = None) -> None:
        if at_slot is None:
            self.oslot += 1
            slot = self.oslot
        else:
            slot = at_slot
            self.oslot = max(self.oslot, slot)
        q = Quorum(self.cfg.ids)
        q.ack(self.id)
        self.olog[slot] = OEntry(self.ballot, pair, quorum=q)
        self.ordered.add(pair)
        self.socket.broadcast(OAccept(self.ballot, slot, pair[0], pair[1]))
        if q.majority():
            self._commit_o(slot)

    # ---- sequencer election (Multi-Paxos phase-1 on the O-log) ----------
    def run_seq_phase1(self) -> None:
        self.ballot = next_ballot(self.ballot, self.id)
        self.active = False
        self.seq_quorum = Quorum(self.cfg.ids)
        self.seq_quorum.ack(self.id)
        self.seq1b_logs = {self.id: self._olog_payload()}
        self.socket.broadcast(Seq1a(self.ballot))

    def _olog_payload(self) -> Dict[int, list]:
        return {s: [e.ballot, e.pair[0], e.pair[1], e.commit]
                for s, e in self.olog.items()}

    def handle_seq1a(self, m: Seq1a) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
        self.socket.send(ballot_id(m.ballot),
                         Seq1b(self.ballot, str(self.id),
                               self._olog_payload()))

    def handle_seq1b(self, m: Seq1b) -> None:
        if m.ballot != self.ballot or self.active:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
            return
        self.seq_quorum.ack(ID(m.id))
        self.seq1b_logs[ID(m.id)] = m.olog
        if self.seq_quorum.majority() and ballot_id(self.ballot) == self.id:
            self._become_sequencer()

    def _become_sequencer(self) -> None:
        """Merge Seq1b O-logs (committed wins, else highest ballot),
        NOOP-fill holes, re-propose the window, rebuild the ordered
        set FROM THE POST-MERGE LOG — a stale tentative pair my old log
        held that the merge displaced must drop out of ``ordered`` so a
        retried OReq can re-enqueue it."""
        self.active = True
        merged: Dict[int, Tuple[int, Tuple[str, int], bool]] = {}
        top = self.oslot
        for log in self.seq1b_logs.values():
            for s_raw, (bal, owner, cidx, committed) in log.items():
                s = int(s_raw)
                top = max(top, s)
                pair = (owner, int(cidx))
                cur = merged.get(s)
                if committed:
                    merged[s] = (bal, pair, True)
                elif cur is None or (not cur[2] and bal > cur[0]):
                    merged[s] = (bal, pair, False)
        self.ordered = set(self.executed) | set(self.committed)
        # everything below every acker's GC base was executed cluster-
        # wide; scan only from the lowest slot any payload still carries
        low = max(min([self.execute] + list(merged.keys())), self.gc_base)
        for s in range(low, top + 1):
            bal, pair, committed = merged.get(s, (0, NOOP_PAIR, False))
            prev = self.olog.get(s)
            if prev is not None and prev.commit:
                self.ordered.add(prev.pair)
                self.committed.add(prev.pair)
                continue
            if committed:
                self.olog[s] = OEntry(bal, pair, commit=True)
                self.ordered.add(pair)
                self.committed.add(pair)
            else:
                self._propose_o(pair, at_slot=s)
        self.ordered.discard(NOOP_PAIR)
        self.committed.discard(NOOP_PAIR)
        self.oslot = max(self.oslot, top)
        self._exec()
        self._drain_queue()

    # ---- O-log phase 2 --------------------------------------------------
    def handle_oaccept(self, m: OAccept) -> None:
        if m.slot < self.gc_base:
            return      # GC'd: executed cluster-wide; never resurrect
        if m.ballot >= self.ballot:
            if m.ballot > self.ballot:
                self.ballot = m.ballot
                self.active = False
            e = self.olog.get(m.slot)
            if e is None or (not e.commit and m.ballot >= e.ballot):
                self.olog[m.slot] = OEntry(m.ballot, (m.owner, m.cidx))
                self.ordered.add((m.owner, m.cidx))
                self.ordered.discard(NOOP_PAIR)
            self.oslot = max(self.oslot, m.slot)
        self.socket.send(ballot_id(m.ballot),
                         OAck(self.ballot, m.slot, str(self.id)))

    def handle_oack(self, m: OAck) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
            return
        e = self.olog.get(m.slot)
        if (self.active and e is not None and not e.commit
                and m.ballot == self.ballot == e.ballot):
            e.quorum.ack(ID(m.id))
            if e.quorum.majority():
                self._commit_o(m.slot)

    def _commit_o(self, slot: int) -> None:
        e = self.olog[slot]
        e.commit = True
        if e.pair != NOOP_PAIR:
            self.committed.add(e.pair)
            self._unqueue(e.pair)
        self.socket.broadcast(OCommit(self.ballot, slot, e.pair[0],
                                      e.pair[1]))
        self._exec()

    def handle_ocommit(self, m: OCommit) -> None:
        if m.slot < self.gc_base:
            return      # GC'd: executed cluster-wide; never resurrect
        pair = (m.owner, m.cidx)
        self.olog[m.slot] = OEntry(m.ballot, pair, commit=True)
        if pair != NOOP_PAIR:
            self.ordered.add(pair)
            self.committed.add(pair)
            self._unqueue(pair)
        self.oslot = max(self.oslot, m.slot)
        self._exec()

    def handle_ofrontier(self, m: OFrontier) -> None:
        if m.ballot > self.ballot:
            self.ballot = m.ballot
            self.active = False
        self.peer_front[ID(m.id)] = max(
            self.peer_front.get(ID(m.id), 0), m.execute)
        if self.execute < m.execute:
            self.socket.send(ID(m.id), OFetch(self.execute, str(self.id)))

    def _gc_olog(self) -> None:
        """Prune O-log slots (and their pair bookkeeping) every replica
        has executed past; ``ctab`` keeps at-most-once for anything
        pruned.  Needs a frontier report from every peer — a silent
        (dead) peer pauses GC rather than risking a pruned slot someone
        still needs."""
        if len(self.peer_front) < len(self.cfg.ids) - 1:
            return
        w = min([self.execute] + list(self.peer_front.values()))
        new_base = w - self.GC_MARGIN
        if new_base <= self.gc_base:
            return
        for s in range(self.gc_base, new_base):
            e = self.olog.pop(s, None)
            if e is not None and e.pair != NOOP_PAIR:
                self.ordered.discard(e.pair)
                self.committed.discard(e.pair)
                self.executed.discard(e.pair)
                self._unqueue(e.pair)
                # the command BODIES dominate memory: a slot below the
                # watermark executed on every replica, so its body (and
                # my own C-quorum bookkeeping) can never be needed again
                self.cstore.pop(e.pair, None)
                if e.pair[0] == str(self.id):
                    self.cquorum.pop(e.pair[1], None)
                    self.cchosen.discard(e.pair[1])
        self.gc_base = new_base

    def handle_ofetch(self, m: OFetch) -> None:
        for s in range(m.slot, m.slot + 64):
            e = self.olog.get(s)
            if e is None or not e.commit:
                break
            self.socket.send(ID(m.id), OCommit(e.ballot, s, e.pair[0],
                                               e.pair[1]))

    # ---- execution: O-log order, body-gated, at-most-once ---------------
    def _exec(self) -> None:
        self._stalled_pair = None
        while True:
            e = self.olog.get(self.execute)
            if e is None or not e.commit:
                break
            pair = e.pair
            if pair != NOOP_PAIR and pair not in self.executed:
                cmd = self.cstore.get(pair)
                if cmd is None:
                    self._stalled_pair = pair
                    break               # body not here yet: stall, not skip
                last = (self.ctab.get(cmd.client_id)
                        if cmd.client_id else None)
                if last is not None and cmd.command_id <= last[0]:
                    value = last[1] if cmd.command_id == last[0] else b""
                else:
                    value = self.db.execute(cmd)
                    if cmd.client_id:
                        self.ctab[cmd.client_id] = (cmd.command_id, value)
                self.executed.add(pair)
                if pair[0] == str(self.id):
                    req = self.creq.pop(pair[1], None)
                    if req is not None:
                        req.reply(Reply(cmd, value=value))
            self.execute += 1


def new_replica(id: ID, cfg: Config) -> SDPaxosReplica:
    return SDPaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  ``cr`` is the command-body relay a
# holder sends a staller in answer to a ``cneed`` fetch — on the host
# that relay IS a (re)sent CAccept (handle_cfetch), so both the
# original broadcast plane and the relay plane project onto CAccept.
# The host's OFrontier/OFetch watchdog traffic has no sim plane (the
# lock-step kernel needs no liveness prodding) and is simply absent
# from the map.
TRACE_MSG_MAP = {
    "ca": "CAccept", "cack": "CAck", "cneed": "CFetch", "cr": "CAccept",
    "oreq": "OReq", "p1a": "Seq1a", "p1b": "Seq1b",
    "p2a": "OAccept", "p2b": "OAck", "p3": "OCommit",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    # C-plane (decentralized command replication)
    "c_next":     "cnext",       # my proposed command count
    "c_stored":   "cstore",      # per-owner stored commands
    "c_ack":      "cquorum",     # store acks <-> per-command Quorum
    "o_seen":     "cchosen",     # chosen (majority-stored) frontier
    "o_enq":      "queued",      # owner tokens handed to the sequencer
    "exec_c":     "executed",    # per-owner executed frontier
    # O-log (centralized ordering; shared ballot-ring planes)
    "p1_acks":    "seq_quorum",  # sequencer-election ack bitmask
    "log_bal":    "olog",        # O-log ring planes <-> OEntry fields
    "log_cmd":    "olog",
    "log_commit": "olog",
    "log_acks":   "olog",        # OAck bitmask <-> OEntry.quorum
    "next_slot":  "oslot",
    "kv":         "db",
    "base":       "",  # ring-window base: gc_base prunes the host dict
    "proposed":   "",  # own-ballot OAccept mask: implied by OEntry
    "timer":      "",  # election step-timer: host elections are wall-clock
    "stuck":      "",  # frontier-stall retry counter (kernel-only)
    # on-device observability (PR 11) — measurement planes, excluded
    # from the trace witness hash; the host twins are the registry's
    # live latency histograms and the post-hoc linearizability checker
    "m_prop_t":      "",
    "m_commit_dt":   "",   # pending deltas for the deferred flush
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
}
