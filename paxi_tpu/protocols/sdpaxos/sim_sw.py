"""FROZEN pre-rewrite reference: the sliding-window (ring-position)
lane-major sdpaxos kernel, kept verbatim from before the fixed-cell
rewrite (PR 15) as the equivalence-proof counterpart.

Ring layout contract (the OLD one): ring position ``i`` holds absolute
slot ``base + i``; every base advance is a ``ring.shift_window`` data
movement.  The live kernel in ``sim.py`` holds absolute slot ``a`` at
cell ``a % S`` forever (sim/cell.py) and must stay BIT-CANONICALLY
equal to this module on pinned fuzz seeds: same PRNG draws, same
outboxes, same counters, and a state that matches after rolling each
ring plane to window order (cell.window_view_np) —
tests/test_fixed_cell_equiv.py enforces it, and ``python -m paxi_tpu
profile --gathers`` diffs the two compiled HLOs' gather counts.  Do
not edit except to mirror a semantic (non-layout) change in sim.py.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import ballot_ring as br
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ballot_ring import NO_CMD
from paxi_tpu.sim.ring import diag2, dst_major
from paxi_tpu.sim.ring import pick_src as _pick_src
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

IDX_BITS = 20  # cidx field width in the executed command id

# the ballot-ring planes ballot_ring.py owns (the O-log); this kernel
# adds the C-plane and kv
BR_KEYS = br.KEYS


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        # decentralized command replication (cumulative go-back-N)
        "ca": ("cidx",),      # owner -> all: body of my command #cidx
        "cack": ("n",),       # all -> owner: stored your [0, n)
        "oreq": ("n",),       # owner -> all: my chosen frontier is n
        # pull-side body recovery: an execution stalled on a body its
        # (possibly dead) owner never delivered asks everyone; any
        # holder relays.  Without this a perm-crashed owner whose
        # chosen body missed the sequencer wedges ordering cluster-wide
        "cneed": ("owner", "cidx"),   # staller -> all: I need (o, i)
        "cr": ("owner", "cidx"),      # holder -> staller: relayed body
        # centralized ordering: Multi-Paxos on owner tokens
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(owner, cidx):
    """Executed command id for owner's cidx-th command (KV payload)."""
    return (owner << IDX_BITS) | cidx


def cmd_key(cmd, n_keys):
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        # ---- C-plane (decentralized command replication) ----
        c_next=jnp.zeros((R, G), i32),     # my proposed command count
        c_stored=jnp.zeros((R, R, G), i32),  # [me, owner] stored count
        c_ack=jnp.zeros((R, R, G), i32),   # [owner, dst] acked count
        o_seen=jnp.zeros((R, R, G), i32),  # [me, owner] chosen frontier
        o_enq=jnp.zeros((R, R, G), i32),   # [seqr, owner] tokens ordered
        exec_c=jnp.zeros((R, R, G), i32),  # [me, owner] tokens executed
        # ---- O-log (centralized ordering; shared ring machinery) ----
        ballot=jnp.zeros((R, G), i32),
        active=jnp.zeros((R, G), bool),
        p1_acks=jnp.zeros((R, G), i32),
        base=jnp.zeros((R, G), i32),
        log_bal=jnp.zeros((R, S, G), i32),
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),   # owner token / NOOP
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),
        proposed=jnp.zeros((R, S, G), bool),
        next_slot=jnp.zeros((R, G), i32),
        execute=jnp.zeros((R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),
        # on-device observability (PR-11 template: m_ measurement
        # planes, witness-hash-excluded, never read by protocol logic
        # — PXM10x): m_prop_t records each O-slot's FIRST propose step
        # at the sequencer; commits store their propose->commit delta
        # in the position-free m_commit_dt pending plane and the
        # runner's deferred flush log2-bins it (metrics/lathist);
        # m_inscan_viol accumulates the in-scan linearizability
        # spot-check (sim/inscan)
        m_prop_t=jnp.zeros((R, S, G), i32),
        m_commit_dt=jnp.zeros((R, S, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    own_diag = ridx[:, None, None] == ridx[None, :, None]   # (R, R, 1)

    st = {k: state[k] for k in BR_KEYS}
    # measurement planes (never passed into ballot_ring: the helpers
    # shift the log planes by base deltas, so m_prop_t is re-aligned
    # here by the SAME delta after every base-moving call)
    m_prop_t = state["m_prop_t"]
    m_lat_hist = state["m_lat_hist"]
    m_lat_sum = state["m_lat_sum"]
    c_next = state["c_next"]
    c_stored = state["c_stored"]
    c_ack = state["c_ack"]
    o_seen = state["o_seen"]
    o_enq = state["o_enq"]
    exec_c = state["exec_c"]
    kv = state["kv"]
    G = c_next.shape[-1]

    T = dst_major                         # (src, dst, G) -> (me, src, G)

    # ================= C-plane: decentralized replication ===============
    # receive command bodies, in order (cumulative take)
    m = inbox["ca"]
    take = T(m["valid"]) & (T(m["cidx"]) == c_stored)    # (me, owner, G)
    c_stored = c_stored + take

    # receive relayed bodies (pull-side recovery; any src may relay any
    # owner's next-needed body — dedup'd by the cumulative-take rule)
    m = inbox["cr"]
    rv, ro, rc = T(m["valid"]), T(m["owner"]), T(m["cidx"])  # (me, src, G)
    rhit = (rv[:, :, None, :]
            & (ro[:, :, None, :] == ridx[None, None, :, None])
            & (rc[:, :, None, :] == c_stored[:, None, :, :]))
    c_stored = c_stored + jnp.any(rhit, axis=1)          # (me, owner, G)

    # serve body-need requests: respond if I hold the asked index
    m = inbox["cneed"]
    nv = T(m["valid"])                                   # (me, staller, G)
    no = jnp.clip(T(m["owner"]), 0, R - 1)
    nc = T(m["cidx"])
    stored_at = jnp.zeros_like(nc)
    for o in range(R):
        stored_at = jnp.where(no == o, c_stored[:, o, :][:, None, :],
                              stored_at)
    # (me, staller, G) is already the (src, dst, G) outbox orientation
    out_cr = {
        "valid": nv & (nc >= 0) & (nc < stored_at),
        "owner": no,
        "cidx": nc,
    }

    # receive cumulative store-acks for my commands
    m = inbox["cack"]
    c_ack = jnp.maximum(
        c_ack, jnp.where(T(m["valid"]), T(m["n"]), 0))   # (owner, dst, G)

    # chosen = MAJ-th largest of my ack row (self-store included)
    ack_row = jnp.where(own_diag, c_next[:, None, :], c_ack)
    chosen = jnp.sort(ack_row, axis=1)[:, R - MAJ, :]    # (owner, G)

    # learn everyone's chosen frontiers (cumulative, crash-survivable)
    m = inbox["oreq"]
    o_seen = jnp.maximum(
        o_seen, jnp.where(T(m["valid"]), T(m["n"]), 0))  # (me, owner, G)
    o_seen = jnp.maximum(o_seen, jnp.where(own_diag, chosen[:, None, :], 0))

    # propose a new command of my own (closed-loop, bounded backlog)
    my_exec = diag2(exec_c)                              # (R, G)
    c_do = (c_next - my_exec) < S
    c_next = c_next + c_do
    c_stored = c_stored + (own_diag & c_do[:, None, :])  # self-store

    # C-accept out: per-destination go-back-N (what I think dst needs);
    # a duplicate is an ignored no-op at the receiver
    out_ca = {
        "valid": c_ack < c_next[:, None, :],             # (owner, dst, G)
        "cidx": jnp.maximum(jnp.minimum(c_ack, c_next[:, None, :] - 1), 0),
    }
    # cumulative acks + chosen-frontier gossip, every step (cheap heal);
    # c_stored[me, owner] is exactly the (src=me, dst=owner) plane
    out_cack = {
        "valid": jnp.ones((R, R, G), bool),
        "n": c_stored,
    }
    out_oreq = {
        "valid": jnp.ones((R, R, G), bool),
        "n": jnp.broadcast_to(chosen[:, None, :], (R, R, G)),
    }

    # ============ O-log: shared Multi-Paxos core over owner tokens ======
    st, out_p1b, promote = br.promise_p1a(st, inbox["p1a"])
    st, p1_win, amask = br.tally_p1b(st, inbox["p1b"], MAJ, STRIDE)
    b0 = st["base"]
    st, ex = br.adopt_best_acker(st, amask, p1_win,
                                 {"kv": kv, "exec_c": exec_c})
    kv, exec_c = ex["kv"], ex["exec_c"]
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)
    st = br.merge_acker_logs(st, amask, p1_win)
    # a takeover restarts the adopted slots' latency clocks (re-owned
    # re-proposals measure from the takeover, like the paxos kernel)
    m_prop_t = jnp.where(p1_win[:, None, :] & st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)

    # ---------------- phase-1 win: rebuild per-owner token counts -------
    # tokens ordered for owner o = tokens executed (exec_c) + o's tokens
    # in my window at or above the execute frontier (everything not yet
    # executed is in-window: the ring slides only past executed slots)
    abs_ = st["base"][:, None, :] + sidx[None, :, None]
    at_or_above = (abs_ >= st["execute"][:, None, :]) \
        & (abs_ < st["next_slot"][:, None, :])
    rebuilt = jnp.zeros_like(o_enq)
    for o in range(R):
        cnt = jnp.sum(at_or_above & (st["log_cmd"] == o), axis=1)  # (R, G)
        rebuilt = jnp.where(ridx[None, :, None] == o,
                            (exec_c[:, o, :] + cnt)[:, None, :], rebuilt)
    o_enq = jnp.where(p1_win[:, None, :], rebuilt, o_enq)

    st, out_p2b, acc_ok, _ = br.accept_p2a(st, inbox["p2a"])
    st, newly = br.tally_p2b(st, inbox["p2b"], MAJ, STRIDE)
    # in-kernel commit latency: every newly committed (seqr, slot)
    # stores its propose->commit step delta in the pending plane; the
    # runner's deferred flush log2-bins it (see init_state)
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly, dt, state["m_commit_dt"])
    m_lat_sum = m_lat_sum + jnp.sum(jnp.where(newly, dt, 0),
                                    axis=(0, 1), dtype=jnp.int32)
    b0 = st["base"]
    st, ex, c_has, c_bal = br.apply_p3(st, inbox["p3"],
                                       {"kv": kv, "exec_c": exec_c})
    kv, exec_c = ex["kv"], ex["exec_c"]
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)

    # ---------------- sequencer proposes (backlog or re-proposal) -------
    # ordering queue: deepest-backlog owner's token (replaces the paxos
    # kernel's self-generated client command)
    is_leader = st["active"] & br.own_bal_mask(st, STRIDE)
    has_re, can_new, prop_rel, prop_slot, oh_p, re_cmd = \
        br.repropose_target(st)
    backlog = jnp.maximum(o_seen - o_enq, 0)             # (seqr, owner, G)
    pick_o = jnp.argmax(backlog, axis=1).astype(jnp.int32)   # (seqr, G)
    has_bl = jnp.any(backlog > 0, axis=1)
    is_new = ~has_re & can_new & has_bl
    prop_cmd = jnp.where(is_new, pick_o, re_cmd)
    do = is_leader & (has_re | is_new)
    # latency clock: a slot's FIRST propose starts it (re-proposals
    # and go-back-N retries keep the original start)
    m_prop_t = jnp.where(do[:, None, :] & oh_p & ~st["proposed"]
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    st, out_p2a = br.propose_write(st, do, is_new, prop_cmd, prop_slot,
                                   oh_p)
    enq_bump = (is_new & do)[:, None, :] \
        & (ridx[None, :, None] == pick_o[:, None, :])
    o_enq = o_enq + enq_bump

    # ---------------- execute committed O-prefix (body-gated) -----------
    execute = st["execute"]
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(st["active"])
    need_own = jnp.full_like(execute, -1)
    need_idx = jnp.zeros_like(execute)
    for e in range(cfg.exec_window):
        rel = execute + e - st["base"]
        oh_e = sidx[None, :, None] == rel[:, None, :]
        com = jnp.any(oh_e & st["log_commit"], axis=1)
        cmd_e = jnp.sum(jnp.where(oh_e, st["log_cmd"], 0), axis=1)
        is_tok = cmd_e >= 0
        own_e = jnp.clip(cmd_e, 0, R - 1)
        stored_e = _pick_src(jnp.swapaxes(c_stored, 0, 1), own_e)
        ec_e = _pick_src(jnp.swapaxes(exec_c, 0, 1), own_e)
        body_ok = ec_e < stored_e
        # first body-stall of this step: ask everyone for my next-NEEDED
        # body — cumulative c_stored, NOT exec_c: adoption can jump
        # exec_c ahead of the local store, and relays are only
        # acceptable in cumulative order, draining the gap one body per
        # round trip
        blk = running & com & is_tok & ~body_ok
        first_blk = blk & (need_own < 0)
        need_own = jnp.where(first_blk, own_e, need_own)
        need_idx = jnp.where(first_blk, stored_e, need_idx)
        runnable = com & (~is_tok | body_ok)
        running = running & runnable
        wr = running & is_tok
        full_e = encode_cmd(own_e, ec_e)   # (owner, position) -> command
        bump = wr[:, None, :] & (ridx[None, :, None] == own_e[:, None, :])
        exec_c = exec_c + bump
        key_e = cmd_key(full_e, K)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, full_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced
    out_cneed = {
        "valid": jnp.broadcast_to((need_own >= 0)[:, None, :], (R, R, G)),
        "owner": jnp.broadcast_to(need_own[:, None, :], (R, R, G)),
        "cidx": jnp.broadcast_to(need_idx[:, None, :], (R, R, G)),
    }

    # ---------------- wrap-up: P3 out, retry, election, slide -----------
    out_p3 = br.p3_out(st, newly, new_execute, is_leader, ctx.t)
    st = br.retry_stuck(st, new_execute, is_leader, cfg.retry_timeout)
    heard = promote | acc_ok | (c_has & (c_bal >= st["ballot"]))
    st, out_p1a = br.election_tick(st, heard, ctx.rng, cfg)
    b0 = st["base"]
    st = br.slide_window(st, new_execute, RETAIN)
    m_prop_t = _shift(m_prop_t, st["base"] - b0, 0)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device per group
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["execute"], st["execute"], state["base"], st["base"],
        state["base"][:, None, :] + sidx[None, :, None],
        st["base"][:, None, :] + sidx[None, :, None],
        state["log_cmd"], st["log_cmd"],
        state["log_commit"], st["log_commit"],
        kv=kv, lane_major=True)

    new_state = dict(st, c_next=c_next, c_stored=c_stored, c_ack=c_ack,
                     o_seen=o_seen, o_enq=o_enq, exec_c=exec_c, kv=kv,
                     m_prop_t=m_prop_t, m_commit_dt=m_commit_dt,
                     m_lat_hist=m_lat_hist, m_lat_sum=m_lat_sum,
                     m_inscan_viol=m_inscan_viol)
    outbox = {"ca": out_ca, "cack": out_cack, "oreq": out_oreq,
              "cneed": out_cneed, "cr": out_cr,
              "p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "commands_proposed": jnp.sum(state["c_next"]),
        "has_sequencer": jnp.sum(jnp.any(state["active"], axis=0)
                                 .astype(jnp.int32)),
        # on-device observability scalars (PR-11 contract; the
        # histogram itself rides in state as m_lat_hist)
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """The paxos O-log oracle (agreement / stability / ballot
    monotonicity / executed-prefix-committed) — token->command binding
    is a pure function of the agreed O-log, so O-log agreement IS
    execution-order agreement — plus monotone C-plane frontiers.
    (exec_c <= c_stored is NOT asserted: snapshot adoption legally
    jumps exec_c ahead of the local store until go-back-N heals it;
    live execution is body-gated regardless.)"""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    v_cmono = jnp.sum(new["c_stored"] < old["c_stored"])
    v_cmono = v_cmono + jnp.sum(new["c_next"] < old["c_next"])
    v_cmono = v_cmono + jnp.sum(new["exec_c"] < old["exec_c"])

    return (v_agree + v_stable + v_bal + v_exec
            + v_cmono).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="sdpaxos_sw",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
