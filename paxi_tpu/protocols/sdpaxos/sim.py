"""SDPaxos (semi-decentralized Paxos) as a pure TPU kernel.

Reference: the paxi lineage's sdpaxos/ package (SURVEY §2.2 "others" —
the SoCC'18 protocol): command replication is **decentralized** — every
replica is the command leader for the commands it receives and
replicates them from where they arrive (a C-instance per command) —
while ordering is **centralized** — one elected sequencer assigns
global sequence slots (O-instances).  A command is executable once BOTH
its C-instance is durable on a majority and its O-instance is
committed; execution follows O-log order.  The sequencer is recovered
with ordinary Paxos ballots, so a sequencer crash costs one election,
not availability.

TPU re-design (lane-major layout — see sim/lanes.py; not a translation):
- **O-log = the Multi-Paxos ring machinery** (protocols/paxos/sim.py):
  ballot election with jittered timers, P1 merge by reference, P2
  acceptance under bit-packed ack masks, P3 commit + frontier, snapshot
  catch-up, and a sliding window over absolute slots.
- **O-entries are owner tokens, bound positionally.**  The reference
  names (owner, index) pairs in O-instances; here an O-entry carries
  only the owner id, and the t-th committed token of owner ``o`` maps
  to o's t-th command.  The binding is a pure function of the agreed
  O-log, so ordering is **idempotent across sequencer failovers**: a
  token lost below the new sequencer's P1 quorum is simply re-counted
  into the backlog and re-proposed, and a token double-adopted by a log
  merge just orders the owner's next command early — no per-index
  recovery state, no duplicate/gap hazard for the count-based pointer
  rebuild.  (An index-named design needs a per-instance recovery map;
  on TPU that is a gather-heavy set where a cumulative count is free.)
- **C-replication is frontier-shaped, not ring-shaped.**  Owners
  propose their own commands strictly in order and command bodies are
  deterministic functions of (owner, cidx) (as everywhere in this
  suite: paxos's encode_cmd, chain's encode_val), so a replica's copy
  of owner ``o``'s command log is fully described by a cumulative
  count ``c_stored[me, o]``.  C-accepts carry go-back-N cumulative
  indices per destination and heal drops in ~1 RTT; ``Quorum.ACK``
  over C-instances becomes the MAJ-th order statistic of the cumulative
  ack row (a sort over the tiny R axis replaces per-instance bitmasks).
- The owner reports its *chosen* (majority-stored) frontier to everyone
  (``oreq``, cumulative); every replica tracks ``o_seen[me, owner]`` so
  any future sequencer can enqueue without a handoff.  The active
  sequencer proposes one backlog token per step (deepest backlog
  first) — the paxos kernel's closed-loop client replaced by the
  ordering queue.
- On winning the O-ballot, the new sequencer rebuilds its per-owner
  token counts from the merged window plus its executed prefix
  (``exec_c``); P1-quorum intersection guarantees every *committed*
  token is visible to the merge, exactly the reference's recovery
  argument.
- Execution walks the committed O-prefix; a token of owner ``o``
  applies command ``(o, exec_c[me, o])`` only when that body is locally
  durable (``exec_c < c_stored``) — a missing body stalls execution
  (liveness), never reorders it (safety).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr

from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.ring import diag2, dst_major
from paxi_tpu.sim.ring import pick_src as _pick_src
from paxi_tpu.sim.ring import require_packable
from paxi_tpu.sim.ring import shift_row as _shift_row
from paxi_tpu.sim.ring import shift_window as _shift
from paxi_tpu.sim.ring import take_replica as _take_replica
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1    # empty O-log entry
NOOP = -2      # hole filled by a recovering sequencer
IDX_BITS = 20  # cidx field width in the executed command id


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    return {
        # decentralized command replication (cumulative go-back-N)
        "ca": ("cidx",),      # owner -> all: body of my command #cidx
        "cack": ("n",),       # all -> owner: stored your [0, n)
        "oreq": ("n",),       # owner -> all: my chosen frontier is n
        # pull-side body recovery: an execution stalled on a body its
        # (possibly dead) owner never delivered asks everyone; any
        # holder relays.  Without this a perm-crashed owner whose
        # chosen body missed the sequencer wedges ordering cluster-wide
        "cneed": ("owner", "cidx"),   # staller -> all: I need (o, i)
        "cr": ("owner", "cidx"),      # holder -> staller: relayed body
        # centralized ordering: Multi-Paxos on owner tokens
        "p1a": ("bal",),
        "p1b": ("bal",),
        "p2a": ("bal", "slot", "cmd"),
        "p2b": ("bal", "slot"),
        "p3": ("bal", "slot", "cmd", "upto"),
    }


def encode_cmd(owner, cidx):
    """Executed command id for owner's cidx-th command (KV payload)."""
    return (owner << IDX_BITS) | cidx


def cmd_key(cmd, n_keys):
    return fib_key(cmd, n_keys)


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, S, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        # ---- C-plane (decentralized command replication) ----
        c_next=jnp.zeros((R, G), i32),     # my proposed command count
        c_stored=jnp.zeros((R, R, G), i32),  # [me, owner] stored count
        c_ack=jnp.zeros((R, R, G), i32),   # [owner, dst] acked count
        o_seen=jnp.zeros((R, R, G), i32),  # [me, owner] chosen frontier
        o_enq=jnp.zeros((R, R, G), i32),   # [seqr, owner] tokens ordered
        exec_c=jnp.zeros((R, R, G), i32),  # [me, owner] tokens executed
        # ---- O-log (centralized ordering; paxos ring machinery) ----
        ballot=jnp.zeros((R, G), i32),
        active=jnp.zeros((R, G), bool),
        p1_acks=jnp.zeros((R, G), i32),
        base=jnp.zeros((R, G), i32),
        log_bal=jnp.zeros((R, S, G), i32),
        log_cmd=jnp.full((R, S, G), NO_CMD, i32),   # owner token / NOOP
        log_commit=jnp.zeros((R, S, G), bool),
        log_acks=jnp.zeros((R, S, G), i32),
        proposed=jnp.zeros((R, S, G), bool),
        next_slot=jnp.zeros((R, G), i32),
        execute=jnp.zeros((R, G), i32),
        kv=jnp.zeros((R, K, G), i32),
        timer=jnp.broadcast_to(
            (jnp.arange(R, dtype=i32) * cfg.election_timeout)[:, None],
            (R, G)),
        stuck=jnp.zeros((R, G), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, S, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, STRIDE = cfg.majority, cfg.ballot_stride
    RETAIN = max(S // 2, 1)
    ridx = jnp.arange(R, dtype=jnp.int32)
    sidx = jnp.arange(S, dtype=jnp.int32)
    src_bit = (jnp.int32(1) << ridx)[:, None, None]   # also self-bit for
    self_bit2 = (jnp.int32(1) << ridx)[:, None]       # (R, S, G) planes
    own_diag = ridx[:, None, None] == ridx[None, :, None]   # (R, R, 1)

    c_next = state["c_next"]
    c_stored = state["c_stored"]
    c_ack = state["c_ack"]
    o_seen = state["o_seen"]
    o_enq = state["o_enq"]
    exec_c = state["exec_c"]
    ballot = state["ballot"]
    active = state["active"]
    p1_acks = state["p1_acks"]
    base = state["base"]
    log_bal = state["log_bal"]
    log_cmd = state["log_cmd"]
    log_commit = state["log_commit"]
    log_acks = state["log_acks"]
    proposed = state["proposed"]
    next_slot = state["next_slot"]
    execute = state["execute"]
    kv = state["kv"]
    G = ballot.shape[-1]

    T = dst_major                         # (src, dst, G) -> (me, src, G)

    # ================= C-plane: decentralized replication ===============
    # receive command bodies, in order (cumulative take)
    m = inbox["ca"]
    take = T(m["valid"]) & (T(m["cidx"]) == c_stored)    # (me, owner, G)
    c_stored = c_stored + take

    # receive relayed bodies (pull-side recovery; any src may relay any
    # owner's next-needed body — dedup'd by the cumulative-take rule)
    m = inbox["cr"]
    rv, ro, rc = T(m["valid"]), T(m["owner"]), T(m["cidx"])  # (me, src, G)
    rhit = (rv[:, :, None, :]
            & (ro[:, :, None, :] == ridx[None, None, :, None])
            & (rc[:, :, None, :] == c_stored[:, None, :, :]))
    c_stored = c_stored + jnp.any(rhit, axis=1)          # (me, owner, G)

    # serve body-need requests: respond if I hold the asked index
    m = inbox["cneed"]
    nv = T(m["valid"])                                   # (me, staller, G)
    no = jnp.clip(T(m["owner"]), 0, R - 1)
    nc = T(m["cidx"])
    stored_at = jnp.zeros_like(nc)
    for o in range(R):
        stored_at = jnp.where(no == o, c_stored[:, o, :][:, None, :],
                              stored_at)
    # (me, staller, G) is already the (src, dst, G) outbox orientation
    out_cr = {
        "valid": nv & (nc >= 0) & (nc < stored_at),
        "owner": no,
        "cidx": nc,
    }

    # receive cumulative store-acks for my commands
    m = inbox["cack"]
    c_ack = jnp.maximum(
        c_ack, jnp.where(T(m["valid"]), T(m["n"]), 0))   # (owner, dst, G)

    # chosen = MAJ-th largest of my ack row (self-store included)
    ack_row = jnp.where(own_diag, c_next[:, None, :], c_ack)
    chosen = jnp.sort(ack_row, axis=1)[:, R - MAJ, :]    # (owner, G)

    # learn everyone's chosen frontiers (cumulative, crash-survivable)
    m = inbox["oreq"]
    o_seen = jnp.maximum(
        o_seen, jnp.where(T(m["valid"]), T(m["n"]), 0))  # (me, owner, G)
    o_seen = jnp.maximum(o_seen, jnp.where(own_diag, chosen[:, None, :], 0))

    # propose a new command of my own (closed-loop, bounded backlog)
    my_exec = diag2(exec_c)                              # (R, G)
    c_do = (c_next - my_exec) < S
    c_next = c_next + c_do
    c_stored = c_stored + (own_diag & c_do[:, None, :])  # self-store

    # C-accept out: per-destination go-back-N (what I think dst needs);
    # a duplicate is an ignored no-op at the receiver
    out_ca = {
        "valid": c_ack < c_next[:, None, :],             # (owner, dst, G)
        "cidx": jnp.maximum(jnp.minimum(c_ack, c_next[:, None, :] - 1), 0),
    }
    # cumulative acks + chosen-frontier gossip, every step (cheap heal);
    # c_stored[me, owner] is exactly the (src=me, dst=owner) plane
    out_cack = {
        "valid": jnp.ones((R, R, G), bool),
        "n": c_stored,
    }
    out_oreq = {
        "valid": jnp.ones((R, R, G), bool),
        "n": jnp.broadcast_to(chosen[:, None, :], (R, R, G)),
    }

    # ================= O-log: Multi-Paxos over owner tokens =============
    # ---------------- P1a: promise to the highest proposer --------------
    m = inbox["p1a"]
    b_in = jnp.where(m["valid"], m["bal"], 0)
    p1a_bal = jnp.max(b_in, axis=0)
    p1a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    promote = p1a_bal > ballot
    ballot = jnp.maximum(ballot, p1a_bal)
    active = active & ~promote
    p1_acks = jnp.where(promote, 0, p1_acks)
    p1b_valid = promote[:, None, :] & (ridx[None, :, None]
                                       == p1a_src[:, None, :])
    out_p1b = {"valid": p1b_valid,
               "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G))}

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx[:, None])

    # ---------------- P1b: collect phase-1 acks -------------------------
    m = inbox["p1b"]
    cond = m["valid"] & (m["bal"] == ballot[None, :, :]) \
        & own_bal[None, :, :]
    p1_acks = p1_acks | jnp.sum(jnp.where(cond, src_bit, 0), axis=0)
    p1_win = own_bal & ~active \
        & (jax.lax.population_count(p1_acks) >= MAJ)
    amask = ((p1_acks[:, None, :] >> ridx[None, :, None]) & 1).astype(bool)

    # ---------------- phase-1 win: state transfer from best acker -------
    exec_am = jnp.where(amask, execute[None, :, :], -1)
    f_src = jnp.argmax(exec_am, axis=1).astype(jnp.int32)
    front = jnp.max(exec_am, axis=1)
    el_ad = p1_win & (front > execute)
    kv = jnp.where(el_ad[:, None, :], _take_replica(kv, f_src), kv)
    exec_c = jnp.where(el_ad[:, None, :], _take_replica(exec_c, f_src),
                       exec_c)
    execute = jnp.where(el_ad, front, execute)
    next_slot = jnp.where(el_ad, jnp.maximum(next_slot, front), next_slot)
    f_base = _take_replica(base, f_src)
    adv_el = jnp.where(el_ad, jnp.maximum(f_base - base, 0), 0)
    base = jnp.where(el_ad, jnp.maximum(f_base, base), base)
    log_bal = _shift(log_bal, adv_el, 0)
    log_cmd = _shift(log_cmd, adv_el, NO_CMD)
    log_commit = _shift(log_commit, adv_el, False)
    proposed = _shift(proposed, adv_el, False)
    log_acks = _shift(log_acks, adv_el, 0)

    # ---------------- phase-1 win: merge ackers' O-logs -----------------
    best_bal = jnp.full_like(log_bal, -1)
    merged_cmd = jnp.full_like(log_cmd, NO_CMD)
    merged_commit = jnp.zeros_like(log_commit)
    committed_cmd = jnp.full_like(log_cmd, NO_CMD)
    for s in range(R):
        sel_s = amask[:, s, :]
        adv_s = base - base[s][None, :]
        lb_s = _shift_row(log_bal[s], adv_s, -1)
        lc_s = _shift_row(log_cmd[s], adv_s, NO_CMD)
        lm_s = _shift_row(log_commit[s], adv_s, False)
        lb_s = jnp.where(sel_s[:, None, :], lb_s, -1)
        lm_s = lm_s & sel_s[:, None, :]
        upd = lb_s > best_bal
        best_bal = jnp.where(upd, lb_s, best_bal)
        merged_cmd = jnp.where(upd, lc_s, merged_cmd)
        committed_cmd = jnp.where(lm_s & ~merged_commit, lc_s,
                                  committed_cmd)
        merged_commit = merged_commit | lm_s
    abs_ = base[:, None, :] + sidx[None, :, None]
    has_acc = (best_bal > 0) | merged_commit
    top = jnp.max(jnp.where(has_acc, abs_ + 1, 0), axis=1)
    new_next = jnp.maximum(next_slot, top)
    in_win = abs_ < new_next[:, None, :]
    w = p1_win[:, None, :]
    adopt_cmd = jnp.where(merged_commit, committed_cmd,
                          jnp.where(best_bal > 0, merged_cmd, NOOP))
    log_cmd = jnp.where(w & in_win, adopt_cmd, log_cmd)
    log_bal = jnp.where(w & in_win, ballot[:, None, :], log_bal)
    log_commit = jnp.where(w & in_win, merged_commit | log_commit,
                           log_commit)
    proposed = jnp.where(w, in_win & (merged_commit | log_commit), proposed)
    log_acks = jnp.where(w, jnp.where(in_win, src_bit, 0), log_acks)
    next_slot = jnp.where(p1_win, new_next, next_slot)
    active = active | p1_win

    # ---------------- phase-1 win: rebuild per-owner token counts -------
    # tokens ordered for owner o = tokens executed (exec_c) + o's tokens
    # in my window at or above the execute frontier (everything not yet
    # executed is in-window: the ring slides only past executed slots)
    at_or_above = (abs_ >= execute[:, None, :]) \
        & (abs_ < next_slot[:, None, :])
    rebuilt = jnp.zeros_like(o_enq)
    for o in range(R):
        cnt = jnp.sum(at_or_above & (log_cmd == o), axis=1)     # (R, G)
        rebuilt = jnp.where(ridx[None, :, None] == o,
                            (exec_c[:, o, :] + cnt)[:, None, :], rebuilt)
    o_enq = jnp.where(p1_win[:, None, :], rebuilt, o_enq)

    # ---------------- P2a: accept from the highest-ballot leader --------
    m = inbox["p2a"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    a_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    a_bal = jnp.max(b_in, axis=0)
    a_has = a_bal > 0
    a_slot = _pick_src(m["slot"], a_src)
    a_cmd = _pick_src(m["cmd"], a_src)
    acc_ok = a_has & (a_bal >= ballot)
    demote = acc_ok & (a_bal > ballot)
    ballot = jnp.where(acc_ok, a_bal, ballot)
    active = active & ~demote
    p1_acks = jnp.where(demote, 0, p1_acks)
    a_rel = a_slot - base
    a_inw = (a_rel >= 0) & (a_rel < S)
    oh = acc_ok[:, None, :] & (sidx[None, :, None] == a_rel[:, None, :])
    writable = oh & (log_bal <= a_bal[:, None, :]) & ~log_commit
    log_bal = jnp.where(writable, a_bal[:, None, :], log_bal)
    log_cmd = jnp.where(writable, a_cmd[:, None, :], log_cmd)
    out_p2b = {
        "valid": (acc_ok & a_inw)[:, None, :]
        & (ridx[None, :, None] == a_src[:, None, :]),
        "bal": jnp.broadcast_to(a_bal[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(a_slot[:, None, :], (R, R, G)),
    }

    own_bal = (ballot > 0) & (ballot % STRIDE == ridx[:, None])

    # ---------------- P2b: sequencer tallies acks, commits --------------
    m = inbox["p2b"]
    okb = m["valid"] & (m["bal"] == ballot[None, :, :]) \
        & (active & own_bal)[None, :, :]
    brel = m["slot"] - base[None, :, :]
    for s in range(R):
        oh_s = okb[s][:, None, :] \
            & (sidx[None, :, None] == brel[s][:, None, :])
        log_acks = log_acks | jnp.where(oh_s, jnp.int32(1) << s, 0)
    acks_n = jax.lax.population_count(log_acks)
    newly = ((active & own_bal)[:, None, :] & (acks_n >= MAJ)
             & ~log_commit & (log_cmd != NO_CMD) & proposed)
    log_commit = log_commit | newly

    # ---------------- P3: commit notifications --------------------------
    m = inbox["p3"]
    b_in = jnp.where(m["valid"], m["bal"], -1)
    c_src = jnp.argmax(b_in, axis=0).astype(jnp.int32)
    c_bal = jnp.max(b_in, axis=0)
    c_has = c_bal > 0
    c_slot = _pick_src(m["slot"], c_src)
    c_cmd = _pick_src(m["cmd"], c_src)
    c_upto = _pick_src(m["upto"], c_src)
    abs_ = base[:, None, :] + sidx[None, :, None]
    c_rel = c_slot - base
    oh = c_has[:, None, :] & (sidx[None, :, None] == c_rel[:, None, :])
    log_cmd = jnp.where(oh, c_cmd[:, None, :], log_cmd)
    log_bal = jnp.where(oh, jnp.maximum(log_bal, c_bal[:, None, :]),
                        log_bal)
    log_commit = log_commit | oh
    ohu = (c_has[:, None, :] & (abs_ < c_upto[:, None, :])
           & (log_bal == c_bal[:, None, :]) & (log_cmd != NO_CMD))
    log_commit = log_commit | ohu

    # ---------------- P3: snapshot catch-up for deep laggards -----------
    src_base = _take_replica(base, c_src)
    adopt = c_has & (execute < src_base)
    adv_a = jnp.where(adopt, src_base - base, 0)
    my_bal = _shift(log_bal, adv_a, 0)
    my_cmd = _shift(log_cmd, adv_a, NO_CMD)
    my_com = _shift(log_commit, adv_a, False)
    s_bal = _take_replica(log_bal, c_src)
    s_cmd = _take_replica(log_cmd, c_src)
    s_com = _take_replica(log_commit, c_src)
    a2 = adopt[:, None, :]
    log_bal = jnp.where(a2, jnp.where(s_com, s_bal, my_bal), log_bal)
    log_cmd = jnp.where(a2, jnp.where(s_com, s_cmd, my_cmd), log_cmd)
    log_commit = jnp.where(a2, s_com | my_com, log_commit)
    proposed = jnp.where(a2, False, proposed)
    log_acks = jnp.where(a2, 0, log_acks)
    kv = jnp.where(adopt[:, None, :], _take_replica(kv, c_src), kv)
    exec_c = jnp.where(adopt[:, None, :], _take_replica(exec_c, c_src),
                       exec_c)
    execute = jnp.where(adopt, _take_replica(execute, c_src), execute)
    next_slot = jnp.where(adopt, jnp.maximum(next_slot, execute), next_slot)
    base = jnp.where(adopt, src_base, base)
    abs_ = base[:, None, :] + sidx[None, :, None]

    # ---------------- sequencer proposes (backlog or re-proposal) -------
    is_leader = active & own_bal
    mask_re = (~log_commit) & (~proposed) & (abs_ < next_slot[:, None, :])
    first_re = jnp.argmin(jnp.where(mask_re, sidx[None, :, None], S),
                          axis=1)
    has_re = jnp.any(mask_re, axis=1)
    can_new = (next_slot - base) < S
    rel_next = jnp.clip(next_slot - base, 0, S - 1)
    prop_rel = jnp.where(has_re, first_re, rel_next).astype(jnp.int32)
    prop_slot = base + prop_rel
    # ordering queue: deepest-backlog owner's token (replaces the paxos
    # kernel's self-generated client command)
    backlog = jnp.maximum(o_seen - o_enq, 0)             # (seqr, owner, G)
    pick_o = jnp.argmax(backlog, axis=1).astype(jnp.int32)   # (seqr, G)
    has_bl = jnp.any(backlog > 0, axis=1)
    is_new = ~has_re & can_new & has_bl
    oh_p = sidx[None, :, None] == prop_rel[:, None, :]
    re_cmd = jnp.sum(jnp.where(oh_p, log_cmd, 0), axis=1)
    re_cmd = jnp.where(re_cmd == NO_CMD, NOOP, re_cmd)
    prop_cmd = jnp.where(is_new, pick_o, re_cmd)
    do = is_leader & (has_re | is_new)
    oh = do[:, None, :] & oh_p
    log_bal = jnp.where(oh, ballot[:, None, :], log_bal)
    log_cmd = jnp.where(oh & ~log_commit, prop_cmd[:, None, :], log_cmd)
    proposed = proposed | oh
    log_acks = log_acks | jnp.where(oh, src_bit, 0)
    next_slot = next_slot + (is_new & do)
    enq_bump = (is_new & do)[:, None, :] \
        & (ridx[None, :, None] == pick_o[:, None, :])
    o_enq = o_enq + enq_bump
    out_p2a = {
        "valid": jnp.broadcast_to(do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to(prop_slot[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(prop_cmd[:, None, :], (R, R, G)),
    }

    # ---------------- execute committed O-prefix (body-gated) -----------
    advanced = jnp.zeros_like(execute)
    running = jnp.ones_like(active)
    need_own = jnp.full_like(execute, -1)
    need_idx = jnp.zeros_like(execute)
    kidx = jnp.arange(K, dtype=jnp.int32)
    for e in range(cfg.exec_window):
        rel = execute + e - base
        oh_e = sidx[None, :, None] == rel[:, None, :]
        com = jnp.any(oh_e & log_commit, axis=1)
        cmd_e = jnp.sum(jnp.where(oh_e, log_cmd, 0), axis=1)
        is_tok = cmd_e >= 0
        own_e = jnp.clip(cmd_e, 0, R - 1)
        stored_e = _pick_src(jnp.swapaxes(c_stored, 0, 1), own_e)
        ec_e = _pick_src(jnp.swapaxes(exec_c, 0, 1), own_e)
        body_ok = ec_e < stored_e
        # first body-stall of this step: ask everyone for my next-NEEDED
        # body — cumulative c_stored, NOT exec_c: adoption can jump
        # exec_c ahead of the local store, and relays are only
        # acceptable in cumulative order, draining the gap one body per
        # round trip
        blk = running & com & is_tok & ~body_ok
        first_blk = blk & (need_own < 0)
        need_own = jnp.where(first_blk, own_e, need_own)
        need_idx = jnp.where(first_blk, stored_e, need_idx)
        runnable = com & (~is_tok | body_ok)
        running = running & runnable
        wr = running & is_tok
        full_e = encode_cmd(own_e, ec_e)   # (owner, position) -> command
        bump = wr[:, None, :] & (ridx[None, :, None] == own_e[:, None, :])
        exec_c = exec_c + bump
        key_e = cmd_key(full_e, K)
        ohk = wr[:, None, :] & (kidx[None, :, None] == key_e[:, None, :])
        kv = jnp.where(ohk, full_e[:, None, :], kv)
        advanced = advanced + running
    new_execute = execute + advanced
    out_cneed = {
        "valid": jnp.broadcast_to((need_own >= 0)[:, None, :], (R, R, G)),
        "owner": jnp.broadcast_to(need_own[:, None, :], (R, R, G)),
        "cidx": jnp.broadcast_to(need_idx[:, None, :], (R, R, G)),
    }

    # ---------------- P3 out: newly committed + frontier retransmit -----
    low_new = jnp.argmin(jnp.where(newly, sidx[None, :, None], S), axis=1)
    any_new = jnp.any(newly, axis=1)
    span = jnp.maximum(new_execute - base, 1)
    rr = ctx.t % span
    p3_rel = jnp.where(any_new, low_new, rr).astype(jnp.int32)
    p3_rel = jnp.clip(p3_rel, 0, S - 1)
    oh_3 = sidx[None, :, None] == p3_rel[:, None, :]
    p3_committed = jnp.any(oh_3 & log_commit, axis=1)
    p3_cmd = jnp.sum(jnp.where(oh_3, log_cmd, 0), axis=1)
    p3_do = is_leader & p3_committed
    out_p3 = {
        "valid": jnp.broadcast_to(p3_do[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
        "slot": jnp.broadcast_to((base + p3_rel)[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(p3_cmd[:, None, :], (R, R, G)),
        "upto": jnp.broadcast_to(new_execute[:, None, :], (R, R, G)),
    }

    # ---------------- stuck-frontier retry (go-back-N) ------------------
    # A dropped P2a/P2b leaves its slot unproposable forever (P2a is
    # sent once); on a stall re-open EVERY uncommitted in-flight slot so
    # the proposer re-proposes one per step instead of one per timeout —
    # a deep uncommitted backlog under sustained drops drains in O(N)
    # steps, not O(N * retry_timeout)
    stalled = is_leader & (new_execute == execute) \
        & (next_slot > new_execute)
    stuck = jnp.where(stalled, state["stuck"] + 1, 0)
    retry = stuck >= cfg.retry_timeout
    ohr = (retry[:, None, :] & ~log_commit
           & (abs_ >= new_execute[:, None, :])
           & (abs_ < next_slot[:, None, :]))
    proposed = proposed & ~ohr
    stuck = jnp.where(retry, 0, stuck)

    # ---------------- election timer ------------------------------------
    heard = promote | acc_ok | (c_has & (c_bal >= ballot))
    k_jit = jr.fold_in(ctx.rng, 17)
    jitter = jr.randint(k_jit, ballot.shape, 0, cfg.backoff + 1)
    timer = jnp.where(heard | active,
                      cfg.election_timeout + jitter,
                      state["timer"] - 1)
    fire = ~active & (timer <= 0)
    new_bal = (jnp.max(ballot, axis=0)[None, :] // STRIDE + 1) * STRIDE \
        + ridx[:, None]
    ballot = jnp.where(fire, new_bal, ballot)
    p1_acks = jnp.where(fire, self_bit2, p1_acks)
    timer = jnp.where(fire, cfg.election_timeout + jitter, timer)
    out_p1a = {
        "valid": jnp.broadcast_to(fire[:, None, :], (R, R, G)),
        "bal": jnp.broadcast_to(ballot[:, None, :], (R, R, G)),
    }

    # ---------------- slide the O-ring window ---------------------------
    new_base = jnp.maximum(base, new_execute - RETAIN)
    adv = new_base - base
    log_bal = _shift(log_bal, adv, 0)
    log_cmd = _shift(log_cmd, adv, NO_CMD)
    log_commit = _shift(log_commit, adv, False)
    proposed = _shift(proposed, adv, False)
    log_acks = _shift(log_acks, adv, 0)

    new_state = dict(
        c_next=c_next, c_stored=c_stored, c_ack=c_ack, o_seen=o_seen,
        o_enq=o_enq, exec_c=exec_c,
        ballot=ballot, active=active, p1_acks=p1_acks, base=new_base,
        log_bal=log_bal, log_cmd=log_cmd, log_commit=log_commit,
        log_acks=log_acks, proposed=proposed, next_slot=next_slot,
        execute=new_execute, kv=kv, timer=timer, stuck=stuck,
    )
    outbox = {"ca": out_ca, "cack": out_cack, "oreq": out_oreq,
              "cneed": out_cneed, "cr": out_cr,
              "p1a": out_p1a, "p1b": out_p1b, "p2a": out_p2a,
              "p2b": out_p2b, "p3": out_p3}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        "committed_slots": jnp.sum(jnp.max(state["execute"], axis=0)),
        "min_execute": jnp.sum(jnp.min(state["execute"], axis=0)),
        "commands_proposed": jnp.sum(state["c_next"]),
        "has_sequencer": jnp.sum(jnp.any(state["active"], axis=0)
                                 .astype(jnp.int32)),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """The paxos O-log oracle (agreement / stability / ballot
    monotonicity / executed-prefix-committed) — token->command binding
    is a pure function of the agreed O-log, so O-log agreement IS
    execution-order agreement — plus monotone C-plane frontiers.
    (exec_c <= c_stored is NOT asserted: snapshot adoption legally
    jumps exec_c ahead of the local store until go-back-N heals it;
    live execution is body-gated regardless.)"""
    BIG = jnp.int32(2**30)
    S = cfg.n_slots
    sidx = jnp.arange(S, dtype=jnp.int32)
    base, c, cmd = new["base"], new["log_commit"], new["log_cmd"]

    align = jnp.max(base, axis=0)[None, :] - base
    a_c = _shift(c, align, False)
    a_cmd = _shift(cmd, align, NO_CMD)
    mx = jnp.max(jnp.where(a_c, a_cmd, -BIG), axis=0)
    mn = jnp.min(jnp.where(a_c, a_cmd, BIG), axis=0)
    n_c = jnp.sum(a_c, axis=0)
    v_agree = jnp.sum((n_c >= 1) & (mx != mn))

    adv = base - old["base"]
    o_c = _shift(old["log_commit"], adv, False)
    o_cmd = _shift(old["log_cmd"], adv, NO_CMD)
    v_stable = jnp.sum(o_c & (~c | (cmd != o_cmd)))
    v_stable = v_stable + jnp.sum(new["execute"] < base)

    v_bal = jnp.sum(new["ballot"] < old["ballot"])

    abs_ = base[:, None, :] + sidx[None, :, None]
    v_exec = jnp.sum((abs_ < new["execute"][:, None, :]) & ~c)

    v_cmono = jnp.sum(new["c_stored"] < old["c_stored"])
    v_cmono = v_cmono + jnp.sum(new["c_next"] < old["c_next"])
    v_cmono = v_cmono + jnp.sum(new["exec_c"] < old["exec_c"])

    return (v_agree + v_stable + v_bal + v_exec
            + v_cmono).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="sdpaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
