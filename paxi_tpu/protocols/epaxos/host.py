"""EPaxos replica for the host (deployment) runtime.

Reference: paxi epaxos/ [driver] — leaderless: the replica receiving a
command becomes its *command leader* in its own instance space
``(replica, instance)``; PreAccept computes conflict attributes
(seq, deps) which acceptors merge from their conflict maps; identical
replies from a fast quorum (ceil(3N/4)) commit on the fast path,
otherwise Accept (majority) fixes the merged attributes, then Commit;
execution topologically orders the committed dependency graph by
strongly-connected components (Tarjan, epaxos exec.go) with seq as the
tiebreak.  Deps use the standard max-interfering-instance-per-replica
vector form.

Recovery (epaxos Prepare/PrepareReply, explicit-prepare): a watchdog
scans for instances stuck uncommitted past ``recovery_timeout`` —
either blocking local execution as deps of committed instances, or
carrying an unanswered client request — and runs Prepare at a higher
ballot.  On a majority of PrepareReplies the recoverer finishes the
instance: seen-committed => re-Commit; seen-accepted => Accept the
highest-ballot attrs; seen-preaccepted => Accept the identical-attr
group only when it has >= floor(N/2) members excluding the owner's own
reply (the fast-quorum-intersection bound — any surviving fast-path
commit leaves that many identical non-owner replies in every prepare
majority); below that threshold the attrs may be missing interfering
commands committed on a disjoint slow-path quorum, so recovery
restarts phase 1 instead (re-PreAccept at the recovery ballot,
recomputing the dep union over a live majority) before Accepting;
seen-nowhere => commit a NOOP to unblock the hole.

Liveness fallback (slow path): the command leader schedules an Accept
round once a MAJORITY of PreAcceptReplies is in but the fast quorum
has not materialized within ``accept_fallback`` seconds — without it,
one dead replica out of N=3 (or two of N=5) wedges every command even
though a live majority exists.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.ballot import next_ballot
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import fast_quorum_size, majority_size
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

NONE, PREACCEPTED, ACCEPTED, COMMITTED, EXECUTED = 0, 1, 2, 3, 4

NOOP_KEY = -1


@register_message
@dataclass
class PreAccept:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0
    ballot: int = 0       # >0 when a recoverer restarts phase 1
    src: str = ""         # who runs the round (defaults to owner)


@register_message
@dataclass
class PreAcceptReply:
    owner: str
    inst: int
    seq: int
    deps: Dict[str, int]
    id: str
    ballot: int = 0       # echoes the round's ballot (0 = owner's)


@register_message
@dataclass
class Accept:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0
    ballot: int = 0       # >0 when a recoverer drives the round
    src: str = ""         # who runs the round (defaults to owner)


@register_message
@dataclass
class AcceptReply:
    owner: str
    inst: int
    id: str
    ballot: int = 0


@register_message
@dataclass
class Prepare:
    """Recovery phase-1: claim instance (owner, inst) at a new ballot."""

    owner: str
    inst: int
    ballot: int
    src: str


@register_message
@dataclass
class PrepareReply:
    owner: str
    inst: int
    ballot: int           # the replier's promised ballot after this msg
    status: int           # NONE/PREACCEPTED/ACCEPTED/COMMITTED/EXECUTED
    accepted_ballot: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    id: str
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class Commit:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0


@dataclass
class Instance:
    command: Command
    seq: int
    deps: Dict[ID, int]
    status: int = PREACCEPTED
    request: Optional[Request] = None
    # leader-side tallies: distinct acker sets, so retransmit-induced
    # duplicate replies can never fake a quorum
    acked: set = field(default_factory=set)
    accept_acked: set = field(default_factory=set)
    changed: bool = False
    # recovery state
    ballot: int = 0            # promised ballot (0 = owner's implicit)
    accepted_ballot: int = 0   # ballot the current attrs were taken at
    born: float = field(default_factory=time.monotonic)
    fallback_armed: bool = False


@dataclass
class _Recovery:
    """Recoverer-side tally for one Prepare round over (owner, inst)."""

    ballot: int
    replies: Dict[ID, PrepareReply] = field(default_factory=dict)
    phase: int = 1             # 1 = prepare, 3 = re-preaccept, 2 = accept
    # distinct acker sets, so retransmit-induced duplicate replies can
    # never fake a quorum (same rationale as Instance.accept_acked)
    accept_acked: set = field(default_factory=set)
    decided: bool = False
    born: float = field(default_factory=time.monotonic)
    # re-preaccept (restarted phase 1) attribute union
    cmd: Optional[Command] = None
    seq: int = 0
    deps: Dict[ID, int] = field(default_factory=dict)
    pre_acked: set = field(default_factory=set)


class EPaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.insts: Dict[ID, Dict[int, Instance]] = {i: {} for i in cfg.ids}
        self.next_inst = 0
        # conflict map: key -> owner -> latest interfering instance
        self.conflicts: Dict[int, Dict[ID, int]] = {}
        self.n = cfg.n
        self.fast = fast_quorum_size(cfg.n)
        self.maj = majority_size(cfg.n)
        self.fast_commits = 0
        self.slow_commits = 0
        self.recoveries: Dict[Tuple[ID, int], _Recovery] = {}
        # every instance not yet EXECUTED: the watchdog and the executor
        # walk this set instead of the full (ever-growing) instance log
        self._live: set = set()
        self.recovery_timeout = 0.5    # uncommitted-instance age trigger
        self.recovery_interval = 0.15  # watchdog period
        self.accept_fallback = 0.15    # majority-but-no-fast-quorum timer
        self.register(Request, self.handle_request)
        self.register(PreAccept, self.handle_preaccept)
        self.register(PreAcceptReply, self.handle_preaccept_reply)
        self.register(Accept, self.handle_accept)
        self.register(AcceptReply, self.handle_accept_reply)
        self.register(Commit, self.handle_commit)
        self.register(Prepare, self.handle_prepare)
        self.register(PrepareReply, self.handle_prepare_reply)

    async def start(self) -> None:
        await super().start()
        self._tasks.append(asyncio.create_task(self._recovery_watchdog()))

    # ---- attribute computation (exec.go conflict map) -------------------
    def _attrs(self, key: int, excl: Tuple[ID, int]) -> Tuple[int, Dict[ID, int]]:
        deps: Dict[ID, int] = {}
        seq = 0
        for owner, j in self.conflicts.get(key, {}).items():
            if (owner, j) == excl:
                j -= 1
                if j < 0:
                    continue
            deps[owner] = j
            e = self.insts[owner].get(j)
            if e is not None:
                seq = max(seq, e.seq)
        return seq + 1, deps

    def _record(self, owner: ID, inst: int, e: Instance) -> None:
        self.insts[owner][inst] = e
        if e.status < EXECUTED:
            self._live.add((owner, inst))
        k = e.command.key
        if k == NOOP_KEY:
            return                 # NOOPs never interfere
        cur = self.conflicts.setdefault(k, {})
        cur[owner] = max(cur.get(owner, -1), inst)

    # ---- command leader path --------------------------------------------
    def handle_request(self, req: Request) -> None:
        inst = self.next_inst
        self.next_inst += 1
        cmd = req.command
        seq, deps = self._attrs(cmd.key, (self.id, inst))
        e = Instance(cmd, seq, dict(deps), request=req)
        e.acked.add(self.id)
        self._record(self.id, inst, e)
        self.socket.broadcast(PreAccept(
            str(self.id), inst, cmd.key, cmd.value, seq,
            {str(k): v for k, v in deps.items()},
            cmd.client_id, cmd.command_id))
        self._leader_check(inst, e)   # single-node cluster commits at once

    def handle_preaccept(self, m: PreAccept) -> None:
        owner = ID(m.owner)
        cmd = Command(m.key, m.value, m.client_id, m.command_id)
        mseq, mdeps = self._attrs(m.key, (owner, m.inst))
        seq = max(m.seq, mseq)
        deps = {ID(k): v for k, v in m.deps.items()}
        for k, v in mdeps.items():
            deps[k] = max(deps.get(k, -1), v)
        prev = self.insts[owner].get(m.inst)
        if prev is not None and m.ballot < prev.ballot:
            return    # promised a higher-ballot recoverer; sender is stale
        if prev is None or prev.status < ACCEPTED:
            self._record(owner, m.inst,
                         Instance(cmd, seq, dict(deps), ballot=m.ballot,
                                  request=prev.request if prev else None))
        self.socket.send(ID(m.src) if m.src else owner, PreAcceptReply(
            m.owner, m.inst, seq, {str(k): v for k, v in deps.items()},
            str(self.id), m.ballot))

    def handle_preaccept_reply(self, m: PreAcceptReply) -> None:
        if m.ballot > 0:
            # reply to a recoverer's restarted phase 1 (see _repreaccept)
            owner = ID(m.owner)
            r = self.recoveries.get((owner, m.inst))
            if r is not None and r.phase == 3 and m.ballot == r.ballot:
                self._recovery_preaccept_ack(owner, m.inst, r, m)
            return
        e = self.insts[self.id].get(m.inst)
        if e is None or e.status != PREACCEPTED or e.request is None:
            return
        e.acked.add(ID(m.id))
        deps = {ID(k): v for k, v in m.deps.items()}
        if m.seq != e.seq or deps != e.deps:
            e.changed = True
            e.seq = max(e.seq, m.seq)
            for k, v in deps.items():
                e.deps[k] = max(e.deps.get(k, -1), v)
        self._leader_check(m.inst, e)

    def _leader_check(self, inst: int, e: Instance) -> None:
        if e.ballot > 0:
            return   # a recoverer claimed this instance; stop driving it
        if len(e.acked) >= self.fast and not e.changed:
            self.fast_commits += 1
            self._commit(self.id, inst, e)
        elif len(e.acked) >= self.fast and e.changed:
            self._run_accept(inst, e)
        elif len(e.acked) >= self.maj and not e.fallback_armed:
            # fast quorum may never materialize (dead replicas); after a
            # grace period run the always-safe slow path on the majority
            e.fallback_armed = True
            asyncio.get_running_loop().call_later(
                self.accept_fallback, self._fallback_accept, inst)

    def _fallback_accept(self, inst: int) -> None:
        e = self.insts[self.id].get(inst)
        if (e is not None and e.status == PREACCEPTED and e.ballot == 0
                and e.request is not None and len(e.acked) >= self.maj):
            self._run_accept(inst, e)

    def _run_accept(self, inst: int, e: Instance) -> None:
        e.status = ACCEPTED
        e.accepted_ballot = e.ballot
        e.accept_acked = {self.id}
        c = e.command
        self.socket.broadcast(Accept(
            str(self.id), inst, c.key, c.value, e.seq,
            {str(k): v for k, v in e.deps.items()},
            c.client_id, c.command_id, e.ballot, str(self.id)))
        if len(e.accept_acked) >= self.maj:
            self.slow_commits += 1
            self._commit(self.id, inst, e)

    def handle_accept(self, m: Accept) -> None:
        owner = ID(m.owner)
        cmd = Command(m.key, m.value, m.client_id, m.command_id)
        prev = self.insts[owner].get(m.inst)
        if prev is not None and m.ballot < prev.ballot:
            return        # promised a higher-ballot recoverer
        e = Instance(cmd, m.seq, {ID(k): v for k, v in m.deps.items()},
                     status=ACCEPTED,
                     request=prev.request if prev else None,
                     ballot=m.ballot, accepted_ballot=m.ballot)
        if prev is None or prev.status < COMMITTED:
            self._record(owner, m.inst, e)
        self.socket.send(ID(m.src) if m.src else owner,
                         AcceptReply(m.owner, m.inst, str(self.id),
                                     m.ballot))

    def handle_accept_reply(self, m: AcceptReply) -> None:
        owner = ID(m.owner)
        r = self.recoveries.get((owner, m.inst))
        if r is not None and r.phase == 2 and m.ballot == r.ballot:
            self._recovery_accept_ack(owner, m.inst, r, ID(m.id))
            return
        if owner != self.id:
            return
        e = self.insts[self.id].get(m.inst)
        if (e is None or e.status != ACCEPTED or e.request is None
                or m.ballot != e.ballot):
            return   # ballot mismatch: a recoverer superseded this round
        e.accept_acked.add(ID(m.id))
        if len(e.accept_acked) >= self.maj:
            self.slow_commits += 1
            self._commit(self.id, m.inst, e)

    def _commit(self, owner: ID, inst: int, e: Instance) -> None:
        e.status = COMMITTED
        c = e.command
        self.socket.broadcast(Commit(
            str(owner), inst, c.key, c.value, e.seq,
            {str(k): v for k, v in e.deps.items()},
            c.client_id, c.command_id))
        self._execute()

    # ---- recovery (epaxos Prepare/PrepareReply, explicit prepare) -------
    async def _recovery_watchdog(self) -> None:
        while True:
            await asyncio.sleep(self.recovery_interval)
            try:
                # GC recovery records whose instance committed via a
                # competing recoverer or the returning owner; also
                # expire stalled rounds (lost Prepare/Accept broadcast)
                # so the stuck-scan can retry them at a higher ballot
                now = time.monotonic()
                for (o, i) in list(self.recoveries):
                    e = self.insts[o].get(i)
                    r = self.recoveries[(o, i)]
                    if e is not None and e.status >= COMMITTED:
                        del self.recoveries[(o, i)]
                    elif now - r.born > 2 * self.recovery_timeout:
                        del self.recoveries[(o, i)]
                for owner, inst in self._stuck_instances():
                    self.recover(owner, inst)
            except Exception:     # never kill the watchdog
                from paxi_tpu.utils import log
                import traceback
                log.errorf("%s: recovery watchdog: %s", self.id,
                           traceback.format_exc())

    def _stuck_instances(self) -> List[Tuple[ID, int]]:
        """Instances needing takeover, from the _live set only:
        uncommitted past the timeout and either blocking execution as a
        direct dep of a committed instance, or known locally on a
        peer's row.  Dep holes (instances we have never seen) get a
        placeholder so the same age gate applies to them."""
        now = time.monotonic()
        stuck: List[Tuple[ID, int]] = []
        holes: List[Tuple[ID, int]] = []
        for (owner, i) in self._live:
            e = self.insts[owner].get(i)
            if e is None or e.status >= EXECUTED:
                continue
            if e.status == COMMITTED:
                for p, j in e.deps.items():
                    if j < 0:
                        continue
                    d = self.insts[p].get(j)
                    if d is None:
                        holes.append((p, j))
                    elif (d.status < COMMITTED
                            and now - d.born > self.recovery_timeout
                            and (p, j) not in self.recoveries):
                        stuck.append((p, j))
            elif (owner != self.id
                    and now - e.born > self.recovery_timeout
                    and (owner, i) not in self.recoveries):
                stuck.append((owner, i))
            elif (owner == self.id and e.ballot == 0
                    and e.request is not None
                    and now - e.born > self.recovery_timeout):
                # own stalled round (lost PreAccepts/Accepts and below
                # the fallback's majority): retransmit; dedup by the
                # distinct-acker sets, so this can never fake a quorum
                self._retransmit(i, e)
        for (p, j) in holes:
            # first sighting: start the age clock, recover next rounds
            ph = Instance(Command(NOOP_KEY, b""), 0, {}, status=NONE)
            self.insts[p][j] = ph
            self._live.add((p, j))
        return stuck

    def _retransmit(self, inst: int, e: Instance) -> None:
        e.born = time.monotonic()
        c = e.command
        if e.status == PREACCEPTED:
            self.socket.broadcast(PreAccept(
                str(self.id), inst, c.key, c.value, e.seq,
                {str(k): v for k, v in e.deps.items()},
                c.client_id, c.command_id))
        elif e.status == ACCEPTED:
            self.socket.broadcast(Accept(
                str(self.id), inst, c.key, c.value, e.seq,
                {str(k): v for k, v in e.deps.items()},
                c.client_id, c.command_id, e.ballot, str(self.id)))

    def recover(self, owner: ID, inst: int) -> None:
        """Take over (owner, inst) at a ballot above anything seen."""
        if (owner, inst) in self.recoveries:
            return
        e = self.insts[owner].get(inst)
        if e is not None and e.status >= COMMITTED:
            return
        if e is None:
            e = Instance(Command(NOOP_KEY, b""), 0, {}, status=NONE)
            self.insts[owner][inst] = e
            self._live.add((owner, inst))
        b = next_ballot(max(e.ballot, e.accepted_ballot), self.id)
        r = _Recovery(ballot=b)
        self.recoveries[(owner, inst)] = r
        e.ballot = b
        r.replies[self.id] = PrepareReply(
            str(owner), inst, b, e.status, e.accepted_ballot,
            e.command.key, e.command.value, e.seq,
            {str(k): v for k, v in e.deps.items()}, str(self.id),
            e.command.client_id, e.command.command_id)
        self.socket.broadcast(Prepare(str(owner), inst, b, str(self.id)))
        self._recovery_decide(owner, inst, r)

    def handle_prepare(self, m: Prepare) -> None:
        owner = ID(m.owner)
        e = self.insts[owner].get(m.inst)
        if e is None:
            e = Instance(Command(NOOP_KEY, b""), 0, {}, status=NONE)
            self.insts[owner][m.inst] = e
            self._live.add((owner, m.inst))
        if m.ballot > e.ballot:
            e.ballot = m.ballot
        self.socket.send(ID(m.src), PrepareReply(
            m.owner, m.inst, e.ballot, e.status, e.accepted_ballot,
            e.command.key, e.command.value, e.seq,
            {str(k): v for k, v in e.deps.items()}, str(self.id),
            e.command.client_id, e.command.command_id))

    def handle_prepare_reply(self, m: PrepareReply) -> None:
        owner = ID(m.owner)
        r = self.recoveries.get((owner, m.inst))
        if r is None or r.decided:
            return
        if m.ballot > r.ballot:
            # a higher-ballot recoverer owns this instance now; back off
            # (the watchdog re-triggers if it dies too)
            del self.recoveries[(owner, m.inst)]
            e = self.insts[owner].get(m.inst)
            if e is not None:
                e.ballot = max(e.ballot, m.ballot)
                e.born = time.monotonic()
            return
        if m.ballot < r.ballot:
            return   # stale reply from an older prepare round of ours
        r.replies[ID(m.id)] = m
        self._recovery_decide(owner, m.inst, r)

    def _recovery_decide(self, owner: ID, inst: int, r: _Recovery) -> None:
        if r.decided or len(r.replies) < self.maj:
            return
        replies = list(r.replies.values())
        committed = [p for p in replies if p.status >= COMMITTED]
        accepted = [p for p in replies if p.status == ACCEPTED]
        preaccepted = [p for p in replies if p.status == PREACCEPTED]
        r.decided = True
        if committed:
            p = committed[0]
            self._finish_recovery(owner, inst, r, p, commit=True)
        elif accepted:
            p = max(accepted, key=lambda p: p.accepted_ballot)
            self._finish_recovery(owner, inst, r, p, commit=False)
        elif preaccepted:
            # A surviving fast-path commit implies >= floor(N/2)
            # identical replies from acceptors OTHER than the owner in
            # any prepare majority (fast-quorum intersection).  Only
            # that condition licenses jumping straight to Accept; a
            # bare plurality — e.g. the owner's initial attrs echoed by
            # one acceptor — says nothing about dependency completeness
            # (an interfering command may have committed on a disjoint
            # slow-path quorum that never saw this one).  Below the
            # threshold, restart phase 1 at the recovery ballot to
            # recompute the dep union from live conflict maps.
            groups: Dict[tuple, List[PrepareReply]] = {}
            for p in preaccepted:
                sig = (p.seq, tuple(sorted(p.deps.items())), p.key, p.value)
                groups.setdefault(sig, []).append(p)

            def support(g: List[PrepareReply]) -> int:
                return sum(1 for p in g if ID(p.id) != owner)

            best = max(groups.values(), key=support)
            if support(best) >= self.n // 2:
                self._finish_recovery(owner, inst, r, best[0], commit=False)
            else:
                self._repreaccept(owner, inst, r, best[0])
        else:
            # nobody saw the command: commit a NOOP to unblock the hole
            noop = PrepareReply(str(owner), inst, r.ballot, NONE, 0,
                                NOOP_KEY, b"", 0, {}, str(self.id))
            self._finish_recovery(owner, inst, r, noop, commit=True)

    def _finish_recovery(self, owner: ID, inst: int, r: _Recovery,
                         p: PrepareReply, commit: bool) -> None:
        cmd = Command(p.key, p.value, p.client_id, p.command_id)
        deps = {ID(k): v for k, v in p.deps.items()}
        prev = self.insts[owner].get(inst)
        e = Instance(cmd, p.seq, dict(deps),
                     request=prev.request if prev else None,
                     ballot=r.ballot, accepted_ballot=r.ballot)
        if commit:
            e.status = COMMITTED
            self._record(owner, inst, e)   # NOOPs skip the conflict map
            del self.recoveries[(owner, inst)]
            self._commit(owner, inst, e)
        else:
            e.status = ACCEPTED
            self._record(owner, inst, e)
            r.phase = 2
            r.accept_acked = {self.id}
            self.socket.broadcast(Accept(
                str(owner), inst, cmd.key, cmd.value, e.seq,
                {str(k): v for k, v in e.deps.items()},
                cmd.client_id, cmd.command_id, r.ballot, str(self.id)))
            self._recovery_accept_ack(owner, inst, r, None)

    def _repreaccept(self, owner: ID, inst: int, r: _Recovery,
                     p: PrepareReply) -> None:
        """Restarted phase 1 (epaxos explicit-prepare's TryPreAccept
        analog): re-PreAccept the command at the recovery ballot,
        recomputing seq/deps as the union over a majority of acceptors'
        live conflict maps, then Accept — never the fast path."""
        cmd = Command(p.key, p.value, p.client_id, p.command_id)
        r.phase = 3
        r.cmd = cmd
        mseq, mdeps = self._attrs(cmd.key, (owner, inst))
        r.seq = max(p.seq, mseq)
        r.deps = {ID(k): v for k, v in p.deps.items()}
        for k, v in mdeps.items():
            r.deps[k] = max(r.deps.get(k, -1), v)
        r.pre_acked = {self.id}
        prev = self.insts[owner].get(inst)
        self._record(owner, inst, Instance(
            cmd, r.seq, dict(r.deps),
            request=prev.request if prev else None, ballot=r.ballot))
        self.socket.broadcast(PreAccept(
            str(owner), inst, cmd.key, cmd.value, r.seq,
            {str(k): v for k, v in r.deps.items()},
            cmd.client_id, cmd.command_id, r.ballot, str(self.id)))
        self._recovery_preaccept_ack(owner, inst, r, None)

    def _recovery_preaccept_ack(self, owner: ID, inst: int, r: _Recovery,
                                m: Optional[PreAcceptReply]) -> None:
        if m is not None:
            r.pre_acked.add(ID(m.id))
            r.seq = max(r.seq, m.seq)
            for k, v in m.deps.items():
                kid = ID(k)
                r.deps[kid] = max(r.deps.get(kid, -1), v)
        if len(r.pre_acked) < self.maj:
            return
        merged = PrepareReply(
            str(owner), inst, r.ballot, PREACCEPTED, r.ballot,
            r.cmd.key, r.cmd.value, r.seq,
            {str(k): v for k, v in r.deps.items()}, str(self.id),
            r.cmd.client_id, r.cmd.command_id)
        self._finish_recovery(owner, inst, r, merged, commit=False)

    def _recovery_accept_ack(self, owner: ID, inst: int, r: _Recovery,
                             acker: Optional[ID]) -> None:
        if acker is not None:
            r.accept_acked.add(acker)
        if len(r.accept_acked) >= self.maj:
            e = self.insts[owner].get(inst)
            if e is None or e.status >= COMMITTED:
                self.recoveries.pop((owner, inst), None)
                return
            self.slow_commits += 1
            self.recoveries.pop((owner, inst), None)
            self._commit(owner, inst, e)

    def handle_commit(self, m: Commit) -> None:
        owner = ID(m.owner)
        prev = self.insts[owner].get(m.inst)
        if prev is not None and prev.status >= COMMITTED:
            return   # recovery re-Commits must not re-execute
        e = Instance(Command(m.key, m.value, m.client_id, m.command_id),
                     m.seq, {ID(k): v for k, v in m.deps.items()},
                     status=COMMITTED,
                     request=prev.request if prev else None)
        self._record(owner, m.inst, e)
        self._execute()

    # ---- execution (exec.go: Tarjan SCC + seq order) --------------------
    def _execute(self) -> None:
        """Execute every committed instance whose transitive dependency
        closure is committed, SCC-by-SCC in reverse topological order,
        within an SCC by (seq, owner)."""
        index: Dict[Tuple[ID, int], int] = {}
        low: Dict[Tuple[ID, int], int] = {}
        on_stack: Dict[Tuple[ID, int], bool] = {}
        stack: List[Tuple[ID, int]] = []
        counter = [0]
        blocked: Dict[Tuple[ID, int], bool] = {}

        def node(u: Tuple[ID, int]) -> Optional[Instance]:
            return self.insts[u[0]].get(u[1])

        def strongconnect(u: Tuple[ID, int]) -> None:
            # iterative Tarjan (explicit stack) to survive deep chains
            work = [(u, iter(self._neighbors(u)))]
            index[u] = low[u] = counter[0]
            counter[0] += 1
            stack.append(u)
            on_stack[u] = True
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    nw = node(w)
                    if nw is None or nw.status < COMMITTED:
                        blocked[v] = True   # uncommitted dep: defer
                        continue
                    if nw.status >= EXECUTED:
                        continue
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack[w] = True
                        work.append((w, iter(self._neighbors(w))))
                        advanced = True
                        break
                    elif on_stack.get(w):
                        low[v] = min(low[v], index[w])
                    else:
                        # cross-edge into a component already finished
                        # THIS pass: if it was deferred (blocked on an
                        # uncommitted dep), so is everything that
                        # depends on it — without this, a read could
                        # execute ahead of its deferred dependency and
                        # return a stale value (observed under fault
                        # injection: soak_host.py, epaxos, 718
                        # anomalies)
                        blocked[v] = blocked.get(v, False) \
                            or blocked.get(w, False)
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
                    blocked[parent] = blocked.get(parent) or blocked.get(v, False)
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        comp.append(w)
                        if w == v:
                            break
                    if not any(blocked.get(w, False) for w in comp):
                        comp.sort(key=lambda w: (node(w).seq, str(w[0]), w[1]))
                        for w in comp:
                            self._apply(w, node(w))
                    else:
                        for w in comp:
                            blocked[w] = True

        roots = sorted((w for w in self._live
                        if (n := node(w)) is not None
                        and n.status == COMMITTED),
                       key=lambda w: (str(w[0]), w[1]))
        for w in roots:
            if w not in index:
                strongconnect(w)

    def _neighbors(self, u: Tuple[ID, int]) -> List[Tuple[ID, int]]:
        e = self.insts[u[0]].get(u[1])
        if e is None:
            return []
        return [(p, j) for p, j in e.deps.items() if j >= 0]

    def _apply(self, w: Tuple[ID, int], e: Instance) -> None:
        if e.status >= EXECUTED:
            return
        e.status = EXECUTED
        self._live.discard(w)
        if e.command.key == NOOP_KEY:
            if e.request is not None:
                e.request.reply(Reply(e.command, err="noop"))
                e.request = None
            return
        value = self.db.execute(e.command)
        if e.request is not None:
            e.request.reply(Reply(e.command, value=value))
            e.request = None


def new_replica(id: ID, cfg: Config) -> EPaxosReplica:
    return EPaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  The sim splits recovery onto separate
# ballot-carrying planes (racc/raccr/rcmt) so an owner and a recoverer
# broadcasting in the same step never collide on a wheel edge; on the
# host both paths travel the SAME wire classes (Accept/AcceptReply/
# Commit carry the ballot), so the recovery planes fold back onto them.
# The ``gc`` executed-frontier gossip is kernel-internal window flow
# control with no host wire analog (the host's unbounded dict log never
# recycles) — baselined in analysis/baseline.toml.
TRACE_MSG_MAP = {
    "pa": "PreAccept", "par": "PreAcceptReply",
    "acc": "Accept", "accr": "AcceptReply", "cmt": "Commit",
    "prep": "Prepare", "prepr": "PrepareReply",
    "racc": "Accept", "raccr": "AcceptReply", "rcmt": "Commit",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal.
SIM_STATE_MAP = {
    # instance ring SoA <-> Instance aggregates in self.insts
    "cmd":       "insts",
    "seq":       "insts",
    "deps":      "insts",
    "status":    "insts",
    "executed":  "status",           # EXECUTED is a status on the host
    "bal":       "ballot",           # promised ballot per cell
    "abal":      "accepted_ballot",
    "age":       "born",             # frontier-block steps <-> wall-clock age
    # command-leader driving state
    "cur":       "next_inst",
    "pa_acks":   "acked",            # PreAccept ack bitmask <-> set
    "ac_acks":   "accept_acked",
    "agree":     "changed",          # fast-path attr agreement (inverse)
    "seq0":      "seq",              # original vs merged attrs: the host
    "deps0":     "deps",             # folds both into the Instance
    "mseq":      "seq",
    "mdeps":     "deps",
    # recovery driving state <-> Recovery entries
    "rphase":    "recoveries",
    "rowner":    "owner",
    "rinst":     "inst",
    "rballot":   "ballot",
    "racks":     "replies",          # prepare-round replies
    "rstat":     "replies",          # per-replier recorded state
    "rcmd":      "replies",
    "rseq2":     "replies",
    "rabal":     "replies",
    "rdeps2":    "replies",
    "rcseq":     "replies",
    "rcdeps":    "replies",
    "rdcmd":     "recoveries",       # decided attrs driven via Accept
    "rdseq":     "recoveries",
    "rddeps":    "recoveries",
    "aacks":     "accept_acked",
    "base":      "",  # instance ring window: host insts dicts are unbounded
    "stuck":     "",  # leader retry ticks: host fallback timer is wall-clock
    "rstuck":    "",  # recovery retry ticks (kernel-only)
    "recovered": "",  # completed-recovery counter (metrics)
    "gfront":    "",  # GC gossip frontier: the host log never recycles
                      # (see the PXT302 `gc` baseline entry)
    "ccount":    "",  # commit counter (metrics)
    "xcount":    "",  # execution counter (metrics)
    "kcount":    "",  # per-key execution oracle (invariant bookkeeping)
    "khash":     "",
    # on-device observability (PR 11) — measurement planes, excluded
    # from the trace witness hash; the host twins are the registry's
    # live latency histograms and the post-hoc linearizability checker
    "m_prop_t":      "",
    "m_commit_dt":   "",   # pending deltas for the deferred flush
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
}
