"""EPaxos replica for the host (deployment) runtime.

Reference: paxi epaxos/ [driver] — leaderless: the replica receiving a
command becomes its *command leader* in its own instance space
``(replica, instance)``; PreAccept computes conflict attributes
(seq, deps) which acceptors merge from their conflict maps; identical
replies from a fast quorum (ceil(3N/4)) commit on the fast path,
otherwise Accept (majority) fixes the merged attributes, then Commit;
execution topologically orders the committed dependency graph by
strongly-connected components (Tarjan, epaxos exec.go) with seq as the
tiebreak.  Deps use the standard max-interfering-instance-per-replica
vector form.

Like the reference's normal-case code this replica does not implement
the Prepare/recovery path (paxi's epaxos recovery is likewise partial);
the TPU sim kernel (sim.py) fuzzes the same normal-case protocol.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import fast_quorum_size, majority_size
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node

NONE, PREACCEPTED, ACCEPTED, COMMITTED, EXECUTED = 0, 1, 2, 3, 4


@register_message
@dataclass
class PreAccept:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class PreAcceptReply:
    owner: str
    inst: int
    seq: int
    deps: Dict[str, int]
    id: str


@register_message
@dataclass
class Accept:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0


@register_message
@dataclass
class AcceptReply:
    owner: str
    inst: int
    id: str


@register_message
@dataclass
class Commit:
    owner: str
    inst: int
    key: int
    value: bytes
    seq: int
    deps: Dict[str, int]
    client_id: str = ""
    command_id: int = 0


@dataclass
class Instance:
    command: Command
    seq: int
    deps: Dict[ID, int]
    status: int = PREACCEPTED
    request: Optional[Request] = None
    # leader-side tallies
    replies: int = 1
    accept_replies: int = 1
    changed: bool = False


class EPaxosReplica(Node):
    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        self.insts: Dict[ID, Dict[int, Instance]] = {i: {} for i in cfg.ids}
        self.next_inst = 0
        # conflict map: key -> owner -> latest interfering instance
        self.conflicts: Dict[int, Dict[ID, int]] = {}
        self.fast = fast_quorum_size(cfg.n)
        self.maj = majority_size(cfg.n)
        self.fast_commits = 0
        self.slow_commits = 0
        self.register(Request, self.handle_request)
        self.register(PreAccept, self.handle_preaccept)
        self.register(PreAcceptReply, self.handle_preaccept_reply)
        self.register(Accept, self.handle_accept)
        self.register(AcceptReply, self.handle_accept_reply)
        self.register(Commit, self.handle_commit)

    # ---- attribute computation (exec.go conflict map) -------------------
    def _attrs(self, key: int, excl: Tuple[ID, int]) -> Tuple[int, Dict[ID, int]]:
        deps: Dict[ID, int] = {}
        seq = 0
        for owner, j in self.conflicts.get(key, {}).items():
            if (owner, j) == excl:
                j -= 1
                if j < 0:
                    continue
            deps[owner] = j
            e = self.insts[owner].get(j)
            if e is not None:
                seq = max(seq, e.seq)
        return seq + 1, deps

    def _record(self, owner: ID, inst: int, e: Instance) -> None:
        self.insts[owner][inst] = e
        k = e.command.key
        cur = self.conflicts.setdefault(k, {})
        cur[owner] = max(cur.get(owner, -1), inst)

    # ---- command leader path --------------------------------------------
    def handle_request(self, req: Request) -> None:
        inst = self.next_inst
        self.next_inst += 1
        cmd = req.command
        seq, deps = self._attrs(cmd.key, (self.id, inst))
        e = Instance(cmd, seq, dict(deps), request=req)
        self._record(self.id, inst, e)
        self.socket.broadcast(PreAccept(
            str(self.id), inst, cmd.key, cmd.value, seq,
            {str(k): v for k, v in deps.items()},
            cmd.client_id, cmd.command_id))
        self._leader_check(inst, e)   # single-node cluster commits at once

    def handle_preaccept(self, m: PreAccept) -> None:
        owner = ID(m.owner)
        cmd = Command(m.key, m.value, m.client_id, m.command_id)
        mseq, mdeps = self._attrs(m.key, (owner, m.inst))
        seq = max(m.seq, mseq)
        deps = {ID(k): v for k, v in m.deps.items()}
        for k, v in mdeps.items():
            deps[k] = max(deps.get(k, -1), v)
        prev = self.insts[owner].get(m.inst)
        if prev is None or prev.status < ACCEPTED:
            self._record(owner, m.inst, Instance(cmd, seq, dict(deps)))
        self.socket.send(owner, PreAcceptReply(
            m.owner, m.inst, seq, {str(k): v for k, v in deps.items()},
            str(self.id)))

    def handle_preaccept_reply(self, m: PreAcceptReply) -> None:
        e = self.insts[self.id].get(m.inst)
        if e is None or e.status != PREACCEPTED or e.request is None:
            return
        e.replies += 1
        deps = {ID(k): v for k, v in m.deps.items()}
        if m.seq != e.seq or deps != e.deps:
            e.changed = True
            e.seq = max(e.seq, m.seq)
            for k, v in deps.items():
                e.deps[k] = max(e.deps.get(k, -1), v)
        self._leader_check(m.inst, e)

    def _leader_check(self, inst: int, e: Instance) -> None:
        if e.replies >= self.fast and not e.changed:
            self.fast_commits += 1
            self._commit(inst, e)
        elif e.replies >= self.fast and e.changed:
            self._run_accept(inst, e)

    def _run_accept(self, inst: int, e: Instance) -> None:
        e.status = ACCEPTED
        e.accept_replies = 1
        c = e.command
        self.socket.broadcast(Accept(
            str(self.id), inst, c.key, c.value, e.seq,
            {str(k): v for k, v in e.deps.items()},
            c.client_id, c.command_id))
        if e.accept_replies >= self.maj:
            self.slow_commits += 1
            self._commit(inst, e)

    def handle_accept(self, m: Accept) -> None:
        owner = ID(m.owner)
        cmd = Command(m.key, m.value, m.client_id, m.command_id)
        prev = self.insts[owner].get(m.inst)
        e = Instance(cmd, m.seq, {ID(k): v for k, v in m.deps.items()},
                     status=ACCEPTED,
                     request=prev.request if prev else None)
        if prev is None or prev.status < COMMITTED:
            self._record(owner, m.inst, e)
        self.socket.send(owner, AcceptReply(m.owner, m.inst, str(self.id)))

    def handle_accept_reply(self, m: AcceptReply) -> None:
        e = self.insts[self.id].get(m.inst)
        if e is None or e.status != ACCEPTED or e.request is None:
            return
        e.accept_replies += 1
        if e.accept_replies >= self.maj:
            self.slow_commits += 1
            self._commit(m.inst, e)

    def _commit(self, inst: int, e: Instance) -> None:
        e.status = COMMITTED
        c = e.command
        self.socket.broadcast(Commit(
            str(self.id), inst, c.key, c.value, e.seq,
            {str(k): v for k, v in e.deps.items()},
            c.client_id, c.command_id))
        self._execute()

    def handle_commit(self, m: Commit) -> None:
        owner = ID(m.owner)
        prev = self.insts[owner].get(m.inst)
        e = Instance(Command(m.key, m.value, m.client_id, m.command_id),
                     m.seq, {ID(k): v for k, v in m.deps.items()},
                     status=COMMITTED,
                     request=prev.request if prev else None)
        self._record(owner, m.inst, e)
        self._execute()

    # ---- execution (exec.go: Tarjan SCC + seq order) --------------------
    def _execute(self) -> None:
        """Execute every committed instance whose transitive dependency
        closure is committed, SCC-by-SCC in reverse topological order,
        within an SCC by (seq, owner)."""
        index: Dict[Tuple[ID, int], int] = {}
        low: Dict[Tuple[ID, int], int] = {}
        on_stack: Dict[Tuple[ID, int], bool] = {}
        stack: List[Tuple[ID, int]] = []
        counter = [0]
        blocked: Dict[Tuple[ID, int], bool] = {}

        def node(u: Tuple[ID, int]) -> Optional[Instance]:
            return self.insts[u[0]].get(u[1])

        def strongconnect(u: Tuple[ID, int]) -> None:
            # iterative Tarjan (explicit stack) to survive deep chains
            work = [(u, iter(self._neighbors(u)))]
            index[u] = low[u] = counter[0]
            counter[0] += 1
            stack.append(u)
            on_stack[u] = True
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    nw = node(w)
                    if nw is None or nw.status < COMMITTED:
                        blocked[v] = True   # uncommitted dep: defer
                        continue
                    if nw.status >= EXECUTED:
                        continue
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack[w] = True
                        work.append((w, iter(self._neighbors(w))))
                        advanced = True
                        break
                    elif on_stack.get(w):
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
                    blocked[parent] = blocked.get(parent) or blocked.get(v, False)
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        comp.append(w)
                        if w == v:
                            break
                    if not any(blocked.get(w, False) for w in comp):
                        comp.sort(key=lambda w: (node(w).seq, str(w[0]), w[1]))
                        for w in comp:
                            self._apply(node(w))
                    else:
                        for w in comp:
                            blocked[w] = True

        for owner, insts in self.insts.items():
            for i, e in sorted(insts.items()):
                if e.status == COMMITTED and (owner, i) not in index:
                    strongconnect((owner, i))

    def _neighbors(self, u: Tuple[ID, int]) -> List[Tuple[ID, int]]:
        e = self.insts[u[0]].get(u[1])
        if e is None:
            return []
        return [(p, j) for p, j in e.deps.items() if j >= 0]

    def _apply(self, e: Instance) -> None:
        if e.status >= EXECUTED:
            return
        e.status = EXECUTED
        value = self.db.execute(e.command)
        if e.request is not None:
            e.request.reply(Reply(e.command, value=value))
            e.request = None


def new_replica(id: ID, cfg: Config) -> EPaxosReplica:
    return EPaxosReplica(ID(id), cfg)
