"""EPaxos — leaderless consensus with dependency tracking, as a TPU kernel.

Reference: paxi epaxos/ [driver] — every replica owns an instance space
``(replica, instance)``; a command leader PreAccepts a command, acceptors
merge conflict-derived attributes (seq, deps); if a fast quorum
(ceil(3N/4), quorum.go) returns identical attributes the command commits
on the fast path, otherwise the leader runs Accept (majority) with the
merged attributes and then Commit; execution orders the committed
dependency graph by strongly-connected components with seq as tiebreak
(epaxos exec.go, Tarjan SCC).  BASELINE config exercises Zipfian
conflicting keys [driver].

TPU re-design (not a translation):
- **Lane-major batch layout** (see sim/lanes.py): state planes are
  ``(me, owner, I, G)`` / deps ``(me, owner, I, R, G)`` with the group
  axis LAST; owner-driven mailbox planes ``(src, dst, G)`` scatter
  directly onto the (me, owner=src) axes — no gather in the hot
  handlers.  Quorum tallies are bit-packed int32 masks + popcount.
- The per-owner instance window is a sliding **ring** over ABSOLUTE
  instance ids (sim/ring.py): position i holds ``base[me, owner] + i``;
  each (me, owner) window recycles executed prefixes (retaining the
  last I//2 for retransmits/prepares), so the horizon is unbounded in
  O(window) memory.  Deps carry absolute ids: below my window ->
  satisfied (the ring only slides past locally-executed cells);
  in-window -> a graph edge; above my window -> execution blocks until
  my window catches up.  Out-of-window messages are ignored unacked
  (the owner's window flow control throttles to the majority's
  execution progress); a prepare request OUTSIDE my window gets no
  reply (below base: answering "no record" for an instance I executed
  could let a recoverer NOOP over a committed value; above: the
  ballot promise could not be durably recorded).
- Conflict attribute computation (exec.go's conflict map) is a masked
  max over the recorded window, vectorized over all inboxes at once.
- Execution replaces Tarjan with **boolean transitive closure by
  repeated matrix squaring** over the window graph — log2(R*I) bool
  matmuls that map straight onto the MXU (ops/closure.py keeps the
  matrix VMEM-resident on TPU).  SCCs are ``reach & reach^T``; a
  committed instance executes when every cross-SCC instance it reaches
  is executed; same-key executables are always in one SCC (two
  conflicting commands see each other through quorum intersection), so
  per-step application in global (seq, id) order is linearizable.
- The in-kernel safety oracle: commit agreement on (cmd, seq, deps),
  commit/execute stability, and cross-replica agreement of the per-key
  execution hash chain.
- **In-kernel recovery** (epaxos Prepare/PrepareReply, the analog of
  host.py's rule): a per-cell promised-ballot plane ``bal`` gates the
  owner's implicit-ballot-0 PreAccept/Accept; each replica ages the
  cells blocking its execution frontier (committed-unexecuted work
  reaching an uncommitted cell) and past a per-replica staggered
  timeout runs one Prepare round at a higher ballot over the most-aged
  cell.  PrepareReplies carry the replier's recorded state
  (status/seq/deps/accepted-ballot) AND its freshly computed conflict
  attributes for the command (the command id is a pure function of
  (owner, inst), so repliers need not have seen it) — the reference's
  restart-phase-1 (TryPreAccept) collapses into the same round.  The
  decision rule, in order: any committed reply -> commit it; otherwise
  wait for a FAST-sized prepare quorum, then: any accepted reply ->
  Accept the max-abal one; >= 2*FAST-R identical ballot-0 preaccepts
  (reached by every possibly-fast-committed value, and implying the
  value is visible to every future commit quorum — see THRESH in
  step() for why a majority-prepare rule is NOT enough) -> Accept
  those attrs; any preaccept -> Accept the attr-union of recorded +
  fresh conflict attrs over the quorum (no commit was possible, and
  the union covers every conflict committed anywhere by quorum
  intersection); else -> commit NOOP (the prepare quorum's raised
  ballots make the owner's original fast and slow paths both
  unreachable).  A
  permanently crashed leader's stalled instances are finished by the
  survivors (FuzzConfig.perm_crash); an alive owner whose instance was
  recovered moves on when it sees the cell committed.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.metrics import lathist
from paxi_tpu.ops.closure import transitive_closure
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim import inscan
from paxi_tpu.sim.ring import (diag2, dst_major, require_packable,
                               shift_deps, shift_window)
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1
ST_NONE, ST_PRE, ST_ACC, ST_COMMIT = 0, 1, 2, 3
HASH_PRIME = 1000003


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    R = cfg.n_replicas
    dep_fields = tuple(f"d{p}" for p in range(R))
    return {
        "pa": ("inst", "seq") + dep_fields,           # PreAccept
        "par": ("inst", "seq") + dep_fields,          # PreAcceptReply
        "acc": ("inst", "seq") + dep_fields,          # Accept
        "accr": ("inst",),                            # AcceptReply
        "cmt": ("inst", "seq", "cmd") + dep_fields,   # Commit
        # recovery planes (ballot-carrying), separate from the owner-
        # driven ones so an owner and a recoverer broadcasting in the
        # same step never collide on a (type, src, dst) wheel edge
        "prep": ("owner", "inst", "ballot"),          # Prepare
        # cmdv distinguishes a NOOP-committed/accepted cell (NO_CMD)
        # from the owner's real command
        "prepr": ("owner", "inst", "ballot", "stat", "cmdv", "seq",
                  "abal", "cseq") + dep_fields
                 + tuple(f"c{p}" for p in range(R)),  # PrepareReply
        "racc": ("owner", "inst", "ballot", "cmdv", "seq") + dep_fields,
        "raccr": ("owner", "inst", "ballot"),
        "rcmt": ("owner", "inst", "cmdv", "seq") + dep_fields,
        # GC gossip: each replica's contiguous executed frontier per
        # owner column, broadcast every step — windows recycle only
        # past the GLOBAL minimum (see the slide block)
        "gc": tuple(f"f{p}" for p in range(R)),
    }


def encode_cmd(owner, inst):
    """The command id is a pure function of (owner, absolute inst) — so
    recovery repliers can compute conflict attrs for instances they
    never saw.  24 bits of instance space: a 16M-instance horizon per
    owner before ids wrap."""
    return (owner << 24) | (inst & 0xFFFFFF)


def cmd_key(cmd, n_keys):
    return fib_key(cmd, n_keys)


def _deps_T(m, R, prefix="d"):
    """Gather dep fields d0..dR-1 of a (src, dst, G) mailbox into the
    receiver-major (me, src, R, G) stack."""
    return jnp.stack([jnp.swapaxes(m[f"{prefix}{p}"], 0, 1)
                      for p in range(R)], axis=2)


def _deps_out(deps, R, shape):
    """Spread (..., R, G) deps into broadcast per-field (src, dst, G)
    planes (deps indexed me-major: (me, R, G) -> broadcast over dst)."""
    return {f"d{p}": jnp.broadcast_to(deps[:, None, p], shape)
            for p in range(R)}


def init_state(cfg: SimConfig, rng: jax.Array, n_groups: int):
    R, I, K, G = cfg.n_replicas, cfg.n_slots, cfg.n_keys, n_groups
    del rng
    require_packable(R)
    i32 = jnp.int32
    return dict(
        # instance RING SoA, (me, owner, I, G): position i holds
        # absolute instance base[me, owner] + i (sim/ring.py); the
        # window slides past executed prefixes, so the horizon is
        # unbounded.  deps (me, owner, I, R, G) hold ABSOLUTE ids.
        base=jnp.zeros((R, R, G), i32),
        cmd=jnp.full((R, R, I, G), NO_CMD, i32),
        seq=jnp.zeros((R, R, I, G), i32),
        deps=jnp.full((R, R, I, R, G), -1, i32),
        status=jnp.zeros((R, R, I, G), i32),
        executed=jnp.zeros((R, R, I, G), bool),
        # recovery ballot planes: promised ballot per cell (0 = the
        # owner's implicit ballot) + the ballot attrs were accepted at
        bal=jnp.zeros((R, R, I, G), i32),
        abal=jnp.zeros((R, R, I, G), i32),
        # steps each cell has been blocking my execution frontier
        age=jnp.zeros((R, R, I, G), i32),
        # command-leader driving state (one in-flight instance each)
        cur=jnp.zeros((R, G), i32),
        phase=jnp.zeros((R, G), i32),    # 0 idle, 1 preaccept, 2 accept
        pa_acks=jnp.zeros((R, G), i32),  # bit-packed
        ac_acks=jnp.zeros((R, G), i32),
        agree=jnp.ones((R, G), bool),
        seq0=jnp.zeros((R, G), i32),     # original proposed attrs
        deps0=jnp.full((R, R, G), -1, i32),
        mseq=jnp.zeros((R, G), i32),     # merged attrs
        mdeps=jnp.full((R, R, G), -1, i32),
        stuck=jnp.zeros((R, G), i32),
        # one in-flight recovery per replica over cell (rowner, rinst)
        # at ballot rballot; rphase 0 idle / 1 prepare / 2 accept
        rphase=jnp.zeros((R, G), i32),
        rowner=jnp.zeros((R, G), i32),
        rinst=jnp.zeros((R, G), i32),
        rballot=jnp.zeros((R, G), i32),
        rstuck=jnp.zeros((R, G), i32),
        racks=jnp.zeros((R, G), i32),    # prepare-round ack bitmask
        # per-replier recorded state + fresh conflict attrs
        rstat=jnp.zeros((R, R, G), i32),
        rcmd=jnp.full((R, R, G), NO_CMD, i32),
        rseq2=jnp.zeros((R, R, G), i32),
        rabal=jnp.zeros((R, R, G), i32),
        rdeps2=jnp.full((R, R, R, G), -1, i32),
        rcseq=jnp.zeros((R, R, G), i32),
        rcdeps=jnp.full((R, R, R, G), -1, i32),
        # decided attrs being driven through the recovery Accept
        rdcmd=jnp.full((R, G), NO_CMD, i32),
        rdseq=jnp.zeros((R, G), i32),
        rddeps=jnp.full((R, R, G), -1, i32),
        aacks=jnp.zeros((R, G), i32),
        recovered=jnp.zeros((G,), i32),  # completed recoveries (metric)
        # latest-known executed frontier per (peer, owner) from the GC
        # gossip; the window slides only past min over peers
        gfront=jnp.zeros((R, R, R, G), i32),
        # cumulative per-replica counters (the window recycles, so
        # metrics cannot be recomputed from resident cells)
        ccount=jnp.zeros((R, G), i32),   # commit events seen at me
        xcount=jnp.zeros((R, G), i32),   # execution events at me
        # per-key execution oracle: count + order-sensitive hash chain
        kcount=jnp.zeros((R, K, G), i32),
        khash=jnp.zeros((R, K, G), i32),
        # on-device observability (PR-11 template: m_ measurement
        # planes, witness-hash-excluded, never read by protocol logic
        # — PXM10x): m_prop_t records the step a cell was FIRST
        # recorded at each replica; a cell's commit stores the
        # record->commit step delta in the position-free m_commit_dt
        # pending plane and the runner's deferred flush log2-bins it
        # (metrics/lathist); m_inscan_viol accumulates the in-scan
        # linearizability spot-check (sim/inscan)
        m_prop_t=jnp.zeros((R, R, I, G), i32),
        m_commit_dt=jnp.zeros((R, R, I, G), i32),
        m_lat_hist=lathist.empty_hist(G),
        m_lat_sum=jnp.zeros((G,), i32),
        m_inscan_viol=jnp.zeros((G,), i32),
    )


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, I, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, FAST = cfg.majority, cfg.fast_size
    # identical-preaccept threshold over a FAST-sized prepare quorum:
    # 2*FAST-R both (a) is always reached by a fast-committed value
    # (|prepare ∩ fast-quorum\owner| >= FAST+FAST-R) and (b) implies the
    # value is recorded at >= R-MAJ+1 replicas, so EVERY later commit
    # quorum of a conflicting command sees it — closing the unordered-
    # conflict recovery hole (a MAJ prepare with MAJ+FAST-R identical
    # replies satisfies (a) but not (b): a conflicting slow-path commit
    # can then miss the recovered instance entirely and execution order
    # diverges across replicas)
    THRESH = max(2 * FAST - R, 1)
    NN = R * I
    ridx = jnp.arange(R, dtype=jnp.int32)
    iidx = jnp.arange(I, dtype=jnp.int32)
    self_bit = (jnp.int32(1) << ridx)[:, None]           # (R, 1)

    cmd = state["cmd"]                # (me, owner, I, G)
    seq = state["seq"]
    deps = state["deps"]              # (me, owner, I, R, G)
    status = state["status"]
    executed = state["executed"]
    bal, abal, age = state["bal"], state["abal"], state["age"]
    cur, phase = state["cur"], state["phase"]
    pa_acks, ac_acks = state["pa_acks"], state["ac_acks"]
    agree = state["agree"]
    seq0, deps0 = state["seq0"], state["deps0"]
    mseq, mdeps = state["mseq"], state["mdeps"]
    rphase, rowner = state["rphase"], state["rowner"]
    rinst, rballot = state["rinst"], state["rballot"]
    rstuck, racks = state["rstuck"], state["racks"]
    rstat, rcmd = state["rstat"], state["rcmd"]
    rseq2, rabal = state["rseq2"], state["rabal"]
    rdeps2, rcseq, rcdeps = state["rdeps2"], state["rcseq"], state["rcdeps"]
    rdcmd, rdseq, rddeps = state["rdcmd"], state["rdseq"], state["rddeps"]
    aacks = state["aacks"]
    recovered = state["recovered"]
    gfront = state["gfront"]          # (me, peer, owner, G)
    base = state["base"]              # (me, owner, G) window bases
    ccount, xcount = state["ccount"], state["xcount"]
    kcount, khash = state["kcount"], state["khash"]
    G = cur.shape[-1]
    status_in = status               # pre-step statuses (commit counting)

    T = dst_major                                    # (me, src, G)

    def conflict_attrs(cmd_t, seq_t, status_t, new_cmd, excl_owner,
                       excl_inst):
        """Attrs (seq, deps) derived from the given window state for
        ``new_cmd`` (lead dims (me, X, G)), excluding the instance
        itself.  Callers pass the CURRENT mid-step table: the reference
        processes messages one at a time, so of two conflicting
        commands meeting at a shared replica in the same step, the
        later-computed attrs MUST see the earlier recording — computing
        everything from the pre-step snapshot let both commit blind to
        each other (an unordered conflicting pair whose execution order
        then diverges across replicas).
        Returns seq (me, X, G), deps (me, X, R, G)."""
        k_tab = cmd_key(cmd_t, K)                        # (me, owner, I, G)
        recorded_tab = (status_t >= ST_PRE) & (cmd_t != NO_CMD)
        k_new = cmd_key(new_cmd, K)                      # (me, X, G)
        abs_i = base[:, None, :, None, :] \
            + iidx[None, None, None, :, None]            # (me,1,owner,I,G)
        is_self = ((ridx[None, None, :, None, None]
                    == excl_owner[:, :, None, None, :])
                   & (abs_i == excl_inst[:, :, None, None, :]))
        conflict = (recorded_tab[:, None] & ~is_self
                    & (k_tab[:, None] == k_new[:, :, None, None, :]))
        # (me, X, owner, I, G); deps reported as ABSOLUTE instance ids
        cseq = jnp.max(jnp.where(conflict, seq_t[:, None], 0),
                       axis=(2, 3))
        cdep = jnp.max(jnp.where(conflict, abs_i, -1),
                       axis=3)                           # (me, X, R, G)
        return cseq + 1, cdep

    # ---------------- PreAccept: record, merge conflict attrs, reply ----
    m = inbox["pa"]
    v = T(m["valid"])                                    # (me, src, G)
    pa_inst = T(m["inst"])                               # ABSOLUTE
    pa_seq = T(m["seq"])
    pa_deps = _deps_T(m, R)                              # (me, src, R, G)
    # owner == src: the ring position maps against base[me, owner=src],
    # whose axes line up with the (me, src) message planes directly
    pa_rel = pa_inst - base
    v = v & (pa_rel >= 0) & (pa_rel < I)   # out-of-window: ignore, no ack
    oh_cell = iidx[None, None, :, None] == pa_rel[:, :, None, :]
    # the owner's implicit ballot is 0: once a recoverer's Prepare
    # touched the cell (bal > 0), its PreAccepts are stale — no record,
    # no reply (host handle_preaccept's ballot gate)
    cell_free = jnp.sum(jnp.where(oh_cell, bal, 0), axis=2) == 0
    v = v & cell_free
    pa_cmd = encode_cmd(ridx[None, :, None], pa_inst)    # (me, src, G)
    # pass 1: record the proposals' PRESENCE (proposed attrs) so that
    # two conflicting PreAccepts landing at this replica in the same
    # step see each other in pass 2 (mutual deps -> one SCC)
    wr = (v & (jnp.sum(jnp.where(oh_cell, status, 0), axis=2)
               < ST_PRE))[:, :, None, :] & oh_cell       # status-monotone
    cmd = jnp.where(wr, pa_cmd[:, :, None, :], cmd)
    seq = jnp.where(wr, pa_seq[:, :, None, :], seq)
    deps = jnp.where(wr[:, :, :, None, :],
                     pa_deps[:, :, None, :, :], deps)
    status = jnp.where(wr, ST_PRE, status)
    # pass 2: conflict attrs from the UPDATED table, merge, re-record
    a_seq, a_dep = conflict_attrs(cmd, seq, status, pa_cmd,
                                  jnp.broadcast_to(ridx[None, :, None],
                                                   pa_inst.shape),
                                  pa_inst)
    r_seq = jnp.maximum(pa_seq, a_seq)                   # (me, src, G)
    r_deps = jnp.maximum(pa_deps, a_dep)                 # (me, src, R, G)
    seq = jnp.where(wr, r_seq[:, :, None, :], seq)
    deps = jnp.where(wr[:, :, :, None, :],
                     r_deps[:, :, None, :, :], deps)
    out_par = {"valid": v, "inst": pa_inst, "seq": r_seq,
               **{f"d{p}": r_deps[:, :, p] for p in range(R)}}

    # ---------------- PreAcceptReply at the command leader --------------
    m = inbox["par"]
    v = T(m["valid"])
    rp_inst = T(m["inst"])
    rp_seq = T(m["seq"])
    rp_deps = _deps_T(m, R)
    ok = (v & (rp_inst == cur[:, None, :]) & (phase == 1)[:, None, :])
    same = (rp_seq == seq0[:, None, :]) & jnp.all(
        rp_deps == deps0[:, None], axis=2)
    agree = agree & jnp.all(~ok | same, axis=1)
    mseq = jnp.maximum(mseq, jnp.max(jnp.where(ok, rp_seq, 0), axis=1))
    mdeps = jnp.maximum(mdeps, jnp.max(
        jnp.where(ok[:, :, None, :], rp_deps, -1), axis=1))
    pa_acks = pa_acks | jnp.sum(
        jnp.where(ok, (jnp.int32(1) << ridx)[None, :, None], 0), axis=1)
    n_pa = jax.lax.population_count(pa_acks)
    fast_commit = (phase == 1) & agree & (n_pa >= FAST)
    go_accept = (phase == 1) & ~fast_commit & (n_pa >= MAJ) & (
        (~agree & (n_pa >= FAST))
        | (state["stuck"] >= cfg.retry_timeout))

    # ---------------- AcceptReply then Accept ---------------------------
    m = inbox["accr"]
    ok = (T(m["valid"]) & (T(m["inst"]) == cur[:, None, :])
          & (phase == 2)[:, None, :])
    ac_acks = ac_acks | jnp.sum(
        jnp.where(ok, (jnp.int32(1) << ridx)[None, :, None], 0), axis=1)
    slow_commit = (phase == 2) \
        & (jax.lax.population_count(ac_acks) >= MAJ)

    m = inbox["acc"]
    v = T(m["valid"])
    ac_inst = T(m["inst"])                               # absolute
    ac_seq = T(m["seq"])
    ac_deps = _deps_T(m, R)
    ac_rel = ac_inst - base
    v = v & (ac_rel >= 0) & (ac_rel < I)
    oh_cell = iidx[None, None, :, None] == ac_rel[:, :, None, :]
    cell_free = jnp.sum(jnp.where(oh_cell, bal, 0), axis=2) == 0
    v = v & cell_free
    ac_cmd = encode_cmd(ridx[None, :, None], ac_inst)
    wr = (v & (jnp.sum(jnp.where(oh_cell, status, 0), axis=2)
               < ST_ACC))[:, :, None, :] & oh_cell
    cmd = jnp.where(wr, ac_cmd[:, :, None, :], cmd)
    seq = jnp.where(wr, ac_seq[:, :, None, :], seq)
    deps = jnp.where(wr[:, :, :, None, :], ac_deps[:, :, None, :, :], deps)
    status = jnp.where(wr & (status < ST_COMMIT),
                       jnp.maximum(status, ST_ACC), status)
    out_accr = {"valid": v, "inst": ac_inst}

    # ---------------- Commit delivery (owner-driven) --------------------
    m = inbox["cmt"]
    v = T(m["valid"])
    cm_inst = T(m["inst"])                               # absolute
    cm_seq = T(m["seq"])
    cm_cmd = T(m["cmd"])
    cm_deps = _deps_T(m, R)
    cm_rel = cm_inst - base
    v = v & (cm_rel >= 0) & (cm_rel < I)
    oh_cell = iidx[None, None, :, None] == cm_rel[:, :, None, :]
    wr = (v & (jnp.sum(jnp.where(oh_cell, status, 0), axis=2)
               < ST_COMMIT))[:, :, None, :] & oh_cell
    cmd = jnp.where(wr, cm_cmd[:, :, None, :], cmd)
    seq = jnp.where(wr, cm_seq[:, :, None, :], seq)
    deps = jnp.where(wr[:, :, :, None, :], cm_deps[:, :, None, :, :], deps)
    status = jnp.where(wr, ST_COMMIT, status)

    # ---------------- leader transitions --------------------------------
    dec_seq = jnp.where(fast_commit, seq0, mseq)
    dec_deps = jnp.where(fast_commit[:, None, :], deps0, mdeps)
    do_commit = fast_commit | slow_commit
    base_own = diag2(base)                               # (R, G)
    rel_cur = jnp.clip(cur - base_own, 0, I - 1)
    my_cmd = encode_cmd(ridx[:, None], cur)              # (R, G)
    oh_me = ((ridx[:, None, None, None] == ridx[None, :, None, None])
             & (iidx[None, None, :, None] == rel_cur[:, None, None, :]))
    wrm = do_commit[:, None, None, :] & oh_me
    cmd = jnp.where(wrm, my_cmd[:, None, None, :], cmd)
    seq = jnp.where(wrm, dec_seq[:, None, None, :], seq)
    deps = jnp.where(wrm[:, :, :, None, :],
                     dec_deps[:, None, None, :, :], deps)
    status = jnp.where(wrm, ST_COMMIT, status)
    out_cmt_new = {
        "valid": jnp.broadcast_to(do_commit[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(cur[:, None, :], (R, R, G)),
        "seq": jnp.broadcast_to(dec_seq[:, None, :], (R, R, G)),
        "cmd": jnp.broadcast_to(my_cmd[:, None, :], (R, R, G)),
        **_deps_out(dec_deps, R, (R, R, G)),
    }

    # accept phase start
    wra = go_accept[:, None, None, :] & oh_me
    seq = jnp.where(wra, mseq[:, None, None, :], seq)
    deps = jnp.where(wra[:, :, :, None, :], mdeps[:, None, None, :, :],
                     deps)
    status = jnp.where(wra & (status < ST_COMMIT),
                       jnp.maximum(status, ST_ACC), status)
    ac_acks = jnp.where(go_accept, self_bit, ac_acks)
    out_acc = {
        "valid": jnp.broadcast_to(go_accept[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(cur[:, None, :], (R, R, G)),
        "seq": jnp.broadcast_to(mseq[:, None, :], (R, R, G)),
        **_deps_out(mdeps, R, (R, R, G)),
    }

    # my in-flight instance was finished externally (a recoverer drove
    # it to commit, possibly as NOOP): move on — in ANY phase, including
    # idle, or the owner's pipeline deadlocks on the recovered cell
    my_status0 = diag2(status)
    in_win_cur = cur - base_own < I
    ext_commit = ~do_commit & in_win_cur & (jnp.sum(
        jnp.where(iidx[None, :, None] == rel_cur[:, None, :],
                  my_status0, 0), axis=1) == ST_COMMIT)
    phase = jnp.where(do_commit | ext_commit, 0,
                      jnp.where(go_accept, 2, phase))
    cur = cur + (do_commit | ext_commit)
    stuck = jnp.where(do_commit | go_accept | ext_commit, 0,
                      state["stuck"])

    # ---------------- propose the next command --------------------------
    # window flow control: my next instance must be ring-resident
    propose = (phase == 0) & (cur - base_own < I)
    p_inst = cur                                         # absolute
    p_rel = jnp.clip(cur - base_own, 0, I - 1)
    p_cmd = encode_cmd(ridx[:, None], p_inst)
    p_seq, p_deps = conflict_attrs(cmd, seq, status, p_cmd[:, None, :],
                                   jnp.broadcast_to(ridx[:, None, None],
                                                    (R, 1, G)),
                                   p_inst[:, None, :])
    p_seq, p_deps = p_seq[:, 0], p_deps[:, 0]            # (R,G),(R,R,G)
    oh_p = ((ridx[:, None, None, None] == ridx[None, :, None, None])
            & (iidx[None, None, :, None] == p_rel[:, None, None, :]))
    # my own cell may have been recovery-touched (bal > 0): I still
    # record my proposal if the cell is empty, but acceptors will gate
    wrp = (propose & (jnp.sum(
        jnp.where(iidx[None, :, None] == p_rel[:, None, :],
                  status[ridx, ridx], 0), axis=1) < ST_PRE)
    )[:, None, None, :] & oh_p
    cmd = jnp.where(wrp, p_cmd[:, None, None, :], cmd)
    seq = jnp.where(wrp, p_seq[:, None, None, :], seq)
    deps = jnp.where(wrp[:, :, :, None, :], p_deps[:, None, None, :, :],
                     deps)
    status = jnp.where(wrp, ST_PRE, status)
    seq0 = jnp.where(propose, p_seq, seq0)
    deps0 = jnp.where(propose[:, None, :], p_deps, deps0)
    mseq = jnp.where(propose, p_seq, mseq)
    mdeps = jnp.where(propose[:, None, :], p_deps, mdeps)
    agree = jnp.where(propose, True, agree)
    pa_acks = jnp.where(propose, self_bit, pa_acks)
    phase = jnp.where(propose, 1, phase)

    # retransmit the in-flight phase message when stuck
    retry = stuck >= cfg.retry_timeout
    send_pa = propose | (retry & (phase == 1))
    send_acc = go_accept | (retry & (phase == 2))
    out_pa = {
        "valid": jnp.broadcast_to(send_pa[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(p_inst[:, None, :], (R, R, G)),
        "seq": jnp.broadcast_to(seq0[:, None, :], (R, R, G)),
        **_deps_out(deps0, R, (R, R, G)),
    }
    out_acc["valid"] = jnp.broadcast_to(send_acc[:, None, :], (R, R, G))
    stuck = jnp.where(retry, 0, stuck + (phase > 0))

    # late/periodic commit retransmit: round-robin over my in-window
    # committed instances so followers with dropped cmt messages heal
    # (laggards that fell behind the window stall — like the reference,
    # which has no snapshot transfer for epaxos)
    span = jnp.clip(cur - base_own, 1, I)                # (R, G)
    rr_rel = jnp.clip(cur - base_own - 1, 0, I - 1) - (ctx.t % span)
    rr_rel = jnp.clip(rr_rel, 0, I - 1)
    rr = base_own + rr_rel                               # absolute
    oh_rr = iidx[None, :, None] == rr_rel[:, None, :]
    mine = diag2
    my_status = mine(status)                             # (R, I, G)
    rr_cmd = jnp.sum(jnp.where(oh_rr, mine(cmd), 0), axis=1)
    rr_seq = jnp.sum(jnp.where(oh_rr, mine(seq), 0), axis=1)
    my_deps = mine(deps)                                 # (R, I, R, G)
    rr_deps = jnp.sum(jnp.where(oh_rr[:, :, None, :], my_deps, 0), axis=1)
    rr_committed = (jnp.sum(jnp.where(oh_rr, my_status, 0), axis=1)
                    == ST_COMMIT) & ~do_commit
    out_cmt = {
        "valid": out_cmt_new["valid"] | rr_committed[:, None, :],
        "inst": jnp.where(out_cmt_new["valid"], out_cmt_new["inst"],
                          rr[:, None, :]),
        "seq": jnp.where(out_cmt_new["valid"], out_cmt_new["seq"],
                         rr_seq[:, None, :]),
        "cmd": jnp.where(out_cmt_new["valid"], out_cmt_new["cmd"],
                         rr_cmd[:, None, :]),
        **{f"d{p}": jnp.where(out_cmt_new["valid"],
                              out_cmt_new[f"d{p}"],
                              rr_deps[:, None, p])
           for p in range(R)},
    }

    # ================ RECOVERY =========================================
    # ---------------- Prepare: raise cell ballots, reply ----------------
    m = inbox["prep"]
    v = T(m["valid"])                                    # (me, src, G)
    pr_own = jnp.clip(T(m["owner"]), 0, R - 1)
    pr_inst = T(m["inst"])                               # absolute
    pr_bal = T(m["ballot"])
    # ring position of the requested cell per possible owner column
    pr_rel = pr_inst[:, :, None, :] - base[:, None, :, :]  # (me,src,own,G)
    # per-cell max prepare ballot this step (collision: max wins);
    # out-of-window positions simply match no one-hot
    oh5 = (v[:, :, None, None, :]
           & (ridx[None, None, :, None, None] == pr_own[:, :, None, None, :])
           & (iidx[None, None, None, :, None]
              == pr_rel[:, :, :, None, :]))              # (me,src,own,I,G)
    cell_max = jnp.max(jnp.where(oh5, pr_bal[:, :, None, None, :], 0),
                       axis=1)                           # (me, own, I, G)
    bal = jnp.maximum(bal, cell_max)
    # reply per edge: src gets my recorded state for its requested cell
    # iff its ballot won the cell (== new bal).  A request BELOW my
    # window gets NO reply: I executed and recycled that instance, so
    # answering "no record" could let the recoverer NOOP-commit over a
    # value I know committed.  An above-window request also gets no
    # reply: the promise could not be recorded in a resident cell.
    prepr_fields = []
    for s in range(R):
        o_s, i_s, b_s = pr_own[:, s], pr_inst[:, s], pr_bal[:, s]
        base_sel = jnp.sum(jnp.where(ridx[None, :, None]
                                     == o_s[:, None, :], base, 0), axis=1)
        rel_s = i_s - base_sel                           # (me, G)
        ohc = ((ridx[None, :, None, None] == o_s[:, None, None, :])
               & (iidx[None, None, :, None] == rel_s[:, None, None, :]))
        # ohc: (me, own, I, G); at most one cell set

        def cell(pl):
            return jnp.sum(jnp.where(ohc, pl, 0), axis=(1, 2))

        # in-window only: a below-window cell was executed+recycled
        # here (replying "no record" could NOOP over a committed
        # value), and an above-window reply cannot durably record the
        # ballot promise (oh5 matched no cell), so counting it toward
        # the prepare quorum would break the NOOP-commit safety rule
        okr = v[:, s] & (b_s >= cell(bal)) & (rel_s >= 0) & (rel_s < I)
        st_s = cell(status)
        cm_s = cell(cmd)
        sq_s = cell(seq)
        ab_s = cell(abal)
        dp_s = jnp.sum(jnp.where(ohc[:, :, :, None, :], deps, 0),
                       axis=(1, 2))
        dp_s = jnp.where(st_s[:, None, :] >= ST_PRE, dp_s, -1)
        # fresh conflict attrs for the cell's (deterministic) command
        fr_cmd = encode_cmd(o_s, i_s)                    # (me, G)
        f_seq, f_deps = conflict_attrs(cmd, seq, status,
                                       fr_cmd[:, None, :],
                                       o_s[:, None, :], i_s[:, None, :])
        prepr_fields.append(dict(
            ok=okr, owner=o_s, inst=i_s, ballot=b_s, stat=st_s,
            cmdv=cm_s, seq=sq_s, abal=ab_s, deps=dp_s,
            cseq=f_seq[:, 0], cdeps=f_deps[:, 0]))
    out_prepr = {
        "valid": jnp.stack([f["ok"] for f in prepr_fields], axis=1),
        "owner": jnp.stack([f["owner"] for f in prepr_fields], axis=1),
        "inst": jnp.stack([f["inst"] for f in prepr_fields], axis=1),
        "ballot": jnp.stack([f["ballot"] for f in prepr_fields], axis=1),
        "stat": jnp.stack([f["stat"] for f in prepr_fields], axis=1),
        "cmdv": jnp.stack([f["cmdv"] for f in prepr_fields], axis=1),
        "seq": jnp.stack([f["seq"] for f in prepr_fields], axis=1),
        "abal": jnp.stack([f["abal"] for f in prepr_fields], axis=1),
        "cseq": jnp.stack([f["cseq"] for f in prepr_fields], axis=1),
        **{f"d{p}": jnp.stack([f["deps"][:, p] for f in prepr_fields],
                              axis=1) for p in range(R)},
        **{f"c{p}": jnp.stack([f["cdeps"][:, p] for f in prepr_fields],
                              axis=1) for p in range(R)},
    }
    # NOTE: out_prepr planes are (me, dst, G) — me replies to each dst

    # ---------------- PrepareReply tally at the recoverer ---------------
    m = inbox["prepr"]
    v = T(m["valid"])                                    # (me, src, G)
    ok = (v & (T(m["owner"]) == rowner[:, None, :])
          & (T(m["inst"]) == rinst[:, None, :])
          & (T(m["ballot"]) == rballot[:, None, :])
          & (rphase == 1)[:, None, :])
    racks = racks | jnp.sum(
        jnp.where(ok, (jnp.int32(1) << ridx)[None, :, None], 0), axis=1)
    rstat = jnp.where(ok, T(m["stat"]), rstat)
    rcmd = jnp.where(ok, T(m["cmdv"]), rcmd)
    rseq2 = jnp.where(ok, T(m["seq"]), rseq2)
    rabal = jnp.where(ok, T(m["abal"]), rabal)
    rcseq = jnp.where(ok, T(m["cseq"]), rcseq)
    rdeps2 = jnp.where(ok[:, :, None, :], _deps_T(m, R), rdeps2)
    rcdeps = jnp.where(ok[:, :, None, :], _deps_T(m, R, "c"), rcdeps)

    # ---------------- recovery decision ---------------------------------
    acked = ((racks[:, None, :] >> ridx[None, :, None]) & 1).astype(bool)
    # a committed reply is self-certifying; every other case needs the
    # full FAST-sized prepare quorum (see THRESH above).  Recovery
    # therefore needs R-FAST+1 failures to stall — the price of the
    # fast path, as in the reference
    n_rep = jax.lax.population_count(racks)
    have_prep = (rphase == 1) & (n_rep >= FAST)          # (me, G)
    st_ok = jnp.where(acked, rstat, ST_NONE)             # (me, rep, G)
    # 1. any committed reply
    is_com = st_ok == ST_COMMIT
    any_com = jnp.any(is_com, axis=1)
    # 2. any accepted reply: max abal wins
    is_acc = st_ok == ST_ACC
    any_acc = jnp.any(is_acc, axis=1)
    acc_bal = jnp.max(jnp.where(is_acc, rabal, -1), axis=1)
    # 3. identical ballot-0 preaccepts >= THRESH
    is_pre = (st_ok == ST_PRE) & (rabal == 0)
    same_ij = ((rseq2[:, :, None, :] == rseq2[:, None, :, :])
               & jnp.all(rdeps2[:, :, None] == rdeps2[:, None, :],
                         axis=3))                        # (me, i, j, G)
    ident_cnt = jnp.sum(is_pre[:, :, None, :] & is_pre[:, None, :, :]
                        & same_ij, axis=2)               # (me, i, G)
    ident_cnt = jnp.where(is_pre, ident_cnt, 0)
    has_ident = jnp.any(ident_cnt >= THRESH, axis=1)
    # 4. any preaccept at all (regardless of recorded ballot)
    any_pre = jnp.any(st_ok == ST_PRE, axis=1)

    # decided attrs per case (first-match unrolled picks)
    d_cmd = jnp.full((R, G), NO_CMD, jnp.int32)
    d_seq = jnp.zeros((R, G), jnp.int32)
    d_deps = jnp.full((R, R, G), -1, jnp.int32)
    for s in range(R - 1, -1, -1):
        pick_c = is_com[:, s]
        d_cmd = jnp.where(pick_c, rcmd[:, s], d_cmd)
        d_seq = jnp.where(pick_c, rseq2[:, s], d_seq)
        d_deps = jnp.where(pick_c[:, None, :], rdeps2[:, s], d_deps)
    a_cmd_d = jnp.full((R, G), NO_CMD, jnp.int32)
    a_seq_d = jnp.zeros((R, G), jnp.int32)
    a_deps_d = jnp.full((R, R, G), -1, jnp.int32)
    for s in range(R - 1, -1, -1):
        pick_a = is_acc[:, s] & (rabal[:, s] == acc_bal)
        a_cmd_d = jnp.where(pick_a, rcmd[:, s], a_cmd_d)
        a_seq_d = jnp.where(pick_a, rseq2[:, s], a_seq_d)
        a_deps_d = jnp.where(pick_a[:, None, :], rdeps2[:, s], a_deps_d)
    i_seq_d = jnp.zeros((R, G), jnp.int32)
    i_deps_d = jnp.full((R, R, G), -1, jnp.int32)
    best_cnt = jnp.max(ident_cnt, axis=1)
    for s in range(R - 1, -1, -1):
        pick_i = is_pre[:, s] & (ident_cnt[:, s] == best_cnt) \
            & (best_cnt >= THRESH)
        i_seq_d = jnp.where(pick_i, rseq2[:, s], i_seq_d)
        i_deps_d = jnp.where(pick_i[:, None, :], rdeps2[:, s], i_deps_d)
    # union case: recorded attrs of preaccepts + fresh attrs of all acked
    pre_any = st_ok == ST_PRE
    u_seq = jnp.maximum(
        jnp.max(jnp.where(pre_any, rseq2, 0), axis=1),
        jnp.max(jnp.where(acked, rcseq, 0), axis=1))
    u_deps = jnp.maximum(
        jnp.max(jnp.where(pre_any[:, :, None, :], rdeps2, -1), axis=1),
        jnp.max(jnp.where(acked[:, :, None, :], rcdeps, -1), axis=1))
    # the recovered instance never depends on itself
    self_col = ridx[None, :, None] == rowner[:, None, :]  # (me, R, G)
    u_deps = jnp.where(self_col & (u_deps == rinst[:, None, :]), -1,
                       u_deps)

    r_cmdv = encode_cmd(jnp.clip(rowner, 0, R - 1),
                        jnp.maximum(rinst, 0))
    dec_commit = (rphase == 1) & any_com
    dec_accept = have_prep & ~any_com & (any_acc | has_ident | any_pre)
    f_seq_d = jnp.where(any_acc, a_seq_d,
                        jnp.where(has_ident, i_seq_d, u_seq))
    f_deps_d = jnp.where(any_acc[:, None, :], a_deps_d,
                         jnp.where(has_ident[:, None, :], i_deps_d,
                                   u_deps))
    # accepted values may themselves be NOOPs from an earlier recovery;
    # preaccepted values are always the owner's real command
    f_cmd_d = jnp.where(any_acc, a_cmd_d, r_cmdv)
    dec_noop = have_prep & ~any_com & ~any_acc & ~has_ident & ~any_pre

    # commit-now path (case 1 and the NOOP case): apply + broadcast rcmt
    do_rcmt = dec_commit | dec_noop
    cm_cmd2 = jnp.where(dec_commit, d_cmd, NO_CMD)
    cm_seq2 = jnp.where(dec_commit, d_seq, 0)
    cm_deps2 = jnp.where(dec_commit[:, None, :], d_deps, -1)
    # accept path: record decided attrs, broadcast racc at rballot
    rdcmd = jnp.where(dec_accept, f_cmd_d, rdcmd)
    rdseq = jnp.where(dec_accept, f_seq_d, rdseq)
    rddeps = jnp.where(dec_accept[:, None, :], f_deps_d, rddeps)
    rphase = jnp.where(do_rcmt, 0, jnp.where(dec_accept, 2, rphase))
    aacks = jnp.where(dec_accept, self_bit, aacks)
    rstuck = jnp.where(do_rcmt | dec_accept, 0, rstuck)

    # ---------------- recovery Accept handling (racc) -------------------
    m = inbox["racc"]
    v = T(m["valid"])
    ra_own = jnp.clip(T(m["owner"]), 0, R - 1)
    ra_inst = T(m["inst"])                               # absolute
    ra_bal = T(m["ballot"])
    ra_cmdv = T(m["cmdv"])
    ra_seq = T(m["seq"])
    ra_deps = _deps_T(m, R)
    ra_rel = ra_inst[:, :, None, :] - base[:, None, :, :]  # (me,src,own,G)
    oh5 = (v[:, :, None, None, :]
           & (ridx[None, None, :, None, None] == ra_own[:, :, None, None, :])
           & (iidx[None, None, None, :, None]
              == ra_rel[:, :, :, None, :]))
    bal_b = jnp.broadcast_to(ra_bal[:, :, None, None, :], oh5.shape)
    gate = oh5 & (bal_b >= bal[:, None]) & (status[:, None] < ST_COMMIT)
    # per-cell winner: max ballot among gating raccs this step
    win_bal = jnp.max(jnp.where(gate, bal_b, -1), axis=1)  # (me,own,I,G)
    any_win = win_bal >= 0
    wf = jnp.zeros((R, R, I, G), jnp.int32)
    ws = jnp.zeros((R, R, I, G), jnp.int32)
    wd = jnp.full((R, R, I, R, G), -1, jnp.int32)
    for s in range(R - 1, -1, -1):
        hit = gate[:, s] & (bal_b[:, s] == win_bal)
        wf = jnp.where(hit, ra_cmdv[:, s, None, None, :], wf)
        ws = jnp.where(hit, ra_seq[:, s, None, None, :], ws)
        wd = jnp.where(hit[:, :, :, None, :],
                       ra_deps[:, s, None, None, :, :], wd)
    cmd = jnp.where(any_win, wf, cmd)
    seq = jnp.where(any_win, ws, seq)
    deps = jnp.where(any_win[:, :, :, None, :], wd, deps)
    status = jnp.where(any_win, jnp.maximum(status, ST_ACC), status)
    abal = jnp.where(any_win, win_bal, abal)
    bal = jnp.where(any_win, win_bal, bal)
    # raccr to each src whose ballot won its cell
    okr = []
    for s in range(R):
        hit = gate[:, s] & (bal_b[:, s] == win_bal)
        okr.append(jnp.any(hit, axis=(1, 2)))
    out_raccr = {
        "valid": jnp.stack(okr, axis=1),
        "owner": T(m["owner"]),
        "inst": T(m["inst"]),
        "ballot": T(m["ballot"]),
    }

    # ---------------- raccr tally -> rcmt --------------------------------
    m = inbox["raccr"]
    ok = (T(m["valid"]) & (T(m["owner"]) == rowner[:, None, :])
          & (T(m["inst"]) == rinst[:, None, :])
          & (T(m["ballot"]) == rballot[:, None, :])
          & (rphase == 2)[:, None, :])
    aacks = aacks | jnp.sum(
        jnp.where(ok, (jnp.int32(1) << ridx)[None, :, None], 0), axis=1)
    acc_done = (rphase == 2) & (jax.lax.population_count(aacks) >= MAJ)
    do_rcmt2 = do_rcmt | acc_done
    cm_cmd2 = jnp.where(acc_done, rdcmd, cm_cmd2)
    cm_seq2 = jnp.where(acc_done, rdseq, cm_seq2)
    cm_deps2 = jnp.where(acc_done[:, None, :], rddeps, cm_deps2)
    rphase = jnp.where(acc_done, 0, rphase)
    recovered = recovered + jnp.sum(do_rcmt2, axis=0)
    out_rcmt = {
        "valid": jnp.broadcast_to(do_rcmt2[:, None, :], (R, R, G)),
        "owner": jnp.broadcast_to(rowner[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(rinst[:, None, :], (R, R, G)),
        "cmdv": jnp.broadcast_to(cm_cmd2[:, None, :], (R, R, G)),
        "seq": jnp.broadcast_to(cm_seq2[:, None, :], (R, R, G)),
        **_deps_out(cm_deps2, R, (R, R, G)),
    }
    # apply my own recovery commit locally (ring position vs my base)
    rc_base = jnp.sum(jnp.where(ridx[None, :, None]
                                == jnp.clip(rowner, 0, R - 1)[:, None, :],
                                base, 0), axis=1)        # (me, G)
    oh_rc = ((ridx[None, :, None, None]
              == jnp.clip(rowner, 0, R - 1)[:, None, None, :])
             & (iidx[None, None, :, None]
                == (rinst - rc_base)[:, None, None, :]))
    wr = do_rcmt2[:, None, None, :] & oh_rc & (status < ST_COMMIT)
    cmd = jnp.where(wr, cm_cmd2[:, None, None, :], cmd)
    seq = jnp.where(wr, cm_seq2[:, None, None, :], seq)
    deps = jnp.where(wr[:, :, :, None, :], cm_deps2[:, None, None, :, :],
                     deps)
    status = jnp.where(wr, ST_COMMIT, status)

    # ---------------- rcmt delivery --------------------------------------
    m = inbox["rcmt"]
    v = T(m["valid"])
    rc_own = jnp.clip(T(m["owner"]), 0, R - 1)
    rc_inst = T(m["inst"])                               # absolute
    rc_cmdv = T(m["cmdv"])
    rc_seq = T(m["seq"])
    rc_deps = _deps_T(m, R)
    rc_rel = rc_inst[:, :, None, :] - base[:, None, :, :]
    oh5 = (v[:, :, None, None, :]
           & (ridx[None, None, :, None, None] == rc_own[:, :, None, None, :])
           & (iidx[None, None, None, :, None]
              == rc_rel[:, :, :, None, :]))
    hit_any = jnp.any(oh5, axis=1)                       # (me, own, I, G)
    wf = jnp.zeros((R, R, I, G), jnp.int32)
    ws = jnp.zeros((R, R, I, G), jnp.int32)
    wd = jnp.full((R, R, I, R, G), -1, jnp.int32)
    for s in range(R - 1, -1, -1):
        hit = oh5[:, s]
        wf = jnp.where(hit, rc_cmdv[:, s, None, None, :], wf)
        ws = jnp.where(hit, rc_seq[:, s, None, None, :], ws)
        wd = jnp.where(hit[:, :, :, None, :],
                       rc_deps[:, s, None, None, :, :], wd)
    wr = hit_any & (status < ST_COMMIT)
    cmd = jnp.where(wr, wf, cmd)
    seq = jnp.where(wr, ws, seq)
    deps = jnp.where(wr[:, :, :, None, :], wd, deps)
    status = jnp.where(wr, ST_COMMIT, status)

    # ---------------- execution: closure -> SCC -> ordered apply --------
    committed = (status == ST_COMMIT).reshape(R, NN, G)
    seq_f = seq.reshape(R, NN, G)
    cmd_f = cmd.reshape(R, NN, G)
    exec_f = executed.reshape(R, NN, G)
    deps_f = deps.reshape(R, NN, R, G)
    # deps hold ABSOLUTE ids: below my window -> executed here already
    # (the ring only slides past executed cells), satisfied, no edge;
    # in-window -> an edge; above my window -> the dependency is not
    # yet resident, block the source until my window catches up
    A = jnp.zeros((R, NN, NN, G), bool)
    fblock = jnp.zeros((R, NN, G), bool)
    for q in range(R):
        tgt = deps_f[:, :, q, :]                         # (R, NN, G) abs
        rel_q = tgt - base[:, q, None, :]
        inw_q = (tgt >= 0) & (rel_q >= 0) & (rel_q < I)
        fblock = fblock | ((tgt >= 0) & (rel_q >= I))
        col = q * I + jnp.clip(rel_q, 0, I - 1)
        A = A | (inw_q[:, :, None, :]
                 & (jnp.arange(NN)[None, None, :, None]
                    == col[:, :, None, :]))
    A = A & committed[:, :, None, :]    # only committed sources constrain
    reach = jnp.moveaxis(
        transitive_closure(jnp.moveaxis(A, -1, 1)), 1, -1)
    # an above-window dep blocks not just its direct source but every
    # instance that can reach it (an SCC mate of a blocked instance must
    # not execute ahead of the mate's unresident dependency)
    blocked = jnp.any(reach & (~committed | fblock)[:, None, :, :],
                      axis=2) | fblock
    ready = committed & ~blocked & ~exec_f
    scc = reach & jnp.swapaxes(reach, 1, 2)
    cross = reach & ~scc
    exec_ok = ready & ~jnp.any(cross & ~exec_f[:, None, :, :], axis=2)
    # above every encodable cmd id: owner <= 30 (require_packable),
    # so cmd = (owner << 24) | inst24 <= (31 << 24) | 0xFFFFFF < 2^29
    BIG = jnp.int32(1 << 29)
    new_exec = exec_f
    kidx = jnp.arange(K, dtype=jnp.int32)
    for _ in range(cfg.exec_window):
        cand = exec_ok & ~new_exec
        any_c = jnp.any(cand, axis=1)                    # (R, G)
        # replica-independent total order: (seq, cmd id) lexicographic
        # — ring positions differ across replicas, command ids do not.
        # Two-stage min; ties only between NOOPs (cmd == NO_CMD), whose
        # simultaneous execution is key-neutral.
        mseq_e = jnp.min(jnp.where(cand, seq_f, BIG), axis=1)
        cand2 = cand & (seq_f == mseq_e[:, None, :])
        mcmd_e = jnp.min(jnp.where(cand2, cmd_f, BIG), axis=1)
        oh_pick = cand2 & (cmd_f == mcmd_e[:, None, :])
        c_e = mcmd_e
        k_e = cmd_key(c_e, K)
        upd = any_c & (c_e != NO_CMD)
        ohk = upd[:, None, :] & (kidx[None, :, None] == k_e[:, None, :])
        khash = jnp.where(ohk, khash * HASH_PRIME + c_e[:, None, :],
                          khash)
        kcount = kcount + ohk
        new_exec = new_exec | oh_pick
    executed = new_exec.reshape(R, R, I, G)

    # ---------------- recovery trigger: age blocking cells ---------------
    # a cell is "needed" when committed-unexecuted work reaches it and it
    # is not committed — exactly the frontier blockers
    src_live = committed & ~new_exec
    needed = (jnp.any(src_live[:, :, None, :] & reach, axis=1)
              & ~committed).reshape(R, R, I, G)
    age = jnp.where(needed, age + 1, 0)
    # staggered per-replica patience breaks recoverer duels
    patience = cfg.election_timeout + ridx[:, None] * cfg.backoff
    age_f = age.reshape(R, NN, G)
    worst = jnp.max(age_f, axis=1)                       # (R, G)
    fire = (rphase == 0) & (worst > patience)
    pick = jnp.argmax(age_f, axis=1).astype(jnp.int32)   # (R, G)
    f_own = pick // I
    f_pos = pick % I                                     # ring position
    f_base = jnp.sum(jnp.where(ridx[None, :, None] == f_own[:, None, :],
                               base, 0), axis=1)
    f_inst = f_base + f_pos                              # absolute
    # ballot: above anything I've seen for the cell, tagged with my id
    oh_f = ((ridx[None, :, None, None] == f_own[:, None, None, :])
            & (iidx[None, None, :, None] == f_pos[:, None, None, :]))
    cell_bal = jnp.max(jnp.where(oh_f, bal, 0), axis=(1, 2))
    new_rbal = (jnp.maximum(cell_bal, rballot) // cfg.ballot_stride + 1) \
        * cfg.ballot_stride + ridx[:, None]
    rowner = jnp.where(fire, f_own, rowner)
    rinst = jnp.where(fire, f_inst, rinst)
    rballot = jnp.where(fire, new_rbal, rballot)
    rphase = jnp.where(fire, 1, rphase)
    racks = jnp.where(fire, self_bit, racks)
    rstuck = jnp.where(fire, 0, rstuck)
    # my own promise + self-reply into the tally
    bal = jnp.where(fire[:, None, None, :] & oh_f,
                    jnp.maximum(bal, new_rbal[:, None, None, :]), bal)
    self_stat = jnp.sum(jnp.where(oh_f, status, 0), axis=(1, 2))
    self_cmd = jnp.sum(jnp.where(oh_f, cmd, 0), axis=(1, 2))
    self_seq = jnp.sum(jnp.where(oh_f, seq, 0), axis=(1, 2))
    self_abal = jnp.sum(jnp.where(oh_f, abal, 0), axis=(1, 2))
    self_deps = jnp.sum(jnp.where(oh_f[:, :, :, None, :], deps, 0),
                        axis=(1, 2))
    self_deps = jnp.where(self_stat[:, None, :] >= ST_PRE, self_deps, -1)
    sf_cmd = encode_cmd(f_own, f_inst)
    sf_seq, sf_deps = conflict_attrs(cmd, seq, status, sf_cmd[:, None, :],
                                     f_own[:, None, :], f_inst[:, None, :])
    eye = (ridx[:, None, None] == ridx[None, :, None])   # (me, rep, 1)
    rstat = jnp.where(fire[:, None, :] & eye, self_stat[:, None, :], rstat)
    rcmd = jnp.where(fire[:, None, :] & eye, self_cmd[:, None, :], rcmd)
    rseq2 = jnp.where(fire[:, None, :] & eye, self_seq[:, None, :], rseq2)
    rabal = jnp.where(fire[:, None, :] & eye, self_abal[:, None, :], rabal)
    rcseq = jnp.where(fire[:, None, :] & eye, sf_seq[:, 0][:, None, :],
                      rcseq)
    rdeps2 = jnp.where((fire[:, None, :] & eye)[:, :, None, :],
                       self_deps[:, None, :, :], rdeps2)
    rcdeps = jnp.where((fire[:, None, :] & eye)[:, :, None, :],
                       sf_deps[:, 0][:, None, :, :], rcdeps)

    # recovery retransmit (periodic, not every-step: rstuck is kept
    # monotone for the give-up horizon, so retry on the cadence)
    rstuck = jnp.where(rphase > 0, rstuck + 1, 0)
    r_retry = (rphase > 0) & (rstuck > 0) \
        & (rstuck % cfg.retry_timeout == 0)
    give_up = rstuck >= 3 * cfg.retry_timeout
    rphase = jnp.where(give_up, 0, rphase)
    out_prep = {
        "valid": jnp.broadcast_to(
            (fire | (r_retry & (rphase == 1)))[:, None, :], (R, R, G)),
        "owner": jnp.broadcast_to(rowner[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(rinst[:, None, :], (R, R, G)),
        "ballot": jnp.broadcast_to(rballot[:, None, :], (R, R, G)),
    }
    out_racc = {
        "valid": jnp.broadcast_to(
            (dec_accept | (r_retry & (rphase == 2)))[:, None, :],
            (R, R, G)),
        "owner": jnp.broadcast_to(rowner[:, None, :], (R, R, G)),
        "inst": jnp.broadcast_to(rinst[:, None, :], (R, R, G)),
        "ballot": jnp.broadcast_to(rballot[:, None, :], (R, R, G)),
        "cmdv": jnp.broadcast_to(rdcmd[:, None, :], (R, R, G)),
        "seq": jnp.broadcast_to(rdseq[:, None, :], (R, R, G)),
        **_deps_out(rddeps, R, (R, R, G)),
    }

    # ---------------- cumulative counters (pre-slide layouts align) -----
    newly_c = (status == ST_COMMIT) & (status_in < ST_COMMIT)
    ccount = ccount + jnp.sum(newly_c, axis=(1, 2))
    xcount = xcount + jnp.sum(new_exec & ~exec_f, axis=1)

    # in-kernel commit latency (PR-11 template): a cell's clock starts
    # at its FIRST record here (own proposal or pa/acc/cmt delivery —
    # retransmits keep the original start via the ==0 guard); a newly
    # committed cell stores its record->commit step delta in the
    # pending plane for the runner's deferred flush
    m_prop_t = state["m_prop_t"]
    m_prop_t = jnp.where((status >= ST_PRE) & (status_in == ST_NONE)
                         & (m_prop_t == 0), ctx.t, m_prop_t)
    dt = jnp.clip(ctx.t - m_prop_t, 0, None)
    m_commit_dt = jnp.where(newly_c, dt, state["m_commit_dt"])
    m_lat_sum = state["m_lat_sum"] + jnp.sum(
        jnp.where(newly_c, dt, 0), axis=(0, 1, 2), dtype=jnp.int32)

    # ---------------- GC gossip + slide the instance rings --------------
    # my contiguous executed frontier per owner column (absolute)
    lead_exec = jnp.sum(jnp.cumprod(executed.astype(jnp.int32), axis=2),
                        axis=2)                          # (me, owner, G)
    my_front = base + lead_exec
    m = inbox["gc"]
    for s in range(R):
        fr_s = jnp.stack([T(m[f"f{p}"])[:, s] for p in range(R)],
                         axis=1)                         # (me, owner, G)
        got = T(m["valid"])[:, s][:, None, :]
        gfront = gfront.at[:, s].set(
            jnp.where(got, jnp.maximum(gfront[:, s], fr_s),
                      gfront[:, s]))
    eye3 = (ridx[:, None, None, None] == ridx[None, :, None, None])
    gfront = jnp.where(eye3, my_front[:, None], gfront)
    out_gc = {
        "valid": jnp.ones((R, R, G), bool),
        **{f"f{p}": jnp.broadcast_to(my_front[:, None, p], (R, R, G))
           for p in range(R)},
    }
    # recycle only past the GLOBAL minimum executed frontier: a cell a
    # replica recycles must be executed EVERYWHERE, else a new command
    # could commit blind to a recycled conflict that a laggard still
    # holds uncommitted (divergent per-key execution order).  The
    # min-over-peers watermark stalls if a replica dies permanently —
    # exactly the reference's GC/stability semantics; survivors retain
    # one window's worth of headroom.  RETAIN keeps recent cells
    # answerable for prepares/retransmits.
    RETAIN = max(I // 2, 1)
    # gfront's diagonal is my_front, so gmin <= my_front and the
    # advance can never pass my own executed prefix (lead_exec)
    gmin = jnp.min(gfront, axis=1)                       # (me, owner, G)
    adv = jnp.maximum(gmin - RETAIN - base, 0)
    base = base + adv
    cmd = shift_window(cmd, adv, NO_CMD)
    seq = shift_window(seq, adv, 0)
    status = shift_window(status, adv, ST_NONE)
    executed = shift_window(executed, adv, False)
    bal = shift_window(bal, adv, 0)
    abal = shift_window(abal, adv, 0)
    age = shift_window(age, adv, 0)
    deps = shift_deps(deps, adv)
    m_prop_t = shift_window(m_prop_t, adv, 0)

    # in-scan linearizability spot-check (sim/inscan): an independent
    # oracle beside invariants(), accumulated on device per group.
    # Frontier plane = the per-key execution counters (monotone by
    # construction), register plane = the per-key hash chains — equal
    # counts must mean equal chains, the in-scan slice of invariant 4.
    abs_in = (state["base"][:, :, None, :]
              + iidx[None, None, :, None])
    abs_out = base[:, :, None, :] + iidx[None, None, :, None]
    m_inscan_viol = state["m_inscan_viol"] + inscan.spot_check(
        state["kcount"], kcount, state["base"], base,
        abs_in, abs_out, state["cmd"], cmd,
        state["status"] == ST_COMMIT, status == ST_COMMIT,
        kv=khash, lane_major=True)

    new_state = dict(
        base=base, cmd=cmd, seq=seq, deps=deps, status=status,
        executed=executed, bal=bal, abal=abal, age=age, cur=cur,
        phase=phase, pa_acks=pa_acks, ac_acks=ac_acks, agree=agree,
        seq0=seq0, deps0=deps0, mseq=mseq, mdeps=mdeps, stuck=stuck,
        rphase=rphase, rowner=rowner, rinst=rinst, rballot=rballot,
        rstuck=rstuck, racks=racks, rstat=rstat, rcmd=rcmd, rseq2=rseq2,
        rabal=rabal, rdeps2=rdeps2, rcseq=rcseq, rcdeps=rcdeps,
        rdcmd=rdcmd, rdseq=rdseq, rddeps=rddeps, aacks=aacks,
        recovered=recovered, gfront=gfront, ccount=ccount,
        xcount=xcount, kcount=kcount, khash=khash,
        m_prop_t=m_prop_t, m_commit_dt=m_commit_dt,
        m_lat_hist=state["m_lat_hist"], m_lat_sum=m_lat_sum,
        m_inscan_viol=m_inscan_viol,
    )
    outbox = {"pa": out_pa, "par": out_par, "acc": out_acc,
              "accr": out_accr, "cmt": out_cmt, "prep": out_prep,
              "prepr": out_prepr, "racc": out_racc, "raccr": out_raccr,
              "rcmt": out_rcmt, "gc": out_gc}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    return {
        # cumulative counters (the ring recycles, so the resident
        # window no longer reflects history): most-advanced replica
        "committed_slots": jnp.sum(jnp.max(state["ccount"], axis=0)),
        "executed": jnp.sum(jnp.max(state["xcount"], axis=0)),
        "recovered": jnp.sum(state["recovered"]),
        # on-device observability scalars (PR-11 contract; the
        # histogram itself rides in state as m_lat_hist)
        "commit_lat_sum": jnp.sum(state["m_lat_sum"]),
        "commit_lat_n": (jnp.sum(state["m_lat_hist"])
                         + jnp.sum((state["m_commit_dt"] > 0)
                                   .astype(jnp.int32))),
        "inscan_violations": jnp.sum(state["m_inscan_viol"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Commit agreement: two replicas that both committed an
    absolute (p, j) agree on (cmd, seq, deps) — checked on the
    base-aligned common window.  2. Stability: ring-resident commits
    never change attrs or un-commit; the window only advances.
    3. Executed is monotone under the slide; executed implies
    committed.  4. Execution-order agreement: replicas with equal
    per-key counts have equal per-key hash chains."""
    base = new["base"]                                   # (me, R, G)
    align = jnp.max(base, axis=0)[None] - base

    def al(pl, fill):
        return shift_window(pl, align, fill)

    c = al(new["status"] == ST_COMMIT, False)            # (me, R, I, G)
    a_cmd = al(new["cmd"], NO_CMD)
    a_seq = al(new["seq"], 0)
    a_deps = shift_deps(new["deps"], align)
    pair = c[:, None] & c[None, :]
    same = ((a_cmd[:, None] == a_cmd[None, :])
            & (a_seq[:, None] == a_seq[None, :])
            & jnp.all(a_deps[:, None] == a_deps[None, :], axis=4))
    v_agree = jnp.sum(pair & ~same) // 2

    adv = base - old["base"]
    o_c = shift_window(old["status"] == ST_COMMIT, adv, False)
    o_cmd = shift_window(old["cmd"], adv, NO_CMD)
    o_seq = shift_window(old["seq"], adv, 0)
    o_deps = shift_deps(old["deps"], adv)
    n_c = new["status"] == ST_COMMIT
    v_stable = jnp.sum(o_c & (~n_c | (new["cmd"] != o_cmd)
                              | (new["seq"] != o_seq)
                              | jnp.any(new["deps"] != o_deps, axis=3)))
    v_stable = v_stable + jnp.sum(adv < 0)

    o_x = shift_window(old["executed"], adv, False)
    v_exec_mono = jnp.sum(o_x & ~new["executed"])
    v_exec_com = jnp.sum(new["executed"] & ~n_c)

    eqc = new["kcount"][:, None] == new["kcount"][None, :]
    eqh = new["khash"][:, None] == new["khash"][None, :]
    v_order = jnp.sum(eqc & ~eqh) // 2

    return (v_agree + v_stable + v_exec_mono + v_exec_com
            + v_order).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="epaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
    batched=True,
)
