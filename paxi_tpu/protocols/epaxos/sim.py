"""EPaxos — leaderless consensus with dependency tracking, as a TPU kernel.

Reference: paxi epaxos/ [driver] — every replica owns an instance space
``(replica, instance)``; a command leader PreAccepts a command, acceptors
merge conflict-derived attributes (seq, deps); if a fast quorum
(ceil(3N/4), quorum.go) returns identical attributes the command commits
on the fast path, otherwise the leader runs Accept (majority) with the
merged attributes and then Commit; execution orders the committed
dependency graph by strongly-connected components with seq as tiebreak
(epaxos exec.go, Tarjan SCC).  BASELINE config exercises Zipfian
conflicting keys [driver].

TPU re-design (not a translation):
- The per-replica instance window is a dense SoA: ``cmd/seq/status
  [R, R, I]`` and ``deps[R, R, I, R]`` — deps in the standard
  max-conflict-per-owner vector form (one int per owner replica).
- Conflict attribute computation (exec.go's conflict map) is a masked
  max over the recorded window, vectorized over all inboxes at once.
- Execution replaces Tarjan with **boolean transitive closure by
  repeated matrix squaring** over the window graph — log2(R*I) bool
  matmuls that map straight onto the MXU.  SCCs are ``reach & reach^T``;
  a committed instance executes when every cross-SCC instance it
  reaches is executed; same-key executables are always in one SCC (two
  conflicting commands see each other through quorum intersection), so
  per-step application in global (seq, id) order is linearizable.
- The in-kernel safety oracle: commit agreement on (cmd, seq, deps),
  commit/execute stability, and cross-replica agreement of the per-key
  execution hash chain.

Failure recovery (epaxos Prepare/PrepareReply, TryPreAccept) is
implemented in the host runtime (`epaxos/host.py`); the sim kernel
exercises the fast/slow agreement paths and SCC execution under
drop/dup/delay/partition and transient-crash fuzz.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paxi_tpu.ops.closure import transitive_closure
from paxi_tpu.ops.hashing import fib_key
from paxi_tpu.sim.types import SimConfig, SimProtocol, StepCtx

NO_CMD = -1
ST_NONE, ST_PRE, ST_ACC, ST_COMMIT = 0, 1, 2, 3
HASH_PRIME = 1000003


def mailbox_spec(cfg: SimConfig) -> Dict[str, Tuple[str, ...]]:
    R = cfg.n_replicas
    dep_fields = tuple(f"d{p}" for p in range(R))
    return {
        "pa": ("inst", "seq", "cmd") + dep_fields,    # PreAccept
        "par": ("inst", "seq") + dep_fields,          # PreAcceptReply
        "acc": ("inst", "seq", "cmd") + dep_fields,   # Accept
        "accr": ("inst",),                            # AcceptReply
        "cmt": ("inst", "seq", "cmd") + dep_fields,   # Commit
    }


def encode_cmd(owner, inst):
    return (owner << 8) | inst          # unique per (owner, inst), I <= 256


def cmd_key(cmd, n_keys):
    return fib_key(cmd, n_keys)


def _deps_pack(m, R, prefix="d"):
    """Gather dep fields d0..dR-1 from a mailbox into (..., R)."""
    return jnp.stack([m[f"{prefix}{p}"] for p in range(R)], axis=-1)


def _deps_out(deps, R, shape):
    """Spread (..., R) deps into broadcast per-field planes."""
    return {f"d{p}": jnp.broadcast_to(deps[..., p], shape)
            for p in range(R)}


def init_state(cfg: SimConfig, rng: jax.Array):
    R, I, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    del rng
    return dict(
        cmd=jnp.full((R, R, I), NO_CMD, jnp.int32),
        seq=jnp.zeros((R, R, I), jnp.int32),
        deps=jnp.full((R, R, I, R), -1, jnp.int32),
        status=jnp.zeros((R, R, I), jnp.int32),
        executed=jnp.zeros((R, R, I), bool),
        # command-leader driving state (one in-flight instance per replica)
        cur=jnp.zeros((R,), jnp.int32),
        phase=jnp.zeros((R,), jnp.int32),     # 0 idle, 1 preaccept, 2 accept
        pa_acks=jnp.zeros((R, R), bool),
        ac_acks=jnp.zeros((R, R), bool),
        agree=jnp.ones((R,), bool),
        seq0=jnp.zeros((R,), jnp.int32),      # original proposed attrs
        deps0=jnp.full((R, R), -1, jnp.int32),
        mseq=jnp.zeros((R,), jnp.int32),      # merged attrs
        mdeps=jnp.full((R, R), -1, jnp.int32),
        stuck=jnp.zeros((R,), jnp.int32),
        # per-key execution oracle: count + order-sensitive hash chain
        kcount=jnp.zeros((R, K), jnp.int32),
        khash=jnp.zeros((R, K), jnp.int32),
    )


def _conflict_attrs(state_cmd, state_seq, state_status, new_cmd, excl_owner,
                    excl_inst, cfg: SimConfig):
    """Attributes (seq, deps) a replica derives for ``new_cmd`` from its
    recorded window, excluding the instance itself.

    state_*: (R_own, I) views of ONE replica's window; new_cmd scalar-ish
    broadcastable leading dims.  Returns (seq, deps[R]).
    """
    R, I, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    k_new = cmd_key(new_cmd, K)                              # (...,)
    k_tab = cmd_key(state_cmd, K)                            # (..., R, I)
    recorded = state_status >= ST_PRE
    pidx = jnp.arange(R, dtype=jnp.int32)
    iidx = jnp.arange(I, dtype=jnp.int32)
    is_self = ((pidx[:, None] == excl_owner[..., None, None])
               & (iidx[None, :] == excl_inst[..., None, None]))
    conflict = (recorded & (k_tab == k_new[..., None, None]) & ~is_self
                & (state_cmd != NO_CMD))   # recovery NOOPs never interfere
    cseq = jnp.max(jnp.where(conflict, state_seq, 0), axis=-1)   # (..., R)
    cseq = jnp.max(cseq, axis=-1)                                # (...,)
    cdep = jnp.max(jnp.where(conflict, iidx[None, :], -1), axis=-1)  # (...,R)
    return cseq + 1, cdep


def step(state, inbox, ctx: StepCtx):
    cfg = ctx.cfg
    R, I, K = cfg.n_replicas, cfg.n_slots, cfg.n_keys
    MAJ, FAST = cfg.majority, cfg.fast_size
    N = R * I
    ridx = jnp.arange(R, dtype=jnp.int32)
    iidx = jnp.arange(I, dtype=jnp.int32)

    cmd = state["cmd"]
    seq = state["seq"]
    deps = state["deps"]
    status = state["status"]
    executed = state["executed"]
    cur = state["cur"]
    phase = state["phase"]
    pa_acks = state["pa_acks"]
    ac_acks = state["ac_acks"]
    agree = state["agree"]
    seq0, deps0 = state["seq0"], state["deps0"]
    mseq, mdeps = state["mseq"], state["mdeps"]
    kcount, khash = state["kcount"], state["khash"]

    def record(cmd_a, seq_a, deps_a, status_a, v, owner, inst, c, s, d, st):
        """Masked write of (c, s, d, st) at [me, owner(me), inst(me)].

        v/owner/inst/c/s: (R, R) planes (me, src); d: (R, R, R).
        Writes are status-monotone: a phase only overwrites attributes
        recorded by a strictly lower phase (late PreAccepts cannot
        clobber Accept attrs; commits are frozen forever)."""
        oh = (v[:, :, None, None]
              & (ridx[None, None, :, None] == owner[:, :, None, None])
              & (iidx[None, None, None, :] == inst[:, :, None, None]))
        # each (owner, inst) cell has exactly one driving src (= owner),
        # so at most one src writes a given cell per step and a flat
        # any()/argmax() over the src axis is collision-free
        hit = jnp.any(oh, axis=1)                         # (me, R, I)
        pick = jnp.argmax(oh, axis=1)                     # (me, R, I) src idx
        c_w = jnp.take_along_axis(
            jnp.broadcast_to(c[:, :, None, None], oh.shape),
            pick[:, None], axis=1)[:, 0]
        s_w = jnp.take_along_axis(
            jnp.broadcast_to(s[:, :, None, None], oh.shape),
            pick[:, None], axis=1)[:, 0]
        st_i = jnp.int32(st)
        wr_c = hit & (status_a < st_i)
        cmd_a = jnp.where(wr_c, c_w, cmd_a)
        seq_a = jnp.where(wr_c, s_w, seq_a)
        d_w = jnp.take_along_axis(
            jnp.broadcast_to(d[:, :, None, None, :],
                             oh.shape + (R,)),
            pick[:, None, :, :, None] * jnp.ones(
                (1, 1, 1, 1, R), jnp.int32), axis=1)[:, 0]
        deps_a = jnp.where(wr_c[..., None], d_w, deps_a)
        status_a = jnp.where(hit, jnp.maximum(status_a, st_i), status_a)
        return cmd_a, seq_a, deps_a, status_a

    # ---------------- PreAccept: merge conflict attrs, reply ------------
    m = inbox["pa"]
    v = jnp.transpose(m["valid"])                          # (me, src)
    pa_inst = jnp.transpose(m["inst"])
    pa_seq = jnp.transpose(m["seq"])
    pa_cmd = jnp.transpose(m["cmd"])
    pa_deps = jnp.stack([jnp.transpose(m[f"d{p}"]) for p in range(R)],
                        axis=-1)                           # (me, src, R)
    own_src = jnp.broadcast_to(ridx[None, :], (R, R))      # owner == src
    a_seq, a_dep = _conflict_attrs(
        cmd[:, None], seq[:, None], status[:, None],
        pa_cmd, own_src, pa_inst, cfg)                     # (me, src[,R])
    r_seq = jnp.maximum(pa_seq, a_seq)
    r_deps = jnp.maximum(pa_deps, a_dep)
    cmd, seq, deps, status = record(
        cmd, seq, deps, status, v, own_src, pa_inst,
        pa_cmd, r_seq, r_deps, ST_PRE)
    out_par = {"valid": v, "inst": pa_inst, "seq": r_seq,
               **_deps_out(r_deps, R, (R, R))}

    # ---------------- PreAcceptReply at the command leader --------------
    m = inbox["par"]
    v = jnp.transpose(m["valid"])                          # (ldr, src)
    rp_inst = jnp.transpose(m["inst"])
    rp_seq = jnp.transpose(m["seq"])
    rp_deps = jnp.stack([jnp.transpose(m[f"d{p}"]) for p in range(R)],
                        axis=-1)
    ok = v & (rp_inst == cur[:, None]) & (phase == 1)[:, None] & ~pa_acks
    pa_acks = pa_acks | ok
    same = (rp_seq == seq0[:, None]) & jnp.all(
        rp_deps == deps0[:, None, :], axis=-1)
    agree = agree & jnp.all(~ok | same, axis=1)
    mseq = jnp.maximum(mseq, jnp.max(jnp.where(ok, rp_seq, 0), axis=1))
    mdeps = jnp.maximum(mdeps, jnp.max(
        jnp.where(ok[..., None], rp_deps, -1), axis=1))
    n_pa = jnp.sum(pa_acks, axis=1)
    fast_commit = (phase == 1) & agree & (n_pa >= FAST)
    go_accept = (phase == 1) & ~fast_commit & (n_pa >= MAJ) & (
        (~agree & (n_pa >= FAST))
        | (state["stuck"] >= cfg.retry_timeout))

    # ---------------- AcceptReply then Accept ---------------------------
    m = inbox["accr"]
    v = jnp.transpose(m["valid"])
    ok = v & (jnp.transpose(m["inst"]) == cur[:, None]) & (phase == 2)[:, None]
    ac_acks = ac_acks | ok
    slow_commit = (phase == 2) & (jnp.sum(ac_acks, axis=1) >= MAJ)

    m = inbox["acc"]
    v = jnp.transpose(m["valid"])
    ac_inst = jnp.transpose(m["inst"])
    ac_seq = jnp.transpose(m["seq"])
    ac_cmd = jnp.transpose(m["cmd"])
    ac_deps = jnp.stack([jnp.transpose(m[f"d{p}"]) for p in range(R)],
                        axis=-1)
    cmd, seq, deps, status = record(
        cmd, seq, deps, status, v, own_src, ac_inst,
        ac_cmd, ac_seq, ac_deps, ST_ACC)
    out_accr = {"valid": v, "inst": ac_inst}

    # ---------------- Commit delivery -----------------------------------
    m = inbox["cmt"]
    v = jnp.transpose(m["valid"])
    cm_inst = jnp.transpose(m["inst"])
    cm_seq = jnp.transpose(m["seq"])
    cm_cmd = jnp.transpose(m["cmd"])
    cm_deps = jnp.stack([jnp.transpose(m[f"d{p}"]) for p in range(R)],
                        axis=-1)
    cmd, seq, deps, status = record(
        cmd, seq, deps, status, v, own_src, cm_inst,
        cm_cmd, cm_seq, cm_deps, ST_COMMIT)

    # ---------------- leader transitions --------------------------------
    # fast/slow commit: freeze my instance as committed with the decided
    # attrs (fast: originals == everyone's; slow: merged)
    dec_seq = jnp.where(fast_commit, seq0, mseq)
    dec_deps = jnp.where(fast_commit[:, None], deps0, mdeps)
    do_commit = fast_commit | slow_commit
    my_cmd = encode_cmd(ridx, jnp.clip(cur, 0, I - 1))
    oh_me = (ridx[:, None, None] == ridx[None, :, None]) \
        & (iidx[None, None, :] == jnp.clip(cur, 0, I - 1)[:, None, None])
    wrm = do_commit[:, None, None] & oh_me
    cmd = jnp.where(wrm, my_cmd[:, None, None], cmd)
    seq = jnp.where(wrm, dec_seq[:, None, None], seq)
    deps = jnp.where(wrm[..., None], dec_deps[:, None, None, :], deps)
    status = jnp.where(wrm, ST_COMMIT, status)
    out_cmt_new = {
        "valid": jnp.broadcast_to(do_commit[:, None], (R, R)),
        "inst": jnp.broadcast_to(cur[:, None], (R, R)),
        "seq": jnp.broadcast_to(dec_seq[:, None], (R, R)),
        "cmd": jnp.broadcast_to(my_cmd[:, None], (R, R)),
        **_deps_out(jnp.broadcast_to(dec_deps[:, None, :], (R, R, R)),
                    R, (R, R)),
    }

    # accept phase start
    wra = go_accept[:, None, None] & oh_me
    seq = jnp.where(wra, mseq[:, None, None], seq)
    deps = jnp.where(wra[..., None], mdeps[:, None, None, :], deps)
    status = jnp.where(wra, jnp.maximum(status, ST_ACC), status)
    ac_acks = jnp.where(go_accept[:, None], ridx[None, :] == ridx[:, None],
                        ac_acks)
    out_acc = {
        "valid": jnp.broadcast_to(go_accept[:, None], (R, R)),
        "inst": jnp.broadcast_to(cur[:, None], (R, R)),
        "seq": jnp.broadcast_to(mseq[:, None], (R, R)),
        "cmd": jnp.broadcast_to(my_cmd[:, None], (R, R)),
        **_deps_out(jnp.broadcast_to(mdeps[:, None, :], (R, R, R)),
                    R, (R, R)),
    }

    phase = jnp.where(do_commit, 0, jnp.where(go_accept, 2, phase))
    cur = cur + do_commit
    stuck = jnp.where(do_commit | go_accept, 0, state["stuck"])

    # ---------------- propose the next command --------------------------
    propose = (phase == 0) & (cur < I)
    p_inst = jnp.clip(cur, 0, I - 1)
    p_cmd = encode_cmd(ridx, p_inst)
    p_seq, p_deps = _conflict_attrs(cmd, seq, status, p_cmd,
                                    ridx, p_inst, cfg)     # own-window attrs
    oh_p = (ridx[:, None, None] == ridx[None, :, None]) \
        & (iidx[None, None, :] == p_inst[:, None, None])
    wrp = propose[:, None, None] & oh_p
    cmd = jnp.where(wrp, p_cmd[:, None, None], cmd)
    seq = jnp.where(wrp, p_seq[:, None, None], seq)
    deps = jnp.where(wrp[..., None], p_deps[:, None, None, :], deps)
    status = jnp.where(wrp, jnp.maximum(status, ST_PRE), status)
    seq0 = jnp.where(propose, p_seq, seq0)
    deps0 = jnp.where(propose[:, None], p_deps, deps0)
    mseq = jnp.where(propose, p_seq, mseq)
    mdeps = jnp.where(propose[:, None], p_deps, mdeps)
    agree = jnp.where(propose, True, agree)
    pa_acks = jnp.where(propose[:, None], ridx[None, :] == ridx[:, None],
                        pa_acks)
    phase = jnp.where(propose, 1, phase)

    # retransmit the in-flight phase message when stuck
    retry = (stuck >= cfg.retry_timeout)
    send_pa = propose | (retry & (phase == 1))
    send_acc = go_accept | (retry & (phase == 2))
    out_pa = {
        "valid": jnp.broadcast_to(send_pa[:, None], (R, R)),
        "inst": jnp.broadcast_to(p_inst[:, None], (R, R)),
        "seq": jnp.broadcast_to(seq0[:, None], (R, R)),
        "cmd": jnp.broadcast_to(encode_cmd(ridx, p_inst)[:, None], (R, R)),
        **_deps_out(jnp.broadcast_to(deps0[:, None, :], (R, R, R)),
                    R, (R, R)),
    }
    out_acc["valid"] = jnp.broadcast_to(send_acc[:, None], (R, R))
    stuck = jnp.where(retry, 0, stuck + (phase > 0))

    # late/periodic commit retransmit: round-robin over my committed
    # instances so followers with dropped cmt messages eventually heal
    rr = ctx.t % jnp.maximum(cur, 1)
    rr_cmd = cmd[ridx, ridx, rr]
    rr_committed = (status[ridx, ridx, rr] == ST_COMMIT) & ~jnp.any(
        out_cmt_new["valid"], axis=1)
    out_cmt = {
        "valid": out_cmt_new["valid"] | rr_committed[:, None],
        "inst": jnp.where(out_cmt_new["valid"], out_cmt_new["inst"],
                          rr[:, None] * jnp.ones((1, R), jnp.int32)),
        "seq": jnp.where(out_cmt_new["valid"], out_cmt_new["seq"],
                         seq[ridx, ridx, rr][:, None]),
        "cmd": jnp.where(out_cmt_new["valid"], out_cmt_new["cmd"],
                         rr_cmd[:, None]),
        **{f"d{p}": jnp.where(out_cmt_new["valid"], out_cmt_new[f"d{p}"],
                              deps[ridx, ridx, rr, p][:, None])
           for p in range(R)},
    }

    # ---------------- execution: closure -> SCC -> ordered apply --------
    committed = (status == ST_COMMIT).reshape(R, N)
    seq_f = seq.reshape(R, N)
    cmd_f = cmd.reshape(R, N)
    exec_f = executed.reshape(R, N)
    # adjacency: u=(p,j) -> v=(q, deps[u][q])
    A = jnp.zeros((R, N, N), bool)
    deps_f = deps.reshape(R, N, R)
    for q in range(R):
        tgt = deps_f[:, :, q]                              # (R, N)
        has = tgt >= 0
        col = q * I + jnp.clip(tgt, 0, I - 1)
        A = A | (has[:, :, None]
                 & (jnp.arange(N)[None, None, :] == col[:, :, None]))
    A = A & committed[:, :, None]       # only committed sources constrain
    # MXU-shaped reachability: Pallas VMEM-resident squaring on TPU,
    # plain XLA elsewhere (ops/closure.py)
    reach = transitive_closure(A)
    # an instance is ready when every reachable dep is committed
    blocked = jnp.any(reach & ~committed[:, None, :], axis=2)
    ready = committed & ~blocked & ~exec_f
    scc = reach & jnp.swapaxes(reach, 1, 2)
    cross = reach & ~scc
    exec_ok = ready & ~jnp.any(cross & ~exec_f[:, None, :], axis=2)
    # apply up to exec_window commands in global (seq, id) order
    BIG = jnp.int32(1 << 20)
    order = seq_f * N + jnp.arange(N)[None, :]
    new_exec = exec_f
    for _ in range(cfg.exec_window):
        cand = exec_ok & ~new_exec
        pick = jnp.argmin(jnp.where(cand, order, BIG), axis=1)   # (R,)
        any_c = jnp.any(cand, axis=1)
        c_e = cmd_f[ridx, pick]
        k_e = cmd_key(c_e, K)
        ohk = any_c[:, None] & (jnp.arange(K)[None, :] == k_e[:, None])
        khash = jnp.where(ohk, khash * HASH_PRIME + c_e[:, None], khash)
        kcount = kcount + ohk
        new_exec = new_exec | (any_c[:, None]
                               & (jnp.arange(N)[None, :] == pick[:, None]))
    executed = new_exec.reshape(R, R, I)

    new_state = dict(
        cmd=cmd, seq=seq, deps=deps, status=status, executed=executed,
        cur=cur, phase=phase, pa_acks=pa_acks, ac_acks=ac_acks,
        agree=agree, seq0=seq0, deps0=deps0, mseq=mseq, mdeps=mdeps,
        stuck=stuck, kcount=kcount, khash=khash,
    )
    outbox = {"pa": out_pa, "par": out_par, "acc": out_acc,
              "accr": out_accr, "cmt": out_cmt}
    return new_state, outbox


def metrics(state, cfg: SimConfig):
    com = jnp.any(state["status"] == ST_COMMIT, axis=0)    # (R, I) anywhere
    return {
        "committed_slots": jnp.sum(com),
        "executed": jnp.max(jnp.sum(state["executed"], axis=(1, 2))),
        "fastpath_cur": jnp.sum(state["cur"]),
    }


def invariants(old, new, cfg: SimConfig) -> jax.Array:
    """1. Commit agreement: two replicas that both committed (p, j)
    agree on (cmd, seq, deps).  2. Stability: commits never change
    attrs or un-commit; executed is monotone.  3. Executed implies
    committed.  4. Execution-order agreement: replicas with equal
    per-key counts have equal per-key hash chains."""
    c = new["status"] == ST_COMMIT                        # (Rv, R, I)
    pair = c[:, None] & c[None, :]                        # (Rv, Rv, R, I)
    same = ((new["cmd"][:, None] == new["cmd"][None, :])
            & (new["seq"][:, None] == new["seq"][None, :])
            & jnp.all(new["deps"][:, None] == new["deps"][None, :],
                      axis=-1))
    v_agree = jnp.sum(pair & ~same) // 2

    was = old["status"] == ST_COMMIT
    v_stable = jnp.sum(was & ((new["status"] != ST_COMMIT)
                              | (new["cmd"] != old["cmd"])
                              | (new["seq"] != old["seq"])
                              | jnp.any(new["deps"] != old["deps"],
                                        axis=-1)))
    v_exec_mono = jnp.sum(old["executed"] & ~new["executed"])
    v_exec_com = jnp.sum(new["executed"] & ~c)

    eqc = new["kcount"][:, None] == new["kcount"][None, :]
    eqh = new["khash"][:, None] == new["khash"][None, :]
    v_order = jnp.sum(eqc & ~eqh) // 2

    return (v_agree + v_stable + v_exec_mono + v_exec_com
            + v_order).astype(jnp.int32)


PROTOCOL = SimProtocol(
    name="epaxos",
    mailbox_spec=mailbox_spec,
    init_state=init_state,
    step=step,
    metrics=metrics,
    invariants=invariants,
)
