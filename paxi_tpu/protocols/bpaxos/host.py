"""Compartmentalized BPaxos replica for the host (deployment) runtime.

Reference: "Bipartisan Paxos" + "HT-Paxos" (PAPERS.md) — the same
protocol the TPU sim kernel (sim.py) runs as masked array updates, in
event-driven form with **node-id role assignment** over the sorted
cluster ids:

- ids[0 .. n_proxies)                      -> proxy leaders
- ids[n_proxies .. n_proxies + rows*cols)  -> the acceptor grid
  (row-major: acceptor i sits at (i // cols, i % cols))
- the rest                                 -> replica executors

Proxy leaders own disjoint slot stripes (slot ``s`` belongs to proxy
``s % P``), so there is no global leader and no election: client
commands batch in a ``BatchBuffer`` (host/batch.py) and ONE grid round
decides the whole batch — a slot holds a command *list*, BP2a/BP3
carry it, and batch atomicity rides on slot atomicity (a BP2a reaches
an acceptor with the entire batch or not at all).

Quorums are the r x w grid (core/quorum.py ``grid_row``/``grid_col``):
a write needs ONE FULL ROW of acks, a recovery read ONE FULL COLUMN —
every row/column pair shares exactly one cell, which paxi-lint's PXQ
rowcol rule proves from both call sites.  Messaging is *thrifty*: a
proposal goes only to its target row, a recovery probe only to one
column.

Takeover recovery (gap strikes): a proxy that keeps learning commits
above a hole in the shared log (``_gap_strikes`` counts BP3s that land
while its execute frontier is stalled) runs classic per-slot Paxos
recovery at a fresh higher ballot — column read, adopt the
highest-ballot value (else NOOP = empty batch), row write.  Strike
thresholds stagger by stripe distance so the hole's owner retries
first; repeated strikes rotate the row/column so a crashed acceptor
is eventually avoided.  The ``noread`` twin module disables exactly
the column read (``RECOVERY_READS = False``) — the seeded bug the
hunt pipeline must reproduce.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from paxi_tpu.core.ballot import ballot, ballot_id
from paxi_tpu.core.command import Command, Reply, Request
from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.core.quorum import Quorum
from paxi_tpu.host.batch import BatchBuffer
from paxi_tpu.host.codec import register_message
from paxi_tpu.host.node import Node


def _wire_cmds(cmds: List[Command]) -> List[list]:
    return [[c.key, c.value, c.client_id, c.command_id] for c in cmds]


def _cmds_from_wire(wire) -> List[Command]:
    return [Command(int(k), v, cid, int(cmid)) for k, v, cid, cmid in wire]


def _idents(cmds: List[Command]) -> List[Tuple[str, int]]:
    return [(c.client_id, c.command_id) for c in cmds]


@register_message
@dataclass
class BP1a:
    """Recovery column-read probe for one slot."""

    ballot: int
    slot: int


@register_message
@dataclass
class BP1b:
    """An acceptor's promise + its accepted (ballot, batch) for the
    probed slot (vballot == 0: nothing accepted)."""

    ballot: int
    slot: int
    vballot: int
    cmds: list = field(default_factory=list)
    id: str = ""


@register_message
@dataclass
class BP2a:
    """One grid write round for one slot carrying a whole command
    batch ([] = NOOP filler from recovery)."""

    ballot: int
    slot: int
    cmds: list = field(default_factory=list)


@register_message
@dataclass
class BP2b:
    ballot: int
    slot: int
    id: str = ""


@register_message
@dataclass
class BP3:
    """Commit notification to the learner roles (proxies + replicas)."""

    ballot: int
    slot: int
    cmds: list = field(default_factory=list)


@dataclass
class Entry:
    """A learner/proposer log slot: the accepted batch with a parallel
    request list (requests[i] answers cmds[i]; None for commands whose
    client connection lives elsewhere)."""

    ballot: int
    cmds: List[Command] = field(default_factory=list)
    commit: bool = False
    requests: List[Optional[Request]] = field(default_factory=list)
    quorum: Optional[Quorum] = None
    timestamp: float = 0.0

    def live_requests(self) -> List[Request]:
        return [r for r in self.requests if r is not None]


@dataclass
class RecState:
    """The per-proxy takeover-recovery FSM (one slot at a time)."""

    slot: int
    ballot: int
    phase: int                   # 1 = column read, 2 = row write
    quorum: Quorum
    vballot: int = 0
    cmds: List[Command] = field(default_factory=list)
    attempt: int = 1
    strikes0: int = 0            # gap-strike count at start (restart gate)


class BPaxosReplica(Node):
    RECOVERY_READS = True        # the noread twin flips this

    def __init__(self, id: ID, cfg: Config):
        super().__init__(id, cfg)
        ids = cfg.ids
        P, GR, GC = cfg.n_proxies, cfg.grid_rows, cfg.grid_cols
        A = GR * GC
        if len(ids) < P + A + 1:
            raise ValueError(
                f"bpaxos needs >= n_proxies + grid_rows*grid_cols + 1 "
                f"nodes (got {len(ids)}, need {P + A + 1})")
        self.gr, self.gc = GR, GC
        self.proxies = ids[:P]
        self.acceptors = ids[P:P + A]
        self.replicas = ids[P + A:]
        self.rank = ids.index(self.id)
        self.is_proxy = self.rank < P
        self.is_acceptor = P <= self.rank < P + A
        # proxy state: a fixed per-proxy ballot (no elections), the
        # next own-stripe slot, and the learner log
        self.bal0 = ballot(1, self.id)
        self.next_slot = self.rank
        self.log: Dict[int, Entry] = {}
        self.execute = 0
        # acceptor state: slot -> [promised ballot, accepted ballot,
        # accepted wire batch]
        self.acc: Dict[int, list] = {}
        # at-most-once session table (paxos host precedent)
        self.ctab: Dict[str, Tuple[int, bytes]] = {}
        self.safety_violations = 0   # sticky commit-divergence counter
        self.recovered = 0
        self._rec: Optional[RecState] = None
        self._rec_attempt = 0
        self._gap_at = -1
        self._gap_strikes = 0
        # wall-clock gap poller (real deployments only — wall timers
        # never fire under the virtual-clock fabric, where the
        # strike-based path keeps replays deterministic): fires
        # takeover recovery for a hole that outlives the poll interval
        # even when no further commits arrive to strike it
        self._gap_handle = None
        self._gap_armed_at = -1
        self._rec_polls = 0
        if self.is_proxy:
            self.batch = BatchBuffer(
                self._flush_batch, max_size=cfg.batch_size,
                max_wait=0.0 if self.socket.fabric is not None
                else cfg.batch_wait,
                metrics=self.metrics)
        self.register(Request, self.handle_request)
        if self.is_acceptor:
            self.register(BP1a, self.handle_bp1a)
            self.register(BP2a, self.handle_bp2a)
        else:
            self.register(BP3, self.handle_bp3)
        if self.is_proxy:
            self.register(BP1b, self.handle_bp1b)
            self.register(BP2b, self.handle_bp2b)

    # ---- grid membership ----------------------------------------------
    def _row(self, r: int) -> List[ID]:
        return self.acceptors[r * self.gc:(r + 1) * self.gc]

    def _col(self, c: int) -> List[ID]:
        return self.acceptors[c::self.gc]

    def _learners(self) -> List[ID]:
        return [i for i in self.proxies + self.replicas if i != self.id]

    # ---- client requests ----------------------------------------------
    def handle_request(self, req: Request) -> None:
        if self.is_proxy:
            self.batch.add(req)
        else:
            # key-stable proxy routing keeps fabric replays deterministic
            self.forward(self.proxies[req.command.key
                                      % len(self.proxies)], req)

    def _flush_batch(self, reqs: List[Request]) -> None:
        """BatchBuffer flush: ONE grid round for the whole batch, on my
        own slot stripe, messaged thriftily to the target row."""
        cmds = [r.command for r in reqs]
        slot = self.next_slot
        self.next_slot += len(self.proxies)
        q = Quorum(self.acceptors)
        self.log[slot] = Entry(self.bal0, cmds, requests=list(reqs),
                               quorum=q, timestamp=time.time())
        m = BP2a(self.bal0, slot, _wire_cmds(cmds))
        for a in self._row(slot % self.gr):
            self.socket.send(a, m)

    # ---- acceptors -----------------------------------------------------
    def handle_bp1a(self, m: BP1a) -> None:
        st = self.acc.setdefault(m.slot, [0, 0, []])
        if m.ballot >= st[0]:
            st[0] = m.ballot
            self.socket.send(ballot_id(m.ballot),
                             BP1b(m.ballot, m.slot, st[1], list(st[2]),
                                  str(self.id)))

    def handle_bp2a(self, m: BP2a) -> None:
        st = self.acc.setdefault(m.slot, [0, 0, []])
        if m.ballot >= st[0]:
            st[0] = st[1] = m.ballot
            st[2] = list(m.cmds)
            self.socket.send(ballot_id(m.ballot),
                             BP2b(m.ballot, m.slot, str(self.id)))
        # a superseded write gets no ack: the proposer's row can never
        # complete once any row member promised a higher ballot

    # ---- proxies: tallies ----------------------------------------------
    def handle_bp1b(self, m: BP1b) -> None:
        rec = self._rec
        if (rec is None or rec.phase != 1 or m.slot != rec.slot
                or m.ballot != rec.ballot):
            return
        rec.quorum.ack(ID(m.id))
        if m.vballot > rec.vballot:
            rec.vballot = m.vballot
            rec.cmds = _cmds_from_wire(m.cmds)
        if rec.quorum.grid_col(self.gc):
            # ONE FULL COLUMN read: adopt the highest accepted batch
            # (it intersects every possibly-chosen row), else NOOP
            self._rec_write(rec.cmds if rec.vballot > 0 else [])

    def _rec_write(self, cmds: List[Command]) -> None:
        rec = self._rec
        rec.phase = 2
        rec.cmds = cmds
        rec.quorum = Quorum(self.acceptors)
        m = BP2a(rec.ballot, rec.slot, _wire_cmds(cmds))
        for a in self._row(rec.attempt % self.gr):
            self.socket.send(a, m)

    def handle_bp2b(self, m: BP2b) -> None:
        rec = self._rec
        if (rec is not None and rec.phase == 2 and m.slot == rec.slot
                and m.ballot == rec.ballot):
            rec.quorum.ack(ID(m.id))
            if rec.quorum.grid_row(self.gc):
                self._rec = None
                self.recovered += 1
                self._commit(rec.slot, rec.ballot, rec.cmds)
                # a dead stripe leaves a RUN of holes: once in repair
                # mode, chain straight onto the next one instead of
                # waiting out a fresh strike round per hole
                self._maybe_chain_recovery()
            return
        e = self.log.get(m.slot)
        if (e is not None and not e.commit and e.quorum is not None
                and m.ballot == e.ballot == self.bal0):
            e.quorum.ack(ID(m.id))
            if e.quorum.grid_row(self.gc):
                self._commit(m.slot, e.ballot, e.cmds)

    def _commit(self, slot: int, bal: int, cmds: List[Command]) -> None:
        m = BP3(bal, slot, _wire_cmds(cmds))
        for i in self._learners():
            self.socket.send(i, m)
        self._learn(slot, bal, cmds)
        # own commits strike too: a proxy whose peer died would
        # otherwise never notice the holes its own commits straddle
        self._gap_tick(slot)

    # ---- learners ------------------------------------------------------
    def handle_bp3(self, m: BP3) -> None:
        self._learn(m.slot, m.ballot, _cmds_from_wire(m.cmds))
        if self.is_proxy:
            self._skip_to(m.slot)
            self._gap_tick(m.slot)

    def _skip_to(self, s: int) -> None:
        """Mencius-style stripe skip: a peer's stripe advanced past my
        next own slot — NOOP-fill mine up to it so the shared log stays
        hole-free at idle proxies (execution, hence every client reply,
        needs the contiguous prefix)."""
        while self.next_slot < s:
            slot = self.next_slot
            self.next_slot += len(self.proxies)
            self.log[slot] = Entry(self.bal0, [], requests=[],
                                   quorum=Quorum(self.acceptors),
                                   timestamp=time.time())
            m = BP2a(self.bal0, slot, [])
            for a in self._row(slot % self.gr):
                self.socket.send(a, m)

    def _learn(self, slot: int, bal: int, cmds: List[Command]) -> None:
        e = self.log.get(slot)
        reqs: List[Optional[Request]] = []
        if e is not None:
            if _idents(e.cmds) == _idents(cmds):
                reqs = e.requests
            else:
                if e.commit:
                    # a committed slot changed identity: the safety
                    # violation the grid intersection exists to prevent
                    # (reproducible via the noread twin) — count it
                    # sticky so the hunt oracle sees it after the run
                    self.safety_violations += 1
                for req in e.live_requests():
                    # our batch lost the slot: re-propose it elsewhere
                    self.handle_client_request(req)
        self.log[slot] = Entry(bal, cmds, commit=True, requests=reqs)
        self._exec()
        self._arm_gap_timer()

    def _exec(self) -> None:
        while True:
            e = self.log.get(self.execute)
            if e is None or not e.commit:
                break
            reqs = e.requests
            if not reqs:
                if e.cmds:
                    self.db.apply_batch(e.cmds, self.ctab)
                self.execute += 1
                continue
            for i, cmd in enumerate(e.cmds):
                req = reqs[i] if i < len(reqs) else None
                last = (self.ctab.get(cmd.client_id)
                        if cmd.client_id else None)
                if last is not None and cmd.command_id <= last[0]:
                    value = last[1] if cmd.command_id == last[0] else b""
                else:
                    value = self.db.execute(cmd)
                    if cmd.client_id:
                        self.ctab[cmd.client_id] = (cmd.command_id, value)
                if req is not None:
                    req.reply(Reply(cmd, value=value))
            e.requests = []
            self.execute += 1
        if self.execute != self._gap_at:
            self._gap_at = self.execute
            self._gap_strikes = 0

    # ---- takeover recovery ---------------------------------------------
    def _gap_tick(self, slot: int) -> None:
        """A commit landed above a stalled frontier: strike.  Enough
        strikes (staggered so the hole's owner moves first) start —
        or restart, rotating the row/column — slot recovery."""
        if slot <= self.execute:
            return
        if self._gap_at != self.execute:
            self._gap_at = self.execute
            self._gap_strikes = 0
        self._gap_strikes += 1
        hole = self.execute
        e = self.log.get(hole)
        if e is not None and e.commit:
            return
        owner = hole % len(self.proxies)
        stag = (self.rank - owner) % len(self.proxies)
        need = 3 + 3 * stag
        if self._rec is None:
            if self._gap_strikes >= need:
                self._recover(hole)
        elif self._gap_strikes - self._rec.strikes0 >= 6:
            self._recover(self._rec.slot)   # stuck: rotate row/column

    def _maybe_chain_recovery(self) -> None:
        hole = self.execute
        e = self.log.get(hole)
        if (self._rec is None and (e is None or not e.commit)
                and any(s > hole and x.commit
                        for s, x in self.log.items())):
            self._recover(hole)

    def _gap_pending(self) -> bool:
        """Is execution stalled on a hole below known commits?"""
        e = self.log.get(self.execute)
        return (e is None or not e.commit) and \
            any(s > self.execute and x.commit
                for s, x in self.log.items())

    def _arm_gap_timer(self) -> None:
        if (not self.is_proxy or self.socket.fabric is not None
                or self._gap_handle is not None
                or not self._gap_pending()):
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        owner = self.execute % len(self.proxies)
        stag = (self.rank - owner) % len(self.proxies)
        self._gap_armed_at = self.execute
        self._gap_handle = loop.call_later(0.05 * (1 + stag),
                                           self._gap_poll)

    def _gap_poll(self) -> None:
        self._gap_handle = None
        if not self._gap_pending():
            self._rec_polls = 0
            return
        if self._rec is None:
            if self.execute == self._gap_armed_at:
                self._rec_polls = 0
                self._recover(self.execute)
        else:
            # an in-flight recovery outliving several polls is stuck on
            # a dead row/column member: restart (rotates both)
            self._rec_polls += 1
            if self._rec_polls >= 4:
                self._rec_polls = 0
                self._recover(self._rec.slot)
        self._arm_gap_timer()

    def _recover(self, slot: int) -> None:
        self._rec_attempt += 1
        rec = RecState(slot=slot,
                       ballot=ballot(1 + self._rec_attempt, self.id),
                       phase=1, quorum=Quorum(self.acceptors),
                       attempt=self._rec_attempt,
                       strikes0=self._gap_strikes)
        self._rec = rec
        if not self.RECOVERY_READS:
            # the seeded bug: blind NOOP write without the column read
            self._rec_write([])
            return
        m = BP1a(rec.ballot, slot)
        for a in self._col(rec.attempt % self.gc):
            self.socket.send(a, m)


def new_replica(id: ID, cfg: Config) -> BPaxosReplica:
    return BPaxosReplica(ID(id), cfg)


# sim mailbox name -> host message class, for the cross-runtime trace
# projection (trace/host.py).  Wire-level identity: the sim kernel's
# five mailbox planes are exactly the host runtime's five message
# classes (the fabric's tick flushes make trace-driven batches fill 1,
# so the per-slot correspondence holds during replays).
TRACE_MSG_MAP = {
    "p1a": "BP1a", "p1b": "BP1b", "p2a": "BP2a", "p2b": "BP2b",
    "p3": "BP3",
}

# sim state field -> host attribute, for the static parity check
# (analysis/parity.py PXS7xx).  Empty string = kernel-internal, no
# host analog.  Serves both `bpaxos` (sim.py PROTOCOL) and the
# `bpaxos_noread` twin (same state vocabulary).
SIM_STATE_MAP = {
    "abal":       "acc",        # promised ballot <-> acc[slot][0]
    "vbal":       "acc",        # accepted ballot <-> acc[slot][1]
    "vcmd":       "acc",        # accepted batch <-> acc[slot][2]
    "vbsz":       "acc",        # batch size <-> len(acc[slot][2])
    "committed":  "log",        # commit plane <-> Entry.commit
    "proposed":   "log",        # own-stripe in-flight <-> Entry existence
    "p2_acks":    "log",        # row-ack bitmask <-> Entry.quorum
    "next_slot":  "next_slot",
    "execute":    "execute",
    "kv":         "db",
    "cum_cmds":   "db",         # executed-command count <-> applied state
    "stuck":      "_gap_strikes",  # frontier-stall <-> gap strikes
    "rec_slot":   "_rec",       # the takeover FSM aggregate (RecState)
    "rec_bal":    "_rec",
    "rec_phase":  "_rec",
    "rec_acks":   "_rec",
    "rec_vbal":   "_rec",
    "rec_vcmd":   "_rec",
    "rec_vbsz":   "_rec",
    "rec_round":  "_rec_attempt",
    "recovered":  "recovered",
    "base":       "",   # ring-window base: the host log is an unbounded dict
    "rec_timer":  "",   # step-timer: host restarts are strike-driven
    # on-device observability (PR 11) — measurement planes, excluded
    # from the trace witness hash; the host twins are the registry's
    # live latency histograms and the post-hoc linearizability checker
    "m_prop_t":      "",
    "m_lat_hist":    "",
    "m_lat_sum":     "",
    "m_inscan_viol": "",
}


# ---- hunt-engine hooks (paxi_tpu/hunt/classify.py) ----------------------
# Gap-strike takeover is evidence-driven: after the replayed schedule it
# takes several fault-free commits to strike the hole, run the recovery
# round and surface any divergence — the default 10-step tail ends
# before that converges (40 is what the bpaxos_noread control needs).
HUNT_TAIL_STEPS = 40


def HUNT_ORACLE(cluster) -> int:
    """Safety-violation count after a replay: sticky commit-divergence
    counters plus cross-node disagreement on committed batches (the
    host analog of the sim kernel's agreement + stability oracle)."""
    bad = 0
    seen: Dict[int, list] = {}
    for i in cluster.ids:
        r = cluster[i]
        bad += getattr(r, "safety_violations", 0)
        for s, e in getattr(r, "log", {}).items():
            if not e.commit:
                continue
            ident = _idents(e.cmds)
            if s in seen and seen[s] != ident:
                bad += 1
            seen.setdefault(s, ident)
    return bad
