"""Bipartisan/compartmentalized Paxos: decoupled proxy-leader /
acceptor-grid / replica roles with HT-Paxos batched accepts.  ``sim``
is the lane-major TPU kernel, ``host`` the asyncio deployment runtime,
``noread`` the seeded-bug hunt twin (recovery without the column
read)."""
