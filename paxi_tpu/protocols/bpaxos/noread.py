"""Host twin of the ``bpaxos_noread`` seeded-bug sim kernel.

The same deliberately UNSAFE recovery on the asyncio runtime: takeover
skips the grid's column read and blind-writes NOOP at a higher ballot
(``RECOVERY_READS = False`` in host.py), so a recovered slot can
overwrite an already-chosen batch — exactly the mistake the
row x column intersection (and paxi-lint's PXQ rowcol proof) exists to
prevent.  Because the sim twin and this replica share the bug, a sim
witness replayed through the virtual-clock fabric MUST reproduce on
the host (``HUNT_ORACLE`` counts the commit divergence), making this
the hunt pipeline's end-to-end ``reproduced`` control for a real
protocol (trace/demo_host.py covers the demo kernel).

NOT a correctness case: never add it to the fuzz-soak oracle matrix.
"""

from __future__ import annotations

from paxi_tpu.core.config import Config
from paxi_tpu.core.ident import ID
from paxi_tpu.protocols.bpaxos.host import (  # noqa: F401  (re-exports
    HUNT_ORACLE, HUNT_TAIL_STEPS, SIM_STATE_MAP, TRACE_MSG_MAP,
    BPaxosReplica)

# paxi-lint (analysis/tracemap.py): analyze this module AS its base —
# the message classes, maps and state vocabulary all live in host.py
TWIN_OF = "paxi_tpu.protocols.bpaxos.host"


class NoReadReplica(BPaxosReplica):
    RECOVERY_READS = False


def new_replica(id: ID, cfg: Config) -> NoReadReplica:
    return NoReadReplica(ID(id), cfg)
